"""UID pack codec: block-compressed sorted u64 UID lists, device-friendly.

TPU-native replacement for the reference's group-varint delta codec
(/root/reference/codec/codec.go:36 Encoder / :139 Decoder, 256-UID blocks,
per-block u64 Base, blocks split when the 32 MSBs differ, codec.go:117).

Design difference (deliberate, per SURVEY.md §2.7(1)): group-varint decode is
a byte-serial SSE trick that does not map to the TPU. We instead store, per
256-UID block, the u64 base plus *absolute* uint32 offsets from that base
(`uid - base`, guaranteed < 2^32 by the same 32-MSB split rule). Offsets are
random-access (no prefix-sum on decode) and upload to the device as plain
uint32 lanes. On disk, offsets are bit-packed to the block's max width
(serialize/deserialize below), giving compression comparable to the
reference's group-varint for clustered UIDs while keeping decode a pure
shift/mask that XLA vectorizes.

Segments: for device set-ops, a pack is viewed as segments keyed by the high
32 bits. Within one segment all UIDs share the hi-32 word, so set algebra
runs in 32-bit local space (ops/setops.py); cross-segment ops align segments
host-side (matching the reference's per-block Base comparisons in
algo/packed.go).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from dgraph_tpu.x import config

BLOCK_SIZE = 256
_MAGIC = b"UPK1"

# Adaptive per-block container form (Roaring-style, arxiv 1907.01032): a
# block whose uid range fits in a fixed-size bitset AND whose density
# clears 1/8 is "bitmap-eligible" — the set kernels run word-wise
# AND/ANDNOT over the bitset instead of merging sorted offsets, and the
# serializer stores the bitset when it is smaller than the bit-packed
# offsets. BITMAP_BITS is the fixed in-memory bitset size per block
# (DGRAPH_TPU_BITMAP_BLOCK_BITS, multiple of 64; 0 disables the bitmap
# containers entirely).
def _sanitize_bitmap_bits(v: int) -> int:
    if v <= 0:
        return 0
    return max(64, (int(v) + 63) // 64 * 64)


BITMAP_BITS = _sanitize_bitmap_bits(int(config.get("BITMAP_BLOCK_BITS")))
BITMAP_WORDS = BITMAP_BITS // 64
# serialized bitmap container marker: the width byte of a block header is
# <= 32 for bit-packed offsets; 0xFF flags "payload is a bitset"
_BITMAP_FORM = 0xFF


@dataclass
class UidPack:
    """Block-compressed sorted u64 UID list.

    bases:   (nblocks,) uint64 — first UID of each block
    counts:  (nblocks,) int32  — #UIDs in each block (<= BLOCK_SIZE)
    offsets: (nblocks, BLOCK_SIZE) uint32 — uid - base, padded with 0xFFFFFFFF
    num_uids: total count
    """

    bases: np.ndarray
    counts: np.ndarray
    offsets: np.ndarray
    num_uids: int
    # lazily-computed per-block max UIDs (block_maxes); immutable like the
    # block arrays themselves
    _maxes: Optional[np.ndarray] = field(
        default=None, repr=False, compare=False
    )
    # lazily-built bitmap sidecar (block_bitmaps): (words, ok) where words
    # is (nblocks, BITMAP_WORDS) uint64 (None when no block is eligible)
    # and ok is the (nblocks,) bool eligibility mask
    _bm: Optional[tuple] = field(default=None, repr=False, compare=False)

    def __len__(self) -> int:
        return self.num_uids

    @property
    def nblocks(self) -> int:
        return self.bases.shape[0]

    def approx_bytes(self) -> int:
        """On-disk size estimate (per-block best of bit-packed/bitmap;
        same container pick as _serialize_block)."""
        total = len(_MAGIC) + 12 + self.nblocks * 11
        for i in range(self.nblocks):
            c = int(self.counts[i])
            total += _block_payload_bytes(self.offsets[i, :c], c)[0]
        return total


def _width_bits(offsets: np.ndarray) -> int:
    if offsets.size == 0:
        return 0
    m = int(offsets.max())
    return max(1, m.bit_length())


def encode(uids: np.ndarray) -> UidPack:
    """Encode a sorted (strictly increasing) u64 array into a UidPack.

    Blocks hold up to BLOCK_SIZE UIDs and never span a hi-32 boundary
    (mirrors codec.go:117's split rule so offsets always fit uint32).
    """
    uids = np.asarray(uids, dtype=np.uint64)
    n = uids.shape[0]
    if n == 0:
        return UidPack(
            bases=np.zeros((0,), np.uint64),
            counts=np.zeros((0,), np.int32),
            offsets=np.zeros((0, BLOCK_SIZE), np.uint32),
            num_uids=0,
        )
    if n <= BLOCK_SIZE and (uids[-1] >> np.uint64(32)) == (
        uids[0] >> np.uint64(32)
    ):
        # single-block fast path: the dominant bulk-load shape (small
        # per-key lists) — no segment scan, no per-block loop
        offsets = np.full((1, BLOCK_SIZE), 0xFFFFFFFF, np.uint32)
        offsets[0, :n] = (uids - uids[0]).astype(np.uint32)
        return UidPack(
            bases=uids[:1].copy(),
            counts=np.array([n], np.int32),
            offsets=offsets,
            num_uids=n,
        )
    hi = (uids >> np.uint64(32)).astype(np.uint64)
    # block boundary every BLOCK_SIZE elements or at hi-32 changes
    seg_starts = np.flatnonzero(np.concatenate([[True], hi[1:] != hi[:-1]]))
    starts: List[int] = []
    seg_bounds = list(seg_starts) + [n]
    for si in range(len(seg_bounds) - 1):
        s, e = int(seg_bounds[si]), int(seg_bounds[si + 1])
        starts.extend(range(s, e, BLOCK_SIZE))
    nb = len(starts)
    bases = np.zeros((nb,), np.uint64)
    counts = np.zeros((nb,), np.int32)
    offsets = np.full((nb, BLOCK_SIZE), 0xFFFFFFFF, np.uint32)
    bounds = starts + [n]
    for bi in range(nb):
        s = bounds[bi]
        e = min(bounds[bi + 1], s + BLOCK_SIZE)
        blk = uids[s:e]
        # Base is the first UID (not hi-masked): offsets stay small for
        # clustered blocks, minimizing the bit-pack width. Safe because a
        # block never spans a hi-32 boundary, so offsets always fit uint32.
        bases[bi] = blk[0]
        counts[bi] = e - s
        offsets[bi, : e - s] = (blk - bases[bi]).astype(np.uint32)
    return UidPack(bases=bases, counts=counts, offsets=offsets, num_uids=n)


def decode(pack: UidPack) -> np.ndarray:
    """Decode a UidPack back to a sorted u64 array. Ref codec.go:444 Decode.

    Implemented as a full-range partial decode — one vectorized/native pass
    instead of the old per-block Python loop. Single-block packs (the
    dominant fan-out shape: small per-key lists) take a direct slice, no
    native marshaling."""
    if pack.num_uids == 0:
        return np.zeros((0,), np.uint64)
    if pack.nblocks == 1:
        c = int(pack.counts[0])
        return pack.bases[0] + pack.offsets[0, :c].astype(np.uint64)
    return decode_blocks(pack, np.arange(pack.nblocks, dtype=np.int64))


def block_maxes(pack: UidPack) -> np.ndarray:
    """(nblocks,) uint64 — last (max) UID of each block.

    Together with `pack.bases` this is the per-block skip metadata of the
    compressed-domain set ops (ops/packed_setops.py): a block's UID range is
    [bases[i], maxes[i]], ranges are disjoint and ascending. Derivable from
    the next block's base in the reference (algo/packed.go walks per-block
    Base values); here the last in-block offset gives the exact max. Cached
    on the pack — the metadata is immutable once encoded."""
    if pack._maxes is None:
        nb = pack.nblocks
        if nb == 0:
            pack._maxes = np.zeros((0,), np.uint64)
        else:
            last = np.maximum(pack.counts.astype(np.int64) - 1, 0)
            pack._maxes = pack.bases + pack.offsets[
                np.arange(nb), last
            ].astype(np.uint64)
    return pack._maxes


def bitmap_eligible(pack: UidPack) -> np.ndarray:
    """(nblocks,) bool — True where the block's uid range fits the fixed
    BITMAP_BITS bitset AND its density clears 1/8 (count * 8 > range).
    The per-block cardinality metadata behind the adaptive kernel pick:
    eligible blocks materialize as bitsets (block_bitmaps) and run the
    word-wise AND/ANDNOT kernels; the rest stay sorted-offset form."""
    nb = pack.nblocks
    if nb == 0 or BITMAP_BITS == 0:
        return np.zeros((nb,), bool)
    rng = block_maxes(pack) - pack.bases
    return (rng < np.uint64(BITMAP_BITS)) & (
        pack.counts.astype(np.uint64) * np.uint64(8) > rng
    )


def block_bitmaps(
    pack: UidPack,
) -> Tuple[Optional[np.ndarray], Optional[np.ndarray], np.ndarray]:
    """(words, rows, ok): the pack's bitmap sidecar, COMPACT — `words` is
    a (n_eligible, BITMAP_WORDS) uint64 matrix holding only the eligible
    blocks' fixed-size bitsets (bit j of block i's row <=> uid
    bases[i]+j present), `rows` is the (nblocks,) int32 indirection
    (words-row index, or -1 for offsets-only blocks), and `ok` the bool
    eligibility mask. `words`/`rows` are None when NO block is eligible
    (the all-sparse case: nothing allocates), and a mostly-sparse pack
    pays only for its dense blocks. Cached on the pack like block_maxes;
    the block arrays are immutable once encoded."""
    if pack._bm is None:
        ok = bitmap_eligible(pack)
        if not ok.any():
            pack._bm = (None, None, ok)
            return pack._bm
        idxs = np.flatnonzero(ok)
        rows = np.full((pack.nblocks,), -1, np.int32)
        rows[idxs] = np.arange(idxs.size, dtype=np.int32)
        words = np.zeros((idxs.size, BITMAP_WORDS), np.uint64)
        from dgraph_tpu import native

        if not native.pack_build_bitmaps(
            pack.counts, pack.offsets, rows, BITMAP_BITS, words
        ):
            # numpy fallback: one flat scatter over all eligible blocks
            mat = pack.offsets[idxs]
            valid = (
                np.arange(mat.shape[1], dtype=np.int32)[None, :]
                < pack.counts[idxs][:, None]
            )
            ri, ji = np.nonzero(valid)
            offs = mat[ri, ji].astype(np.uint64)
            np.bitwise_or.at(
                words,
                (ri, (offs >> np.uint64(6)).astype(np.int64)),
                np.uint64(1) << (offs & np.uint64(63)),
            )
        pack._bm = (words, rows, ok)
    return pack._bm


def offsets_to_bitmap(offs: np.ndarray, nbits: int) -> np.ndarray:
    """Conversion helper: uint32 in-block offsets (< nbits) -> uint64
    bitset words, little-endian bit order (bit j <=> offset j)."""
    words = np.zeros(((nbits + 63) // 64,), np.uint64)
    o = np.asarray(offs, np.uint64)
    np.bitwise_or.at(
        words,
        (o >> np.uint64(6)).astype(np.int64),
        np.uint64(1) << (o & np.uint64(63)),
    )
    return words


def bitmap_to_offsets(words: np.ndarray, nbits: int) -> np.ndarray:
    """Inverse of offsets_to_bitmap: set bits -> sorted uint32 offsets."""
    bits = np.unpackbits(
        np.ascontiguousarray(words, np.uint64).view(np.uint8),
        bitorder="little",
    )[:nbits]
    return np.flatnonzero(bits).astype(np.uint32)


def decode_blocks(pack: UidPack, idxs: np.ndarray) -> np.ndarray:
    """Decode ONLY the blocks in `idxs` (sorted ascending) -> sorted u64.

    The partial decoder behind the block-skip set ops: candidate blocks
    found by range overlap decode; everything else stays compressed. The
    native fast path (codec.cpp pack_decode_blocks) avoids the (k, 256)
    gather temp; the numpy fallback is a masked broadcast."""
    idxs = np.asarray(idxs, dtype=np.int64)
    if idxs.size == 0:
        return np.zeros((0,), np.uint64)
    if idxs.size <= 4:
        # few blocks: per-block slices beat the ctypes marshal and the
        # masked broadcast alike
        parts = []
        for bi in idxs:
            c = int(pack.counts[bi])
            parts.append(
                pack.bases[bi] + pack.offsets[bi, :c].astype(np.uint64)
            )
        return parts[0] if len(parts) == 1 else np.concatenate(parts)
    from dgraph_tpu import native

    got = native.pack_decode_blocks(
        pack.bases, pack.counts, pack.offsets, idxs
    )
    if got is not None:
        return got
    counts = pack.counts[idxs].astype(np.int64)
    rows = pack.offsets[idxs]
    mask = np.arange(BLOCK_SIZE, dtype=np.int64)[None, :] < counts[:, None]
    return (pack.bases[idxs][:, None] + rows.astype(np.uint64))[mask]


def decode_packs(packs: List[UidPack]) -> Tuple[np.ndarray, np.ndarray]:
    """Decode N packs into a ragged (flat u64 buffer, int64[n+1] prefix
    offsets) pair in one pass — pack i's uids are
    flat[offsets[i]:offsets[i+1]]. The level-batched fan-out read shape:
    one call materializes a whole traversal level instead of N per-key
    decode round-trips (native fast path codec.cpp packs_decode_many)."""
    from dgraph_tpu import native

    got = native.packs_decode_many(packs)
    if got is not None:
        return got
    rows = [decode(p) for p in packs]
    offs = np.zeros((len(rows) + 1,), np.int64)
    if rows:
        np.cumsum([len(r) for r in rows], out=offs[1:])
    flat = (
        np.concatenate(rows) if rows else np.zeros((0,), np.uint64)
    ).astype(np.uint64, copy=False)
    return flat, offs


def merge_packs(packs: List[UidPack]) -> UidPack:
    """Concatenate packs holding disjoint ascending UID ranges (multi-part
    posting-list parts, ref posting/list.go:519 pIterator) into one logical
    pack WITHOUT decoding — pure block-array concatenation, so the merged
    view feeds the compressed-domain ops directly."""
    packs = [p for p in packs if p.num_uids]
    if not packs:
        return encode(np.zeros((0,), np.uint64))
    if len(packs) == 1:
        return packs[0]
    return UidPack(
        bases=np.concatenate([p.bases for p in packs]),
        counts=np.concatenate([p.counts for p in packs]),
        offsets=np.concatenate([p.offsets for p in packs]),
        num_uids=sum(p.num_uids for p in packs),
    )


def split_segments(uids: np.ndarray) -> Dict[int, np.ndarray]:
    """Split a sorted u64 array into {hi32: sorted uint32 lo-array} segments."""
    uids = np.asarray(uids, dtype=np.uint64)
    out: Dict[int, np.ndarray] = {}
    if uids.size == 0:
        return out
    # sorted input: equal first/last hi-words means ONE segment — the
    # overwhelmingly common case (uids cluster far below 2^32), and this
    # function runs once per row of every level-batched dispatch
    hi0 = int(uids[0] >> np.uint64(32))
    if int(uids[-1] >> np.uint64(32)) == hi0:
        out[hi0] = (uids & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        return out
    hi = (uids >> np.uint64(32)).astype(np.uint64)
    starts = np.flatnonzero(np.concatenate([[True], hi[1:] != hi[:-1]]))
    bounds = list(starts) + [uids.size]
    for si in range(len(bounds) - 1):
        s, e = int(bounds[si]), int(bounds[si + 1])
        out[int(hi[s])] = (uids[s:e] & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    return out


def join_segments(segments: Dict[int, np.ndarray]) -> np.ndarray:
    """Inverse of split_segments."""
    parts = []
    for h in sorted(segments):
        lo = segments[h].astype(np.uint64)
        parts.append((np.uint64(h) << np.uint64(32)) | lo)
    if not parts:
        return np.zeros((0,), np.uint64)
    return np.concatenate(parts)


# ---------------------------------------------------------------------------
# Serialization: bit-packed per-block offsets (disk/wire format).
# ---------------------------------------------------------------------------


def _bitpack(vals, width):
    from dgraph_tpu import native

    return native.bitpack(vals, width)


def _bitunpack(data, count, width):
    from dgraph_tpu import native

    return native.bitunpack(data, count, width)


def _block_payload_bytes(offs: np.ndarray, c: int):
    """(payload_bytes, use_bitmap, width, max_offset) — the ONE container
    pick shared by _serialize_block and approx_bytes, so the on-disk
    size estimate can never drift from the serializer."""
    w = _width_bits(offs)
    packed_nbytes = (c * w + 7) // 8
    rng = int(offs[-1]) if c else 0
    if BITMAP_BITS and c and rng <= 0xFFFF:
        bm_nbytes = 2 + (rng + 8) // 8
        if bm_nbytes < packed_nbytes:
            return bm_nbytes, True, w, rng
    return packed_nbytes, False, w, rng


def _serialize_block(base: int, offs: np.ndarray, c: int) -> bytes:
    """One block record, in whichever container form is smaller:

      packed  [<QHB> base count width]  + bit-packed offsets
      bitmap  [<QHB> base count 0xFF]   + <H> max-offset + bitset bytes

    A dense block (small max offset relative to count) stores as a raw
    little-endian bitset over its base — the on-disk face of the bitmap
    containers (Roaring-style, arxiv 1907.01032). The 0xFF marker can
    never collide with a real width (widths are <= 32), so old packed
    records stay readable; records WITH bitmap blocks are not readable
    by pre-bitmap builds (pin DGRAPH_TPU_BITMAP_BLOCK_BITS=0 to keep
    writing the legacy form in a mixed-version store). The native bulk
    writer (bulkload.cpp serialize_uids) emits only the packed form;
    both forms deserialize."""
    _, use_bitmap, w, rng = _block_payload_bytes(offs, c)
    if use_bitmap:
        words = offsets_to_bitmap(offs, rng + 1)
        return (
            struct.pack("<QHB", base, c, _BITMAP_FORM)
            + struct.pack("<H", rng)
            + words.view(np.uint8)[: (rng + 8) // 8].tobytes()
        )
    return struct.pack("<QHB", base, c, w) + _bitpack(offs, w)


def serialize_uids(uids: np.ndarray) -> bytes:
    """Serialized pack straight from a sorted uid array — skips the
    UidPack materialization for the dominant small-list case (bulk-load
    reduce hot path; wire format identical to serialize(encode(uids)))."""
    n = len(uids)
    if n == 0:
        return _MAGIC + struct.pack("<QI", 0, 0)
    if n <= BLOCK_SIZE and (int(uids[-1]) >> 32) == (int(uids[0]) >> 32):
        base = int(uids[0])
        offs = (uids - uids[0]).astype(np.uint32)
        return (
            _MAGIC
            + struct.pack("<QI", n, 1)
            + _serialize_block(base, offs, n)
        )
    return serialize(encode(uids))


def serialize(pack: UidPack) -> bytes:
    """Per-block container pick: bit-packed offsets at the block's max
    width, or a raw bitset when the block is dense enough that the bitset
    is smaller (_serialize_block). Ref codec.go:393 Encode (group-varint
    there; fixed-width lanes / bitmap containers here — see module
    docstring)."""
    parts = [_MAGIC, struct.pack("<QI", pack.num_uids, pack.nblocks)]
    for bi in range(pack.nblocks):
        c = int(pack.counts[bi])
        parts.append(
            _serialize_block(
                int(pack.bases[bi]), pack.offsets[bi, :c], c
            )
        )
    return b"".join(parts)


def deserialize(data: bytes) -> UidPack:
    if data[:4] != _MAGIC:
        raise ValueError("bad UidPack magic")
    num_uids, nb = struct.unpack_from("<QI", data, 4)
    # bound-check untrusted header before allocating (disk/wire input)
    if nb * 11 + 16 > len(data):
        raise ValueError(f"corrupt UidPack: {nb} blocks exceeds data size")
    pos = 4 + 12
    bases = np.zeros((nb,), np.uint64)
    counts = np.zeros((nb,), np.int32)
    offsets = np.full((nb, BLOCK_SIZE), 0xFFFFFFFF, np.uint32)
    for bi in range(nb):
        base, c, w = struct.unpack_from("<QHB", data, pos)
        pos += 11
        if c > BLOCK_SIZE or (w > 32 and w != _BITMAP_FORM):
            raise ValueError(
                f"corrupt UidPack block: count={c} width={w}"
            )
        if w == _BITMAP_FORM:
            # bitmap container: <H> max-offset + little-endian bitset
            if pos + 2 > len(data):
                raise ValueError("truncated UidPack bitmap header")
            (rng,) = struct.unpack_from("<H", data, pos)
            pos += 2
            nbytes = (rng + 8) // 8
            if pos + nbytes > len(data):
                raise ValueError("truncated UidPack block data")
            bits = np.unpackbits(
                np.frombuffer(data, np.uint8, nbytes, pos),
                bitorder="little",
            )[: rng + 1]
            offs = np.flatnonzero(bits).astype(np.uint32)
            if offs.size != c:
                raise ValueError(
                    f"corrupt UidPack bitmap block: popcount "
                    f"{offs.size} != count {c}"
                )
            pos += nbytes
        else:
            nbytes = (c * w + 7) // 8
            if pos + nbytes > len(data):
                raise ValueError("truncated UidPack block data")
            offs = _bitunpack(data[pos : pos + nbytes], c, w)
            pos += nbytes
        bases[bi] = base
        counts[bi] = c
        offsets[bi, :c] = offs
    if int(counts.sum()) != num_uids:
        raise ValueError(
            f"corrupt UidPack: header num_uids={num_uids} != "
            f"sum of block counts {int(counts.sum())}"
        )
    return UidPack(bases=bases, counts=counts, offsets=offsets, num_uids=num_uids)


def _bitpack_py(vals: np.ndarray, width: int) -> bytes:
    """Pack uint32 values into `width`-bit little-endian lanes."""
    if width == 0 or vals.size == 0:
        return b""
    v = vals.astype(np.uint64)
    nbits = vals.size * width
    nbytes = (nbits + 7) // 8
    buf = np.zeros((nbytes,), np.uint8)
    bitpos = np.arange(vals.size, dtype=np.uint64) * np.uint64(width)
    # write each value byte-by-byte (width <= 32 so spans <= 5 bytes)
    for byte_i in range(5):
        byte_idx = (bitpos >> np.uint64(3)) + np.uint64(byte_i)
        shift = (bitpos & np.uint64(7)).astype(np.uint64)
        chunk = ((v << shift) >> np.uint64(8 * byte_i)) & np.uint64(0xFF)
        valid = byte_idx < nbytes
        np.bitwise_or.at(
            buf, byte_idx[valid].astype(np.int64), chunk[valid].astype(np.uint8)
        )
    return buf.tobytes()


def _bitunpack_py(data: bytes, count: int, width: int) -> np.ndarray:
    if width == 0 or count == 0:
        return np.zeros((count,), np.uint32)
    buf = np.frombuffer(data, dtype=np.uint8)
    # read 8 bytes window per value via padded u64 gather
    padded = np.zeros((buf.size + 8,), np.uint8)
    padded[: buf.size] = buf
    bitpos = np.arange(count, dtype=np.uint64) * np.uint64(width)
    byte_idx = (bitpos >> np.uint64(3)).astype(np.int64)
    shift = (bitpos & np.uint64(7)).astype(np.uint64)
    window = np.zeros((count,), np.uint64)
    for b in range(8):
        window |= padded[byte_idx + b].astype(np.uint64) << np.uint64(8 * b)
    mask = (np.uint64(1) << np.uint64(width)) - np.uint64(1)
    return ((window >> shift) & mask).astype(np.uint32)

from dgraph_tpu.codec.uidpack import UidPack, encode, decode, split_segments

"""Vector similarity index: brute-force matmul top-k with an IVF tier.

Replaces the reference's HNSW (/root/reference/tok/hnsw/persistent_hnsw.go)
behind the same index-boundary semantics (tok/index/index.go:93 VectorIndex:
Search/SearchWithUid/Insert, per-call ef / distance_threshold options,
filtered search). HNSW's pointer-chasing beam search is hostile to the TPU
(SURVEY.md §2.7(7)); the sanctioned replacement is:

  - brute-force: scores = Q @ V.T on the MXU (bfloat16 matmul, f32
    accumulation) + lax.top_k — exact, recall 1.0;
  - IVF: k-means centroids trained *on device* (the batched Lloyd step is
    a matmul + segment-sum — this is models' training loop), searches probe
    the nprobe nearest cells only.

Metrics match tok/hnsw/helper.go:98-114: euclidean, cosine, dotproduct.
Supported distance ordering: smaller = closer (dot negated).

Mutability: inserts/deletes buffer host-side and fold into the padded
device matrix lazily (the MVCC analog of pack re-upload on rollup).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

import numpy as np

_PAD_ROWS = 256


def _pow2_rows(n: int) -> int:
    return max(_PAD_ROWS, 1 << (max(1, n) - 1).bit_length())


class VectorIndex:
    def __init__(
        self,
        pred: str,
        metric: str = "euclidean",
        ivf_threshold: int = 200_000,
        nlist: Optional[int] = None,
        nprobe: Optional[int] = None,
    ):
        if metric not in ("euclidean", "cosine", "dotproduct"):
            raise ValueError(f"unknown metric {metric!r}")
        self.pred = pred
        self.metric = metric
        self.ivf_threshold = ivf_threshold
        self.nlist = nlist
        self.nprobe = nprobe

        self._uids: List[int] = []
        self._rows: Dict[int, int] = {}  # uid -> row
        self._vecs: Optional[np.ndarray] = None  # (cap, d) padded
        self._n = 0
        self._dirty = True
        self._device = None  # jnp arrays (vecs, uids, norms)
        self._ivf = None

    # -- mutation -------------------------------------------------------------

    def insert(self, uid: int, vec) -> None:
        vec = np.asarray(vec, dtype=np.float32).reshape(-1)
        if self._vecs is None:
            self._vecs = np.zeros((_PAD_ROWS, vec.shape[0]), np.float32)
        if vec.shape[0] != self._vecs.shape[1]:
            raise ValueError(
                f"dim mismatch: index {self._vecs.shape[1]}, got {vec.shape[0]}"
            )
        row = self._rows.get(uid)
        if row is None:
            if self._n == self._vecs.shape[0]:
                grown = np.zeros(
                    (self._vecs.shape[0] * 2, self._vecs.shape[1]), np.float32
                )
                grown[: self._n] = self._vecs[: self._n]
                self._vecs = grown
            row = self._n
            self._n += 1
            self._rows[uid] = row
            self._uids.append(uid)
        self._vecs[row] = vec
        self._dirty = True

    def remove(self, uid: int) -> None:
        row = self._rows.pop(uid, None)
        if row is None:
            return
        last = self._n - 1
        if row != last:
            last_uid = self._uids[last]
            self._vecs[row] = self._vecs[last]
            self._rows[last_uid] = row
            self._uids[row] = last_uid
        self._uids.pop()
        self._n = last
        self._dirty = True

    def __len__(self) -> int:
        return self._n

    # -- device state ---------------------------------------------------------

    def _sync_device(self):
        import os as _os

        import jax
        import jax.numpy as jnp

        if not self._dirty and self._device is not None:
            return
        cap = _pow2_rows(self._n)
        d = self._vecs.shape[1]
        mat = np.zeros((cap, d), np.float32)
        mat[: self._n] = self._vecs[: self._n]
        uids = np.zeros((cap,), np.uint64)
        uids[: self._n] = np.asarray(self._uids, np.uint64)
        valid = np.zeros((cap,), bool)
        valid[: self._n] = True
        self._mesh = None
        shard = _os.environ.get("DGRAPH_TPU_SHARD_VECTORS", "") == "1"
        if shard and len(jax.devices()) > 1:
            # row-shard the corpus over the device mesh: per-shard top-k,
            # all_gather, global reduce (parallel/mesh.py sharded_topk —
            # the TP-over-rows data plane for 1M×768-class corpora)
            from jax.sharding import NamedSharding, PartitionSpec as P

            from dgraph_tpu.parallel import mesh as pmesh

            mesh = pmesh.make_mesh()
            ndev = mesh.devices.size
            rows = -(-cap // ndev) * ndev
            if rows != cap:
                mat = np.vstack([mat, np.zeros((rows - cap, d), np.float32)])
                uids = np.concatenate(
                    [uids, np.zeros((rows - cap,), np.uint64)]
                )
                valid = np.concatenate(
                    [valid, np.zeros((rows - cap,), bool)]
                )
            sh = NamedSharding(mesh, P("data"))
            self._mesh = mesh
            self._device = {
                "vecs": jax.device_put(jnp.asarray(mat), sh),
                "uids": uids,  # host: gathered indices map back to uids
                "valid": jax.device_put(jnp.asarray(valid), sh),
                "sqnorm": None,
            }
            self._dirty = False
            if self._n >= self.ivf_threshold:
                self._train_ivf(mat[: self._n])
            else:
                self._ivf = None
            return
        self._device = {
            "vecs": jnp.asarray(mat),
            "uids": jnp.asarray(uids),
            "valid": jnp.asarray(valid),
            "sqnorm": jnp.asarray((mat * mat).sum(axis=1)),
        }
        self._dirty = False
        if self._n >= self.ivf_threshold:
            self._train_ivf(mat[: self._n])
        else:
            self._ivf = None

    # -- search ----------------------------------------------------------------

    def search(
        self,
        q,
        k: int,
        ef: Optional[int] = None,
        distance_threshold: Optional[float] = None,
        allowed: Optional[np.ndarray] = None,
    ) -> np.ndarray:
        """Top-k closest uids (sorted closest-first).

        `allowed`: optional sorted uid filter (ref index.go:66 SearchFilter).
        `ef`: candidate-pool override, kept for HNSW API compat — used as
        the IVF candidate width.
        """
        if self._n == 0:
            return np.zeros((0,), np.uint64)
        self._sync_device()
        import jax.numpy as jnp

        q = np.asarray(q, dtype=np.float32).reshape(-1)
        kk = min(max(k, 1), self._n)
        pool = max(kk, ef or 0)
        allowed_set = None
        if allowed is not None:
            allowed_set = np.asarray(allowed, np.uint64)
            # filter drops candidates; widen the pool up-front
            pool = max(pool, 4 * kk)

        # widen the candidate pool until k survivors or the whole set seen
        # (the HNSW analog is raising ef; ref index.go VectorIndexOptions)
        while True:
            if getattr(self, "_mesh", None) is not None:
                from dgraph_tpu.parallel import mesh as pmesh

                npool = min(max(pool, kk), self._n)
                dd, idx = pmesh.sharded_topk(
                    self._mesh,
                    self._device["vecs"],
                    self._device["valid"],
                    jnp.asarray(q),
                    npool,
                )
                cand_dists = np.asarray(dd)
                cand_uids = self._device["uids"][np.asarray(idx)]
            elif self._ivf is not None:
                cand_uids, cand_dists = self._ivf_search(q, max(pool, 4 * kk))
            else:
                dists = _distances(
                    self._device["vecs"],
                    self._device["sqnorm"],
                    jnp.asarray(q),
                    self.metric,
                )
                dists = jnp.where(self._device["valid"], dists, jnp.inf)
                npool = min(max(pool, kk), self._n)
                neg, idx = _top_k(-dists, npool)
                cand_dists = -np.asarray(neg)
                cand_uids = np.asarray(self._device["uids"])[np.asarray(idx)]

            out = []
            for u, dist in zip(cand_uids, cand_dists):
                if not math.isfinite(dist):
                    continue
                if distance_threshold is not None and dist > distance_threshold:
                    break  # dists ascend: nothing closer follows
                if allowed_set is not None and not _in_sorted(allowed_set, u):
                    continue
                out.append(int(u))
                if len(out) == kk:
                    break
            exhausted = len(cand_uids) >= self._n or pool >= self._n
            if len(out) == kk or exhausted or allowed_set is None:
                return np.asarray(out, np.uint64)
            pool = min(pool * 4, self._n)

    def search_with_uid(self, uid: int, k: int, **kw) -> np.ndarray:
        row = self._rows.get(int(uid))
        if row is None:
            return np.zeros((0,), np.uint64)
        res = self.search(self._vecs[row], k + 1, **kw)
        return np.asarray([u for u in res if int(u) != int(uid)][:k], np.uint64)

    # -- IVF -------------------------------------------------------------------

    def _train_ivf(self, mat: np.ndarray, iters: int = 10):
        """Device k-means (Lloyd): assign = argmin distance matmul;
        update = segment mean. One jitted step, scanned."""
        import jax
        import jax.numpy as jnp

        n, d = mat.shape
        nlist = self.nlist or int(max(16, math.sqrt(n) * 2))
        nlist = min(nlist, n)
        rng = np.random.default_rng(0)
        cents = mat[rng.choice(n, nlist, replace=False)].copy()

        X = jnp.asarray(mat)
        xsq = (X * X).sum(axis=1)

        @jax.jit
        def step(c):
            csq = (c * c).sum(axis=1)
            d2 = xsq[:, None] - 2.0 * (X @ c.T) + csq[None, :]
            assign = jnp.argmin(d2, axis=1)
            sums = jax.ops.segment_sum(X, assign, num_segments=nlist)
            cnts = jax.ops.segment_sum(
                jnp.ones((n,), jnp.float32), assign, num_segments=nlist
            )
            newc = jnp.where(
                cnts[:, None] > 0, sums / jnp.maximum(cnts, 1.0)[:, None], c
            )
            return newc, assign

        c = jnp.asarray(cents)
        for _ in range(iters):
            c, assign = step(c)
        c_np = np.asarray(c)

        # multi-assignment: each vector lands in its 2 nearest cells —
        # big recall win for weakly-clustered data at 2x cell memory
        # (the reference's HNSW achieves the same via graph redundancy)
        csq = (c_np * c_np).sum(axis=1)
        d2 = (
            (mat * mat).sum(axis=1)[:, None]
            - 2.0 * (mat @ c_np.T)
            + csq[None, :]
        )
        top2 = np.argpartition(d2, 1, axis=1)[:, :2]
        rows_rep = np.repeat(np.arange(n), 2)
        cells_rep = top2.reshape(-1)

        order = np.argsort(cells_rep, kind="stable")
        sorted_cells = cells_rep[order]
        starts = np.searchsorted(sorted_cells, np.arange(nlist))
        ends = np.searchsorted(sorted_cells, np.arange(nlist), side="right")
        maxlen = max(1, int((ends - starts).max()))
        cells = np.full((nlist, maxlen), -1, np.int64)
        for ci in range(nlist):
            rws = rows_rep[order[starts[ci] : ends[ci]]]
            cells[ci, : len(rws)] = rws
        if self.nprobe is None:
            # probe ~12% of cells by default: keeps recall@10 >= ~0.9 even
            # on unclustered data while still skipping most of the corpus
            self.nprobe = max(16, nlist // 8)
        self._ivf = {
            "centroids": c_np,
            "cells": cells,
            "cell_lens": (ends - starts).astype(np.int32),
        }
        # cell-major contiguous copy of the (multi-assigned) corpus: probed
        # cells then read as GEMV-friendly slices instead of fancy gathers
        # (the gather copy dominated IVF query time). 2x corpus memory;
        # skipped for huge corpora where the gather path is kept.
        flat_rows = rows_rep[order]
        if mat.nbytes * 2 <= int(1e9):
            self._ivf["flat_vecs"] = np.ascontiguousarray(mat[flat_rows])
            self._ivf["flat_rows"] = flat_rows
            self._ivf["starts"] = starts
            self._ivf["ends"] = ends

    def _ivf_search(self, q: np.ndarray, pool: int):
        import jax.numpy as jnp

        ivf = self._ivf
        cents = ivf["centroids"]
        d2 = ((cents - q[None, :]) ** 2).sum(axis=1)
        probe = np.argsort(d2)[: self.nprobe]
        if "flat_vecs" in ivf:
            # contiguous per-cell slices: distances via slab GEMVs
            starts, ends = ivf["starts"], ivf["ends"]
            fr = ivf["flat_rows"]
            fv = ivf["flat_vecs"]
            row_parts = []
            dist_parts = []
            for ci in probe:
                s0, s1 = int(starts[ci]), int(ends[ci])
                if s1 <= s0:
                    continue
                row_parts.append(fr[s0:s1])
                dist_parts.append(
                    _distances_np(fv[s0:s1], q, self.metric)
                )
            if not row_parts:
                return np.zeros((0,), np.uint64), np.zeros((0,), np.float32)
            rows = np.concatenate(row_parts)
            dists = np.concatenate(dist_parts)
            # drop multi-assignment duplicates, keep best distance per row
            orderr = np.argsort(rows, kind="stable")
            rows, dists = rows[orderr], dists[orderr]
            first = np.concatenate(
                [[True], rows[1:] != rows[:-1]]
            )
            rows, dists = rows[first], dists[first]
        else:
            rows = np.concatenate([ivf["cells"][ci] for ci in probe])
            rows = np.unique(rows[rows >= 0])  # multi-assignment duplicates
            if rows.size == 0:
                return np.zeros((0,), np.uint64), np.zeros((0,), np.float32)
            sub = self._vecs[rows]
            dists = _distances_np(sub, q, self.metric)
        k = min(pool, rows.size)
        sel = np.argpartition(dists, k - 1)[:k]
        sel = sel[np.argsort(dists[sel])]
        uids = np.asarray(self._uids, np.uint64)[rows[sel]]
        return uids, dists[sel]


def _top_k(x, k):
    import jax.lax as lax

    return lax.top_k(x, k)


def _distances(V, sqnorm, q, metric):
    import jax.numpy as jnp

    dot = V @ q
    if metric == "dotproduct":
        return -dot
    if metric == "cosine":
        qn = jnp.sqrt((q * q).sum())
        vn = jnp.sqrt(sqnorm)
        return 1.0 - dot / jnp.maximum(vn * qn, 1e-12)
    # euclidean (squared — same ordering, cheaper; sqrt applied nowhere
    # because the reference compares distances relatively too)
    qsq = (q * q).sum()
    return sqnorm - 2.0 * dot + qsq


def _distances_np(V, q, metric):
    dot = V @ q
    if metric == "dotproduct":
        return -dot
    if metric == "cosine":
        qn = np.sqrt((q * q).sum())
        vn = np.sqrt((V * V).sum(axis=1))
        return 1.0 - dot / np.maximum(vn * qn, 1e-12)
    return ((V - q[None, :]) ** 2).sum(axis=1)


def _in_sorted(arr: np.ndarray, v) -> bool:
    i = np.searchsorted(arr, v)
    return i < arr.size and arr[i] == v

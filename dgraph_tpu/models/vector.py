"""Vector similarity index: quantized scan engine + brute/IVF tiers.

Replaces the reference's HNSW (/root/reference/tok/hnsw/persistent_hnsw.go)
behind the same index-boundary semantics (tok/index/index.go:93 VectorIndex:
Search/SearchWithUid/Insert, per-call ef / distance_threshold options,
filtered search). HNSW's pointer-chasing beam search is hostile to the TPU
(SURVEY.md §2.7(7)); the sanctioned replacements are:

  - QUANTIZED engine (default on CPU-backend hosts, `DGRAPH_TPU_VEC_QUANT`):
    the corpus is stored as per-row asymmetric int8 (v ≈ scale*code+offset,
    scale/offset/code-sum/exact-sqnorm sidecars — a 4x memory-bandwidth cut
    on the scan-dominated host path), scored by the native qint8 kernels
    (codec.cpp vec_qi8_topk / vec_qi8_topk_idx: SIMD int8 dot, fused
    partial top-k, deterministic low-index tie-break), and the surviving
    pool is reranked EXACTLY in float32 so quantization error cannot
    reorder the final top-k (`DGRAPH_TPU_VEC_RERANK` * k candidates).
    Its IVF tier is INCREMENTAL: centroids train once via sampled
    mini-batch k-means, rows are assigned lazily to their 2 nearest cells
    (per-cell row-id lists over the row-aligned code matrix — inserts
    append to cells, removes tombstone in place, and NO mutation ever
    retrains or re-lays-out the index inline; a deferred repartition
    runs when tombstone garbage passes live/4 (cells reassigned,
    centroids kept) or when the max/avg cell ratio GROWS past
    `DGRAPH_TPU_VEC_REBUILD_IMBALANCE` x its post-build baseline —
    imbalance the data had at build time is the baseline, not a
    trigger, since reassigning under the same centroids reproduces it;
    mutation-driven hot cells retrain the centroids on a sample).

  - jitted float32 paths (the A/B escape hatch `DGRAPH_TPU_VEC_QUANT=0`,
    and the device path on real accelerators — unchanged in shape):
    brute-force scores = Q @ V.T on the MXU + lax.top_k in ONE dispatch
    with an optimization barrier (without it XLA recomputes the matmul
    per sort pass — 82ms -> 2.3ms per query on a v5e for 100k x 256);
    IVF probes top-M fixed-size slabs so the whole search is one
    static-shape dispatch (no host loop over cells).

Every search picks brute vs IVF per CALL from the probed-pool-vs-corpus
cost model (`_ivf_pick`): the batched jit probe gathers (m_slabs*SLAB, d)
floats PER QUERY while the brute matmul reads the corpus once per batch,
so a probe pool that undercuts the corpus 15x can still lose at batch 64
(the VECTOR_1M_CPU.json r5 inversion: IVF 5.8 qps vs brute 12.2). The
quantized engine's probe runs the same scan kernel as its brute tier, so
there the crossover is simply probed-rows ~ corpus-rows.

Metrics match tok/hnsw/helper.go:98-114: euclidean, cosine, dotproduct.
Supported distance ordering: smaller = closer (dot negated).

Mutability: rows are append-only with tombstones (no swap-compaction, so
quantized sidecars and IVF cell ids stay valid across removes); the
jitted device matrix compacts lazily on rebuild (the MVCC analog of pack
re-upload on rollup), while the quantized engine folds mutations in
incrementally.
"""

from __future__ import annotations

import functools
import math
import threading
import time
from typing import Dict, Optional

import numpy as np

from dgraph_tpu.x import config

_PAD_ROWS = 256
_SLAB = 128  # IVF slab rows; one slab belongs to exactly one cell

# below this many live rows the jitted float brute scan is already sub-ms
# and exact — quantization is a bandwidth optimization, not a small-corpus
# one (tests monkeypatch this to force the quantized engine on tiny data)
_QUANT_MIN = 4096

_METRIC_ID = {"euclidean": 0, "cosine": 1, "dotproduct": 2}

_EMPTY_U64 = np.zeros((0,), np.uint64)

# native int8 top-2 cell assignment engages above this many multiply-
# accumulates (rows * nlist * dim) — below it the exact numpy path is
# already fast and keeps small-corpus layouts float-exact (tests force
# the native path by zeroing this)
_ASSIGN_NATIVE_MIN_MACS = 2e10


def _nthreads() -> int:
    """Worker threads for the native quantized kernels: the VEC_THREADS
    knob, 0 = one per core."""
    t = int(config.get("VEC_THREADS"))
    if t > 0:
        return t
    import os

    return os.cpu_count() or 1


# ---------------------------------------------------------------------------
# Attribution counters (mirrors ops/packed_setops.COUNTERS: per-thread,
# snapshot() consumed by observe.profile_scope into extensions.profile)
# ---------------------------------------------------------------------------


class _VecCounters(threading.local):
    """Per-thread vector-kernel accounting (threads serve independent
    queries; the coalesced batch leader accounts for its whole batch)."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.searches = 0       # queries served (any tier)
        self.probe_cells = 0    # IVF cells probed
        self.rerank_pool = 0    # candidates reranked in float32
        self.scan_rows = 0      # rows scored by the quantized kernels
        self.scan_ns = 0        # quantized scan time
        self.rerank_ns = 0      # float32 rerank time
        self.path_quant_ivf = 0
        self.path_quant_brute = 0
        self.path_jit_ivf = 0
        self.path_jit_brute = 0

    def snapshot(self) -> dict:
        return {
            "searches": self.searches,
            "probe_cells": self.probe_cells,
            "rerank_pool": self.rerank_pool,
            "scan_rows": self.scan_rows,
            "scan_ns": self.scan_ns,
            "rerank_ns": self.rerank_ns,
            "path_quant_ivf": self.path_quant_ivf,
            "path_quant_brute": self.path_quant_brute,
            "path_jit_ivf": self.path_jit_ivf,
            "path_jit_brute": self.path_jit_brute,
        }


COUNTERS = _VecCounters()


def reset_counters():
    COUNTERS.reset()


def counters() -> dict:
    return COUNTERS.snapshot()


def _metrics():
    from dgraph_tpu.utils.observe import METRICS

    return METRICS


_BACKEND_CPU: Optional[bool] = None


def _cpu_backend() -> bool:
    """True when jax would dispatch to a host CPU backend (or jax is
    absent entirely) — the regime where the quantized scan engine beats
    the jitted float paths. Cached: the backend cannot change after
    first init."""
    global _BACKEND_CPU
    if _BACKEND_CPU is None:
        try:
            import jax

            _BACKEND_CPU = jax.default_backend() == "cpu"
        except Exception:
            _BACKEND_CPU = True
    return _BACKEND_CPU


def _pow2_rows(n: int) -> int:
    return max(_PAD_ROWS, 1 << (max(1, n) - 1).bit_length())


@functools.lru_cache(maxsize=64)
def _jit_brute(metric: str, npool: int):
    """One-dispatch brute scorer: distances -> barrier -> top-k."""
    import jax
    import jax.numpy as jnp

    def run(V, sqnorm, valid, q):
        d = _distances(V, sqnorm, q, metric)
        d = jnp.where(valid, d, jnp.inf)
        d = jax.lax.optimization_barrier(d)
        neg, idx = jax.lax.top_k(-d, npool)
        return -neg, idx

    return jax.jit(run)


@functools.lru_cache(maxsize=64)
def _jit_brute_batch(metric: str, npool: int):
    import jax
    import jax.numpy as jnp

    def run(V, sqnorm, valid, Q):
        d = _distances_batch(V, sqnorm, Q, metric)
        d = jnp.where(valid[None, :], d, jnp.inf)
        d = jax.lax.optimization_barrier(d)
        neg, idx = jax.lax.top_k(-d, npool)
        return -neg, idx

    return jax.jit(run)


def _dedup_first(rows: np.ndarray) -> np.ndarray:
    """Indices of the first occurrence of each row id, in original order.
    Probe results ascend by distance, so the first occurrence of a
    multi-assigned row is its best distance. Input must be filtered to
    valid (>=0) rows."""
    _, first = np.unique(rows, return_index=True)
    return np.sort(first)


def _probe_plan(ivf: dict, pool: int):
    """Widen the static slab probe in pow2 factors until it covers the
    requested candidate pool (bounded jit signatures); npool carries 2x
    slack for multi-assignment duplicates."""
    base_pool = 64
    factor = 1
    while factor * base_pool < pool and ivf["m_slabs"] * factor < ivf[
        "n_slabs"
    ]:
        factor *= 2
    m = int(min(ivf["n_slabs"], ivf["m_slabs"] * factor))
    npool = int(min(max(pool, 1) * 2, m * _SLAB))
    return m, npool


def _ivf_probe(metric: str, m_slabs: int, npool: int):
    """The IVF probe body shared by the single-query and batched jits:
    centroid scores -> top-M slabs -> gather -> distances -> top-k.
    All shapes static."""
    import jax
    import jax.numpy as jnp

    def run(cents, csq, slab_cell, flat_vecs, flat_sq, flat_rows, q):
        # nearest cells by centroid distance (always euclidean on the
        # centroid geometry — probe selection only, not result ranking)
        cd = csq - 2.0 * (cents @ q) + (q * q).sum()
        slab_score = cd[slab_cell]
        _, sidx = jax.lax.top_k(-slab_score, m_slabs)
        sub = flat_vecs[sidx]            # (M, S, d) gather
        rows = flat_rows[sidx].reshape(-1)
        S, d = sub.shape[1], sub.shape[2]
        V = sub.reshape(m_slabs * S, d)
        dd = _distances(V, flat_sq[sidx].reshape(-1), q, metric)
        dd = jnp.where(rows >= 0, dd, jnp.inf)
        dd = jax.lax.optimization_barrier(dd)
        neg, idx = jax.lax.top_k(-dd, npool)
        return -neg, rows[idx]

    return run


@functools.lru_cache(maxsize=64)
def _jit_ivf(metric: str, m_slabs: int, npool: int):
    import jax

    return jax.jit(_ivf_probe(metric, m_slabs, npool))


@functools.lru_cache(maxsize=64)
def _jit_ivf_batch(metric: str, m_slabs: int, npool: int):
    """Batched IVF probe: the _ivf_probe pipeline vmapped over queries, so
    a whole query batch is ONE device dispatch + ONE host fetch. Through a
    remote-device tunnel this amortizes the per-dispatch round trip the
    same way the query engine's whole-level batching does."""
    import jax

    one = _ivf_probe(metric, m_slabs, npool)

    def run(cents, csq, slab_cell, flat_vecs, flat_sq, flat_rows, Q):
        return jax.vmap(
            one, in_axes=(None, None, None, None, None, None, 0)
        )(cents, csq, slab_cell, flat_vecs, flat_sq, flat_rows, Q)

    return jax.jit(run)


# ---------------------------------------------------------------------------
# Scalar quantization (per-row asymmetric int8)
# ---------------------------------------------------------------------------


def _quantize(V: np.ndarray):
    """Per-row asymmetric int8: v_ij ≈ scale_i*code_ij + offset_i with
    codes in [-127, 127]. Returns (codes i8, scales f32, offsets f32,
    csums i32). Constant rows quantize to all-zero codes with the exact
    value in the offset."""
    V = np.ascontiguousarray(V, np.float32)
    mn = V.min(axis=1)
    mx = V.max(axis=1)
    offsets = ((mx + mn) * np.float32(0.5)).astype(np.float32)
    scales = np.maximum(
        (mx - mn) / np.float32(254.0), np.float32(1e-20)
    ).astype(np.float32)
    codes = np.clip(
        np.rint((V - offsets[:, None]) / scales[:, None]), -127, 127
    ).astype(np.int8)
    # int64 accumulate then narrow: d*127 fits i32 for any real dim, the
    # wide accumulate just keeps the reduction overflow-free
    csums = codes.sum(axis=1, dtype=np.int64).astype(np.int32)
    return codes, scales, offsets, csums


def _quantize_queries(Q: np.ndarray, metric: str):
    """Quantized query batch + the exact per-query stat the distance
    reconstruction needs (q·q for euclidean, |q| for cosine)."""
    Q = np.ascontiguousarray(Q, np.float32)
    qc, qscales, qoffsets, qcsums = _quantize(Q)
    qsq = (Q * Q).sum(axis=1, dtype=np.float32)
    if metric == "cosine":
        qstats = np.sqrt(qsq).astype(np.float32)
    elif metric == "euclidean":
        qstats = qsq.astype(np.float32)
    else:
        qstats = np.zeros((len(Q),), np.float32)
    return qc, qscales, qoffsets, qcsums, qstats


def _qi8_scan_py(
    codes, scales, offsets, csums, sqnorms, valid,
    qc, qscale, qoffset, qcsum, qstat, metric: str, k: int,
    rows: Optional[np.ndarray] = None,
):
    """Pure-numpy mirror of the native qint8 kernels (used when the
    native lib is unavailable): the integer dot is computed exactly (f64
    matmul holds any int8 dot exactly), the float32 reconstruction uses
    the same formula, and ties break toward the lower row index."""
    if rows is None:
        rows = np.flatnonzero(valid).astype(np.int64)
    else:
        rows = np.asarray(rows, np.int64)
        rows = rows[valid[rows] != 0]
    if rows.size == 0:
        return np.full((k,), -1, np.int64), np.full((k,), np.inf, np.float32)
    d = codes.shape[1]
    d8 = codes[rows].astype(np.float64) @ qc.astype(np.float64)
    s = scales[rows]
    o = offsets[rows]
    dot = (
        np.float32(qscale)
        * (s * d8.astype(np.float32) + o * np.float32(qcsum))
        + np.float32(qoffset)
        * (s * csums[rows].astype(np.float32) + np.float32(d) * o)
    )
    sq = sqnorms[rows]
    if metric == "euclidean":
        dist = (sq - np.float32(2.0) * dot + np.float32(qstat)).astype(
            np.float32
        )
    elif metric == "cosine":
        vn = np.sqrt(sq)
        dist = (
            np.float32(1.0)
            - dot / np.maximum(vn * np.float32(qstat), np.float32(1e-12))
        ).astype(np.float32)
    else:
        dist = (-dot).astype(np.float32)
    order = np.lexsort((rows, dist))[:k]
    out_i = np.full((k,), -1, np.int64)
    out_d = np.full((k,), np.inf, np.float32)
    out_i[: order.size] = rows[order]
    out_d[: order.size] = dist[order]
    return out_i, out_d


# ---------------------------------------------------------------------------
# Centroid training (sampled mini-batch k-means) + top-2 assignment
# ---------------------------------------------------------------------------


def _train_centroids(X: np.ndarray, nlist: int, rng) -> np.ndarray:
    """Mini-batch k-means (Sculley 2010) on a bounded sample: the full
    Lloyd-on-100k-sample train this replaces cost 255s at 1Mx768
    (VECTOR_1M_CPU.json) — the mini-batch pass is bounded by
    steps*B*nlist*d regardless of corpus size."""
    n, d = X.shape
    nlist = max(1, min(nlist, n))
    sample_n = int(min(n, max(32 * nlist, 16384)))
    S = X if sample_n >= n else X[rng.choice(n, sample_n, replace=False)]
    cents = S[rng.choice(len(S), nlist, replace=False)].astype(
        np.float32
    ).copy()
    if nlist <= 1:
        return cents
    counts = np.zeros((nlist,), np.float32)
    B = min(2048, len(S))
    steps = int(min(max(12, 4 * len(S) // max(B, 1)), 48))
    for _ in range(steps):
        batch = S[rng.integers(0, len(S), B)]
        csq = (cents * cents).sum(axis=1)
        a = np.argmin(csq[None, :] - 2.0 * (batch @ cents.T), axis=1)
        order = np.argsort(a, kind="stable")
        ao = a[order]
        starts = np.flatnonzero(np.r_[True, ao[1:] != ao[:-1]])
        sums = np.add.reduceat(batch[order], starts, axis=0)
        uniq = ao[starts]
        cnt = np.diff(np.r_[starts, len(ao)]).astype(np.float32)
        counts[uniq] += cnt
        lr = (cnt / counts[uniq])[:, None]
        cents[uniq] = cents[uniq] * (1.0 - lr) + (
            sums / cnt[:, None]
        ) * lr
    return cents


def _assign_top1(X: np.ndarray, cents: np.ndarray) -> np.ndarray:
    csq = (cents * cents).sum(axis=1)
    out = np.empty((len(X),), np.int32)
    ch = max(256, int(8e6 // max(len(cents), 1)))
    for off in range(0, len(X), ch):
        xc = X[off : off + ch]
        out[off : off + ch] = np.argmin(
            csq[None, :] - 2.0 * (xc @ cents.T), axis=1
        )
    return out


def _assign_top2_exact(X: np.ndarray, cents: np.ndarray) -> np.ndarray:
    """(m, 2) nearest-two centroid ids, chunked so the distance matrix
    stays bounded."""
    nlist = len(cents)
    m = len(X)
    out = np.empty((m, 2), np.int32)
    if nlist == 1:
        out[:] = 0
        return out
    csq = (cents * cents).sum(axis=1)
    ch = max(256, int(8e6 // nlist))
    for off in range(0, m, ch):
        xc = X[off : off + ch]
        d2 = csq[None, :] - 2.0 * (xc @ cents.T)
        p = np.argpartition(d2, 1, axis=1)[:, :2].astype(np.int32)
        dp = np.take_along_axis(d2, p, axis=1)
        swap = dp[:, 0] > dp[:, 1]
        p[swap] = p[swap][:, ::-1]
        out[off : off + ch] = p
    return out


def _assign_top2(X: np.ndarray, cents: np.ndarray, rng) -> np.ndarray:
    """Top-2 centroid assignment (multi-assignment doubles only the CELL
    ID lists, not the row-aligned codes — recall insurance at 8 bytes a
    row). Exact for small problems; above ~2e10 MACs the classic
    coarse-to-fine approximation: cluster the centroids into ~sqrt(nlist)
    groups, rank each row only against the members of its nearest few
    groups. An occasional second-best cell is an acceptable layout
    approximation — correctness lives in the probe + rerank."""
    m, d = X.shape
    nlist = len(cents)
    if nlist < 512 or m * nlist * d <= _ASSIGN_NATIVE_MIN_MACS:
        return _assign_top2_exact(X, cents)
    G = max(8, int(round(math.sqrt(nlist))))
    coarse = _train_centroids(cents, G, rng)
    ga = _assign_top1(cents, coarse)
    members = [
        np.flatnonzero(ga == g).astype(np.int32) for g in range(len(coarse))
    ]
    gd = ((coarse[:, None, :] - coarse[None, :, :]) ** 2).sum(axis=-1)
    nbr = np.argsort(gd, axis=1)[:, :4]  # self + 3 nearest groups
    xg = _assign_top1(X, coarse)
    out = np.empty((m, 2), np.int32)
    for g in range(len(coarse)):
        rows = np.flatnonzero(xg == g)
        if rows.size == 0:
            continue
        cand = np.concatenate(
            [members[j] for j in nbr[g] if members[j].size]
        ) if any(members[j].size for j in nbr[g]) else np.arange(
            nlist, dtype=np.int32
        )
        if cand.size < 2:
            cand = np.arange(nlist, dtype=np.int32)
        sub = _assign_top2_exact(X[rows], cents[cand])
        out[rows] = cand[sub]
    return out


class VectorIndex:
    def __init__(
        self,
        pred: str,
        metric: str = "euclidean",
        ivf_threshold: int = 200_000,
        nlist: Optional[int] = None,
        nprobe: Optional[int] = None,
    ):
        if metric not in ("euclidean", "cosine", "dotproduct"):
            raise ValueError(f"unknown metric {metric!r}")
        self.pred = pred
        self.metric = metric
        self.ivf_threshold = ivf_threshold
        self.nlist = nlist
        self.nprobe = nprobe

        # append-only row store with tombstones: a remove (or re-insert)
        # never moves another row, so quantized sidecars and IVF cell ids
        # stay valid across mutations
        self._rows: Dict[int, int] = {}  # uid -> live row
        self._vecs: Optional[np.ndarray] = None  # (cap, d) float32
        self._uid_of: Optional[np.ndarray] = None  # (cap,) uint64, 0=dead
        self._valid: Optional[np.ndarray] = None  # (cap,) uint8
        self._n = 0  # high-water rows (live + tombstoned)
        self._live = 0

        self._dirty = True
        self._device = None  # jnp arrays (vecs, uids, norms) — jit path
        self._uids_np: Optional[np.ndarray] = None  # compacted uid map
        self._ivf = None  # jit-path slab IVF
        self._mesh = None

        # quantized engine state (row-aligned sidecars + incremental IVF)
        self._q: Optional[dict] = None
        self._qivf: Optional[dict] = None
        self._lock = threading.RLock()
        # index-level build accounting ("no full rebuild on mutation" is
        # equivalence-tested against these)
        self.build_count = 0
        self.repartition_count = 0

    # -- mutation -------------------------------------------------------------

    def _grow(self, need_rows: int):
        cap = self._vecs.shape[0]
        if need_rows <= cap:
            return
        newcap = max(cap, 1)  # cap can be 0 after an empty bulk_load
        while newcap < need_rows:
            newcap *= 2
        grown = np.zeros((newcap, self._vecs.shape[1]), np.float32)
        grown[: self._n] = self._vecs[: self._n]
        self._vecs = grown
        u = np.zeros((newcap,), np.uint64)
        u[: self._n] = self._uid_of[: self._n]
        self._uid_of = u
        v = np.zeros((newcap,), np.uint8)
        v[: self._n] = self._valid[: self._n]
        self._valid = v

    def insert(self, uid: int, vec) -> None:
        vec = np.asarray(vec, dtype=np.float32).reshape(-1)
        with self._lock:
            if self._vecs is None:
                self._vecs = np.zeros((_PAD_ROWS, vec.shape[0]), np.float32)
                self._uid_of = np.zeros((_PAD_ROWS,), np.uint64)
                self._valid = np.zeros((_PAD_ROWS,), np.uint8)
            if vec.shape[0] != self._vecs.shape[1]:
                raise ValueError(
                    f"dim mismatch: index {self._vecs.shape[1]}, "
                    f"got {vec.shape[0]}"
                )
            uid = int(uid)
            old = self._rows.get(uid)
            if old is not None:
                # update = tombstone + append: the new value may belong
                # to a different IVF cell, and an in-place overwrite
                # would silently stale the quantized sidecars
                self._tombstone(old)
            self._grow(self._n + 1)
            row = self._n
            self._n += 1
            self._vecs[row] = vec
            self._uid_of[row] = uid
            self._valid[row] = 1
            self._rows[uid] = row
            self._live += 1
            self._dirty = True

    def remove(self, uid: int) -> None:
        with self._lock:
            row = self._rows.pop(int(uid), None)
            if row is None:
                return
            self._tombstone(row)
            self._dirty = True

    def _tombstone(self, row: int) -> None:
        # under self._lock
        self._valid[row] = 0
        self._uid_of[row] = 0
        self._live -= 1
        if self._qivf is not None and row < self._qivf["assigned"]:
            self._qivf["dead"] += 1

    def bulk_load(self, uids, V) -> None:
        """Adopt (uids, V) wholesale — the loader/bench fast path (one
        assignment instead of n inserts; V is adopted, not copied)."""
        V = np.ascontiguousarray(V, np.float32)
        uids = np.asarray(uids, np.uint64)
        if V.ndim != 2 or len(uids) != len(V):
            raise ValueError("bulk_load wants aligned (uids, (n, d) vecs)")
        with self._lock:
            n = len(uids)
            self._vecs = V
            self._uid_of = uids.copy()
            self._valid = np.ones((n,), np.uint8)
            self._rows = {int(u): i for i, u in enumerate(uids)}
            self._n = n
            self._live = n
            self._dirty = True
            self._q = None
            self._qivf = None
            self._device = None
            self._ivf = None

    def __len__(self) -> int:
        return self._live

    @property
    def dim(self) -> Optional[int]:
        """Vector dimensionality, None before the first insert."""
        return None if self._vecs is None else int(self._vecs.shape[1])

    # -- engine choice ---------------------------------------------------------

    def _use_quant(self) -> bool:
        if not (
            bool(config.get("VEC_QUANT"))
            and not bool(config.get("SHARD_VECTORS"))
            and self._live >= _QUANT_MIN
            and _cpu_backend()
        ):
            return False
        from dgraph_tpu import native

        # without the native kernels the quantized path would run on
        # the pure-numpy mirror, which is strictly slower (and far more
        # allocation-hungry) than the jitted float path it displaces —
        # the mirror exists for bit-equality tests, not serving
        return native.NATIVE_AVAILABLE

    @staticmethod
    def _ivf_pick(nq: int, probed_rows: int, n: int, quant: bool) -> bool:
        """Per-call brute-vs-IVF crossover: True = IVF wins.

        Quantized engine: probe and brute run the SAME scan kernel, the
        probe just adds random row access (~30%) — IVF wins whenever the
        probed pool undercuts the corpus.

        Jitted float path: a single-query probe pays a gather plus a
        small matmul against one full-corpus fused matvec (~3x per
        probed row); BATCHED probes gather (m_slabs*SLAB, d) floats per
        query while the brute matmul reads the corpus once per batch —
        the probed pool must undercut the corpus by the batch
        amortization factor too, which is how batched IVF at 3% probe
        still lost to brute 5.8-vs-12.2 qps in the r5 capture."""
        if probed_rows >= n:
            return False
        if quant:
            return probed_rows * 13 < n * 10
        if nq <= 1:
            return probed_rows * 3 < n
        return probed_rows * 3 * min(nq, 16) < n

    def _jit_ivf_wins(self, nq: int) -> bool:
        if self._ivf is None:
            return False
        probed = int(self._ivf["m_slabs"]) * _SLAB
        return self._ivf_pick(nq, probed, max(self._live, 1), quant=False)

    # -- device state (jitted float paths) ------------------------------------

    def _sync_device(self):
        import jax
        import jax.numpy as jnp

        if not self._dirty and self._device is not None:
            return
        with self._lock:
            # gather atomically: the quant path's compaction renumbers
            # rows and swaps these buffers under the same lock, so an
            # unlocked multi-step read here could mix old indices with
            # new (shorter) arrays
            live_idx = np.flatnonzero(self._valid[: self._n])
            nlive = int(live_idx.size)
            cap = _pow2_rows(nlive)
            d = self._vecs.shape[1]
            mat = np.zeros((cap, d), np.float32)
            mat[:nlive] = self._vecs[live_idx]
            uids = np.zeros((cap,), np.uint64)
            uids[:nlive] = self._uid_of[live_idx]
        valid = np.zeros((cap,), bool)
        valid[:nlive] = True
        self._uids_np = uids
        self._mesh = None
        shard = bool(config.get("SHARD_VECTORS"))
        if shard and len(jax.devices()) > 1:
            # row-shard the corpus over the device mesh: per-shard top-k,
            # all_gather, global reduce (parallel/mesh.py sharded_topk —
            # the TP-over-rows data plane for 1M×768-class corpora)
            from jax.sharding import NamedSharding, PartitionSpec as P

            from dgraph_tpu.parallel import mesh as pmesh

            mesh = pmesh.make_mesh()
            ndev = mesh.devices.size
            rows = -(-cap // ndev) * ndev
            if rows != cap:
                mat = np.vstack([mat, np.zeros((rows - cap, d), np.float32)])
                uids = np.concatenate(
                    [uids, np.zeros((rows - cap,), np.uint64)]
                )
                valid = np.concatenate(
                    [valid, np.zeros((rows - cap,), bool)]
                )
                self._uids_np = uids
            sh = NamedSharding(mesh, P("data"))
            self._mesh = mesh
            self._device = {
                "vecs": jax.device_put(jnp.asarray(mat), sh),
                "uids": uids,  # host: gathered indices map back to uids
                "valid": jax.device_put(jnp.asarray(valid), sh),
                "sqnorm": None,
            }
            self._dirty = False
            if nlive >= self.ivf_threshold:
                self._train_ivf(mat[:nlive])
            else:
                self._ivf = None
            return
        self._device = {
            "vecs": jnp.asarray(mat),
            "uids": uids,
            "valid": jnp.asarray(valid),
            "sqnorm": jnp.asarray((mat * mat).sum(axis=1)),
        }
        self._dirty = False
        if nlive >= self.ivf_threshold:
            self._train_ivf(mat[:nlive])
        else:
            self._ivf = None

    # -- search ----------------------------------------------------------------

    def search(
        self,
        q,
        k: int,
        ef: Optional[int] = None,
        distance_threshold: Optional[float] = None,
        allowed: Optional[np.ndarray] = None,
    ) -> np.ndarray:
        """Top-k closest uids (sorted closest-first).

        `allowed`: optional sorted uid filter (ref index.go:66 SearchFilter).
        `ef`: candidate-pool override, kept for HNSW API compat — used as
        the IVF candidate width.
        """
        if self._live == 0:
            return _EMPTY_U64
        q = np.asarray(q, dtype=np.float32).reshape(-1)
        kk = min(max(k, 1), self._live)
        pool = max(kk, ef or 0)
        allowed_set = None
        if allowed is not None:
            allowed_set = np.asarray(allowed, np.uint64)
            # filter drops candidates; widen the pool up-front
            pool = max(pool, 4 * kk)
        if self._use_quant():
            return self._quant_search_filtered(
                q, kk, pool, distance_threshold, allowed_set
            )
        self._sync_device()
        import jax.numpy as jnp

        COUNTERS.searches += 1
        _metrics().inc("vector_search_total")
        # widen the candidate pool until k survivors or the whole set seen
        # (the HNSW analog is raising ef; ref index.go VectorIndexOptions)
        while True:
            if self._mesh is not None:
                from dgraph_tpu.parallel import mesh as pmesh

                npool = min(max(pool, kk), self._live)
                dd, idx = pmesh.sharded_topk(
                    self._mesh,
                    self._device["vecs"],
                    self._device["valid"],
                    jnp.asarray(q),
                    npool,
                )
                cand_dists = np.asarray(dd)
                cand_uids = self._device["uids"][np.asarray(idx)]
            elif self._jit_ivf_wins(1):
                COUNTERS.path_jit_ivf += 1
                cand_uids, cand_dists = self._ivf_search(q, max(pool, 4 * kk))
            else:
                COUNTERS.path_jit_brute += 1
                npool = min(max(pool, kk), self._live)
                fn = _jit_brute(self.metric, int(npool))
                dd, idx = fn(
                    self._device["vecs"],
                    self._device["sqnorm"],
                    self._device["valid"],
                    jnp.asarray(q),
                )
                cand_dists = np.asarray(dd)
                cand_uids = self._uids_np[np.asarray(idx)]

            out = self._filter_candidates(
                cand_uids, cand_dists, kk, distance_threshold, allowed_set
            )
            exhausted = len(cand_uids) >= self._live or pool >= self._live
            if len(out) == kk or exhausted or allowed_set is None:
                return np.asarray(out, np.uint64)
            pool = min(pool * 4, self._live)

    @staticmethod
    def _filter_candidates(cand_uids, cand_dists, kk, threshold, allowed_set):
        out = []
        for u, dist in zip(cand_uids, cand_dists):
            if not math.isfinite(dist):
                continue
            if threshold is not None and dist > threshold:
                break  # dists ascend: nothing closer follows
            if allowed_set is not None and not _in_sorted(allowed_set, u):
                continue
            out.append(int(u))
            if len(out) == kk:
                break
        return out

    def search_batch(self, Q, k: int) -> np.ndarray:
        """Top-k for a batch of queries. Returns (len(Q), min(k, live))
        uids, closest-first; a row with fewer than k survivors pads
        trailing slots with uid 0 — callers must treat 0 as absent, as
        with any uid list.

        Quantized engine: one corpus pass scores the whole batch (brute)
        or per-query cell probes share the row-aligned codes (IVF), with
        exact float32 rerank either way. Jitted paths: ONE device
        dispatch for the batch; the brute tier is exact, the IVF tier
        approximate (same probe as the single-query path, pool 4x k)."""
        if self._live == 0:
            return np.zeros((len(Q), 0), np.uint64)
        Q = np.ascontiguousarray(np.asarray(Q, np.float32))
        if self._use_quant():
            return self._quant_search_batch(Q, k)
        self._sync_device()
        if self._mesh is not None:
            # sharded corpus has no replicated sqnorm; reuse the per-query
            # mesh path (still one dispatch per query)
            return np.stack([self.search(q, k) for q in Q])
        import jax.numpy as jnp

        kk = min(max(k, 1), self._live)
        COUNTERS.searches += len(Q)
        _metrics().inc("vector_search_total", len(Q))
        if self._jit_ivf_wins(len(Q)):
            COUNTERS.path_jit_ivf += len(Q)
            return self._ivf_search_batch(Q, kk)
        COUNTERS.path_jit_brute += len(Q)
        fn = _jit_brute_batch(self.metric, int(kk))
        # pad the batch to a pow2 width: coalesced similar_to dispatches
        # arrive at widths 1..4 and each distinct width is a fresh jit
        # signature otherwise (padded rows are scored and discarded —
        # per-row top-k, so real rows are unaffected)
        m = len(Q)
        mp = max(1, 1 << (m - 1).bit_length())
        Qp = Q if mp == m else np.vstack(
            [Q, np.zeros((mp - m, Q.shape[1]), np.float32)]
        )
        dd, idx = fn(
            self._device["vecs"],
            self._device["sqnorm"],
            self._device["valid"],
            jnp.asarray(Qp),
        )
        return self._uids_np[np.asarray(idx)[:m]]

    def search_one(self, q, k: int) -> np.ndarray:
        """Plain (unfiltered) top-k for ONE query — exactly row 0 of
        `search_batch([q], k)`. The solo form of the coalesced
        similar_to dispatch: solo and coalesced answers are
        byte-identical by construction because every batch row is
        scored independently by the same kernels."""
        return self.search_batch(
            np.asarray(q, np.float32).reshape(1, -1), k
        )[0]

    def search_with_uid(self, uid: int, k: int, **kw) -> np.ndarray:
        with self._lock:
            # row lookup + vector read must be one atomic step: compaction
            # renumbers rows and swaps the array between the two
            row = self._rows.get(int(uid))
            q = None if row is None else self._vecs[row].copy()
        if q is None:
            return _EMPTY_U64
        res = self.search(q, k + 1, **kw)
        return np.asarray(
            [u for u in res if int(u) != int(uid)][:k], np.uint64
        )

    # -- quantized engine ------------------------------------------------------

    def _quant_view(self) -> dict:
        """Sync the quantized sidecars + incremental IVF to the current
        rows and return a scan snapshot. Taken under the index lock;
        the native kernel calls run lock-free on the snapshot (arrays
        are append-only and replaced — never shrunk — so a snapshot
        stays valid across concurrent mutations)."""
        with self._lock:
            self._compact_locked()
            self._quant_sync_locked()
            self._qivf_sync_locked()
            q = self._q
            n = self._n
            ivf = dict(self._qivf) if self._qivf is not None else None
            if ivf is not None:
                # slot-level copy: _assign_rows_locked mutates the live
                # list's slots in place (cells[c] = concatenate(...))
                # with row ids past this snapshot's n; the arrays
                # themselves are replaced, never mutated, so copying
                # the outer list is enough to freeze the snapshot
                ivf["cells"] = list(ivf["cells"])
            return {
                "vecs": self._vecs[:n],
                "codes": q["codes"][:n],
                "scales": q["scales"][:n],
                "offsets": q["offsets"][:n],
                "csums": q["csums"][:n],
                "sqnorms": q["sqnorms"][:n],
                "valid": self._valid[:n],
                "uid_of": self._uid_of[:n],
                "n": n,
                "live": self._live,
                "ivf": ivf,
            }

    def _compact_locked(self):
        """Reclaim tombstoned rows: rebuild the host store on the live
        set once dead rows pass a quarter of it (the same garbage bound
        the IVF repartition uses). Update-heavy workloads tombstone +
        append on every write, so without this the float corpus, int8
        sidecars, and brute-scan cost all grow with total writes, not
        live size. New arrays are built and swapped — concurrent
        searchers keep scanning the old buffers their snapshot
        captured (the bulk_load replacement argument)."""
        dead = self._n - self._live
        if dead <= max(64, self._live // 4):
            return
        live_idx = np.flatnonzero(self._valid[: self._n])
        n = int(live_idx.size)
        self._vecs = np.ascontiguousarray(self._vecs[live_idx])
        self._uid_of = self._uid_of[live_idx].copy()
        self._valid = np.ones((n,), np.uint8)
        self._rows = {int(u): i for i, u in enumerate(self._uid_of)}
        self._n = n
        self._dirty = True
        q = self._q
        if q is not None:
            # live_idx ascends, so already-quantized rows stay a
            # prefix. Gather ONLY that prefix: the sidecar arrays' cap
            # can lag _vecs between syncs, and rows past nq hold no
            # codes yet anyway — the next _quant_sync_locked grows the
            # arrays back to cap and quantizes the tail
            nq = int(np.searchsorted(live_idx, q["nq"]))
            keep = live_idx[:nq]
            for name in ("codes", "scales", "offsets", "csums",
                         "sqnorms"):
                q[name] = np.ascontiguousarray(q[name][keep])
            q["nq"] = nq
        ivf = self._qivf
        if ivf is not None:
            # rows renumbered: cells rebuild on the compacted store
            ivf["cells"] = [
                np.zeros((0,), np.int32) for _ in range(ivf["nlist"])
            ]
            ivf["assigned"] = 0
            ivf["dead"] = 0
            ivf["total_ids"] = 0
            ivf["stamp"] = (-1, -1)
            self.repartition_count += 1

    def _quant_sync_locked(self):
        if self._q is None:
            cap = self._vecs.shape[0]
            d = self._vecs.shape[1]
            self._q = {
                "codes": np.zeros((cap, d), np.int8),
                "scales": np.zeros((cap,), np.float32),
                "offsets": np.zeros((cap,), np.float32),
                "csums": np.zeros((cap,), np.int32),
                "sqnorms": np.zeros((cap,), np.float32),
                "nq": 0,
            }
        q = self._q
        cap = self._vecs.shape[0]
        if q["codes"].shape[0] < cap:
            for name, dt in (
                ("codes", np.int8), ("scales", np.float32),
                ("offsets", np.float32), ("csums", np.int32),
                ("sqnorms", np.float32),
            ):
                old = q[name]
                shape = (cap,) + old.shape[1:]
                grown = np.zeros(shape, dt)
                grown[: old.shape[0]] = old
                q[name] = grown
        # quantize the appended rows: one threaded native pass when
        # available (codes/sidecars bit-identical to the numpy mirror —
        # the 1Mx768 corpus quantizes in seconds instead of the 26s
        # chunked-numpy pass), chunked numpy otherwise
        start = q["nq"]
        if start < self._n:
            from dgraph_tpu import native

            got = (
                native.vec_qi8_quantize(
                    self._vecs[start : self._n], _nthreads()
                )
                if native.NATIVE_AVAILABLE
                else None
            )
            if got is not None:
                codes, scales, offsets, csums, sqnorms = got
                q["codes"][start : self._n] = codes
                q["scales"][start : self._n] = scales
                q["offsets"][start : self._n] = offsets
                q["csums"][start : self._n] = csums
                q["sqnorms"][start : self._n] = sqnorms
                start = self._n
        while start < self._n:
            end = min(self._n, start + 65536)
            V = self._vecs[start:end]
            codes, scales, offsets, csums = _quantize(V)
            q["codes"][start:end] = codes
            q["scales"][start:end] = scales
            q["offsets"][start:end] = offsets
            q["csums"][start:end] = csums
            q["sqnorms"][start:end] = (V * V).sum(
                axis=1, dtype=np.float32
            )
            start = end
        q["nq"] = self._n

    def _qivf_sync_locked(self):
        """Incremental IVF maintenance: build centroids once past the
        threshold, lazily assign appended rows to their 2 nearest cells,
        and repartition only when tombstone garbage passes live/4
        (centroids kept) or the cell imbalance ratio grows past
        VEC_REBUILD_IMBALANCE x its post-build baseline (centroids
        retrained on a sample — kept centroids would reproduce the same
        hot cells)."""
        if self._qivf is None and self._live < self.ivf_threshold:
            # threshold gates BUILDING only: an already-built index must
            # keep assigning appended rows even when live dips below the
            # threshold, or probes would serve while fresh inserts sit
            # in no cell (categorically unreachable, not a recall miss)
            return
        rng = np.random.default_rng(0)
        if self._qivf is None:
            t0 = time.perf_counter()
            knob = int(config.get("VEC_NLIST"))
            nlist = self.nlist or knob or int(
                max(16, math.sqrt(self._live) * 2)
            )
            nlist = max(1, min(nlist, self._live))
            live_idx = np.flatnonzero(self._valid[: self._n])
            cents = _train_centroids(self._vecs[live_idx], nlist, rng)
            # default probe width: ~1% of cells. Top-2 multi-assignment
            # already doubles coverage, and the nprobe sweep on the
            # 1Mx768 bench corpus holds recall@10 >= 0.99 down to
            # nprobe=8 while qps scales ~linearly with the probed pool —
            # the old nlist/16 left an 8x serve speedup on the table
            pknob = int(config.get("VEC_NPROBE"))
            nprobe = self.nprobe or pknob or max(8, nlist // 128)
            self._qivf = {
                "cents": cents,
                "csq": (cents * cents).sum(axis=1),
                "cells": [
                    np.zeros((0,), np.int32) for _ in range(len(cents))
                ],
                "nlist": len(cents),
                "nprobe": int(min(nprobe, len(cents))),
                "assigned": 0,
                "dead": 0,
                "total_ids": 0,
                "stamp": (-1, -1),
            }
            self.build_count += 1
            self._assign_rows_locked(0, self._n, rng)
            dt = time.perf_counter() - t0
            _metrics().set_gauge("vector_index_build_seconds", dt)
            self._qivf["stamp"] = (self._n, self._live)
            self._qivf["base_ratio"] = self._cell_ratio_locked()
            return
        ivf = self._qivf
        if ivf["assigned"] < self._n:
            self._assign_rows_locked(ivf["assigned"], self._n, rng)
        if ivf["stamp"] == (self._n, self._live):
            return
        ivf["stamp"] = (self._n, self._live)
        # deferred repartition triggers (checked only after mutations).
        # Imbalance is relative to the post-build baseline: clustered
        # corpora are imbalanced at build time by nature, and reassigning
        # under unchanged centroids would reproduce that exactly — only
        # GROWTH (mutation skew piling inserts into hot cells) warrants
        # work, and fixing it needs fresh centroids.
        thr = max(1.5, float(config.get("VEC_REBUILD_IMBALANCE")))
        garbage = ivf["dead"] > max(64, self._live // 4)
        imbalanced = self._cell_ratio_locked() > thr * max(
            1.0, ivf.get("base_ratio", 1.0)
        )
        if garbage or imbalanced:
            if imbalanced:
                live_idx = np.flatnonzero(self._valid[: self._n])
                ivf["cents"] = _train_centroids(
                    self._vecs[live_idx], ivf["nlist"], rng
                )
                ivf["csq"] = (ivf["cents"] * ivf["cents"]).sum(axis=1)
                ivf["nlist"] = len(ivf["cents"])
            ivf["cells"] = [
                np.zeros((0,), np.int32) for _ in range(ivf["nlist"])
            ]
            ivf["assigned"] = 0
            ivf["dead"] = 0
            ivf["total_ids"] = 0
            self.repartition_count += 1
            self._assign_rows_locked(0, self._n, rng)
            ivf["base_ratio"] = self._cell_ratio_locked()

    def _cell_ratio_locked(self) -> float:
        """Max/avg live cell length — the probe-cost skew measure."""
        ivf = self._qivf
        lens = np.fromiter(
            (len(c) for c in ivf["cells"]), np.int64, ivf["nlist"]
        )
        avg = max(1.0, float(lens.sum()) / max(ivf["nlist"], 1))
        return float(lens.max(initial=0)) / avg

    def _assign_rows_locked(self, start: int, end: int, rng):
        ivf = self._qivf
        rows = start + np.flatnonzero(self._valid[start:end]).astype(
            np.int64
        )
        if rows.size == 0:
            ivf["assigned"] = end
            return
        d = self._vecs.shape[1]
        a2 = None
        if rows.size * ivf["nlist"] * d > _ASSIGN_NATIVE_MIN_MACS:
            a2 = self._assign_top2_qi8_locked(rows, rng)
        if a2 is None:
            a2 = _assign_top2(self._vecs[rows], ivf["cents"], rng)
        cells = ivf["cells"]
        pc = a2.reshape(-1)
        pr = np.repeat(rows, 2).astype(np.int32)
        order = np.argsort(pc, kind="stable")
        pc = pc[order]
        pr = pr[order]
        starts = np.flatnonzero(np.r_[True, pc[1:] != pc[:-1]])
        bounds = np.r_[starts, len(pc)]
        for si in range(len(starts)):
            c = int(pc[starts[si]])
            seg = pr[bounds[si] : bounds[si + 1]]
            cells[c] = (
                np.concatenate([cells[c], seg]) if cells[c].size
                else seg.copy()
            )
        # only mark the range assigned once the cell appends landed: an
        # exception above (e.g. MemoryError in the big fancy-index
        # gathers) must leave these rows retryable on the next sync,
        # not silently absent from every future IVF probe
        ivf["assigned"] = end
        ivf["total_ids"] += int(pr.size)

    def _assign_top2_qi8_locked(self, rows: np.ndarray, rng):
        """Top-2 centroid assignment on the int8 sidecars: the same
        coarse-to-fine shape as _assign_top2 (cluster the centroids into
        ~sqrt(nlist) groups, rank each row only against its nearest
        groups' members) but with both ranking passes in the threaded
        native kernel over the ALREADY-quantized row codes — at 1Mx768/
        2000 cells this was the 44s that dominated the IVF build. Cell
        choice is approximate in the same sense the coarse pass already
        was (correctness lives in the probe + rerank); determinism is
        preserved (fixed rng, deterministic kernel), so incremental
        assignment of a row equals its fresh-build assignment whenever
        both take this path. Returns (m, 2) int32, or None when the
        native lib is missing (caller falls back to numpy)."""
        from dgraph_tpu import native

        if not native.NATIVE_AVAILABLE:
            return None
        ivf = self._qivf
        cents = ivf["cents"]
        nlist = ivf["nlist"]
        if nlist < 2:
            return None
        q = self._q
        d = cents.shape[1]
        ccodes, cscales, coffsets, ccsums = _quantize(cents)
        csq = np.ascontiguousarray(ivf["csq"], np.float32)
        cvalid = np.ones((nlist,), np.uint8)
        # coarse groups over the centroids (same construction + rng
        # stream as _assign_top2, so both paths see the same geometry)
        G = max(8, int(round(math.sqrt(nlist))))
        coarse = _train_centroids(cents, G, rng)
        gcodes, gscales, goffsets, gcsums = _quantize(coarse)
        gsq = (coarse * coarse).sum(axis=1, dtype=np.float32)
        gvalid = np.ones((len(coarse),), np.uint8)
        # per-group candidate list: the cap nearest centroids to the
        # group's coarse center (a distance ball, NOT the group-member
        # union — member unions on clustered corpora are wildly
        # imbalanced, and truncating them drops exactly the boundary
        # cells that edge rows need, piling those rows into hot central
        # cells: max/avg cell hit 36x on the 1Mx768 bench). cap trades
        # assignment MACs against layout quality; ~1/6 of all cells
        # keeps the layout within a few percent of the exact one.
        cap = int(min(nlist, max(64, math.ceil(nlist / 4))))
        gd2 = (
            (coarse * coarse).sum(axis=1)[:, None]
            - 2.0 * (coarse @ cents.T)
            + csq[None, :]
        )
        near = np.argsort(gd2, axis=1, kind="stable")[:, :cap]
        cat = np.ascontiguousarray(near, np.int32).reshape(-1)
        offs = (np.arange(len(coarse) + 1, dtype=np.int64)) * cap
        # row-side "queries" are the corpus rows' own sidecars (euclidean
        # geometry regardless of the search metric — cell layout is a
        # spatial partition, exactly as in the numpy path)
        m = int(rows.size)
        lo, hi = int(rows[0]), int(rows[-1]) + 1
        if m == hi - lo:  # contiguous (the build / append case): views
            rc = q["codes"][lo:hi]
            rs, ro = q["scales"][lo:hi], q["offsets"][lo:hi]
            rcs, rsq = q["csums"][lo:hi], q["sqnorms"][lo:hi]
        else:
            rc = q["codes"][rows]
            rs, ro = q["scales"][rows], q["offsets"][rows]
            rcs, rsq = q["csums"][rows], q["sqnorms"][rows]
        nt = _nthreads()
        # pass 1: nearest coarse group per row (k=1 over all G groups)
        gfull = np.arange(len(coarse), dtype=np.int32)
        zb = np.zeros((m,), np.int64)
        ze = np.full((m,), len(coarse), np.int64)
        got = native.vec_qi8_topk_lists(
            gcodes, gscales, goffsets, gcsums, gsq, gvalid,
            gfull, zb, ze, rc, rs, ro, rcs, rsq, 0, 1, nt,
        )
        if got is None:
            return None
        xg = got[0][:, 0]
        # pass 2: top-2 cells among the row's group candidate list
        # (slices alias the shared per-group lists — no per-row copies).
        # Queries run in group order so one group's candidate slab
        # (cap x d codes) stays cache-resident across its whole run —
        # unsorted, every query faults the slab back in and the kernel
        # drops ~2x throughput at 1Mx768
        order = np.argsort(xg, kind="stable")
        got = native.vec_qi8_topk_lists(
            ccodes, cscales, coffsets, ccsums, csq, cvalid,
            cat, offs[xg[order]], offs[xg[order] + 1],
            np.ascontiguousarray(rc[order]), rs[order], ro[order],
            rcs[order], rsq[order], 0, 2, nt,
        )
        if got is None:
            return None
        a2 = np.empty((m, 2), np.int64)
        a2[order] = got[0]
        return a2.astype(np.int32)

    def _quant_scan(self, view, qc, qs, qo, qcs, qstat, pool, rows=None):
        """One quantized top-pool scan (full corpus or candidate rows),
        native when available, numpy mirror otherwise. Returns (rows,
        approx dists) trimmed of padding."""
        from dgraph_tpu import native

        t0 = time.perf_counter_ns()
        got = None
        if native.NATIVE_AVAILABLE:
            if rows is None:
                idx, dist, _nv = native.vec_qi8_topk(
                    view["codes"], view["scales"], view["offsets"],
                    view["csums"], view["sqnorms"], view["valid"],
                    qc.reshape(1, -1),
                    np.asarray([qs], np.float32),
                    np.asarray([qo], np.float32),
                    np.asarray([qcs], np.int32),
                    np.asarray([qstat], np.float32),
                    _METRIC_ID[self.metric], int(pool),
                )
                got = (idx[0], dist[0])
            else:
                idx, dist, _w = native.vec_qi8_topk_idx(
                    view["codes"], view["scales"], view["offsets"],
                    view["csums"], view["sqnorms"], view["valid"],
                    rows, qc, float(qs), float(qo), int(qcs),
                    float(qstat), _METRIC_ID[self.metric], int(pool),
                )
                got = (idx, dist)
        if got is None:
            got = _qi8_scan_py(
                view["codes"], view["scales"], view["offsets"],
                view["csums"], view["sqnorms"], view["valid"],
                qc, qs, qo, qcs, qstat, self.metric, int(pool),
                rows=rows,
            )
        COUNTERS.scan_ns += time.perf_counter_ns() - t0
        COUNTERS.scan_rows += int(
            view["live"] if rows is None else len(rows)
        )
        idx, dist = got
        ok = idx >= 0
        return idx[ok], dist[ok]

    def _rerank(self, rows: np.ndarray, q: np.ndarray, view: dict):
        """Exact float32 re-score of the candidate pool; ascending
        (dist, row) — quantization error cannot survive into the final
        ordering. Reads the float corpus from the snapshot (not live
        self._vecs): bulk_load REPLACES the arrays, so a concurrent
        search's row ids are only valid against the buffers its own
        snapshot captured."""
        t0 = time.perf_counter_ns()
        V = view["vecs"][rows]
        dot = V @ q
        sq = view["sqnorms"][rows]
        if self.metric == "euclidean":
            d = sq - np.float32(2.0) * dot + np.float32((q * q).sum())
        elif self.metric == "cosine":
            qn = np.float32(math.sqrt(float((q * q).sum())))
            d = np.float32(1.0) - dot / np.maximum(
                np.sqrt(sq) * qn, np.float32(1e-12)
            )
        else:
            d = -dot
        order = np.lexsort((rows, d))
        COUNTERS.rerank_ns += time.perf_counter_ns() - t0
        COUNTERS.rerank_pool += int(rows.size)
        _metrics().inc("vector_rerank_pool_total", int(rows.size))
        return rows[order], d[order].astype(np.float32)

    def _quant_probe_ids(self, ivf: dict, q: np.ndarray, nprobe=None):
        """Top-nprobe cells by centroid distance; returns (cells picked,
        deduped sorted candidate row ids)."""
        nlist = ivf["nlist"]
        cd = ivf["csq"] - 2.0 * (ivf["cents"] @ q)
        np_ = min(nprobe if nprobe is not None else ivf["nprobe"], nlist)
        if np_ < nlist:
            sel = np.argpartition(cd, np_ - 1)[:np_]
        else:
            sel = np.arange(nlist)
        parts = [ivf["cells"][c] for c in sel if ivf["cells"][c].size]
        COUNTERS.probe_cells += int(len(sel))
        _metrics().inc("vector_probe_cells_total", int(len(sel)))
        if not parts:
            return sel, np.zeros((0,), np.int32)
        # unique: dedups multi-assignment AND sorts ascending — the scan
        # then walks the code matrix in row order (locality + the
        # deterministic tie-break order the kernels pin)
        return sel, np.unique(np.concatenate(parts))

    def _quant_ivf_wins(self, nq: int, ivf: dict, live: int) -> bool:
        est = int(
            ivf["nprobe"] * ivf["total_ids"] / max(ivf["nlist"], 1)
        )
        return self._ivf_pick(nq, est, max(live, 1), quant=True)

    def _quant_topk_one(self, view, q, pool, probe_boost=1):
        """(rows, exact dists, full) for one query: quantized scan (IVF
        probe or full) -> float32 rerank. `probe_boost` scales the
        probed cell count — the widening loop raises it in lockstep
        with the candidate pool, the quant analog of the jitted path's
        pool-scaled _probe_plan (a fixed probe would rescan the same
        candidate set every retry and could never reach allowed uids
        outside the top-nprobe cells). `full` reports whether the scan
        covered every live row (brute / all-cells probe), which is what
        lets the caller's exhaustion test terminate correctly."""
        qc, qs, qo, qcs, qstat = _quantize_queries(
            q.reshape(1, -1), self.metric
        )
        ivf = view["ivf"]
        if ivf is not None:
            nprobe_eff = int(
                min(ivf["nprobe"] * probe_boost, ivf["nlist"])
            )
            est = int(
                nprobe_eff * ivf["total_ids"] / max(ivf["nlist"], 1)
            )
            if nprobe_eff < ivf["nlist"] and self._ivf_pick(
                1, est, max(view["live"], 1), quant=True
            ):
                COUNTERS.path_quant_ivf += 1
                _sel, ids = self._quant_probe_ids(ivf, q, nprobe_eff)
                rows, _ = self._quant_scan(
                    view, qc[0], qs[0], qo[0], qcs[0], qstat[0], pool,
                    rows=ids,
                )
                if rows.size == 0:
                    return (
                        rows.astype(np.int64),
                        np.zeros((0,), np.float32),
                        False,
                    )
                r, dd = self._rerank(rows, q, view)
                return r, dd, False
        COUNTERS.path_quant_brute += 1
        rows, _ = self._quant_scan(
            view, qc[0], qs[0], qo[0], qcs[0], qstat[0], pool
        )
        if rows.size == 0:
            return rows.astype(np.int64), np.zeros((0,), np.float32), True
        r, dd = self._rerank(rows, q, view)
        return r, dd, True

    def _quant_search_filtered(self, q, kk, pool, threshold, allowed_set):
        """The widening single-query search loop on the quantized
        engine (ef / distance_threshold / allowed semantics identical
        to the jitted path — distances here are exact float32)."""
        rer = max(1, int(config.get("VEC_RERANK")))
        view = self._quant_view()
        COUNTERS.searches += 1
        _metrics().inc("vector_search_total")
        boost = 1
        while True:
            p = int(min(max(pool, kk) * rer, view["live"]))
            rows, dists, full = self._quant_topk_one(
                view, q, max(p, kk), probe_boost=boost
            )
            cand_uids = view["uid_of"][rows]
            out = self._filter_candidates(
                cand_uids, dists, kk, threshold, allowed_set
            )
            # exhausted only once a FULL-coverage scan kept a pool as
            # wide as the live set — a partial IVF probe can miss
            # allowed uids that live outside its cells no matter how
            # wide the kept pool is
            exhausted = full and (
                len(rows) >= view["live"] or pool >= view["live"]
            )
            if len(out) == kk or exhausted or allowed_set is None:
                return np.asarray(out, np.uint64)
            pool = min(pool * 4, view["live"])
            boost *= 4

    def _emit_topk_row(self, out, i, rows, q, view, kk):
        """Shared tail of every batch path: drop kernel padding, rerank
        exactly in float32, truncate to k, write uids — one
        implementation so the native and fallback paths cannot diverge
        on the emit contract (the coalescing byte-identity depends on
        it)."""
        rows = rows[rows >= 0]
        if rows.size == 0:
            return
        rows, _d = self._rerank(rows, q, view)
        rows = rows[:kk]
        out[i, : rows.size] = view["uid_of"][rows]

    def _quant_search_batch(self, Q: np.ndarray, k: int) -> np.ndarray:
        view = self._quant_view()
        kk = min(max(k, 1), view["live"])
        rer = max(1, int(config.get("VEC_RERANK")))
        pool = int(min(max(kk * rer, kk), view["live"]))
        qc, qs, qo, qcs, qstat = _quantize_queries(Q, self.metric)
        out = np.zeros((len(Q), kk), np.uint64)
        COUNTERS.searches += len(Q)
        _metrics().inc("vector_search_total", len(Q))
        ivf = view["ivf"]
        if ivf is not None and self._quant_ivf_wins(
            len(Q), ivf, view["live"]
        ):
            from dgraph_tpu import native

            COUNTERS.path_quant_ivf += len(Q)
            # probes stay per-query (same matvec + argpartition + unique
            # as the solo path — bit-identical candidate sets); the scans
            # fuse into ONE threaded kernel dispatch over the CSR form
            ids_list = [
                self._quant_probe_ids(ivf, Q[i])[1] for i in range(len(Q))
            ]
            if native.NATIVE_AVAILABLE:
                lens = np.fromiter(
                    (c.size for c in ids_list), np.int64, len(Q)
                )
                ends = np.cumsum(lens)
                begs = ends - lens
                total = int(ends[-1]) if len(Q) else 0
                cat = (
                    np.concatenate(ids_list) if total
                    else np.zeros((0,), np.int32)
                )
                t0 = time.perf_counter_ns()
                idx, _dist, _sc = native.vec_qi8_topk_lists(
                    view["codes"], view["scales"], view["offsets"],
                    view["csums"], view["sqnorms"], view["valid"],
                    cat, begs, ends, qc, qs, qo, qcs, qstat,
                    _METRIC_ID[self.metric], pool, _nthreads(),
                )
                COUNTERS.scan_ns += time.perf_counter_ns() - t0
                COUNTERS.scan_rows += total
                for i in range(len(Q)):
                    self._emit_topk_row(out, i, idx[i], Q[i], view, kk)
                return out
            for i in range(len(Q)):
                rows, _ = self._quant_scan(
                    view, qc[i], qs[i], qo[i], qcs[i], qstat[i], pool,
                    rows=ids_list[i],
                )
                self._emit_topk_row(out, i, rows, Q[i], view, kk)
            return out
        COUNTERS.path_quant_brute += len(Q)
        from dgraph_tpu import native

        t0 = time.perf_counter_ns()
        if native.NATIVE_AVAILABLE:
            idx, _dist, _nv = native.vec_qi8_topk(
                view["codes"], view["scales"], view["offsets"],
                view["csums"], view["sqnorms"], view["valid"],
                qc, qs, qo, qcs, qstat,
                _METRIC_ID[self.metric], pool,
            )
        else:
            idx = np.empty((len(Q), pool), np.int64)
            for i in range(len(Q)):
                idx[i], _d = _qi8_scan_py(
                    view["codes"], view["scales"], view["offsets"],
                    view["csums"], view["sqnorms"], view["valid"],
                    qc[i], qs[i], qo[i], qcs[i], qstat[i],
                    self.metric, pool,
                )
        COUNTERS.scan_ns += time.perf_counter_ns() - t0
        COUNTERS.scan_rows += int(view["live"]) * len(Q)
        for i in range(len(Q)):
            self._emit_topk_row(out, i, idx[i], Q[i], view, kk)
        return out

    # -- IVF (jitted slab path) ------------------------------------------------

    def _train_ivf(self, mat: np.ndarray):
        """Slab-layout IVF for the jitted device path. Centroids come
        from the shared sampled mini-batch k-means (bounded cost at any
        corpus size — the full-sample Lloyd it replaced took 255s at
        1Mx768); assignment is the shared top-2 (coarse-to-fine above
        the exact-assignment budget)."""
        import jax.numpy as jnp

        t0 = time.perf_counter()
        n, d = mat.shape
        knob = int(config.get("VEC_NLIST"))
        nlist = self.nlist or knob or int(max(16, math.sqrt(n) * 2))
        nlist = max(1, min(nlist, n))
        rng = np.random.default_rng(0)
        c_np = _train_centroids(mat, nlist, rng)
        nlist = len(c_np)
        self.build_count += 1

        # multi-assignment: each vector lands in its 2 nearest cells —
        # big recall win for weakly-clustered data at 2x cell memory
        # (the reference's HNSW achieves the same via graph redundancy)
        t2 = _assign_top2(mat, c_np, rng)
        rows_rep = np.repeat(np.arange(n), 2)
        cells_rep = t2.reshape(-1)

        order = np.argsort(cells_rep, kind="stable")
        sorted_cells = cells_rep[order]
        flat_rows_cm = rows_rep[order]  # cell-major row ids
        starts = np.searchsorted(sorted_cells, np.arange(nlist))
        ends = np.searchsorted(sorted_cells, np.arange(nlist), side="right")
        lens = (ends - starts).astype(np.int64)

        # slab layout: pad each cell to a multiple of _SLAB so every slab
        # belongs to exactly one cell; top-M slab probing is then a
        # static-shape device op (_jit_ivf)
        S = _SLAB
        slabs_per_cell = np.maximum(1, -(-lens // S))
        n_slabs = int(slabs_per_cell.sum())
        flat_rows = np.full((n_slabs * S,), -1, np.int64)
        slab_cell = np.zeros((n_slabs,), np.int32)
        off = 0
        for ci in range(nlist):
            rws = flat_rows_cm[starts[ci] : ends[ci]]
            nsl = int(slabs_per_cell[ci])
            flat_rows[off * S : off * S + len(rws)] = rws
            slab_cell[off : off + nsl] = ci
            off += nsl
        fr2 = flat_rows.reshape(n_slabs, S)
        fv = np.zeros((n_slabs * S, d), np.float32)
        sel = flat_rows >= 0
        fv[sel] = mat[flat_rows[sel]]

        if self.nprobe is None:
            # embedding corpora cluster (the index contract); a handful of
            # nearest cells holds the true neighbors, and multi-assignment
            # covers boundary queries. ef/pool widening scales the probe
            # (the HNSW ef analog) when callers need more.
            pknob = int(config.get("VEC_NPROBE"))
            self.nprobe = pknob if pknob > 0 else max(8, nlist // 32)
        # static slab budget ~ nprobe cells' worth of average slabs
        avg_slabs = max(1.0, n_slabs / nlist)
        m_slabs = int(min(n_slabs, max(8, round(self.nprobe * avg_slabs))))
        fsq = (fv * fv).sum(axis=1).astype(np.float32)
        self._ivf = {
            "centroids": c_np,
            "cell_lens": lens.astype(np.int32),
            "m_slabs": m_slabs,
            "n_slabs": n_slabs,
            "dev": {
                "cents": jnp.asarray(c_np),
                "csq": jnp.asarray((c_np * c_np).sum(axis=1)),
                "slab_cell": jnp.asarray(slab_cell),
                "flat_vecs": jnp.asarray(fv.reshape(n_slabs, S, d)),
                "flat_sq": jnp.asarray(fsq.reshape(n_slabs, S)),
                "flat_rows": jnp.asarray(fr2.astype(np.int32)),
            },
        }
        _metrics().set_gauge(
            "vector_index_build_seconds", time.perf_counter() - t0
        )

    def _ivf_search(self, q: np.ndarray, pool: int):
        """One device dispatch: top-M slabs by centroid distance, gather,
        distances, top-pool. Host only dedupes multi-assigned rows.

        A wider candidate pool (ef / filtered search retries) also widens
        the slab probe by pow2 factors — bounded jit signatures, and the
        recall lever callers expect from raising ef."""
        import jax.numpy as jnp

        ivf = self._ivf
        m, npool = _probe_plan(ivf, pool)
        fn = _jit_ivf(self.metric, int(m), npool)
        dev = ivf["dev"]
        dd, rows = fn(
            dev["cents"],
            dev["csq"],
            dev["slab_cell"],
            dev["flat_vecs"],
            dev["flat_sq"],
            dev["flat_rows"],
            jnp.asarray(q, jnp.float32),
        )
        rows = np.asarray(rows)
        dd = np.asarray(dd)
        ok = rows >= 0
        rows, dd = rows[ok], dd[ok]
        first = _dedup_first(rows)
        rows, dd = rows[first], dd[first]
        k = min(pool, rows.size)
        uids = self._uids_np[rows[:k]]
        return uids, dd[:k]

    def _ivf_search_batch(self, Q: np.ndarray, k: int) -> np.ndarray:
        """Batched IVF (see _jit_ivf_batch). Candidate pool is 4x k (the
        same slack search() applies for filtered pools); rows that end up
        with fewer than k unique survivors pad with uid 0.

        The vmapped probe gathers (m_slabs * _SLAB, d) candidates PER
        QUERY, so the query batch is chunked to keep that intermediate
        under a fixed device budget (at 1Mx768 one query's gather is
        ~190MB — an unchunked 64-batch would alone exceed a v5e's HBM)."""
        import jax.numpy as jnp

        ivf = self._ivf
        m, npool = _probe_plan(ivf, 4 * k)
        d = int(ivf["dev"]["flat_vecs"].shape[2])
        per_q = m * _SLAB * d * 4  # gather bytes per query
        chunk = max(1, min(len(Q), int(2e9 // max(per_q, 1))))
        fn = _jit_ivf_batch(self.metric, int(m), npool)
        dev = ivf["dev"]
        out = np.zeros((len(Q), k), np.uint64)
        for off in range(0, len(Q), chunk):
            qc = np.asarray(Q[off : off + chunk], np.float32)
            if len(qc) < chunk:  # pad to the compiled batch shape
                qc = np.vstack(
                    [qc, np.zeros((chunk - len(qc), qc.shape[1]), np.float32)]
                )
            _, rows = fn(
                dev["cents"],
                dev["csq"],
                dev["slab_cell"],
                dev["flat_vecs"],
                dev["flat_sq"],
                dev["flat_rows"],
                jnp.asarray(qc),
            )
            rows = np.asarray(rows)
            for i in range(min(chunk, len(Q) - off)):
                r = rows[i]
                r = r[r >= 0]
                r = r[_dedup_first(r)][:k]
                out[off + i, : len(r)] = self._uids_np[r]
        return out


def _distances(V, sqnorm, q, metric):
    import jax.numpy as jnp

    dot = V @ q
    if metric == "dotproduct":
        return -dot
    if metric == "cosine":
        qn = jnp.sqrt((q * q).sum())
        vn = jnp.sqrt(sqnorm)
        return 1.0 - dot / jnp.maximum(vn * qn, 1e-12)
    # euclidean (squared — same ordering, cheaper; sqrt applied nowhere
    # because the reference compares distances relatively too)
    qsq = (q * q).sum()
    return sqnorm - 2.0 * dot + qsq


def _distances_batch(V, sqnorm, Q, metric):
    import jax.numpy as jnp

    dot = Q @ V.T  # (nq, n)
    if metric == "dotproduct":
        return -dot
    if metric == "cosine":
        qn = jnp.sqrt((Q * Q).sum(axis=1))
        vn = jnp.sqrt(sqnorm)
        return 1.0 - dot / jnp.maximum(vn[None, :] * qn[:, None], 1e-12)
    qsq = (Q * Q).sum(axis=1)
    return sqnorm[None, :] - 2.0 * dot + qsq[:, None]


def _in_sorted(arr: np.ndarray, v) -> bool:
    i = np.searchsorted(arr, v)
    return i < arr.size and arr[i] == v

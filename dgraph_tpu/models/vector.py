"""Vector similarity index: brute-force matmul top-k with an IVF tier.

Replaces the reference's HNSW (/root/reference/tok/hnsw/persistent_hnsw.go)
behind the same index-boundary semantics (tok/index/index.go:93 VectorIndex:
Search/SearchWithUid/Insert, per-call ef / distance_threshold options,
filtered search). HNSW's pointer-chasing beam search is hostile to the TPU
(SURVEY.md §2.7(7)); the sanctioned replacement is:

  - brute-force: scores = Q @ V.T on the MXU + lax.top_k — exact,
    recall 1.0. The distance computation and the top-k run in ONE jitted
    dispatch with an optimization barrier between them: without the
    barrier XLA fuses the matmul into the bitonic top-k as a producer and
    recomputes it per sort pass (measured 82ms -> 2.3ms per query on a
    real v5e for 100k x 256).
  - IVF: k-means centroids trained on device; the probe is slab-based so
    the whole search is one static-shape device dispatch (no host loop
    over cells — VERDICT r2 weak #4):
      * the cell-major corpus is padded per cell to a multiple of the
        slab size S, so every S-row slab belongs to exactly one cell;
      * searching scores each slab by its cell's centroid distance and
        takes the top-M slabs (M static), gathers those M*S rows, and
        runs distances + top-k over them in the same dispatch.

Metrics match tok/hnsw/helper.go:98-114: euclidean, cosine, dotproduct.
Supported distance ordering: smaller = closer (dot negated).

Mutability: inserts/deletes buffer host-side and fold into the padded
device matrix lazily (the MVCC analog of pack re-upload on rollup).
"""

from __future__ import annotations

import functools
import math
from typing import Dict, List, Optional

import numpy as np

_PAD_ROWS = 256
_SLAB = 128  # IVF slab rows; one slab belongs to exactly one cell


def _pow2_rows(n: int) -> int:
    return max(_PAD_ROWS, 1 << (max(1, n) - 1).bit_length())


@functools.lru_cache(maxsize=64)
def _jit_brute(metric: str, npool: int):
    """One-dispatch brute scorer: distances -> barrier -> top-k."""
    import jax
    import jax.numpy as jnp

    def run(V, sqnorm, valid, q):
        d = _distances(V, sqnorm, q, metric)
        d = jnp.where(valid, d, jnp.inf)
        d = jax.lax.optimization_barrier(d)
        neg, idx = jax.lax.top_k(-d, npool)
        return -neg, idx

    return jax.jit(run)


@functools.lru_cache(maxsize=64)
def _jit_brute_batch(metric: str, npool: int):
    import jax
    import jax.numpy as jnp

    def run(V, sqnorm, valid, Q):
        d = _distances_batch(V, sqnorm, Q, metric)
        d = jnp.where(valid[None, :], d, jnp.inf)
        d = jax.lax.optimization_barrier(d)
        neg, idx = jax.lax.top_k(-d, npool)
        return -neg, idx

    return jax.jit(run)


def _dedup_first(rows: np.ndarray) -> np.ndarray:
    """Indices of the first occurrence of each row id, in original order.
    Probe results ascend by distance, so the first occurrence of a
    multi-assigned row is its best distance. Input must be filtered to
    valid (>=0) rows."""
    _, first = np.unique(rows, return_index=True)
    return np.sort(first)


def _probe_plan(ivf: dict, pool: int):
    """Widen the static slab probe in pow2 factors until it covers the
    requested candidate pool (bounded jit signatures); npool carries 2x
    slack for multi-assignment duplicates."""
    base_pool = 64
    factor = 1
    while factor * base_pool < pool and ivf["m_slabs"] * factor < ivf[
        "n_slabs"
    ]:
        factor *= 2
    m = int(min(ivf["n_slabs"], ivf["m_slabs"] * factor))
    npool = int(min(max(pool, 1) * 2, m * _SLAB))
    return m, npool


def _ivf_probe(metric: str, m_slabs: int, npool: int):
    """The IVF probe body shared by the single-query and batched jits:
    centroid scores -> top-M slabs -> gather -> distances -> top-k.
    All shapes static."""
    import jax
    import jax.numpy as jnp

    def run(cents, csq, slab_cell, flat_vecs, flat_sq, flat_rows, q):
        # nearest cells by centroid distance (always euclidean on the
        # centroid geometry — probe selection only, not result ranking)
        cd = csq - 2.0 * (cents @ q) + (q * q).sum()
        slab_score = cd[slab_cell]
        _, sidx = jax.lax.top_k(-slab_score, m_slabs)
        sub = flat_vecs[sidx]            # (M, S, d) gather
        rows = flat_rows[sidx].reshape(-1)
        S, d = sub.shape[1], sub.shape[2]
        V = sub.reshape(m_slabs * S, d)
        dd = _distances(V, flat_sq[sidx].reshape(-1), q, metric)
        dd = jnp.where(rows >= 0, dd, jnp.inf)
        dd = jax.lax.optimization_barrier(dd)
        neg, idx = jax.lax.top_k(-dd, npool)
        return -neg, rows[idx]

    return run


@functools.lru_cache(maxsize=64)
def _jit_ivf(metric: str, m_slabs: int, npool: int):
    import jax

    return jax.jit(_ivf_probe(metric, m_slabs, npool))


@functools.lru_cache(maxsize=64)
def _jit_ivf_batch(metric: str, m_slabs: int, npool: int):
    """Batched IVF probe: the _ivf_probe pipeline vmapped over queries, so
    a whole query batch is ONE device dispatch + ONE host fetch. Through a
    remote-device tunnel this amortizes the per-dispatch round trip the
    same way the query engine's whole-level batching does."""
    import jax

    one = _ivf_probe(metric, m_slabs, npool)

    def run(cents, csq, slab_cell, flat_vecs, flat_sq, flat_rows, Q):
        return jax.vmap(
            one, in_axes=(None, None, None, None, None, None, 0)
        )(cents, csq, slab_cell, flat_vecs, flat_sq, flat_rows, Q)

    return jax.jit(run)


class VectorIndex:
    def __init__(
        self,
        pred: str,
        metric: str = "euclidean",
        ivf_threshold: int = 200_000,
        nlist: Optional[int] = None,
        nprobe: Optional[int] = None,
    ):
        if metric not in ("euclidean", "cosine", "dotproduct"):
            raise ValueError(f"unknown metric {metric!r}")
        self.pred = pred
        self.metric = metric
        self.ivf_threshold = ivf_threshold
        self.nlist = nlist
        self.nprobe = nprobe

        self._uids: List[int] = []
        self._rows: Dict[int, int] = {}  # uid -> row
        self._vecs: Optional[np.ndarray] = None  # (cap, d) padded
        self._n = 0
        self._dirty = True
        self._device = None  # jnp arrays (vecs, uids, norms)
        self._uids_np: Optional[np.ndarray] = None  # host uid map
        self._ivf = None

    # -- mutation -------------------------------------------------------------

    def insert(self, uid: int, vec) -> None:
        vec = np.asarray(vec, dtype=np.float32).reshape(-1)
        if self._vecs is None:
            self._vecs = np.zeros((_PAD_ROWS, vec.shape[0]), np.float32)
        if vec.shape[0] != self._vecs.shape[1]:
            raise ValueError(
                f"dim mismatch: index {self._vecs.shape[1]}, got {vec.shape[0]}"
            )
        row = self._rows.get(uid)
        if row is None:
            if self._n == self._vecs.shape[0]:
                grown = np.zeros(
                    (self._vecs.shape[0] * 2, self._vecs.shape[1]), np.float32
                )
                grown[: self._n] = self._vecs[: self._n]
                self._vecs = grown
            row = self._n
            self._n += 1
            self._rows[uid] = row
            self._uids.append(uid)
        self._vecs[row] = vec
        self._dirty = True

    def remove(self, uid: int) -> None:
        row = self._rows.pop(uid, None)
        if row is None:
            return
        last = self._n - 1
        if row != last:
            last_uid = self._uids[last]
            self._vecs[row] = self._vecs[last]
            self._rows[last_uid] = row
            self._uids[row] = last_uid
        self._uids.pop()
        self._n = last
        self._dirty = True

    def __len__(self) -> int:
        return self._n

    # -- device state ---------------------------------------------------------

    def _sync_device(self):
        import jax
        import jax.numpy as jnp

        from dgraph_tpu.x import config

        if not self._dirty and self._device is not None:
            return
        cap = _pow2_rows(self._n)
        d = self._vecs.shape[1]
        mat = np.zeros((cap, d), np.float32)
        mat[: self._n] = self._vecs[: self._n]
        uids = np.zeros((cap,), np.uint64)
        uids[: self._n] = np.asarray(self._uids, np.uint64)
        valid = np.zeros((cap,), bool)
        valid[: self._n] = True
        self._uids_np = uids
        self._mesh = None
        shard = bool(config.get("SHARD_VECTORS"))
        if shard and len(jax.devices()) > 1:
            # row-shard the corpus over the device mesh: per-shard top-k,
            # all_gather, global reduce (parallel/mesh.py sharded_topk —
            # the TP-over-rows data plane for 1M×768-class corpora)
            from jax.sharding import NamedSharding, PartitionSpec as P

            from dgraph_tpu.parallel import mesh as pmesh

            mesh = pmesh.make_mesh()
            ndev = mesh.devices.size
            rows = -(-cap // ndev) * ndev
            if rows != cap:
                mat = np.vstack([mat, np.zeros((rows - cap, d), np.float32)])
                uids = np.concatenate(
                    [uids, np.zeros((rows - cap,), np.uint64)]
                )
                valid = np.concatenate(
                    [valid, np.zeros((rows - cap,), bool)]
                )
                self._uids_np = uids
            sh = NamedSharding(mesh, P("data"))
            self._mesh = mesh
            self._device = {
                "vecs": jax.device_put(jnp.asarray(mat), sh),
                "uids": uids,  # host: gathered indices map back to uids
                "valid": jax.device_put(jnp.asarray(valid), sh),
                "sqnorm": None,
            }
            self._dirty = False
            if self._n >= self.ivf_threshold:
                self._train_ivf(mat[: self._n])
            else:
                self._ivf = None
            return
        self._device = {
            "vecs": jnp.asarray(mat),
            "uids": uids,
            "valid": jnp.asarray(valid),
            "sqnorm": jnp.asarray((mat * mat).sum(axis=1)),
        }
        self._dirty = False
        if self._n >= self.ivf_threshold:
            self._train_ivf(mat[: self._n])
        else:
            self._ivf = None

    # -- search ----------------------------------------------------------------

    def search(
        self,
        q,
        k: int,
        ef: Optional[int] = None,
        distance_threshold: Optional[float] = None,
        allowed: Optional[np.ndarray] = None,
    ) -> np.ndarray:
        """Top-k closest uids (sorted closest-first).

        `allowed`: optional sorted uid filter (ref index.go:66 SearchFilter).
        `ef`: candidate-pool override, kept for HNSW API compat — used as
        the IVF candidate width.
        """
        if self._n == 0:
            return np.zeros((0,), np.uint64)
        self._sync_device()
        import jax.numpy as jnp

        q = np.asarray(q, dtype=np.float32).reshape(-1)
        kk = min(max(k, 1), self._n)
        pool = max(kk, ef or 0)
        allowed_set = None
        if allowed is not None:
            allowed_set = np.asarray(allowed, np.uint64)
            # filter drops candidates; widen the pool up-front
            pool = max(pool, 4 * kk)

        # widen the candidate pool until k survivors or the whole set seen
        # (the HNSW analog is raising ef; ref index.go VectorIndexOptions)
        while True:
            if getattr(self, "_mesh", None) is not None:
                from dgraph_tpu.parallel import mesh as pmesh

                npool = min(max(pool, kk), self._n)
                dd, idx = pmesh.sharded_topk(
                    self._mesh,
                    self._device["vecs"],
                    self._device["valid"],
                    jnp.asarray(q),
                    npool,
                )
                cand_dists = np.asarray(dd)
                cand_uids = self._device["uids"][np.asarray(idx)]
            elif self._ivf is not None:
                cand_uids, cand_dists = self._ivf_search(q, max(pool, 4 * kk))
            else:
                npool = min(max(pool, kk), self._n)
                fn = _jit_brute(self.metric, int(npool))
                dd, idx = fn(
                    self._device["vecs"],
                    self._device["sqnorm"],
                    self._device["valid"],
                    jnp.asarray(q),
                )
                cand_dists = np.asarray(dd)
                cand_uids = self._uids_np[np.asarray(idx)]

            out = []
            for u, dist in zip(cand_uids, cand_dists):
                if not math.isfinite(dist):
                    continue
                if distance_threshold is not None and dist > distance_threshold:
                    break  # dists ascend: nothing closer follows
                if allowed_set is not None and not _in_sorted(allowed_set, u):
                    continue
                out.append(int(u))
                if len(out) == kk:
                    break
            exhausted = len(cand_uids) >= self._n or pool >= self._n
            if len(out) == kk or exhausted or allowed_set is None:
                return np.asarray(out, np.uint64)
            pool = min(pool * 4, self._n)

    def search_batch(self, Q, k: int) -> np.ndarray:
        """Top-k for a batch of queries in one device dispatch. Returns
        (len(Q), min(k, len(index))) uids, closest-first.

        Brute tier: exact. IVF tier: approximate (same probe the
        single-query path uses, pool 4x k); a row with fewer than k unique
        survivors pads trailing slots with uid 0 — callers must treat 0 as
        absent, as with any uid list."""
        if self._n == 0:
            return np.zeros((len(Q), 0), np.uint64)
        self._sync_device()
        if getattr(self, "_mesh", None) is not None:
            # sharded corpus has no replicated sqnorm; reuse the per-query
            # mesh path (still one dispatch per query)
            return np.stack([self.search(q, k) for q in np.asarray(Q)])
        import jax.numpy as jnp

        Q = np.asarray(Q, np.float32)
        kk = min(max(k, 1), self._n)
        if self._ivf is not None:
            return self._ivf_search_batch(Q, kk)
        fn = _jit_brute_batch(self.metric, int(kk))
        dd, idx = fn(
            self._device["vecs"],
            self._device["sqnorm"],
            self._device["valid"],
            jnp.asarray(Q),
        )
        return self._uids_np[np.asarray(idx)]

    def search_with_uid(self, uid: int, k: int, **kw) -> np.ndarray:
        row = self._rows.get(int(uid))
        if row is None:
            return np.zeros((0,), np.uint64)
        res = self.search(self._vecs[row], k + 1, **kw)
        return np.asarray([u for u in res if int(u) != int(uid)][:k], np.uint64)

    # -- IVF -------------------------------------------------------------------

    def _train_ivf(self, mat: np.ndarray, iters: int = 10):
        """Device k-means (Lloyd): assign = argmin distance matmul;
        update = segment mean. One jitted step, scanned."""
        import jax
        import jax.numpy as jnp

        n, d = mat.shape
        nlist = self.nlist or int(max(16, math.sqrt(n) * 2))
        nlist = min(nlist, n)
        rng = np.random.default_rng(0)
        cents = mat[rng.choice(n, nlist, replace=False)].copy()

        # Lloyd trains on a bounded subsample: the assignment matrix is
        # n_train x nlist on device, so a 1Mx768 corpus (nlist 2000 ->
        # 8GB if trained on everything) stays within a v5e's HBM next to
        # the brute-tier arrays. FAISS-style sampling: ~64 pts per cell.
        n_train = int(min(n, max(64 * nlist, 100_000)))
        Xtr = mat if n_train >= n else mat[rng.choice(n, n_train, replace=False)]
        X = jnp.asarray(Xtr)
        xsq = (X * X).sum(axis=1)

        @jax.jit
        def step(c):
            csq = (c * c).sum(axis=1)
            d2 = xsq[:, None] - 2.0 * (X @ c.T) + csq[None, :]
            assign = jnp.argmin(d2, axis=1)
            sums = jax.ops.segment_sum(X, assign, num_segments=nlist)
            cnts = jax.ops.segment_sum(
                jnp.ones((n_train,), jnp.float32), assign, num_segments=nlist
            )
            newc = jnp.where(
                cnts[:, None] > 0, sums / jnp.maximum(cnts, 1.0)[:, None], c
            )
            return newc

        c = jnp.asarray(cents)
        for _ in range(iters):
            c = step(c)
        # step's jit closure captured X/xsq as embedded constants; drop the
        # executable too or the training sample stays resident in HBM
        del step, X, xsq

        # multi-assignment: each vector lands in its 2 nearest cells —
        # big recall win for weakly-clustered data at 2x cell memory
        # (the reference's HNSW achieves the same via graph redundancy).
        # The full corpus is assigned in fixed-size chunks so the chunk
        # distance matrix stays small regardless of n.
        CH = 1 << 17

        @jax.jit
        def top2_chunk(c, xc):
            csq = (c * c).sum(axis=1)
            d2 = (xc * xc).sum(axis=1)[:, None] - 2.0 * (xc @ c.T) + csq[None, :]
            d2 = jax.lax.optimization_barrier(d2)
            _, t2 = jax.lax.top_k(-d2, 2)
            return t2

        c_np = np.asarray(c)
        parts = []
        for off in range(0, n, CH):
            chunk = mat[off : off + CH]
            if len(chunk) < CH and n > CH:
                padc = np.zeros((CH, d), np.float32)
                padc[: len(chunk)] = chunk
                parts.append(np.asarray(top2_chunk(c, jnp.asarray(padc)))[: len(chunk)])
            else:
                parts.append(np.asarray(top2_chunk(c, jnp.asarray(chunk))))
        t2 = np.concatenate(parts, axis=0)
        rows_rep = np.repeat(np.arange(n), 2)
        cells_rep = t2.reshape(-1)

        order = np.argsort(cells_rep, kind="stable")
        sorted_cells = cells_rep[order]
        flat_rows_cm = rows_rep[order]  # cell-major row ids
        starts = np.searchsorted(sorted_cells, np.arange(nlist))
        ends = np.searchsorted(sorted_cells, np.arange(nlist), side="right")
        lens = (ends - starts).astype(np.int64)

        # slab layout: pad each cell to a multiple of _SLAB so every slab
        # belongs to exactly one cell; top-M slab probing is then a
        # static-shape device op (_jit_ivf)
        S = _SLAB
        slabs_per_cell = np.maximum(1, -(-lens // S))
        n_slabs = int(slabs_per_cell.sum())
        flat_rows = np.full((n_slabs * S,), -1, np.int64)
        slab_cell = np.zeros((n_slabs,), np.int32)
        off = 0
        for ci in range(nlist):
            rws = flat_rows_cm[starts[ci] : ends[ci]]
            nsl = int(slabs_per_cell[ci])
            flat_rows[off * S : off * S + len(rws)] = rws
            slab_cell[off : off + nsl] = ci
            off += nsl
        fr2 = flat_rows.reshape(n_slabs, S)
        fv = np.zeros((n_slabs * S, d), np.float32)
        sel = flat_rows >= 0
        fv[sel] = mat[flat_rows[sel]]

        if self.nprobe is None:
            # embedding corpora cluster (the index contract); a handful of
            # nearest cells holds the true neighbors, and multi-assignment
            # covers boundary queries. ef/pool widening scales the probe
            # (the HNSW ef analog) when callers need more.
            self.nprobe = max(8, nlist // 32)
        # static slab budget ~ nprobe cells' worth of average slabs
        avg_slabs = max(1.0, n_slabs / nlist)
        m_slabs = int(min(n_slabs, max(8, round(self.nprobe * avg_slabs))))
        fsq = (fv * fv).sum(axis=1).astype(np.float32)
        self._ivf = {
            "centroids": c_np,
            "cell_lens": lens.astype(np.int32),
            "m_slabs": m_slabs,
            "n_slabs": n_slabs,
            "dev": {
                "cents": jnp.asarray(c_np),
                "csq": jnp.asarray((c_np * c_np).sum(axis=1)),
                "slab_cell": jnp.asarray(slab_cell),
                "flat_vecs": jnp.asarray(fv.reshape(n_slabs, S, d)),
                "flat_sq": jnp.asarray(fsq.reshape(n_slabs, S)),
                "flat_rows": jnp.asarray(fr2.astype(np.int32)),
            },
        }

    def _ivf_search(self, q: np.ndarray, pool: int):
        """One device dispatch: top-M slabs by centroid distance, gather,
        distances, top-pool. Host only dedupes multi-assigned rows.

        A wider candidate pool (ef / filtered search retries) also widens
        the slab probe by pow2 factors — bounded jit signatures, and the
        recall lever callers expect from raising ef."""
        import jax.numpy as jnp

        ivf = self._ivf
        m, npool = _probe_plan(ivf, pool)
        fn = _jit_ivf(self.metric, int(m), npool)
        dev = ivf["dev"]
        dd, rows = fn(
            dev["cents"],
            dev["csq"],
            dev["slab_cell"],
            dev["flat_vecs"],
            dev["flat_sq"],
            dev["flat_rows"],
            jnp.asarray(q, jnp.float32),
        )
        rows = np.asarray(rows)
        dd = np.asarray(dd)
        ok = rows >= 0
        rows, dd = rows[ok], dd[ok]
        first = _dedup_first(rows)
        rows, dd = rows[first], dd[first]
        k = min(pool, rows.size)
        uids = self._uids_np[rows[:k]]
        return uids, dd[:k]

    def _ivf_search_batch(self, Q: np.ndarray, k: int) -> np.ndarray:
        """Batched IVF (see _jit_ivf_batch). Candidate pool is 4x k (the
        same slack search() applies for filtered pools); rows that end up
        with fewer than k unique survivors pad with uid 0.

        The vmapped probe gathers (m_slabs * _SLAB, d) candidates PER
        QUERY, so the query batch is chunked to keep that intermediate
        under a fixed device budget (at 1Mx768 one query's gather is
        ~190MB — an unchunked 64-batch would alone exceed a v5e's HBM)."""
        import jax.numpy as jnp

        ivf = self._ivf
        m, npool = _probe_plan(ivf, 4 * k)
        d = int(ivf["dev"]["flat_vecs"].shape[2])
        per_q = m * _SLAB * d * 4  # gather bytes per query
        chunk = max(1, min(len(Q), int(2e9 // max(per_q, 1))))
        fn = _jit_ivf_batch(self.metric, int(m), npool)
        dev = ivf["dev"]
        out = np.zeros((len(Q), k), np.uint64)
        for off in range(0, len(Q), chunk):
            qc = np.asarray(Q[off : off + chunk], np.float32)
            if len(qc) < chunk:  # pad to the compiled batch shape
                qc = np.vstack([qc, np.zeros((chunk - len(qc), qc.shape[1]), np.float32)])
            _, rows = fn(
                dev["cents"],
                dev["csq"],
                dev["slab_cell"],
                dev["flat_vecs"],
                dev["flat_sq"],
                dev["flat_rows"],
                jnp.asarray(qc),
            )
            rows = np.asarray(rows)
            for i in range(min(chunk, len(Q) - off)):
                r = rows[i]
                r = r[r >= 0]
                r = r[_dedup_first(r)][:k]
                out[off + i, : len(r)] = self._uids_np[r]
        return out


def _distances(V, sqnorm, q, metric):
    import jax.numpy as jnp

    dot = V @ q
    if metric == "dotproduct":
        return -dot
    if metric == "cosine":
        qn = jnp.sqrt((q * q).sum())
        vn = jnp.sqrt(sqnorm)
        return 1.0 - dot / jnp.maximum(vn * qn, 1e-12)
    # euclidean (squared — same ordering, cheaper; sqrt applied nowhere
    # because the reference compares distances relatively too)
    qsq = (q * q).sum()
    return sqnorm - 2.0 * dot + qsq


def _distances_batch(V, sqnorm, Q, metric):
    import jax.numpy as jnp

    dot = Q @ V.T  # (nq, n)
    if metric == "dotproduct":
        return -dot
    if metric == "cosine":
        qn = jnp.sqrt((Q * Q).sum(axis=1))
        vn = jnp.sqrt(sqnorm)
        return 1.0 - dot / jnp.maximum(vn[None, :] * qn[:, None], 1e-12)
    qsq = (Q * Q).sum(axis=1)
    return sqnorm[None, :] - 2.0 * dot + qsq[:, None]


def _in_sorted(arr: np.ndarray, v) -> bool:
    i = np.searchsorted(arr, v)
    return i < arr.size and arr[i] == v

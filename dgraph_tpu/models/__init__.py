from dgraph_tpu.models.vector import VectorIndex

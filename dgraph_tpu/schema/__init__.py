from dgraph_tpu.schema.schema import SchemaUpdate, TypeUpdate, State, parse_schema

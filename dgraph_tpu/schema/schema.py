"""Schema state & schema-text parser.

Mirrors /root/reference/schema/: per-predicate SchemaUpdate (directives
@index(tokenizers), @reverse, @count, @upsert, @lang, @unique; list types;
vector index specs — ref protos/pb.proto:479 SchemaUpdate, :505
VectorIndexSpec) plus type definitions, and the schema text parser
(schema/parse.go) for the dgraph schema DSL:

    name: string @index(term, exact) @lang .
    age: int @index(int) .
    friend: [uid] @reverse @count .
    embedding: float32vector @index(hnsw(metric:"euclidean")) .
    type Person { name age friend }
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from dgraph_tpu.types.types import TypeID, type_from_name
from dgraph_tpu.tok.tok import get_tokenizer


@dataclass
class VectorSpec:
    """Vector index factory spec (ref pb.proto:505 VectorIndexSpec)."""

    name: str = "hnsw"  # accepted for compat; executes as brute/IVF on TPU
    options: Dict[str, str] = field(default_factory=dict)

    @property
    def metric(self) -> str:
        return self.options.get("metric", "euclidean")


@dataclass
class SchemaUpdate:
    predicate: str
    value_type: TypeID = TypeID.DEFAULT
    is_list: bool = False
    directive_index: bool = False
    tokenizers: List[str] = field(default_factory=list)
    directive_reverse: bool = False
    count: bool = False
    upsert: bool = False
    lang: bool = False
    unique: bool = False
    no_conflict: bool = False
    vector_specs: List[VectorSpec] = field(default_factory=list)

    @property
    def is_uid(self) -> bool:
        return self.value_type == TypeID.UID

    def tokenizer_objs(self):
        """Tokenizer objects for this predicate, cached on the entry —
        the mutation path calls this per edge, and re-resolving the
        registry each time was measurable on the live write path. A
        schema set replaces the whole SchemaUpdate (fresh cache); the
        key guards against in-place `tokenizers` edits too."""
        key = tuple(self.tokenizers)
        cached = getattr(self, "_tok_cache", None)
        if cached is None or cached[0] != key:
            cached = (key, [get_tokenizer(n) for n in key])
            self._tok_cache = cached
        return cached[1]


@dataclass
class TypeUpdate:
    name: str
    fields: List[str] = field(default_factory=list)


class State:
    """In-memory schema cache (ref schema/schema.go:59 state)."""

    def __init__(self):
        self._preds: Dict[str, SchemaUpdate] = {}
        self._types: Dict[str, TypeUpdate] = {}

    def set(self, su: SchemaUpdate):
        self._preds[su.predicate] = su

    def set_type(self, tu: TypeUpdate):
        self._types[tu.name] = tu

    def get(self, pred: str) -> Optional[SchemaUpdate]:
        return self._preds.get(pred)

    def get_type(self, name: str) -> Optional[TypeUpdate]:
        return self._types.get(name)

    def predicates(self) -> List[str]:
        return list(self._preds)

    def types(self) -> List[str]:
        return list(self._types)

    def delete(self, pred: str):
        self._preds.pop(pred, None)

    def ensure_default(self, pred: str, tid: TypeID = TypeID.DEFAULT) -> SchemaUpdate:
        """Auto-create schema on first mutation (reference behavior when no
        schema declared: type inferred from first value)."""
        su = self._preds.get(pred)
        if su is None:
            su = SchemaUpdate(predicate=pred, value_type=tid)
            if tid == TypeID.UID:
                # inferred uid predicates default to [uid] (ref schema
                # inference: createSchema lists uid edges)
                su.is_list = True
            self._preds[pred] = su
        return su


# ---------------------------------------------------------------------------
# Parser for the schema DSL (ref schema/parse.go).
# ---------------------------------------------------------------------------

_PRED_RE = re.compile(
    r"""^\s*
    (?P<name><[^>]+>|[\w.~\-]+)\s*:\s*
    (?P<list>\[)?\s*(?P<type>\w+)\s*\]?\s*
    (?P<directives>(?:@[\w]+(?:\((?:[^()]|\([^()]*\))*\))?\s*)*)
    \.\s*$""",
    re.VERBOSE,
)
_DIR_RE = re.compile(r"@(\w+)(?:\(((?:[^()]|\([^()]*\))*)\))?")
_TYPE_RE = re.compile(r"type\s+(?P<name>[\w.]+)\s*\{(?P<body>[^}]*)\}", re.DOTALL)


def _strip_angle(name: str) -> str:
    if name.startswith("<") and name.endswith(">"):
        return name[1:-1]
    return name


def parse_schema(text: str) -> tuple[List[SchemaUpdate], List[TypeUpdate]]:
    preds: List[SchemaUpdate] = []
    types: List[TypeUpdate] = []

    # strip comments
    text = re.sub(r"#[^\n]*", "", text)

    # extract type blocks first
    def _take_type(m):
        fields = [f.strip() for f in m.group("body").split() if f.strip()]
        fields = [_strip_angle(f) for f in fields]
        types.append(TypeUpdate(name=m.group("name"), fields=fields))
        return ""

    text = _TYPE_RE.sub(_take_type, text)

    for line in text.split("\n"):
        line = line.strip()
        if not line:
            continue
        m = _PRED_RE.match(line)
        if not m:
            raise ValueError(f"cannot parse schema line: {line!r}")
        su = SchemaUpdate(
            predicate=_strip_angle(m.group("name")),
            value_type=type_from_name(m.group("type")),
            is_list=bool(m.group("list")),
        )
        for dm in _DIR_RE.finditer(m.group("directives") or ""):
            dname, dargs = dm.group(1), dm.group(2)
            if dname == "index":
                su.directive_index = True
                for tokspec in _split_args(dargs or ""):
                    tokspec = tokspec.strip()
                    if not tokspec:
                        continue
                    fm = re.match(r"(\w+)\((.*)\)$", tokspec)
                    if fm:  # factory spec e.g. hnsw(metric:"euclidean")
                        opts = {}
                        for kv in fm.group(2).split(","):
                            if ":" in kv:
                                k, v = kv.split(":", 1)
                                opts[k.strip()] = v.strip().strip('"')
                        su.vector_specs.append(
                            VectorSpec(name=fm.group(1), options=opts)
                        )
                    else:
                        su.tokenizers.append(tokspec)
            elif dname == "reverse":
                su.directive_reverse = True
            elif dname == "count":
                su.count = True
            elif dname == "upsert":
                su.upsert = True
            elif dname == "lang":
                su.lang = True
            elif dname == "unique":
                su.unique = True
            elif dname == "noconflict":
                su.no_conflict = True
            else:
                raise ValueError(f"unknown schema directive @{dname}")
        preds.append(su)
    return preds, types


def _split_args(s: str) -> List[str]:
    """Split on commas not inside parens."""
    out, depth, cur = [], 0, []
    for ch in s:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        out.append("".join(cur))
    return out

"""CLI: the `dgraph` binary equivalent (ref /root/reference/dgraph/cmd).

Subcommands mirror the reference's cobra tree (root.go:80):
  alpha    — serve the HTTP API (ref cmd/alpha)
  bulk     — offline bulk load RDF into a data dir (ref cmd/bulk)
  live     — transactional load into a running data dir (ref cmd/live)
  export   — dump RDF/JSON + schema (ref worker/export.go)
  backup / restore — manifest-chain backups, local or --addr online
             against a live cluster (ref worker/backup*.go,
             worker/online_restore.go)
  cdc      — manage/tail the CDC stream of a running alpha
             (ref worker/cdc.go)
  acl      — user/group/rule administration (ref cmd/acl)
  increment — smoke-test counter (ref cmd/increment)
  debug    — p-dir inspector (ref cmd/debug)
  mcp      — MCP server on stdio (ref cmd/mcp)
  cert     — TLS CA/node/client certs (ref cmd/cert)
  conv     — geo/JSON -> RDF conversion (ref cmd/conv)
  migrate  — relational CSV -> RDF + schema (ref cmd/migrate)
  debuginfo — support bundle (ref cmd/debuginfo)
  top      — top query shapes by latency share (/debug/digests)
  debug-bundle — one-command flight-recorder tarball (metrics,
             digests, history, health, traces, lock graph, config)
  upgrade  — on-disk layout migrations (ref upgrade/)
  version

Usage: python -m dgraph_tpu <subcommand> [...]
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _server(args):
    from dgraph_tpu.api.server import Server
    from dgraph_tpu.x.flags import STORAGE_DEFAULTS, SuperFlag

    key = None
    if getattr(args, "encryption_key_file", None):
        from dgraph_tpu.enc.enc import read_key_file

        key = read_key_file(args.encryption_key_file)
    sf = SuperFlag(getattr(args, "storage", "") or "", STORAGE_DEFAULTS)
    if key is None and sf.get_string("encryption-key-file"):
        from dgraph_tpu.enc.enc import read_key_file

        key = read_key_file(sf.get_string("encryption-key-file"))
    backend = sf.get_string("backend", "mem")
    if backend != "mem":
        from dgraph_tpu.x import config

        config.set_env("STORAGE", backend)
    return Server(data_dir=args.p, encryption_key=key)


def cmd_alpha(args):
    from dgraph_tpu.api.http_server import HTTPServer

    if getattr(args, "cluster", ""):
        from dgraph_tpu.worker.facade import ClusterFacade
        from dgraph_tpu.worker.groups import DistributedCluster
        from dgraph_tpu.x.flags import SuperFlag

        cf = SuperFlag(
            args.cluster,
            "groups=2; replicas=3; learners=0; replicated-zero=false",
        )
        cluster = DistributedCluster(
            n_groups=cf.get_int("groups", 2),
            replicas=cf.get_int("replicas", 3),
            data_dir=args.p,
            learners_per_group=cf.get_int("learners", 0),
            replicated_zero=cf.get_bool("replicated-zero"),
        )
        engine = ClusterFacade(cluster)
    else:
        engine = _server(args)
    if args.schema:
        with open(args.schema) as f:
            engine.alter(f.read())
    if args.acl_secret_file:
        with open(args.acl_secret_file, "rb") as f:
            engine.enable_acl(secret=f.read().strip())
    if args.audit_dir:
        engine.enable_audit(args.audit_dir)
    from dgraph_tpu.x import config as _config

    cdc_sink = args.cdc_file or _config.get("CDC_SINK")
    if cdc_sink:
        from dgraph_tpu.admin.cdc import cdc_for_uri

        cdc_for_uri(engine, cdc_sink)
    if args.rollup_interval > 0:
        from dgraph_tpu.posting.rollup import RollupDaemon

        RollupDaemon(engine, interval_s=args.rollup_interval).start()
    from dgraph_tpu.x.flags import TRACE_DEFAULTS, SuperFlag

    tf = SuperFlag(getattr(args, "trace", "") or "", TRACE_DEFAULTS)
    if tf.get_string("sink-file"):
        from dgraph_tpu.utils import observe

        # point the GLOBAL tracer at the sink (replacing the instance
        # would orphan every module that imported TRACER by value)
        observe.TRACER.set_sink(tf.get_string("sink-file"))
    srv = HTTPServer(engine, host=args.bind, port=args.port).start()
    print(f"alpha listening on http://{args.bind}:{srv.port}")
    if args.grpc_port >= 0:
        from dgraph_tpu.api.grpc_server import serve as grpc_serve

        _, gport = grpc_serve(engine, host=args.bind, port=args.grpc_port)
        print(f"alpha gRPC (api.Dgraph) on {args.bind}:{gport}")
    try:
        import time

        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()


def cmd_bulk(args):
    import time

    from dgraph_tpu.loaders.bulk2 import ParallelBulkLoader

    engine = _server(args)
    if args.schema:
        with open(args.schema) as f:
            engine.alter(f.read())
    t0 = time.time()
    loader = ParallelBulkLoader(engine)
    loader.load_files(list(args.files))
    n = loader.nquads
    engine.kv.sync() if hasattr(engine.kv, "sync") else None
    print(f"bulk loaded {n} nquads in {time.time()-t0:.1f}s")


def cmd_live(args):
    from dgraph_tpu.loaders.live import LiveLoader

    engine = _server(args)
    if args.schema:
        with open(args.schema) as f:
            engine.alter(f.read())
    ll = LiveLoader(engine, batch_size=args.batch)
    for path in args.files:
        ll.load_rdf_file(path)
    print(
        f"live loaded {ll.nquads_loaded} nquads in {ll.txns_committed} txns "
        f"({ll.aborts} aborts)"
    )


def cmd_import(args):
    """dgraphimport equivalent (ref dgraphimport/, the snapshot-stream
    import tool): bulk-load an exported dataset (schema + rdf[.gz]) into
    a fresh or running data dir, picking bulk (offline, rollup writes)
    or live (transactional) mode."""
    import glob as _glob

    files = []
    schema = args.schema
    for pat in args.files:
        for path in sorted(_glob.glob(pat)):
            if path.endswith((".schema", ".schema.gz")):
                schema = schema or path
            else:
                files.append(path)
    args.files = files
    args.schema = schema
    if args.mode == "live":
        return cmd_live(args)
    return cmd_bulk(args)


def cmd_export(args):
    from dgraph_tpu.admin.export import export

    out = export(_server(args), args.out, fmt=args.format)
    print(json.dumps(out))


def _admin_call(addr: str, path: str, timeout: float = 300.0):
    """POST an /admin op against a running alpha; returns the JSON body
    or exits nonzero with the error on stderr."""
    import urllib.error
    import urllib.request

    url = addr.rstrip("/") + path
    req = urllib.request.Request(url, data=b"", method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read())
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read())
        except Exception:
            body = {"errors": [{"message": str(e)}]}
        print(json.dumps(body), file=sys.stderr)
        return None
    except Exception as e:
        print(f"{url}: {e}", file=sys.stderr)
        return None


def cmd_backup(args):
    """Backup a local data dir — or, with --addr, a LIVE cluster: the
    running alpha coordinates a journaled online backup (distributed
    driver when it serves a cluster) while writes keep flowing."""
    from urllib.parse import quote

    if args.addr:
        out = _admin_call(
            args.addr,
            f"/admin/backup?destination={quote(args.dest)}"
            + ("&full=true" if args.full else ""),
        )
        if out is None:
            return 1
        print(json.dumps(out.get("data", out)))
        return 0
    from dgraph_tpu.admin.backup import backup

    entry = backup(_server(args), args.dest, incremental=not args.full)
    print(json.dumps(entry))


def cmd_restore(args):
    """Restore a manifest chain into a local data dir — or, with
    --addr, ONLINE into a live cluster (verified records proposed
    through each group's raft log; leases + snapshot watermark advance
    so the data is immediately visible)."""
    from urllib.parse import quote

    if args.addr:
        out = _admin_call(
            args.addr, f"/admin/restore?source={quote(args.src)}"
        )
        if out is None:
            return 1
        print(json.dumps(out.get("data", out)))
        return 0
    from dgraph_tpu.admin.backup import restore

    n = restore(_server(args), args.src)
    print(f"restored {n} records")


def cmd_cdc(args):
    """Manage the CDC stream of a running alpha: point it at a sink
    (--sink), turn it off (--disable), probe its status (default), or
    tail an ndjson sink file (--follow)."""
    from urllib.parse import quote

    if args.follow:
        import time as _t

        with open(args.follow) as f:
            while True:
                line = f.readline()
                if line:
                    sys.stdout.write(line)
                    sys.stdout.flush()
                elif args.once:
                    return 0
                else:
                    _t.sleep(0.2)
    if args.disable:
        out = _admin_call(args.addr, "/admin/cdc?disable=true")
    elif args.sink:
        out = _admin_call(args.addr, f"/admin/cdc?sink={quote(args.sink)}")
    else:
        out = _admin_call(args.addr, "/admin/cdc")
    if out is None:
        return 1
    print(json.dumps(out.get("data", out)))
    return 0


def cmd_acl(args):
    engine = _server(args)
    acl = engine.enable_acl()
    if args.acl_cmd == "add-user":
        acl.add_user(args.user, args.password)
        print(f"user {args.user} created")
    elif args.acl_cmd == "add-group":
        acl.add_group(args.group)
        print(f"group {args.group} created")
    elif args.acl_cmd == "add-to-group":
        acl.add_user_to_group(args.user, args.group)
        print("ok")
    elif args.acl_cmd == "set-rule":
        acl.set_rule(args.group, args.predicate, args.perm)
        print("ok")


def cmd_increment(args):
    """Smoke test: read-modify-write a counter N times
    (ref dgraph/cmd/increment)."""
    engine = _server(args)
    engine.alter("counter.val: int .")
    for _ in range(args.num):
        txn = engine.new_txn()
        res = txn.query("{ q(func: uid(0x1)) { counter.val } }")
        cur = res["data"]["q"][0]["counter.val"] if res["data"]["q"] else 0
        txn.mutate_rdf(
            set_rdf=f'<0x1> <counter.val> "{cur + 1}"^^<xs:int> .'
        )
        txn.commit()
    res = engine.query("{ q(func: uid(0x1)) { counter.val } }")
    print(f"counter: {res['data']['q'][0]['counter.val']}")


def cmd_debug(args):
    """Inspect a p-dir: key histogram per predicate (ref cmd/debug)."""
    from dgraph_tpu.x import keys as xkeys

    engine = _server(args)
    hist = {}
    for key, _, _ in engine.kv.iterate(b"", 1 << 62):
        try:
            pk = xkeys.parse_key(key)
        except Exception:
            continue
        kind = (
            "schema" if pk.is_schema else
            "type" if pk.is_type else
            "data" if pk.is_data else
            "index" if pk.is_index else
            "reverse" if pk.is_reverse else
            "count"
        )
        hist.setdefault(pk.attr, {}).setdefault(kind, 0)
        hist[pk.attr][kind] += 1
    print(json.dumps(hist, indent=2, sort_keys=True))


def cmd_mcp(args):
    from dgraph_tpu.api.mcp_server import McpServer

    McpServer(_server(args)).serve_stdio()




def cmd_cert(args):
    from dgraph_tpu import tools

    if args.ls:
        for row in tools.cert_ls(args.dir):
            print(row["file"], "|", row["info"].replace("\n", " "))
        return
    made = tools.cert_create(
        args.dir,
        nodes=[n for n in args.nodes.split(",") if n],
        client=args.client or None,
    )
    for k, v in made.items():
        print(f"created {k}: {v}")


def cmd_conv(args):
    from dgraph_tpu import tools

    rdf = []
    if args.geo:
        rdf += tools.conv_geojson(args.geo)
    if args.json_file:
        rdf += tools.conv_json(args.json_file)
    text = "\n".join(rdf) + "\n"
    if args.out == "-":
        sys.stdout.write(text)
    else:
        with open(args.out, "w") as f:
            f.write(text)


def cmd_migrate(args):
    from dgraph_tpu import tools

    tables = dict(kv.split("=", 1) for kv in args.tables.split(","))
    schema, rdf = tools.migrate_csv(tables)
    with open(args.out_schema, "w") as f:
        f.write(schema + "\n")
    with open(args.out_rdf, "w") as f:
        f.write("\n".join(rdf) + "\n")
    print(f"wrote {len(rdf)} nquads to {args.out_rdf}")


def cmd_debuginfo(args):
    from dgraph_tpu import tools

    engine = _server(args)
    bundle = tools.debuginfo(engine, args.out)
    print(f"bundle: {bundle}")


def cmd_decrypt(args):
    """Decrypt an encrypted export/backup file offline (ref
    dgraph/cmd/decrypt/decrypt.go:47 — enc.GetReader + optional gzip,
    output re-gzipped)."""
    import gzip

    from dgraph_tpu.enc import enc

    key = enc.read_key_file(args.encryption_key_file)
    with open(args.file, "rb") as f:
        data = f.read()
    plain = enc.decrypt_stream(data, key)
    if args.file.lower().endswith(".gz"):
        plain = gzip.decompress(plain)
    # the reference writes the output gzip-compressed
    with gzip.open(args.out, "wb") as out:
        out.write(plain)
    print(f"decrypted {args.file} -> {args.out}")


def cmd_upgrade(args):
    from dgraph_tpu import tools

    applied = tools.upgrade(args.p)
    print(
        f"layout now v{tools.layout_version(args.p)}; applied: {applied or 'none'}"
    )

def cmd_lint(args):
    """Run the project-invariant analyzer suite (dgraph_tpu/analysis).

    Exit-code contract (stable, for external CI):
      0 — clean: no unallowlisted violations, no stale allowlist entries
      1 — violations (or stale allowlist entries) found
      2 — internal analyzer error
    """
    import json as _json
    import traceback

    from dgraph_tpu import analysis

    try:
        checkers = None
        if getattr(args, "checker", None):
            unknown = set(args.checker) - set(analysis.CHECKERS)
            if unknown:
                print(
                    f"unknown checker(s) {sorted(unknown)}; available: "
                    f"{sorted(analysis.CHECKERS)}"
                )
                return 2
            checkers = args.checker
        rep = analysis.run(checkers=checkers)
    except Exception:
        traceback.print_exc()
        return 2
    if args.json:
        print(_json.dumps(rep.to_dict(), indent=2))
    else:
        for v in rep.violations:
            print(v.render())
        for a in rep.unused_allows:
            print(
                f"allowlist.py: stale entry ({a.checker}, {a.path}, "
                f"{a.match!r}) matches nothing — remove it"
            )
        print(
            f"lint: {len(rep.violations)} violation(s), "
            f"{len(rep.suppressed)} allowlisted, "
            f"{len(rep.unused_allows)} stale allowlist entr(y/ies)"
        )
    return 0 if rep.ok else 1


def cmd_metrics(args):
    """Scrape the cluster-merged metrics endpoint of a running alpha
    (`/debug/prometheus_metrics`: counters summed across every alpha/
    zero process, histograms bucket-merged, per-instance labels kept)
    and print the exposition text — or, with --json, a parsed
    {counters, gauges, histograms} object."""
    import urllib.request

    from dgraph_tpu.utils import observe

    url = args.addr.rstrip("/") + "/debug/prometheus_metrics"
    try:
        text = urllib.request.urlopen(
            url, timeout=args.timeout
        ).read().decode("utf-8")
    except Exception as e:
        print(f"scrape of {url} failed: {e}", file=sys.stderr)
        return 1
    if args.json:
        parsed = observe.parse_exposition(text)
        print(
            json.dumps(
                {
                    "counters": parsed["counter"],
                    "gauges": parsed["gauge"],
                    "histograms": parsed["histogram"],
                },
                indent=2,
                sort_keys=True,
            )
        )
    else:
        print(text, end="")
    return 0


def render_plan(plan: dict) -> str:
    """Human-readable EXPLAIN rendering of an extensions.plan tree:
    one indented line per (predicate, level) node with uids in/out,
    read strategy, wall time, and kernel counts, preceded by the
    query-level decisions (plan cache, admission, cache tiers,
    micro-batching, set-op routing). Pure — unit-tested against a
    captured plan (tests/test_explain.py)."""
    lines = []
    wall = plan.get("wall_ns")
    head = "Query plan"
    if wall is not None:
        head += f" (wall {wall / 1e6:.2f}ms"
        if "read_ts" in plan:
            head += f", read_ts {plan['read_ts']}"
        if "snapshot_watermark" in plan:
            head += f", watermark {plan['snapshot_watermark']}"
        head += ")"
    lines.append(head)
    pc = plan.get("plan_cache") or {}
    if pc:
        if not pc.get("enabled", True):
            lines.append("  plan cache: disabled")
        else:
            shape = pc.get("shape")
            lines.append(
                "  plan cache: %s%s"
                % (
                    "HIT" if pc.get("hit") else "MISS",
                    f'  shape="{shape}"' if shape else "",
                )
            )
    adm = plan.get("admission") or {}
    if adm:
        lines.append(
            "  admission: cost %s (%s%s)"
            % (
                adm.get("cost"),
                "gate on" if adm.get("enabled") else "gate off",
                ", degraded" if adm.get("degrade") else "",
            )
        )
    cache = plan.get("cache") or {}
    if cache:
        lines.append(
            "  cache: %d memlayer hits / %d misses, "
            "%d batch reads (%d keys), %d point reads"
            % (
                cache.get("memlayer_hits", 0),
                cache.get("memlayer_misses", 0),
                cache.get("batch_reads", 0),
                cache.get("batch_read_keys", 0),
                cache.get("point_reads", 0),
            )
        )
    mb = plan.get("microbatch") or {}
    if mb.get("coalesced") or mb.get("solo"):
        lines.append(
            "  microbatch: %d coalesced (max width %d) / %d solo"
            % (
                mb.get("coalesced", 0),
                mb.get("members_max", 0),
                mb.get("solo", 0),
            )
        )
    setops = plan.get("setops") or []
    if setops:
        packed = sum(1 for s in setops if s.get("verdict") == "packed")
        pushed = sum(1 for s in setops if s.get("verdict") == "pushdown")
        lines.append(
            "  setops: %d decisions, %d packed / %d decoded%s%s"
            % (
                len(setops),
                packed,
                len(setops) - packed - pushed,
                f", {pushed} pushdown" if pushed else "",
                (
                    f" ({plan['setops_dropped']} dropped)"
                    if plan.get("setops_dropped")
                    else ""
                ),
            )
        )
    pl = plan.get("planner") or {}
    if pl:
        if not pl.get("enabled", False):
            lines.append("  planner: off")
        else:
            lines.append(
                "  planner: on, %d reorders, %d pushdowns"
                % (pl.get("reorders", 0), pl.get("pushdowns", 0))
            )
            for so in pl.get("sibling_orders", ()):
                lines.append(
                    "    sibling order: %s" % " -> ".join(so.get("order", ()))
                )
            for ao in pl.get("and_orders", ()):
                lines.append(
                    "    filter AND order: %s"
                    % " -> ".join(str(i) for i in ao.get("order", ()))
                )
    rc = plan.get("result_cache") or {}
    if rc:
        if not rc.get("enabled", False):
            lines.append("  result cache: disabled")
        else:
            lines.append(
                "  result cache: %s (watermark %s)"
                % (
                    "WOULD-HIT (EXPLAIN always executes)"
                    if rc.get("would_hit")
                    else ("eligible, cold" if rc.get("eligible") else "ineligible"),
                    rc.get("watermark"),
                )
            )

    def walk(node, depth):
        kern = node.get("kernels") or {}
        kern_s = ""
        if kern:
            kern_s = " kernels{%s}" % ", ".join(
                f"{k}={int(v)}" for k, v in sorted(kern.items())
            )
        if node.get("read") == "root":
            lines.append(
                "  %s%s (root%s) -> %d uids"
                % (
                    "  " * depth,
                    node.get("attr"),
                    f" func={node['func']}" if node.get("func") else "",
                    node.get("uids_out", 0),
                )
            )
        else:
            est = node.get("est_out")
            lines.append(
                "  %s%s level=%d [%s] %d -> %d uids%s, %.2fms%s"
                % (
                    "  " * depth,
                    node.get("attr"),
                    node.get("level", 0),
                    node.get("read", "?"),
                    node.get("uids_in", 0),
                    node.get("uids_out", 0),
                    f" (est {est})" if est is not None else "",
                    node.get("wall_ns", 0) / 1e6,
                    kern_s,
                )
            )
        for c in node.get("children", ()):
            walk(c, depth + 1)

    for root in plan.get("nodes", ()):
        walk(root, 0)
    return "\n".join(lines)


def cmd_explain(args):
    """EXPLAIN/ANALYZE a query: run it with debug=true against a
    running alpha (--addr) or a local data dir (-p) and render the
    extensions.plan tree as an indented plan."""
    query = args.query
    if query == "-":
        query = sys.stdin.read()
    if args.addr:
        import urllib.request

        req = urllib.request.Request(
            args.addr.rstrip("/") + "/query?debug=true",
            data=query.encode("utf-8"),
            method="POST",
            headers={"Content-Type": "application/dql"},
        )
        try:
            res = json.loads(
                urllib.request.urlopen(req, timeout=args.timeout).read()
            )
        except Exception as e:
            print(f"query against {args.addr} failed: {e}", file=sys.stderr)
            return 1
        if res.get("errors"):
            print(json.dumps(res["errors"], indent=2), file=sys.stderr)
            return 1
    else:
        from dgraph_tpu.api.server import Server

        server = Server(data_dir=args.p)
        res = server.query(query, debug=True)
    plan = (res.get("extensions") or {}).get("plan")
    if plan is None:
        print("no extensions.plan in the response", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(plan, indent=2, sort_keys=True))
    else:
        print(render_plan(plan))
    return 0


def _render_health(h: dict) -> str:
    lines = [
        "status: %s  (instance %s, pid %s, up %.0fs)"
        % (
            h.get("status", "?"), h.get("instance", "?"),
            h.get("pid", "?"), h.get("uptime_s", 0),
        )
    ]
    if "snapshot_watermark" in h:
        lag = h.get("watermark_lag")
        lines.append(
            "watermark: %s%s"
            % (
                h["snapshot_watermark"],
                f" (lag {lag})" if lag is not None else "",
            )
        )
    adm = h.get("admission") or {}
    lines.append(
        "admission: %d in flight, %d shed, %d degraded"
        % (
            adm.get("inflight", 0), adm.get("shed_total", 0),
            adm.get("degraded_queries_total", 0),
        )
    )
    lines.append(
        "commit pipeline depth: %d" % h.get("commit_pipeline_depth", 0)
    )
    for gid, g in sorted((h.get("groups") or {}).items()):
        reps = []
        for nid, r in sorted(g.get("replicas", {}).items()):
            if not r.get("ok"):
                reps.append(f"{nid}:DOWN")
            else:
                tag = "*" if r.get("is_leader") else ""
                lag = r.get("applied_lag", 0)
                reps.append(
                    f"{nid}{tag}@{r.get('applied', 0)}"
                    + (f"(-{lag})" if lag else "")
                )
        lines.append(
            "group %s: %s  [%s]"
            % (
                gid,
                "leader=%s" % g.get("leader")
                if g.get("healthy")
                else "NO LEADER",
                " ".join(reps),
            )
        )
    for name, rep in sorted((h.get("slo") or {}).items()):
        wins = rep.get("windows", {})
        burn = ", ".join(
            f"{w}={v.get('burn_rate')}" for w, v in sorted(wins.items())
        )
        lines.append(
            "slo %s (<=%sms @ %s): burn %s"
            % (name, rep.get("threshold_ms"), rep.get("target"), burn)
        )
    unreachable = h.get("unreachable_instances")
    if unreachable:
        lines.append("unreachable: " + ", ".join(unreachable))
    return "\n".join(lines)


def cmd_health(args):
    """Scrape + print the cluster health/SLO rollup of a running alpha
    (/debug/healthz: per-group raft leadership and applied-index lag,
    snapshot-watermark lag, commit pipeline depth, admission shed and
    degraded rates, multi-window SLO burn rates)."""
    import urllib.request

    url = args.addr.rstrip("/") + "/debug/healthz"
    try:
        h = json.loads(
            urllib.request.urlopen(url, timeout=args.timeout).read()
        )
    except Exception as e:
        print(f"scrape of {url} failed: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(h, indent=2, sort_keys=True))
    else:
        print(_render_health(h))
    return 0


def _render_top(rows, n: int) -> str:
    """Top-N digest rows by latency share — one line per (ns, shape)."""
    total_lat = sum(r.get("lat_sum", 0.0) for r in rows) or 1.0
    lines = [
        "%8s %6s %7s %7s %9s %6s %6s %4s  %s"
        % (
            "CALLS", "ERR", "LAT%", "MEAN_MS", "ROWS", "PHIT%",
            "RHIT%", "NS", "SHAPE",
        )
    ]
    for r in rows[:n]:
        calls = r.get("calls", 0) or 0
        lat = r.get("lat_sum", 0.0)
        shape = r.get("shape", "")
        if len(shape) > 88:
            shape = shape[:85] + "..."
        lines.append(
            "%8d %6d %6.1f%% %7.2f %9d %5.0f%% %5.0f%% %4s  %s"
            % (
                calls,
                r.get("errors", 0),
                100.0 * lat / total_lat,
                (lat / calls * 1e3) if calls else 0.0,
                r.get("rows", 0),
                100.0 * r.get("plan_hits", 0) / calls if calls else 0.0,
                100.0 * r.get("result_hits", 0) / calls if calls else 0.0,
                r.get("ns", "?"),
                shape,
            )
        )
    return "\n".join(lines)


def cmd_top(args):
    """pg_stat_statements for the cluster: scrape /debug/digests of a
    running alpha (cluster-merged per-(namespace, shape) aggregates)
    and render the top-N query shapes by latency share. `--watch`
    refreshes in place every --interval seconds."""
    import urllib.request

    url = args.addr.rstrip("/") + "/debug/digests"

    def fetch():
        body = json.loads(
            urllib.request.urlopen(url, timeout=args.timeout).read()
        )
        return body

    try:
        while True:
            try:
                body = fetch()
            except Exception as e:
                print(f"scrape of {url} failed: {e}", file=sys.stderr)
                return 1
            rows = body.get("digests", [])
            if args.json:
                print(json.dumps(body, indent=2, sort_keys=True))
            else:
                if args.watch:
                    sys.stdout.write("\x1b[2J\x1b[H")
                unreachable = body.get("unreachable_instances") or []
                if unreachable:
                    print(
                        "WARNING: partial merge, unreachable: "
                        + ", ".join(unreachable)
                    )
                print(_render_top(rows, args.n))
            if not args.watch:
                return 0
            time.sleep(max(0.2, args.interval))
    except KeyboardInterrupt:
        return 0


def cmd_debug_bundle(args):
    """One-command flight-recorder capture: fetch merged metrics,
    digests, a history window, health, traces, tablets, the slow-query
    log, and the resolved config from a running alpha, compute the
    static lock graph locally, and pack everything into one tarball. A
    dead alpha (or any failing endpoint) yields a PARTIAL bundle with
    the failure recorded in MANIFEST.json — never an empty exit."""
    import io
    import tarfile
    import urllib.parse
    import urllib.request

    base = args.addr.rstrip("/")
    window = float(args.window)
    endpoints = {
        "metrics.prom": "/debug/prometheus_metrics",
        "digests.json": "/debug/digests",
        "history.json": (
            "/debug/history?" + urllib.parse.urlencode({"window": window})
        ),
        "health.json": "/debug/healthz",
        "traces.json": "/debug/traces",
        "tablets.json": "/debug/tablets",
        "slowlog.jsonl": "/debug/slowlog",
        "config.json": "/debug/config",
    }
    files: dict = {}
    manifest: dict = {
        "generated": time.time(),
        "addr": base,
        "window_s": window,
        "files": {},
        "unreachable_instances": [],
    }
    unreachable = set()
    for name, path in endpoints.items():
        url = base + path
        try:
            data = urllib.request.urlopen(
                url, timeout=args.timeout
            ).read()
            files[name] = data
            manifest["files"][name] = {"ok": True, "bytes": len(data)}
            if name.endswith(".json"):
                try:
                    body = json.loads(data)
                    unreachable.update(
                        body.get("unreachable_instances") or []
                    )
                except ValueError:
                    pass
        except Exception as e:
            manifest["files"][name] = {"ok": False, "error": str(e)}
            print(f"  {name}: FAILED ({e})", file=sys.stderr)
    # the static lock graph (PR 19's analyzer) and resolved config are
    # computed locally — they describe the code/process, not the
    # cluster, so a dead alpha cannot take them down
    try:
        from dgraph_tpu.analysis import load_sources, package_root
        from dgraph_tpu.analysis.check_lockorder import lock_graph

        edges = [
            {
                "outer": outer,
                "inner": inner,
                "path": path,
                "line": line,
                "kind": kind,
            }
            for (outer, inner), (path, line, kind) in sorted(
                lock_graph(load_sources(package_root())).items()
            )
        ]
        files["lockgraph.json"] = json.dumps(
            {"edges": edges}, indent=2
        ).encode()
        manifest["files"]["lockgraph.json"] = {"ok": True}
    except Exception as e:
        manifest["files"]["lockgraph.json"] = {
            "ok": False, "error": str(e),
        }
    if "config.json" not in files:
        from dgraph_tpu.x import config as _cfg

        files["config.json"] = json.dumps(
            _cfg.resolved(), indent=2, default=str
        ).encode()
        manifest["files"]["config.json"] = {"ok": True, "local": True}
    manifest["unreachable_instances"] = sorted(unreachable)
    out_path = args.out or time.strftime("debug-bundle-%Y%m%d-%H%M%S.tar.gz")
    files["MANIFEST.json"] = json.dumps(
        manifest, indent=2, sort_keys=True
    ).encode()
    with tarfile.open(out_path, "w:gz") as tar:
        for name in sorted(files):
            data = files[name]
            info = tarfile.TarInfo(name=f"debug-bundle/{name}")
            info.size = len(data)
            info.mtime = int(manifest["generated"])
            tar.addfile(info, io.BytesIO(data))
    ok = sum(1 for f in manifest["files"].values() if f.get("ok"))
    total = len(manifest["files"])
    partial = "" if ok == total else f" (PARTIAL: {ok}/{total} sections)"
    print(f"wrote {out_path}{partial}")
    if manifest["unreachable_instances"]:
        print(
            "unreachable instances: "
            + ", ".join(manifest["unreachable_instances"])
        )
    return 0


def cmd_metrics_ref(args):
    """Regenerate (or print) the METRICS.md metric-name reference."""
    from dgraph_tpu.utils import observe

    text = observe.metrics_reference()
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"wrote {args.out}")
    else:
        print(text, end="")
    return 0


def cmd_config_ref(args):
    """Regenerate (or print) the CONFIG.md env-var reference."""
    from dgraph_tpu.x import config

    text = config.reference_table()
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"wrote {args.out}")
    else:
        print(text, end="")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(prog="dgraph-tpu")
    ap.add_argument("--version", action="version", version="dgraph-tpu 0.1.0")
    sub = ap.add_subparsers(dest="cmd", required=True)

    def add_p(p):
        p.add_argument("-p", default=None, help="data directory (default: in-memory)")

    p = sub.add_parser("alpha", help="serve the HTTP API")
    p.add_argument(
        "--storage",
        default="",
        help='superflag: "backend=mem|lsm; encryption-key-file=...; memtable-mb=8"',
    )
    p.add_argument(
        "--cluster",
        default="",
        help='serve a sharded cluster: "groups=2; replicas=3; '
        'learners=0; replicated-zero=true"',
    )
    p.add_argument(
        "--trace",
        default="",
        help='superflag: "sink-file=...; ratio=0.01"',
    )
    p.add_argument(
        "--encryption_key_file",
        default=None,
        help="AES key file enabling at-rest value encryption",
    )
    p.add_argument(
        "--grpc_port",
        type=int,
        default=9080,
        help="api.Dgraph gRPC port (-1 disables; 0 = OS-assigned)",
    )
    add_p(p)
    p.add_argument("--bind", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--schema", default=None)
    p.add_argument("--acl-secret-file", default=None)
    p.add_argument("--audit-dir", default=None)
    p.add_argument("--cdc-file", default=None)
    p.add_argument("--rollup-interval", type=float, default=30.0)
    p.set_defaults(fn=cmd_alpha)

    p = sub.add_parser("bulk", help="offline bulk load")
    add_p(p)
    p.add_argument("--schema", default=None)
    p.add_argument(
        "--storage",
        default="",
        help='superflag: "backend=mem|lsm; encryption-key-file=..."',
    )
    p.add_argument("files", nargs="+")
    p.set_defaults(fn=cmd_bulk)

    p = sub.add_parser(
        "import", help="import an exported dataset (dgraphimport equivalent)"
    )
    p.add_argument("files", nargs="+", help="rdf/schema files or globs")
    p.add_argument("-p", default=None)
    p.add_argument("--schema", default=None)
    p.add_argument("--mode", choices=("bulk", "live"), default="bulk")
    p.add_argument("--batch", type=int, default=1000)
    p.set_defaults(fn=cmd_import)

    p = sub.add_parser("live", help="transactional load")
    add_p(p)
    p.add_argument("--schema", default=None)
    p.add_argument("--batch", type=int, default=1000)
    p.add_argument("files", nargs="+")
    p.set_defaults(fn=cmd_live)

    p = sub.add_parser("export")
    add_p(p)
    p.add_argument("--out", required=True)
    p.add_argument("--format", choices=["rdf", "json"], default="rdf")
    p.set_defaults(fn=cmd_export)

    p = sub.add_parser(
        "backup",
        help="manifest-chain backup of a data dir, or (--addr) a "
        "journaled online backup coordinated by a running alpha",
    )
    add_p(p)
    p.add_argument("--dest", required=True)
    p.add_argument("--full", action="store_true")
    p.add_argument(
        "--addr", default="",
        help="base URL of a running alpha (online backup of the live "
        "cluster it serves)",
    )
    p.set_defaults(fn=cmd_backup)

    p = sub.add_parser(
        "restore",
        help="restore a manifest chain into a data dir, or (--addr) "
        "online into a live cluster",
    )
    add_p(p)
    p.add_argument("--src", required=True)
    p.add_argument(
        "--addr", default="",
        help="base URL of a running alpha (online restore)",
    )
    p.set_defaults(fn=cmd_restore)

    p = sub.add_parser(
        "cdc",
        help="manage/tail the CDC stream of a running alpha "
        "(--sink enables, --disable stops, default probes status, "
        "--follow tails an ndjson sink file)",
    )
    p.add_argument(
        "--addr", default="http://127.0.0.1:8080",
        help="base URL of the alpha HTTP endpoint",
    )
    p.add_argument("--sink", default="", help="ndjson sink path to enable")
    p.add_argument("--disable", action="store_true")
    p.add_argument(
        "--follow", default="",
        help="tail this ndjson sink file instead of calling the alpha",
    )
    p.add_argument(
        "--once", action="store_true",
        help="with --follow: dump current contents and exit",
    )
    p.set_defaults(fn=cmd_cdc)

    p = sub.add_parser("acl")
    add_p(p)
    asub = p.add_subparsers(dest="acl_cmd", required=True)
    a = asub.add_parser("add-user")
    a.add_argument("--user", required=True)
    a.add_argument("--password", required=True)
    a = asub.add_parser("add-group")
    a.add_argument("--group", required=True)
    a = asub.add_parser("add-to-group")
    a.add_argument("--user", required=True)
    a.add_argument("--group", required=True)
    a = asub.add_parser("set-rule")
    a.add_argument("--group", required=True)
    a.add_argument("--predicate", required=True)
    a.add_argument("--perm", type=int, required=True)
    p.set_defaults(fn=cmd_acl)

    p = sub.add_parser("increment", help="counter smoke test")
    add_p(p)
    p.add_argument("--num", type=int, default=1)
    p.set_defaults(fn=cmd_increment)

    p = sub.add_parser("debug", help="inspect a data dir")
    add_p(p)
    p.set_defaults(fn=cmd_debug)

    p = sub.add_parser("cert", help="create/list TLS certificates")
    p.add_argument("--dir", default="tls")
    p.add_argument("--nodes", default="", help="comma-separated node CNs")
    p.add_argument("--client", default="")
    p.add_argument("--ls", action="store_true")
    p.set_defaults(fn=cmd_cert)

    p = sub.add_parser("conv", help="convert geojson/json to RDF")
    p.add_argument("--geo", default="")
    p.add_argument("--json", dest="json_file", default="")
    p.add_argument("--out", default="-")
    p.set_defaults(fn=cmd_conv)

    p = sub.add_parser("migrate", help="relational CSV dump -> RDF")
    p.add_argument("--tables", required=True,
                   help="name=path[,name=path...] CSV tables")
    p.add_argument("--out-rdf", default="migrated.rdf")
    p.add_argument("--out-schema", default="migrated.schema")
    p.set_defaults(fn=cmd_migrate)

    p = sub.add_parser("debuginfo", help="collect a support bundle")
    p.add_argument("-p", default=None)
    p.add_argument("--out", default=".")
    p.set_defaults(fn=cmd_debuginfo)

    p = sub.add_parser("upgrade", help="apply on-disk layout migrations")
    p.add_argument("-p", required=True)
    p.set_defaults(fn=cmd_upgrade)

    p = sub.add_parser(
        "decrypt", help="decrypt an encrypted export/backup file"
    )
    p.add_argument("-f", "--file", required=True)
    p.add_argument("-o", "--out", required=True)
    p.add_argument("--encryption-key-file", required=True)
    p.set_defaults(fn=cmd_decrypt)

    p = sub.add_parser("mcp", help="MCP server on stdio")
    add_p(p)
    p.set_defaults(fn=cmd_mcp)

    p = sub.add_parser(
        "lint",
        help="run the project-invariant static-analysis suite "
        "(exit 0 clean / 1 violations / 2 internal error)",
    )
    p.add_argument(
        "--json", action="store_true",
        help="machine-readable report on stdout",
    )
    p.add_argument(
        "--checker", action="append", default=None,
        help="run only this checker (repeatable); default: all",
    )
    p.set_defaults(fn=cmd_lint)

    p = sub.add_parser(
        "metrics",
        help="scrape + print the cluster-merged Prometheus metrics of "
        "a running alpha",
    )
    p.add_argument(
        "--addr", default="http://127.0.0.1:8080",
        help="base URL of the alpha HTTP endpoint",
    )
    p.add_argument(
        "--json", action="store_true",
        help="parsed {counters,gauges,histograms} JSON instead of text",
    )
    p.add_argument("--timeout", type=float, default=5.0)
    p.set_defaults(fn=cmd_metrics)

    p = sub.add_parser(
        "explain",
        help="EXPLAIN/ANALYZE a query: run with debug=true and render "
        "the plan tree",
    )
    p.add_argument("query", help="DQL query text ('-' reads stdin)")
    p.add_argument(
        "--addr", default="",
        help="base URL of a running alpha (default: run locally "
        "against -p / in-memory)",
    )
    add_p(p)
    p.add_argument(
        "--json", action="store_true",
        help="raw extensions.plan JSON instead of the rendered tree",
    )
    p.add_argument("--timeout", type=float, default=15.0)
    p.set_defaults(fn=cmd_explain)

    p = sub.add_parser(
        "health",
        help="scrape + print the cluster health/SLO rollup "
        "(/debug/healthz) of a running alpha",
    )
    p.add_argument(
        "--addr", default="http://127.0.0.1:8080",
        help="base URL of the alpha HTTP endpoint",
    )
    p.add_argument(
        "--json", action="store_true",
        help="raw healthz JSON instead of the rendered summary",
    )
    p.add_argument("--timeout", type=float, default=5.0)
    p.set_defaults(fn=cmd_health)

    p = sub.add_parser(
        "top",
        help="top query shapes by latency share (cluster-merged "
        "/debug/digests — pg_stat_statements for DQL)",
    )
    p.add_argument(
        "--addr", default="http://localhost:8080",
        help="base URL of a running alpha",
    )
    p.add_argument(
        "-n", type=int, default=20, help="rows to show (default 20)"
    )
    p.add_argument(
        "--watch", action="store_true",
        help="refresh in place until interrupted",
    )
    p.add_argument(
        "--interval", type=float, default=2.0,
        help="refresh interval with --watch (seconds)",
    )
    p.add_argument(
        "--json", action="store_true",
        help="raw digest JSON instead of the rendered table",
    )
    p.add_argument("--timeout", type=float, default=5.0)
    p.set_defaults(fn=cmd_top)

    p = sub.add_parser(
        "debug-bundle",
        help="capture metrics, digests, history, health, traces, "
        "slow-query log, lock graph, and resolved config into one "
        "tarball (partial bundle when instances are down)",
    )
    p.add_argument(
        "--addr", default="http://localhost:8080",
        help="base URL of a running alpha",
    )
    p.add_argument(
        "-o", "--out", default=None,
        help="output tarball path (default debug-bundle-<ts>.tar.gz)",
    )
    p.add_argument(
        "--window", type=float, default=600.0,
        help="history window to capture (seconds, default 600)",
    )
    p.add_argument("--timeout", type=float, default=10.0)
    p.set_defaults(fn=cmd_debug_bundle)

    p = sub.add_parser(
        "metrics-ref",
        help="print (or write) the generated metric-name reference "
        "(METRICS.md)",
    )
    p.add_argument("-o", "--out", default=None, help="write to this path")
    p.set_defaults(fn=cmd_metrics_ref)

    p = sub.add_parser(
        "config-ref",
        help="print (or write) the generated DGRAPH_TPU_* env reference",
    )
    p.add_argument("-o", "--out", default=None, help="write to this path")
    p.set_defaults(fn=cmd_config_ref)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())

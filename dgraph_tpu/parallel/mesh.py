"""Multi-device sharding: the distributed data plane.

The reference distributes work via predicate sharding + gRPC fan-out
(/root/reference/worker/groups.go tablet routing, conn/ transport). The
TPU-native equivalent (SURVEY.md §2.3): the *control* plane (membership,
tablet map, txn oracle) stays host-side, while the *data* plane — giant
posting lists and vector matrices — shards across TPU devices over a
jax.sharding.Mesh, with XLA collectives (psum / all_gather) riding ICI.

Axes:
  "data"  — row sharding: UID-pack tiles of one giant list ("sequence
            parallel" analog of the reference's multi-part list splits,
            posting/list.go:44 maxListSize), vector DB rows, k-means
            training batch.

All functions take an explicit Mesh and work on any device count,
including the virtual 8-device CPU mesh used by tests and the driver's
dryrun (xla_force_host_platform_device_count).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dgraph_tpu.ops import setops


def _resolve_shard_map():
    """The shard_map entry point across jax versions: `jax.shard_map`
    (0.5+, takes check_vma=) when present, else the experimental module
    (0.4.x, same semantics but the kwarg is check_rep=). Returns
    (callable, vma_supported)."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm, True
    from jax.experimental.shard_map import shard_map as sm_exp

    return sm_exp, False


_SHARD_MAP, _SHARD_MAP_VMA = _resolve_shard_map()


def shard_map_compat(f=None, *, mesh, in_specs, out_specs, check_vma=None):
    """Version-portable shard_map: maps the replication-check kwarg to
    whichever spelling the installed jax understands (check_vma on
    current jax, check_rep on 0.4.x) and omits it when unset. Usable
    exactly like jax.shard_map, including via functools.partial."""
    kw = {"mesh": mesh, "in_specs": in_specs, "out_specs": out_specs}
    if check_vma is not None:
        kw["check_vma" if _SHARD_MAP_VMA else "check_rep"] = check_vma
    if f is None:
        return partial(_SHARD_MAP, **kw)
    return _SHARD_MAP(f, **kw)


def make_mesh(n_devices: Optional[int] = None, axis: str = "data") -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    return Mesh(np.array(devs[:n]), (axis,))


# ---------------------------------------------------------------------------
# Distributed membership/intersect: a sharded by rows, b replicated.
# The giant-list analog of multi-part posting lists: each device holds a
# contiguous tile of `a`, checks membership against (replicated) `b`.
# ---------------------------------------------------------------------------


def sharded_membership(mesh: Mesh, a: jnp.ndarray, la, b: jnp.ndarray, lb):
    """mask over row-sharded `a` (padded multiple of n_devices)."""

    @partial(
        shard_map_compat,
        mesh=mesh,
        in_specs=(P("data"), P(), P(), P()),
        out_specs=P("data"),
    )
    def _member(a_tile, la_all, b_all, lb_all):
        n = a_tile.shape[0]
        didx = jax.lax.axis_index("data")
        start = didx * n
        # local validity window: index < la - start
        local_len = jnp.clip(la_all - start, 0, n)
        return setops.membership(a_tile, local_len, b_all, lb_all)

    return _member(a, jnp.asarray(la, jnp.int32), b, jnp.asarray(lb, jnp.int32))


def sharded_rows_membership(mesh: Mesh, A, LA, b, lb):
    """Membership of a replicated row batch in a ROW-SHARDED big list.

    A: (n, pa) replicated padded sorted u32 rows; LA: (n,) lengths;
    b: row-sharded padded sorted u32 (multiple of mesh size); lb: total
    valid length. Returns (n, pa) bool mask — element of A present in b.

    This is the query-side face of multi-part posting lists: each device
    holds a tile of the giant list (one or more parts), checks the whole
    level's rows against its tile, and the masks OR-reduce over ICI
    (psum>0). Ref worker/task.go fan-out replaced by one collective."""

    @partial(
        shard_map_compat,
        mesh=mesh,
        in_specs=(P(), P(), P("data"), P()),
        out_specs=P(),
    )
    def _member(A_all, LA_all, b_tile, lb_all):
        tile_n = b_tile.shape[0]
        start = jax.lax.axis_index("data") * tile_n
        local_len = jnp.clip(lb_all - start, 0, tile_n)
        m = jax.vmap(setops.membership, in_axes=(0, 0, None, None))(
            A_all, LA_all, b_tile, local_len
        )
        return jax.lax.psum(m.astype(jnp.int32), "data") > 0

    return _member(
        A, jnp.asarray(LA, jnp.int32), b, jnp.asarray(lb, jnp.int32)
    )


def sharded_intersect_count(mesh: Mesh, a, la, b, lb):
    """Total intersection size of a row-sharded list vs replicated list
    (psum over the mesh)."""

    @partial(
        shard_map_compat,
        mesh=mesh,
        in_specs=(P("data"), P(), P(), P()),
        out_specs=P(),
    )
    def _count(a_tile, la_all, b_all, lb_all):
        n = a_tile.shape[0]
        start = jax.lax.axis_index("data") * n
        local_len = jnp.clip(la_all - start, 0, n)
        m = setops.membership(a_tile, local_len, b_all, lb_all)
        return jax.lax.psum(jnp.sum(m, dtype=jnp.int32), "data")

    return _count(a, jnp.asarray(la, jnp.int32), b, jnp.asarray(lb, jnp.int32))


# ---------------------------------------------------------------------------
# Distributed vector search: V row-sharded, query replicated.
# Local top-k per shard -> all_gather -> global top-k. ("TP" over DB rows.)
# ---------------------------------------------------------------------------


def sharded_topk(mesh: Mesh, V: jnp.ndarray, valid: jnp.ndarray, q: jnp.ndarray, k: int):
    """Returns (global top-k squared-euclidean distances, global row ids)."""

    @partial(
        shard_map_compat,
        mesh=mesh,
        in_specs=(P("data"), P("data"), P()),
        out_specs=(P(), P()),
        # outputs are replicated post-all_gather; vma tracking can't see it
        check_vma=False,
    )
    def _topk(V_tile, valid_tile, q_all):
        rows = V_tile.shape[0]
        d2 = ((V_tile - q_all[None, :]) ** 2).sum(axis=1)
        d2 = jnp.where(valid_tile, d2, jnp.inf)
        kk = min(k, rows)
        neg, idx = jax.lax.top_k(-d2, kk)
        base = jax.lax.axis_index("data") * rows
        gidx = idx + base
        # gather every shard's candidates, then reduce to global top-k
        all_neg = jax.lax.all_gather(neg, "data")
        all_idx = jax.lax.all_gather(gidx, "data")
        flat_neg = all_neg.reshape(-1)
        flat_idx = all_idx.reshape(-1)
        gneg, sel = jax.lax.top_k(flat_neg, k)
        return -gneg, jnp.take(flat_idx, sel)

    return _topk(V, valid, q)


# ---------------------------------------------------------------------------
# Distributed IVF k-means training: THE training step.
# Data-parallel Lloyd iteration: local assign (matmul on MXU), local
# segment-sum, psum-all-reduce of (sums, counts), replicated update.
# ---------------------------------------------------------------------------


def sharded_kmeans_step(mesh: Mesh, X: jnp.ndarray, valid: jnp.ndarray, C: jnp.ndarray):
    """One Lloyd step. X row-sharded (n, d); C replicated (c, d).
    Returns updated replicated centroids."""
    nclusters = C.shape[0]

    @partial(
        shard_map_compat,
        mesh=mesh,
        in_specs=(P("data"), P("data"), P()),
        out_specs=P(),
    )
    def _step(X_tile, valid_tile, C_all):
        xsq = (X_tile * X_tile).sum(axis=1)
        csq = (C_all * C_all).sum(axis=1)
        d2 = xsq[:, None] - 2.0 * (X_tile @ C_all.T) + csq[None, :]
        assign = jnp.argmin(d2, axis=1)
        w = valid_tile.astype(X_tile.dtype)
        sums = jax.ops.segment_sum(
            X_tile * w[:, None], assign, num_segments=nclusters
        )
        cnts = jax.ops.segment_sum(w, assign, num_segments=nclusters)
        sums = jax.lax.psum(sums, "data")
        cnts = jax.lax.psum(cnts, "data")
        return jnp.where(
            cnts[:, None] > 0, sums / jnp.maximum(cnts, 1.0)[:, None], C_all
        )

    return _step(X, valid, C)


def sharded_ivf_train(
    mesh: Mesh, X: np.ndarray, nlist: int, iters: int = 10
) -> np.ndarray:
    """Full distributed k-means: shard rows over the mesh, iterate the
    jitted Lloyd step. Returns trained centroids (host numpy)."""
    n, d = X.shape
    ndev = mesh.devices.size
    pad = (-n) % ndev
    Xp = np.concatenate([X, np.zeros((pad, d), X.dtype)]) if pad else X
    valid = np.concatenate([np.ones((n,), bool), np.zeros((pad,), bool)])

    sh = NamedSharding(mesh, P("data"))
    rep = NamedSharding(mesh, P())
    Xd = jax.device_put(jnp.asarray(Xp), sh)
    Vd = jax.device_put(jnp.asarray(valid), sh)
    rng = np.random.default_rng(0)
    C = jax.device_put(
        jnp.asarray(X[rng.choice(n, min(nlist, n), replace=False)]), rep
    )
    step = jax.jit(
        lambda x, v, c: sharded_kmeans_step(mesh, x, v, c),
        out_shardings=rep,
    )
    for _ in range(iters):
        C = step(Xd, Vd, C)
    return np.asarray(C)

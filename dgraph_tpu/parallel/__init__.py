from dgraph_tpu.parallel.mesh import (
    make_mesh,
    sharded_kmeans_step,
    sharded_topk,
    sharded_membership,
    sharded_ivf_train,
)

"""Raft consensus: leader election + log replication + commit + snapshots.

The reference embeds etcd/raft (SURVEY.md §2.7(4)) and drives it from
worker/draft.go / conn/node.go. Consensus is host-side work, so this is a
from-scratch Python Raft sized for the framework's needs: elections with
randomized timeouts, AppendEntries replication with consistency checks and
backtracking, commit-index advancement by majority match, log compaction
with snapshot installation for lagging peers (snap_req, ref
worker/snapshot.go InstallSnapshot + raftwal deleteUntil), and durable
hardstate/log/snapshot via raft/wal.py (ref raftwal/storage.go:60) —
persisted BEFORE vote/append responses leave the node.

Transport is pluggable: InProcNetwork for deterministic tests (the
dgraphtest analog) and a TCP transport (raft/tcp.py) for multi-process
clusters.

Time is injected (tick(now_ms)) so tests run deterministically with
virtual clocks — no sleeps, no flaky elections.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"


@dataclass
class LogEntry:
    term: int
    data: Any


@dataclass
class Message:
    kind: str  # vote_req, vote_resp, append_req, append_resp, snap_req
    frm: int
    to: int
    term: int
    payload: dict = field(default_factory=dict)
    # W3C traceparent of the sender's ambient span, carried on the TCP
    # plane (conn/messages.RaftEnvelope.trace); "" for untraced traffic
    trace: str = ""


class InProcNetwork:
    """Deterministic in-process message bus with fault injection
    (the jepsen-nemesis analog for tests)."""

    def __init__(self):
        self.inboxes: Dict[int, List[Message]] = {}
        self.partitions: set = set()  # pairs (a, b) that cannot talk
        self.down: set = set()
        self.lock = threading.Lock()

    def register(self, node_id: int):
        self.inboxes[node_id] = []

    def send(self, msg: Message):
        with self.lock:
            if msg.to not in self.inboxes or msg.to in self.down or msg.frm in self.down:
                return
            if (msg.frm, msg.to) in self.partitions or (
                msg.to,
                msg.frm,
            ) in self.partitions:
                return
            self.inboxes[msg.to].append(msg)

    def drain(self, node_id: int) -> List[Message]:
        with self.lock:
            msgs = self.inboxes.get(node_id, [])
            self.inboxes[node_id] = []
            return msgs

    def partition(self, a: int, b: int):
        self.partitions.add((a, b))

    def heal(self):
        self.partitions.clear()
        self.down.clear()


class RaftNode:
    def __init__(
        self,
        node_id: int,
        peers: List[int],
        network,
        apply_cb: Callable[[int, Any], None],
        election_timeout: Tuple[int, int] = (150, 300),
        heartbeat: int = 50,
        seed: Optional[int] = None,
        wal=None,
        snapshot_cb: Optional[Callable[[], bytes]] = None,
        restore_cb: Optional[Callable[[bytes, int], None]] = None,
        compact_every: int = 0,
        learner: bool = False,
        learner_ids: Optional[set] = None,
    ):
        """wal: raft.wal.RaftWal for durability (None = volatile, test-only).
        snapshot_cb() -> bytes captures the applied state machine;
        restore_cb(data, index) replaces it (snapshot install).
        compact_every > 0: leader auto-snapshots/compacts once the entry
        window exceeds that many applied entries (draft.go
        calculateSnapshot analog).
        learner: non-voting member (etcd raft learners / the reference's
        --raft learner nodes): replicates and applies the log but never
        votes, campaigns, or counts toward commit quorum — cheap read
        replicas. learner_ids: the cluster-wide learner set (so voters
        exclude them from majority math)."""
        self.id = node_id
        self.peers = [p for p in peers if p != node_id]
        self.net = network
        self.apply_cb = apply_cb
        self.learner = learner
        self.learner_ids = set(learner_ids or ())
        self.rng = random.Random(seed if seed is not None else node_id)
        self.wal = wal
        self.snapshot_cb = snapshot_cb
        self.restore_cb = restore_cb
        self.compact_every = compact_every

        # persistent state (ref raftwal/): hardstate + entries + snapshot
        self.term = 0
        self.voted_for: Optional[int] = None
        # log window: log[i] is global index snap_index + 1 + i
        self.log: List[LogEntry] = []
        self.snap_index = 0
        self.snap_term = 0
        self.snapshot_data: Optional[bytes] = None

        # volatile
        self.state = FOLLOWER
        self.commit_index = 0  # global index of last committed entry
        self.last_applied = 0
        self.leader_id: Optional[int] = None

        # leader state
        self.next_index: Dict[int, int] = {}
        self.match_index: Dict[int, int] = {}

        self.heartbeat_ms = heartbeat
        self.election_lo, self.election_hi = election_timeout
        self._reset_election_deadline(0)
        self._last_heartbeat_sent = 0
        self.lock = threading.RLock()

        if wal is not None:
            self._recover_from_wal()

    # -- durability ----------------------------------------------------------

    def _recover_from_wal(self):
        hard = self.wal.load_hard()
        if hard is not None:
            self.term, self.voted_for, _, _ = hard
        si, st, entries = self.wal.replay_log()
        self.snap_index, self.snap_term = si, st
        self.log = [LogEntry(t, d) for t, d in entries]
        if si > 0:
            self.snapshot_data = self.wal.load_snapshot()
            if self.snapshot_data is not None and self.restore_cb is not None:
                self.restore_cb(self.snapshot_data, si)
            self.commit_index = si
            self.last_applied = si

    def _persist_hard(self):
        if self.wal is not None:
            self.wal.save_hard(
                self.term, self.voted_for, self.snap_index, self.snap_term
            )

    def _persist_append(self, entry: LogEntry):
        if self.wal is not None:
            self.wal.append_entry(entry.term, entry.data)

    def _persist_flush(self):
        if self.wal is not None:
            self.wal.flush()

    # -- index helpers (global <-> window) ------------------------------------

    def last_index(self) -> int:
        return self.snap_index + len(self.log)

    def term_at(self, idx: int) -> int:
        if idx == self.snap_index:
            return self.snap_term
        off = idx - self.snap_index - 1
        if 0 <= off < len(self.log):
            return self.log[off].term
        return 0

    def entry_at(self, idx: int) -> LogEntry:
        return self.log[idx - self.snap_index - 1]

    def last_log_term(self) -> int:
        return self.log[-1].term if self.log else self.snap_term

    # -- helpers -------------------------------------------------------------

    def _reset_election_deadline(self, now: int):
        self.election_deadline = now + self.rng.randint(
            self.election_lo, self.election_hi
        )

    def _become_follower(self, term: int, now: int):
        self.state = FOLLOWER
        self.term = term
        self.voted_for = None
        self._persist_hard()
        self._reset_election_deadline(now)

    # -- public API -----------------------------------------------------------

    def propose(self, data: Any) -> bool:
        """Append to the leader's log; returns False if not leader
        (ref worker/proposal.go proposeAndWait — waiting is done by the
        caller observing apply)."""
        with self.lock:
            if self.state != LEADER:
                return False
            e = LogEntry(self.term, data)
            self.log.append(e)
            self._persist_append(e)
            self._persist_flush()
            # remember the proposer's ambient trace context (set when a
            # traced RPC handler proposes): the next append broadcast
            # carries it on the wire so the replication hop of a traced
            # proposal stays attributable (RaftEnvelope.trace ->
            # follower-side raft_recv spans)
            from dgraph_tpu.utils.observe import TRACER

            tp = TRACER.current_traceparent()
            if tp:
                self._pending_trace = tp
            self.match_index[self.id] = self.last_index()
            if self._voting_size() == 1:
                # a single-voter group commits on its own match alone —
                # there are no append responses to drive _advance_commit
                # (multi-voter groups advance on responses; scanning the
                # uncommitted backlog per propose would be O(n^2) there)
                self._advance_commit()
            return True

    def is_leader(self) -> bool:
        return self.state == LEADER

    def tick(self, now: int):
        """Advance timers + process inbox. Call regularly (ref
        conn/node.go ticker + draft.go Run loop)."""
        with self.lock:
            for msg in self.net.drain(self.id):
                self._handle(msg, now)
            if self.state == LEADER:
                if now - self._last_heartbeat_sent >= self.heartbeat_ms:
                    self._broadcast_append(now)
            elif now >= self.election_deadline and not self.learner:
                self._start_election(now)
            self._apply_committed()
            if (
                self.compact_every
                and self.snapshot_cb is not None
                and self.last_applied - self.snap_index >= self.compact_every
            ):
                self.take_snapshot()

    def take_snapshot(self):
        """Snapshot the applied state machine and compact the log up to
        last_applied (ref worker/draft.go:1756 calculateSnapshot +
        raftwal deleteUntil)."""
        with self.lock:
            if self.snapshot_cb is None or self.last_applied <= self.snap_index:
                return
            data = self.snapshot_cb()
            idx = self.last_applied
            term = self.term_at(idx)
            drop = idx - self.snap_index
            self.log = self.log[drop:]
            self.snap_index, self.snap_term = idx, term
            self.snapshot_data = data
            if self.wal is not None:
                self.wal.save_snapshot(data)
                self.wal.rewrite_log(
                    idx, term, [(e.term, e.data) for e in self.log]
                )
                self._persist_hard()

    # -- election --------------------------------------------------------------

    def _start_election(self, now: int):
        self.state = CANDIDATE
        self.term += 1
        self.voted_for = self.id
        self.leader_id = None
        self._votes = {self.id}
        self._persist_hard()
        self._reset_election_deadline(now)
        for p in self.peers:
            self.net.send(
                Message(
                    "vote_req",
                    self.id,
                    p,
                    self.term,
                    {
                        "last_log_index": self.last_index(),
                        "last_log_term": self.last_log_term(),
                    },
                )
            )
        if not self.peers:
            self._become_leader(now)

    def _become_leader(self, now: int):
        self.state = LEADER
        self.leader_id = self.id
        self.next_index = {p: self.last_index() + 1 for p in self.peers}
        self.match_index = {p: 0 for p in self.peers}
        self.match_index[self.id] = self.last_index()
        # commit a no-op in the new term so prior-term entries commit
        # immediately (raft §8; etcd does the same on election)
        e = LogEntry(self.term, ("noop", None))
        self.log.append(e)
        self._persist_append(e)
        self._persist_flush()
        self.match_index[self.id] = self.last_index()
        self._broadcast_append(now)

    # -- replication -----------------------------------------------------------

    def _broadcast_append(self, now: int):
        self._last_heartbeat_sent = now
        for p in self.peers:
            self._send_append(p)
        self._pending_trace = ""  # carried on one broadcast round only

    def _send_append(self, p: int):
        ni = self.next_index.get(p, self.last_index() + 1)
        if ni <= self.snap_index:
            # the entries this follower needs were compacted away: install
            # the snapshot instead (worker/snapshot.go:177 streaming analog)
            if self.snapshot_data is not None:
                self.net.send(
                    Message(
                        "snap_req",
                        self.id,
                        p,
                        self.term,
                        {
                            "index": self.snap_index,
                            "snap_term": self.snap_term,
                            "data": self.snapshot_data,
                        },
                    )
                )
            return
        prev_idx = ni - 1
        prev_term = self.term_at(prev_idx)
        off = ni - self.snap_index - 1
        entries = [(e.term, e.data) for e in self.log[off:]]
        self.net.send(
            Message(
                "append_req",
                self.id,
                p,
                self.term,
                {
                    "prev_idx": prev_idx,
                    "prev_term": prev_term,
                    "entries": entries,
                    "leader_commit": self.commit_index,
                },
                trace=getattr(self, "_pending_trace", "") if entries
                else "",
            )
        )

    # -- message handling -------------------------------------------------------

    def _handle(self, m: Message, now: int):
        if m.term > self.term:
            self._become_follower(m.term, now)
        if m.kind == "vote_req":
            self._on_vote_req(m, now)
        elif m.kind == "vote_resp":
            self._on_vote_resp(m, now)
        elif m.kind == "append_req":
            self._on_append_req(m, now)
        elif m.kind == "append_resp":
            self._on_append_resp(m, now)
        elif m.kind == "snap_req":
            self._on_snap_req(m, now)

    def _on_vote_req(self, m: Message, now: int):
        grant = False
        if self.learner or m.frm in self.learner_ids:
            # learners neither vote nor get elected
            self.net.send(
                Message(
                    "vote_resp", self.id, m.frm, self.term, {"granted": False}
                )
            )
            return
        if m.term >= self.term and self.voted_for in (None, m.frm):
            # up-to-date check (§5.4.1)
            llt, lli = self.last_log_term(), self.last_index()
            if (m.payload["last_log_term"], m.payload["last_log_index"]) >= (
                llt,
                lli,
            ):
                grant = True
                self.voted_for = m.frm
                self._persist_hard()  # durable BEFORE the response leaves
                self._reset_election_deadline(now)
        self.net.send(
            Message("vote_resp", self.id, m.frm, self.term, {"granted": grant})
        )

    def _voting_size(self) -> int:
        voters = {self.id, *self.peers} - self.learner_ids
        return len(voters)

    def _on_vote_resp(self, m: Message, now: int):
        if self.state != CANDIDATE or m.term != self.term:
            return
        if m.payload["granted"]:
            self._votes.add(m.frm)
            if len(self._votes - self.learner_ids) * 2 > self._voting_size():
                self._become_leader(now)

    def _on_append_req(self, m: Message, now: int):
        ok = False
        if m.term >= self.term:
            if m.term == self.term and self.state == CANDIDATE:
                self._become_follower(m.term, now)
            self.state = FOLLOWER
            self.leader_id = m.frm
            self._reset_election_deadline(now)
            prev_idx = m.payload["prev_idx"]
            prev_term = m.payload["prev_term"]
            if prev_idx < self.snap_index:
                # everything at/below our snapshot is already committed;
                # only accept the suffix beyond it
                skip = self.snap_index - prev_idx
                if len(m.payload["entries"]) >= skip:
                    m.payload["entries"] = m.payload["entries"][skip:]
                    prev_idx = self.snap_index
                    prev_term = self.snap_term
                    m.payload["prev_idx"] = prev_idx
                    m.payload["prev_term"] = prev_term
                else:
                    prev_idx = -1  # stale heartbeat below snapshot: ignore
            if prev_idx >= 0 and (
                prev_idx == 0
                or (
                    prev_idx <= self.last_index()
                    and self.term_at(prev_idx) == prev_term
                )
            ):
                ok = True
                # append, truncating conflicts (§5.3)
                idx = prev_idx  # global index of the last matching entry
                dirty = False
                for term, data in m.payload["entries"]:
                    off = idx - self.snap_index
                    if off < len(self.log):
                        if self.log[off].term != term:
                            del self.log[off:]
                            if self.wal is not None:
                                self.wal.truncate_from(idx + 1)
                            e = LogEntry(term, data)
                            self.log.append(e)
                            self._persist_append(e)
                            dirty = True
                    else:
                        e = LogEntry(term, data)
                        self.log.append(e)
                        self._persist_append(e)
                        dirty = True
                    idx += 1
                if dirty:
                    self._persist_flush()  # durable BEFORE the ack
                lc = m.payload["leader_commit"]
                if lc > self.commit_index:
                    self.commit_index = min(lc, self.last_index())
        self.net.send(
            Message(
                "append_resp",
                self.id,
                m.frm,
                self.term,
                {"ok": ok, "match": self.last_index() if ok else 0,
                 "hint": self.last_index()},
            )
        )

    def _on_snap_req(self, m: Message, now: int):
        """Install a leader snapshot (lagging/fresh replica catch-up)."""
        if m.term < self.term:
            return
        self.state = FOLLOWER
        self.leader_id = m.frm
        self._reset_election_deadline(now)
        idx, sterm = m.payload["index"], m.payload["snap_term"]
        if idx <= self.snap_index:
            pass  # already have it
        else:
            data = m.payload["data"]
            if self.restore_cb is not None:
                self.restore_cb(data, idx)
            self.snapshot_data = data
            self.log = []
            self.snap_index, self.snap_term = idx, sterm
            self.commit_index = max(self.commit_index, idx)
            self.last_applied = max(self.last_applied, idx)
            if self.wal is not None:
                self.wal.save_snapshot(data)
                self.wal.rewrite_log(idx, sterm, [])
                self._persist_hard()
        self.net.send(
            Message(
                "append_resp",
                self.id,
                m.frm,
                self.term,
                {"ok": True, "match": self.snap_index, "hint": self.last_index()},
            )
        )

    def _on_append_resp(self, m: Message, now: int):
        if self.state != LEADER or m.term != self.term:
            return
        p = m.frm
        if m.payload["ok"]:
            self.match_index[p] = max(self.match_index.get(p, 0), m.payload["match"])
            self.next_index[p] = self.match_index[p] + 1
            self._advance_commit()
        else:
            # backtrack (fast, using follower's log-length hint)
            self.next_index[p] = max(
                1, min(self.next_index.get(p, 1) - 1, m.payload["hint"] + 1)
            )
            self._send_append(p)

    def _advance_commit(self):
        # majority over VOTING members only (learners replicate but never
        # count toward quorum)
        n = self._voting_size()
        for idx in range(self.last_index(), self.commit_index, -1):
            votes = sum(
                1
                for nid, mi in self.match_index.items()
                if mi >= idx and nid not in self.learner_ids
            )
            if votes * 2 > n and self.term_at(idx) == self.term:
                self.commit_index = idx
                break

    def _apply_committed(self):
        while self.last_applied < self.commit_index:
            self.last_applied += 1
            data = self.entry_at(self.last_applied).data
            # the leader's term-start no-op is raft bookkeeping, not state
            if (
                isinstance(data, (tuple, list))
                and len(data) == 2
                and data[0] == "noop"
            ):
                continue
            self.apply_cb(self.last_applied, data)


class RaftCluster:
    """Test/embedding helper: a set of nodes + virtual time pump."""

    def __init__(self, n: int, apply_cbs=None, seed: int = 0, **node_kwargs):
        self.net = InProcNetwork()
        ids = list(range(1, n + 1))
        self.nodes: Dict[int, RaftNode] = {}
        self.applied: Dict[int, List[Any]] = {i: [] for i in ids}
        for i in ids:
            self.net.register(i)
            cb = (
                apply_cbs[i - 1]
                if apply_cbs
                else (lambda idx, d, _i=i: self.applied[_i].append(d))
            )
            self.nodes[i] = RaftNode(
                i, ids, self.net, cb, seed=seed * 100 + i, **node_kwargs
            )
        self.now = 0

    def pump(self, ms: int = 10, times: int = 1):
        for _ in range(times):
            self.now += ms
            for nd in self.nodes.values():
                if nd.id not in self.net.down:
                    nd.tick(self.now)

    def run_until(self, cond, max_ms: int = 20_000, step: int = 10) -> bool:
        waited = 0
        while waited < max_ms:
            if cond():
                return True
            self.pump(step)
            waited += step
        return cond()

    def leader(self) -> Optional[RaftNode]:
        up = [
            nd
            for nd in self.nodes.values()
            if nd.state == LEADER and nd.id not in self.net.down
        ]
        if not up:
            return None
        # highest term wins (stale leaders may linger in partitions)
        return max(up, key=lambda nd: nd.term)

    def elect(self) -> RaftNode:
        assert self.run_until(lambda: self.leader() is not None)
        return self.leader()

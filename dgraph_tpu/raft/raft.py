"""Raft consensus: leader election + log replication + commit.

The reference embeds etcd/raft (SURVEY.md §2.7(4)) and drives it from
worker/draft.go / conn/node.go. Consensus is host-side work, so this is a
from-scratch Python Raft sized for the framework's needs: elections with
randomized timeouts, AppendEntries replication with consistency checks and
backtracking, commit-index advancement by majority match, and snapshot
installation for lagging peers. Transport is pluggable: InProcNetwork for
deterministic tests (the dgraphtest analog) and a TCP transport
(raft/tcp.py) for multi-process clusters.

Time is injected (tick(now_ms)) so tests run deterministically with
virtual clocks — no sleeps, no flaky elections.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"


@dataclass
class LogEntry:
    term: int
    data: Any


@dataclass
class Message:
    kind: str  # vote_req, vote_resp, append_req, append_resp, snap_req
    frm: int
    to: int
    term: int
    payload: dict = field(default_factory=dict)


class InProcNetwork:
    """Deterministic in-process message bus with fault injection
    (the jepsen-nemesis analog for tests)."""

    def __init__(self):
        self.inboxes: Dict[int, List[Message]] = {}
        self.partitions: set = set()  # pairs (a, b) that cannot talk
        self.down: set = set()
        self.lock = threading.Lock()

    def register(self, node_id: int):
        self.inboxes[node_id] = []

    def send(self, msg: Message):
        with self.lock:
            if msg.to not in self.inboxes or msg.to in self.down or msg.frm in self.down:
                return
            if (msg.frm, msg.to) in self.partitions or (
                msg.to,
                msg.frm,
            ) in self.partitions:
                return
            self.inboxes[msg.to].append(msg)

    def drain(self, node_id: int) -> List[Message]:
        with self.lock:
            msgs = self.inboxes.get(node_id, [])
            self.inboxes[node_id] = []
            return msgs

    def partition(self, a: int, b: int):
        self.partitions.add((a, b))

    def heal(self):
        self.partitions.clear()
        self.down.clear()


class RaftNode:
    def __init__(
        self,
        node_id: int,
        peers: List[int],
        network,
        apply_cb: Callable[[int, Any], None],
        election_timeout: Tuple[int, int] = (150, 300),
        heartbeat: int = 50,
        seed: Optional[int] = None,
    ):
        self.id = node_id
        self.peers = [p for p in peers if p != node_id]
        self.net = network
        self.apply_cb = apply_cb
        self.rng = random.Random(seed if seed is not None else node_id)

        # persistent state (ref raftwal/: hardstate + entries; in-mem here,
        # durability via the engine's own WAL above)
        self.term = 0
        self.voted_for: Optional[int] = None
        self.log: List[LogEntry] = []

        # volatile
        self.state = FOLLOWER
        self.commit_index = 0  # 1-based count of committed entries
        self.last_applied = 0
        self.leader_id: Optional[int] = None

        # leader state
        self.next_index: Dict[int, int] = {}
        self.match_index: Dict[int, int] = {}

        self.heartbeat_ms = heartbeat
        self.election_lo, self.election_hi = election_timeout
        self._reset_election_deadline(0)
        self._last_heartbeat_sent = 0
        self.lock = threading.RLock()

    # -- helpers -------------------------------------------------------------

    def _reset_election_deadline(self, now: int):
        self.election_deadline = now + self.rng.randint(
            self.election_lo, self.election_hi
        )

    def last_log_term(self) -> int:
        return self.log[-1].term if self.log else 0

    def _become_follower(self, term: int, now: int):
        self.state = FOLLOWER
        self.term = term
        self.voted_for = None
        self._reset_election_deadline(now)

    # -- public API -----------------------------------------------------------

    def propose(self, data: Any) -> bool:
        """Append to the leader's log; returns False if not leader
        (ref worker/proposal.go proposeAndWait — waiting is done by the
        caller observing apply)."""
        with self.lock:
            if self.state != LEADER:
                return False
            self.log.append(LogEntry(self.term, data))
            self.match_index[self.id] = len(self.log)
            return True

    def is_leader(self) -> bool:
        return self.state == LEADER

    def tick(self, now: int):
        """Advance timers + process inbox. Call regularly (ref
        conn/node.go ticker + draft.go Run loop)."""
        with self.lock:
            for msg in self.net.drain(self.id):
                self._handle(msg, now)
            if self.state == LEADER:
                if now - self._last_heartbeat_sent >= self.heartbeat_ms:
                    self._broadcast_append(now)
            elif now >= self.election_deadline:
                self._start_election(now)
            self._apply_committed()

    # -- election --------------------------------------------------------------

    def _start_election(self, now: int):
        self.state = CANDIDATE
        self.term += 1
        self.voted_for = self.id
        self.leader_id = None
        self._votes = {self.id}
        self._reset_election_deadline(now)
        for p in self.peers:
            self.net.send(
                Message(
                    "vote_req",
                    self.id,
                    p,
                    self.term,
                    {
                        "last_log_index": len(self.log),
                        "last_log_term": self.last_log_term(),
                    },
                )
            )
        if not self.peers:
            self._become_leader(now)

    def _become_leader(self, now: int):
        self.state = LEADER
        self.leader_id = self.id
        self.next_index = {p: len(self.log) + 1 for p in self.peers}
        self.match_index = {p: 0 for p in self.peers}
        self.match_index[self.id] = len(self.log)
        self._broadcast_append(now)

    # -- replication -----------------------------------------------------------

    def _broadcast_append(self, now: int):
        self._last_heartbeat_sent = now
        for p in self.peers:
            self._send_append(p)

    def _send_append(self, p: int):
        ni = self.next_index.get(p, len(self.log) + 1)
        prev_idx = ni - 1
        prev_term = self.log[prev_idx - 1].term if prev_idx >= 1 and prev_idx <= len(self.log) else 0
        entries = [(e.term, e.data) for e in self.log[prev_idx:]]
        self.net.send(
            Message(
                "append_req",
                self.id,
                p,
                self.term,
                {
                    "prev_idx": prev_idx,
                    "prev_term": prev_term,
                    "entries": entries,
                    "leader_commit": self.commit_index,
                },
            )
        )

    # -- message handling -------------------------------------------------------

    def _handle(self, m: Message, now: int):
        if m.term > self.term:
            self._become_follower(m.term, now)
        if m.kind == "vote_req":
            self._on_vote_req(m, now)
        elif m.kind == "vote_resp":
            self._on_vote_resp(m, now)
        elif m.kind == "append_req":
            self._on_append_req(m, now)
        elif m.kind == "append_resp":
            self._on_append_resp(m, now)

    def _on_vote_req(self, m: Message, now: int):
        grant = False
        if m.term >= self.term and self.voted_for in (None, m.frm):
            # up-to-date check (§5.4.1)
            llt, lli = self.last_log_term(), len(self.log)
            if (m.payload["last_log_term"], m.payload["last_log_index"]) >= (
                llt,
                lli,
            ):
                grant = True
                self.voted_for = m.frm
                self._reset_election_deadline(now)
        self.net.send(
            Message("vote_resp", self.id, m.frm, self.term, {"granted": grant})
        )

    def _on_vote_resp(self, m: Message, now: int):
        if self.state != CANDIDATE or m.term != self.term:
            return
        if m.payload["granted"]:
            self._votes.add(m.frm)
            if len(self._votes) * 2 > len(self.peers) + 1:
                self._become_leader(now)

    def _on_append_req(self, m: Message, now: int):
        ok = False
        if m.term >= self.term:
            if m.term == self.term and self.state == CANDIDATE:
                self._become_follower(m.term, now)
            self.state = FOLLOWER
            self.leader_id = m.frm
            self._reset_election_deadline(now)
            prev_idx = m.payload["prev_idx"]
            prev_term = m.payload["prev_term"]
            if prev_idx == 0 or (
                prev_idx <= len(self.log)
                and self.log[prev_idx - 1].term == prev_term
            ):
                ok = True
                # append, truncating conflicts (§5.3)
                idx = prev_idx
                for term, data in m.payload["entries"]:
                    if idx < len(self.log):
                        if self.log[idx].term != term:
                            del self.log[idx:]
                            self.log.append(LogEntry(term, data))
                    else:
                        self.log.append(LogEntry(term, data))
                    idx += 1
                lc = m.payload["leader_commit"]
                if lc > self.commit_index:
                    self.commit_index = min(lc, len(self.log))
        self.net.send(
            Message(
                "append_resp",
                self.id,
                m.frm,
                self.term,
                {"ok": ok, "match": len(self.log) if ok else 0,
                 "hint": len(self.log)},
            )
        )

    def _on_append_resp(self, m: Message, now: int):
        if self.state != LEADER or m.term != self.term:
            return
        p = m.frm
        if m.payload["ok"]:
            self.match_index[p] = max(self.match_index.get(p, 0), m.payload["match"])
            self.next_index[p] = self.match_index[p] + 1
            self._advance_commit()
        else:
            # backtrack (fast, using follower's log-length hint)
            self.next_index[p] = max(
                1, min(self.next_index.get(p, 1) - 1, m.payload["hint"] + 1)
            )
            self._send_append(p)

    def _advance_commit(self):
        n = len(self.peers) + 1
        for idx in range(len(self.log), self.commit_index, -1):
            votes = sum(
                1 for mi in self.match_index.values() if mi >= idx
            )
            if votes * 2 > n and self.log[idx - 1].term == self.term:
                self.commit_index = idx
                break

    def _apply_committed(self):
        while self.last_applied < self.commit_index:
            self.last_applied += 1
            self.apply_cb(self.last_applied, self.log[self.last_applied - 1].data)


class RaftCluster:
    """Test/embedding helper: a set of nodes + virtual time pump."""

    def __init__(self, n: int, apply_cbs=None, seed: int = 0):
        self.net = InProcNetwork()
        ids = list(range(1, n + 1))
        self.nodes: Dict[int, RaftNode] = {}
        self.applied: Dict[int, List[Any]] = {i: [] for i in ids}
        for i in ids:
            self.net.register(i)
            cb = (
                apply_cbs[i - 1]
                if apply_cbs
                else (lambda idx, d, _i=i: self.applied[_i].append(d))
            )
            self.nodes[i] = RaftNode(i, ids, self.net, cb, seed=seed * 100 + i)
        self.now = 0

    def pump(self, ms: int = 10, times: int = 1):
        for _ in range(times):
            self.now += ms
            for nd in self.nodes.values():
                if nd.id not in self.net.down:
                    nd.tick(self.now)

    def run_until(self, cond, max_ms: int = 20_000, step: int = 10) -> bool:
        waited = 0
        while waited < max_ms:
            if cond():
                return True
            self.pump(step)
            waited += step
        return cond()

    def leader(self) -> Optional[RaftNode]:
        up = [
            nd
            for nd in self.nodes.values()
            if nd.state == LEADER and nd.id not in self.net.down
        ]
        if not up:
            return None
        # highest term wins (stale leaders may linger in partitions)
        return max(up, key=lambda nd: nd.term)

    def elect(self) -> RaftNode:
        assert self.run_until(lambda: self.leader() is not None)
        return self.leader()

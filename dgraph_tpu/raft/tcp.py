"""TCP transport for Raft: the cross-host network seam.

The reference's conn/node.go batches raft messages onto long-lived gRPC
streams between peers (BatchAndSendMessages:338, streamMessages:398). This
is the socket equivalent for dgraph-tpu: one listener per node, persistent
outbound connections per peer with automatic reconnect, length-prefixed
frames via conn/frame.py — small control messages stay JSON; bulk
payloads (snapshot installs, big append batches) ride as raw
zlib-compressed blobs (the snappy framing of conn/snappy.go +
worker/snapshot.go:177). Implements the same send/drain interface as
InProcNetwork, so RaftNode is unchanged.
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

from dgraph_tpu.conn import faults
from dgraph_tpu.conn.frame import MAX_FRAME, pack_body, unpack_body
from dgraph_tpu.conn.messages import RaftEnvelope
from dgraph_tpu.raft.raft import Message
from dgraph_tpu.utils.observe import TRACER, parse_traceparent

_LEN = struct.Struct(">I")


class TcpNetwork:
    """Per-process endpoint: local inboxes + outbound peer connections."""

    def __init__(self, peers: Dict[int, Tuple[str, int]]):
        """peers: node_id -> (host, port) for every cluster member."""
        self.peers = peers
        self.inboxes: Dict[int, List[Message]] = {}
        self.lock = threading.Lock()
        self._conns: Dict[int, socket.socket] = {}
        # serializes connect + sendall per peer: frames must not interleave
        # when several locally-hosted nodes write to the same remote socket
        self._send_locks: Dict[int, threading.Lock] = {}
        self._servers: List[socketserver.ThreadingTCPServer] = []
        self.down: set = set()  # local fault injection parity
        self._drop_logged: set = set()

    # -- server side ---------------------------------------------------------

    def register(self, node_id: int):
        """Start listening for this (locally hosted) node."""
        self.inboxes[node_id] = []
        host, port = self.peers[node_id]
        net = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                while True:
                    hdr = self.rfile.read(_LEN.size)
                    if len(hdr) < _LEN.size:
                        return
                    (n,) = _LEN.unpack(hdr)
                    if n > MAX_FRAME:
                        return  # corrupt length header: drop the conn
                    body = self.rfile.read(n)
                    if len(body) < n:
                        return
                    try:
                        env = RaftEnvelope.decode(body)
                        msg = Message(
                            kind=env.kind, frm=env.frm, to=env.to,
                            term=env.term,
                            payload=unpack_body(env.payload)
                            if env.payload
                            else {},
                            trace=env.trace,
                        )
                    except (ValueError, KeyError, TypeError):
                        continue
                    plan = faults.active()
                    if plan is not None:
                        act = plan.decide("raft_recv", str(msg.frm), msg.kind)
                        if act is not None:
                            if act.action in ("drop", "partition"):
                                continue
                            if act.action == "disconnect":
                                return
                            if act.action == "delay":
                                time.sleep(act.delay_s)
                    if msg.trace:
                        # a traced proposal's replication hop: join the
                        # proposer's trace so the follower-side arrival
                        # is attributable in the merged view
                        ctx = parse_traceparent(msg.trace)
                        if ctx is not None:
                            with TRACER.span(
                                "raft_recv", parent=ctx, kind=msg.kind,
                                frm=msg.frm, to=msg.to,
                            ):
                                pass
                    with net.lock:
                        if msg.to in net.inboxes:
                            net.inboxes[msg.to].append(msg)

        class _Server(socketserver.ThreadingTCPServer):
            # must be set on the class: the constructor binds immediately,
            # and a restarting node must rebind through TIME_WAIT
            allow_reuse_address = True
            daemon_threads = True

        srv = _Server((host, port), Handler)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        self._servers.append(srv)
        # update port if OS-assigned (port=0)
        self.peers[node_id] = srv.server_address[:2]

    # -- client side ---------------------------------------------------------

    def _conn_to(self, node_id: int) -> Optional[socket.socket]:
        s = self._conns.get(node_id)
        if s is not None:
            return s
        try:
            s = socket.create_connection(self.peers[node_id], timeout=1.0)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._conns[node_id] = s
            return s
        except OSError:
            return None

    def send(self, msg: Message):
        if msg.frm in self.down or msg.to in self.down:
            return
        if msg.to in self.inboxes:  # local fast path
            with self.lock:
                self.inboxes[msg.to].append(msg)
            return
        act = None
        plan = faults.active()
        if plan is not None:
            act = plan.decide("raft_send", str(msg.to), msg.kind)
            if act is not None:
                if act.action in ("drop", "partition"):
                    return  # lost on the wire: raft retries via timeouts
                if act.action == "disconnect":
                    with self.lock:
                        s = self._conns.pop(msg.to, None)
                    if s is not None:
                        try:
                            s.close()
                        except OSError:
                            pass
                    return
                if act.action == "delay":
                    time.sleep(act.delay_s)
        try:
            body = RaftEnvelope(
                kind=msg.kind, frm=msg.frm, to=msg.to, term=msg.term,
                payload=pack_body(msg.payload) if msg.payload else b"",
                # the proposer's trace context (RaftNode stamps it on
                # the append broadcast that replicates a traced
                # proposal; "" on the untraced tick/heartbeat plane) —
                # msg.trace is the ONLY stamping point: sends happen on
                # the tick thread, so any ambient context here would
                # belong to an unrelated trace
                trace=msg.trace,
            ).encode()
            frame = _LEN.pack(len(body)) + body
        except (TypeError, ValueError):
            # an unserializable payload must never kill the tick thread —
            # but a silent drop would retry forever, so log once per type
            tname = type(msg.payload).__name__
            if tname not in self._drop_logged:
                self._drop_logged.add(tname)
                import logging

                logging.getLogger("dgraph_tpu.raft.tcp").error(
                    "dropping unserializable raft payload (%s) — "
                    "these messages can never succeed", tname,
                )
            return
        with self.lock:
            plock = self._send_locks.setdefault(msg.to, threading.Lock())
        with plock:
            s = self._conn_to(msg.to)
            if s is None:
                return  # peer unreachable: raft retries via timeouts
            try:
                s.sendall(frame)
                if act is not None and act.action == "dup":
                    s.sendall(frame)  # duplicate delivery
            except OSError:
                self._conns.pop(msg.to, None)
                try:
                    s.close()
                except OSError:
                    pass

    def drain(self, node_id: int) -> List[Message]:
        with self.lock:
            msgs = self.inboxes.get(node_id, [])
            self.inboxes[node_id] = []
            return msgs

    def close(self):
        for srv in self._servers:
            srv.shutdown()
            srv.server_close()
        with self.lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for s in conns:
            try:
                s.close()
            except OSError:
                pass

from dgraph_tpu.raft.raft import RaftNode, InProcNetwork

"""Durable Raft state: hardstate + log + snapshot files per node.

Mirrors the contract of /root/reference/raftwal/storage.go:60 (DiskStorage:
HardState, entries, snapshot) without the badger backing: three files in a
per-node directory —

  hard.state  — (term, voted_for, snap_index, snap_term), atomic rewrite
  log.wal     — append-only records: APPEND(term, payload) | TRUNC(index)
                | COMPACT(snap_index, snap_term); replay reconstructs the
                in-memory entry window
  snap.bin    — latest snapshot payload, atomic replace

Raft safety requires hardstate + appended entries be on disk BEFORE a
vote/append response leaves the node (raft paper §5; the reference fsyncs
via badger WAL). `sync=True` fsyncs on every flush and is the production
default (alpha_process/zero_process cfg `wal_sync`, default True); tests
run sync=False (flush-only) for speed — that model survives process
crashes (data is in the OS page cache) but NOT power loss / kernel
panics. Either way the ordering is crash-consistent: a torn tail is
truncated at replay.
"""

from __future__ import annotations

import os
import pickle
import struct
from typing import Any, List, Optional, Tuple

_REC = struct.Struct("<BI")  # kind, payload_len
_K_APPEND = 1
_K_TRUNC = 2
_K_COMPACT = 3


class RaftWal:
    def __init__(self, dirpath: str, sync: bool = False):
        self.dir = dirpath
        self.sync = sync
        os.makedirs(dirpath, exist_ok=True)
        self._hard_path = os.path.join(dirpath, "hard.state")
        self._log_path = os.path.join(dirpath, "log.wal")
        self._snap_path = os.path.join(dirpath, "snap.bin")
        self._log_f = None

    # -- hardstate -----------------------------------------------------------

    def save_hard(self, term: int, voted_for: Optional[int], snap_index: int, snap_term: int):
        blob = pickle.dumps((term, voted_for, snap_index, snap_term))
        tmp = self._hard_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            if self.sync:
                os.fsync(f.fileno())
        os.replace(tmp, self._hard_path)

    def load_hard(self) -> Optional[Tuple[int, Optional[int], int, int]]:
        if not os.path.exists(self._hard_path):
            return None
        try:
            with open(self._hard_path, "rb") as f:
                return pickle.loads(f.read())
        except Exception:
            return None

    # -- log -----------------------------------------------------------------

    def _log_file(self):
        if self._log_f is None:
            self._log_f = open(self._log_path, "ab")
        return self._log_f

    def _append_rec(self, kind: int, payload: bytes):
        f = self._log_file()
        f.write(_REC.pack(kind, len(payload)))
        f.write(payload)

    def append_entry(self, term: int, data: Any):
        self._append_rec(_K_APPEND, pickle.dumps((term, data)))

    def truncate_from(self, index: int):
        """Entries at global index >= `index` are discarded (conflict)."""
        self._append_rec(_K_TRUNC, pickle.dumps(index))

    def compact(self, snap_index: int, snap_term: int):
        self._append_rec(_K_COMPACT, pickle.dumps((snap_index, snap_term)))

    def flush(self):
        if self._log_f is not None:
            self._log_f.flush()
            if self.sync:
                os.fsync(self._log_f.fileno())

    def replay_log(self) -> Tuple[int, int, List[Tuple[int, Any]]]:
        """Returns (snap_index, snap_term, entries) where entries[i] is the
        record at global index snap_index + 1 + i."""
        snap_index = snap_term = 0
        entries: List[Tuple[int, Any]] = []
        if not os.path.exists(self._log_path):
            return snap_index, snap_term, entries
        with open(self._log_path, "rb") as f:
            data = f.read()
        pos, n = 0, len(data)
        valid = 0
        while pos + _REC.size <= n:
            kind, plen = _REC.unpack_from(data, pos)
            if pos + _REC.size + plen > n or kind not in (
                _K_APPEND,
                _K_TRUNC,
                _K_COMPACT,
            ):
                break  # torn tail
            payload = data[pos + _REC.size : pos + _REC.size + plen]
            try:
                obj = pickle.loads(payload)
            except Exception:
                break
            pos += _REC.size + plen
            valid = pos
            if kind == _K_APPEND:
                entries.append(obj)
            elif kind == _K_TRUNC:
                idx = obj
                keep = idx - snap_index - 1
                del entries[max(0, keep):]
            else:
                new_si, new_st = obj
                drop = new_si - snap_index
                del entries[:max(0, drop)]
                snap_index, snap_term = new_si, new_st
        if valid < n:
            with open(self._log_path, "r+b") as f:
                f.truncate(valid)
        return snap_index, snap_term, entries

    def rewrite_log(self, snap_index: int, snap_term: int, entries: List[Tuple[int, Any]]):
        """Compaction housekeeping: rewrite the log file to just the live
        window so it stops growing (ref raftwal deleteUntil)."""
        if self._log_f is not None:
            self._log_f.close()
            self._log_f = None
        tmp = self._log_path + ".tmp"
        with open(tmp, "wb") as f:
            blob = pickle.dumps((snap_index, snap_term))
            f.write(_REC.pack(_K_COMPACT, len(blob)))
            f.write(blob)
            for term, data in entries:
                b = pickle.dumps((term, data))
                f.write(_REC.pack(_K_APPEND, len(b)))
                f.write(b)
            f.flush()
            if self.sync:
                os.fsync(f.fileno())
        os.replace(tmp, self._log_path)

    # -- snapshot --------------------------------------------------------------

    def save_snapshot(self, data: bytes):
        tmp = self._snap_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            if self.sync:
                os.fsync(f.fileno())
        os.replace(tmp, self._snap_path)

    def load_snapshot(self) -> Optional[bytes]:
        if not os.path.exists(self._snap_path):
            return None
        with open(self._snap_path, "rb") as f:
            return f.read()

    def close(self):
        if self._log_f is not None:
            self.flush()
            self._log_f.close()
            self._log_f = None

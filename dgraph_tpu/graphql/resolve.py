"""GraphQL execution: generated API resolved onto the DQL executor.

Mirrors /root/reference/graphql/resolve (query_rewriter.go,
mutation_rewriter.go, resolver.go): for each SDL type T the API exposes
  getT(id/xid), queryT(filter, order, first, offset), aggregateT(filter),
  addT(input, upsert), updateT(input: {filter, set, remove}),
  deleteT(filter), querySimilarTByEmbedding(by, topK, vector)
and resolves them by building internal GraphQuery ASTs (not text) executed
by query.subgraph.Executor, with mutations applied through the
transactional path.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from dgraph_tpu.dql.parser import FilterTree, FuncSpec, GraphQuery, Order
from dgraph_tpu.graphql.parser import Operation, Selection, parse_operation
from dgraph_tpu.graphql.sdl import GqlField, GqlType, parse_sdl, to_dql_schema
from dgraph_tpu.posting.lists import LocalCache
from dgraph_tpu.posting.mutation import DirectedEdge, apply_edge
from dgraph_tpu.posting.pl import OP_DEL, OP_SET
from dgraph_tpu.query.outputjson import JsonEncoder
from dgraph_tpu.query.subgraph import Executor
from dgraph_tpu.types.types import TypeID, Val
from dgraph_tpu.x import keys

_FILTER_OPS = {
    "eq": "eq",
    "in": "eq",
    "le": "le",
    "lt": "lt",
    "ge": "ge",
    "gt": "gt",
    "between": "between",
    "anyofterms": "anyofterms",
    "allofterms": "allofterms",
    "anyoftext": "anyoftext",
    "alloftext": "alloftext",
    "regexp": "regexp",
    "near": "near",
}


class GraphQLError(Exception):
    pass


class GraphQLServer:
    def __init__(self, engine, sdl: str, lambda_url: Optional[str] = None):
        import os
        import threading

        from dgraph_tpu.graphql.auth import parse_authorization

        self.engine = engine
        self.types: Dict[str, GqlType] = parse_sdl(sdl)
        self.sdl = sdl
        self.auth_config = parse_authorization(sdl)
        # --graphql lambda-url analog (ref x.LambdaUrl): explicit arg >
        # engine attr (set by the alpha CLI superflag) > env
        self.lambda_url = (
            lambda_url
            or getattr(engine, "graphql_lambda_url", None)
            or os.environ.get("DGRAPH_TPU_LAMBDA_URL", "")
        )
        self._tls = threading.local()  # per-request JWT claims
        engine.alter(to_dql_schema(self.types))

    # ------------------------------------------------------------------
    # Entry
    # ------------------------------------------------------------------

    def execute(
        self,
        query: str,
        variables: Optional[Dict[str, Any]] = None,
        jwt_token: Optional[str] = None,
        claims: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        try:
            if claims is None and jwt_token and self.auth_config:
                from dgraph_tpu.graphql.auth import claims_from_jwt

                claims = claims_from_jwt(jwt_token, self.auth_config)
            self._tls.claims = claims or {}
            op = parse_operation(query, variables)
            data = {}
            for sel in op.selections:
                if op.kind == "mutation":
                    data[sel.key] = self._resolve_mutation(sel)
                else:
                    data[sel.key] = self._resolve_query(sel)
            return {"data": data}
        except Exception as e:  # noqa: BLE001 — GraphQL error envelope
            return {"data": None, "errors": [{"message": str(e)}]}

    # ------------------------------------------------------------------
    # Query resolution
    # ------------------------------------------------------------------

    def _type_for(self, sel_name: str, prefixes) -> GqlType:
        for pre in prefixes:
            if sel_name.startswith(pre):
                tname = sel_name[len(pre) :]
                t = self.types.get(tname)
                if t:
                    return t
        raise GraphQLError(f"unknown operation {sel_name!r}")

    def _claims(self) -> Dict[str, Any]:
        return getattr(self._tls, "claims", {}) or {}

    def _auth(self, t: GqlType, op: str):
        """True | False | filter-dict for the operation (@auth rules,
        ref graphql/resolve query_rewriter auth injection)."""
        from dgraph_tpu.graphql.auth import evaluate

        if t.auth is None:
            return True
        return evaluate(getattr(t.auth, op), self._claims())

    def _with_auth_filter(self, t: GqlType, fobj, op: str = "query"):
        """Merge the type's auth rule filter into a filter object. Returns
        (filter_obj, allowed)."""
        auth = self._auth(t, op)
        if auth is True:
            return fobj, True
        if auth is False:
            return fobj, False
        if not fobj:
            return auth, True
        return {"and": [fobj, auth]}, True

    def _resolve_query(self, sel: Selection):
        name = sel.name
        if name == "__schema" or name == "__type":
            from dgraph_tpu.graphql.introspection import resolve_introspection

            return resolve_introspection(self.types, sel)
        qt = self.types.get("Query")
        if qt is not None:
            f = qt.fields.get(name)
            if f is not None and f.custom is not None:
                return self._resolve_custom(f, sel)
            if f is not None and f.is_lambda:
                return self._resolve_lambda_root("Query", f, sel)
        if name.startswith("get"):
            t = self._type_for(name, ["get"])
            return self._get(t, sel)
        if name.startswith("querySimilar") and name.endswith("ByEmbedding"):
            tname = name[len("querySimilar") : -len("ByEmbedding")]
            t = self.types.get(tname)
            if not t:
                raise GraphQLError(f"unknown type {tname}")
            return self._similar(t, sel)
        if name.startswith("query"):
            t = self._type_for(name, ["query"])
            return self._query_list(t, sel)
        if name.startswith("aggregate"):
            t = self._type_for(name, ["aggregate"])
            return self._aggregate(t, sel)
        raise GraphQLError(f"unknown query {name!r}")

    @staticmethod
    def _add_typename(results, t: GqlType, sels: List[Selection]):
        """Inject __typename literals the encoder doesn't know about."""
        keys_ = [s.key for s in sels if s.name == "__typename"]
        if not keys_:
            return results
        for r in results:
            for k in keys_:
                r[k] = t.name
        return results

    def _resolve_custom(self, f: GqlField, sel: Selection):
        """@custom(http: {...}) resolver (ref graphql/schema/remote.go +
        resolve/http.go): substitute $args into the URL/body template,
        call the endpoint, project the selection over the JSON reply."""
        import json as _json
        import urllib.parse
        import urllib.request

        from dgraph_tpu.graphql.introspection import _project

        cfg = (f.custom or {}).get("http")
        if not cfg:
            raise GraphQLError(f"@custom field {f.name} has no http config")
        url = cfg.get("url", "")
        for k, v in sel.args.items():
            url = url.replace(f"${k}", urllib.parse.quote(str(v)))
        method = str(cfg.get("method", "GET")).upper()
        body = None
        if cfg.get("body"):
            from dgraph_tpu.graphql.auth import _parse_gql_object, _substitute

            tmpl = _parse_gql_object(cfg["body"]) if isinstance(
                cfg["body"], str
            ) else cfg["body"]
            body = _json.dumps(_substitute(tmpl, sel.args)).encode()
        req = urllib.request.Request(url, data=body, method=method)
        if body is not None:
            req.add_header("Content-Type", "application/json")
        try:
            with urllib.request.urlopen(req, timeout=10) as r:
                payload = _json.loads(r.read() or b"null")
        except Exception as e:
            raise GraphQLError(f"@custom http call failed: {e}") from e
        if sel.selections and isinstance(payload, (dict, list)):
            return _project(payload, sel.selections)
        return payload

    # ------------------------------------------------------------------
    # @lambda (ref wrappers.go buildCustomDirectiveForLambda,
    # custom_http.go GetBodyForLambda)
    # ------------------------------------------------------------------

    def _lambda_post(self, body: dict):
        import json as _json
        import urllib.request

        if not self.lambda_url:
            raise GraphQLError(
                "@lambda field used but no lambda-url configured "
                "(--graphql lambda-url / DGRAPH_TPU_LAMBDA_URL)"
            )
        req = urllib.request.Request(
            self.lambda_url,
            data=_json.dumps(body).encode(),
            method="POST",
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            return _json.loads(r.read() or b"null")

    def _resolve_lambda_root(self, parent: str, f: GqlField, sel: Selection):
        """Query./Mutation.-level @lambda: POST {resolver, args} and return
        the lambda server's value, projected over the selection."""
        from dgraph_tpu.graphql.introspection import _project

        try:
            payload = self._lambda_post(
                {
                    "resolver": f"{parent}.{f.name}",
                    "args": sel.args,
                    "parents": None,
                    "authHeader": self._lambda_auth_header(),
                }
            )
        except GraphQLError:
            raise
        except Exception as e:
            raise GraphQLError(f"@lambda call failed: {e}") from e
        if sel.selections and isinstance(payload, (dict, list)):
            return _project(payload, sel.selections)
        return payload

    def _lambda_auth_header(self):
        cfg = self.auth_config
        if not cfg:
            return None
        return {"key": getattr(cfg, "header", None), "value": None}

    def _enrich_lambda_fields(
        self, t: GqlType, sels: List[Selection], rows: List[dict]
    ) -> None:
        """BATCH-mode @lambda on type fields: one POST per (type, field)
        with every row's scalar fields as `parents`; the response array
        aligns with parents (ref wrappers.go BATCH mode). Recurses into
        object-valued children; hidden __lp_ scalars are stripped."""
        if not rows:
            return
        lam = [
            s
            for s in sels
            if t.fields.get(s.name) is not None and t.fields[s.name].is_lambda
        ]
        for s in sels:  # recurse into nested objects first
            f = t.fields.get(s.name)
            if f is None or f.is_scalar or f.is_lambda:
                continue
            ct = self.types.get(f.type_name)
            if ct is None:
                continue
            for row in rows:
                v = row.get(s.key)
                if isinstance(v, list):
                    self._enrich_lambda_fields(ct, s.selections, v)
                elif isinstance(v, dict):
                    self._enrich_lambda_fields(ct, s.selections, [v])
        if lam:
            parents = []
            for row in rows:
                p = {}
                for fn, fdef in t.fields.items():
                    if not fdef.is_scalar or fdef.is_lambda or fdef.custom:
                        continue
                    if fn in row:
                        p[fn] = row[fn]
                    elif f"__lp_{fn}" in row:
                        p[fn] = row[f"__lp_{fn}"]
                parents.append(p)
            for s in lam:
                try:
                    got = self._lambda_post(
                        {
                            "resolver": f"{t.name}.{s.name}",
                            "parents": parents,
                            "authHeader": self._lambda_auth_header(),
                        }
                    )
                except GraphQLError:
                    raise
                except Exception as e:
                    raise GraphQLError(f"@lambda call failed: {e}") from e
                if isinstance(got, list):
                    if len(got) != len(rows):
                        raise GraphQLError(
                            f"@lambda {t.name}.{s.name}: BATCH response has "
                            f"{len(got)} values for {len(rows)} parents"
                        )
                    vals = got
                else:
                    vals = [got] * len(rows)
                for row, v in zip(rows, vals):
                    row[s.key] = v
        for row in rows:
            for k in [k for k in row if k.startswith("__lp_")]:
                del row[k]

    def _run_block(self, gq: GraphQuery) -> List[dict]:
        cache = LocalCache(
            self.engine.kv,
            self.engine.zero.read_ts(),
            mem=getattr(self.engine, "mem", None),
        )
        ex = Executor(
            cache, self.engine.schema, vector_indexes=self.engine.vector_indexes
        )
        nodes = ex.process([gq])
        enc = JsonEncoder(val_vars=ex.val_vars, schema=self.engine.schema)
        return enc.encode_blocks(nodes).get(gq.attr, [])

    def _selection_children(
        self, t: GqlType, sels: List[Selection]
    ) -> List[GraphQuery]:
        out = []
        has_lambda = False
        selected = set()
        for s in sels:
            f = t.fields.get(s.name)
            if s.name == "__typename":
                continue  # injected post-encode (_add_typename)
            if f is not None and f.is_lambda:
                has_lambda = True  # resolved post-query via the lambda URL
                continue
            if s.name == "id" or (f and f.type_name == "ID"):
                out.append(GraphQuery(attr="uid", is_uid=True, alias=s.key))
                continue
            if f is None:
                raise GraphQLError(f"no field {s.name!r} on type {t.name}")
            selected.add(s.name)
            child = GraphQuery(attr=f"{t.name}.{f.name}", alias=s.key)
            if not f.is_scalar:
                ct = self.types.get(f.type_name)
                if ct is None:
                    raise GraphQLError(f"unknown type {f.type_name}")
                child.children = self._selection_children(ct, s.selections)
            out.append(child)
        if has_lambda:
            # lambda parents carry ALL scalar fields of the type
            # (wrappers.go body template); fetch unselected ones hidden
            for fn, fdef in t.fields.items():
                if (
                    fdef.is_scalar
                    and not fdef.is_lambda
                    and not fdef.custom
                    and fdef.type_name != "ID"
                    and fn not in selected
                ):
                    out.append(
                        GraphQuery(
                            attr=f"{t.name}.{fn}", alias=f"__lp_{fn}"
                        )
                    )
        return out

    def _filter_tree(self, t: GqlType, fobj: dict) -> Optional[FilterTree]:
        parts: List[FilterTree] = []
        for k, v in (fobj or {}).items():
            if k == "and":
                subs = [self._filter_tree(t, x) for x in _as_list(v)]
                parts.append(FilterTree(op="and", children=[s for s in subs if s]))
            elif k == "or":
                subs = [self._filter_tree(t, x) for x in _as_list(v)]
                parts.append(FilterTree(op="or", children=[s for s in subs if s]))
            elif k == "not":
                sub = self._filter_tree(t, v)
                if sub:
                    parts.append(FilterTree(op="not", children=[sub]))
            elif k == "id":
                uids = [int(x, 16) for x in _as_list(v)]
                parts.append(
                    FilterTree(func=FuncSpec(name="uid", args=uids))
                )
            elif k == "has":
                for fname in _as_list(v):
                    f = t.fields.get(fname)
                    if f is None:
                        raise GraphQLError(f"no field {fname!r}")
                    parts.append(
                        FilterTree(
                            func=FuncSpec(name="has", attr=f"{t.name}.{fname}")
                        )
                    )
            else:
                f = t.fields.get(k)
                if f is None:
                    raise GraphQLError(f"no field {k!r} on {t.name}")
                attr = f"{t.name}.{k}"
                if not isinstance(v, dict):
                    v = {"eq": v}
                for opname, arg in v.items():
                    fn = _FILTER_OPS.get(opname)
                    if fn is None:
                        raise GraphQLError(f"bad filter op {opname!r}")
                    if opname == "in":
                        args = _as_list(arg)
                    elif opname == "between":
                        args = [arg.get("min"), arg.get("max")]
                    elif opname == "near":
                        c = arg.get("coordinate", {})
                        args = [
                            [c.get("longitude"), c.get("latitude")],
                            arg.get("distance"),
                        ]
                    elif opname == "regexp":
                        pat = str(arg)
                        if pat.startswith("/"):
                            end = pat.rindex("/")
                            args = [("regex", pat[1:end], pat[end + 1 :])]
                        else:
                            args = [("regex", pat, "")]
                    else:
                        args = [arg]
                    parts.append(
                        FilterTree(func=FuncSpec(name=fn, attr=attr, args=args))
                    )
        if not parts:
            return None
        if len(parts) == 1:
            return parts[0]
        return FilterTree(op="and", children=parts)

    def _query_list(self, t: GqlType, sel: Selection) -> List[dict]:
        fobj, allowed = self._with_auth_filter(t, sel.args.get("filter"))
        if not allowed:
            return []
        gq = GraphQuery(attr="q")
        gq.func = FuncSpec(name="type", attr=t.name)
        gq.filter = self._filter_tree(t, fobj)
        order = sel.args.get("order") or {}
        if "asc" in order:
            gq.order.append(Order(attr=f"{t.name}.{order['asc']}"))
        if "desc" in order:
            gq.order.append(Order(attr=f"{t.name}.{order['desc']}", desc=True))
        gq.first = sel.args.get("first")
        gq.offset = sel.args.get("offset")
        gq.children = self._selection_children(t, sel.selections)
        rows = self._run_block(gq)
        self._enrich_lambda_fields(t, sel.selections, rows)
        return self._add_typename(rows, t, sel.selections)

    def _get(self, t: GqlType, sel: Selection) -> Optional[dict]:
        gq = GraphQuery(attr="q")
        if "id" in sel.args:
            gq.func = FuncSpec(name="uid", args=[int(sel.args["id"], 16)])
            gq.filter = FilterTree(func=FuncSpec(name="type", attr=t.name))
        else:
            xf = t.xid_field()
            if xf is None or xf.name not in sel.args:
                raise GraphQLError(f"get{t.name} requires id or @id field")
            gq.func = FuncSpec(
                name="eq",
                attr=f"{t.name}.{xf.name}",
                args=[sel.args[xf.name]],
            )
        auth = self._auth(t, "query")
        if auth is False:
            return None
        if isinstance(auth, dict):
            extra = self._filter_tree(t, auth)
            gq.filter = (
                extra
                if gq.filter is None
                else FilterTree(op="and", children=[gq.filter, extra])
            )
        gq.children = self._selection_children(t, sel.selections)
        res = self._run_block(gq)
        self._enrich_lambda_fields(t, sel.selections, res)
        return res[0] if res else None

    def _aggregate(self, t: GqlType, sel: Selection) -> dict:
        """aggregateT(filter) { count fieldMin fieldMax fieldSum fieldAvg }
        (ref gqlschema.go aggregate type synthesis)."""
        fobj, allowed = self._with_auth_filter(t, sel.args.get("filter"))
        if not allowed:
            return {
                s.key: (0 if s.name == "count" else None)
                for s in sel.selections
            }
        gq = GraphQuery(attr="q")
        gq.func = FuncSpec(name="type", attr=t.name)
        gq.filter = self._filter_tree(t, fobj)
        count_key = next(
            (s.key for s in sel.selections if s.name == "count"), "count"
        )
        gq.children = [GraphQuery(attr="uid", is_count=True, alias=count_key)]

        # map selections like ageMin/ageMax/ageSum/ageAvg to aggregators
        aggs = []  # (sel_key, field, op)
        for s in sel.selections:
            if s.name == "count":
                continue
            for suffix, op in (
                ("Min", "min"), ("Max", "max"), ("Sum", "sum"), ("Avg", "avg"),
            ):
                if s.name.endswith(suffix):
                    fname = s.name[: -len(suffix)]
                    f = t.fields.get(fname)
                    if f is not None and f.is_scalar:
                        aggs.append((s.key, fname, op))
                    break
        var_of = {}
        for i, (_, fname, _) in enumerate(aggs):
            if fname not in var_of:
                var_of[fname] = f"v{i}"
                gq.children.append(
                    GraphQuery(
                        attr=f"{t.name}.{fname}", var_name=var_of[fname]
                    )
                )
        for key, fname, op in aggs:
            gq.children.append(
                GraphQuery(aggregator=op, val_var=var_of[fname], alias=key)
            )
        res = self._run_block(gq)
        out = {count_key: 0}
        for obj in res:
            out.update(obj)
        wanted = {s.key for s in sel.selections}
        out = {k: v for k, v in out.items() if k in wanted}
        for s in sel.selections:  # absent aggregates -> null
            out.setdefault(s.key, None)
        return out

    def _similar(self, t: GqlType, sel: Selection) -> List[dict]:
        by = sel.args.get("by")
        topk = int(sel.args.get("topK", 10))
        vec = sel.args.get("vector")
        gq = GraphQuery(attr="q")
        import json as _json

        gq.func = FuncSpec(
            name="similar_to",
            attr=f"{t.name}.{by}",
            args=[topk, _json.dumps(vec)],
        )
        gq.children = self._selection_children(t, sel.selections)
        rows = self._run_block(gq)
        self._enrich_lambda_fields(t, sel.selections, rows)
        return rows

    # ------------------------------------------------------------------
    # Mutations (ref resolve/mutation_rewriter.go)
    # ------------------------------------------------------------------

    def _fire_webhook(self, t: GqlType, op: str, uids: List[int], sel: Selection):
        """@lambdaOnMutate fire-and-forget webhook (ref resolve/webhook.go
        sendWebhookEvent; payload shape webhookPayload/eventPayload)."""
        if not t.lambda_on_mutate.get(op) or not self.lambda_url:
            return
        event: Dict[str, Any] = {
            "__typename": t.name,
            "operation": op,
            "commitTs": 0,
        }
        root_uids = [f"0x{u:x}" for u in uids]
        if op == "add":
            event["add"] = {
                "rootUIDs": root_uids,
                "input": _as_list(sel.args.get("input", [])),
            }
        elif op == "update":
            inp = sel.args.get("input", {}) or {}
            event["update"] = {
                "rootUIDs": root_uids,
                "setPatch": inp.get("set"),
                "removePatch": inp.get("remove"),
            }
        else:
            event["delete"] = {"rootUIDs": root_uids}
        body = {"resolver": "$webhook", "event": event}

        import threading

        def post():
            try:
                self._lambda_post(body)
            except Exception:
                pass  # at-most-once, errors only logged by the reference too

        threading.Thread(target=post, daemon=True).start()

    def _resolve_mutation(self, sel: Selection):
        if getattr(self.engine, "draining", False):
            raise GraphQLError("the server is in draining mode")
        name = sel.name
        mt = self.types.get("Mutation")
        if mt is not None:
            f = mt.fields.get(name)
            if f is not None and f.custom is not None:
                return self._resolve_custom(f, sel)
            if f is not None and f.is_lambda:
                return self._resolve_lambda_root("Mutation", f, sel)
        if name.startswith("add"):
            return self._add(self._type_for(name, ["add"]), sel)
        if name.startswith("update"):
            return self._update(self._type_for(name, ["update"]), sel)
        if name.startswith("delete"):
            return self._delete(self._type_for(name, ["delete"]), sel)
        raise GraphQLError(f"unknown mutation {name!r}")

    def _payload(self, t: GqlType, sel: Selection, uids: List[int], num: int):
        out: Dict[str, Any] = {}
        for s in sel.selections:
            if s.name == "numUids":
                out[s.key] = num
            elif s.name == "msg":
                out[s.key] = "Deleted" if sel.name.startswith("delete") else "Ok"
            elif s.name.lower() == t.name.lower():
                gq = GraphQuery(attr="q")
                gq.func = FuncSpec(name="uid", args=uids)
                gq.children = self._selection_children(t, s.selections)
                rows = self._run_block(gq)
                self._enrich_lambda_fields(t, s.selections, rows)
                out[s.key] = rows
        return out

    def _set_field(self, txn, t: GqlType, uid: int, f: GqlField, value, op=OP_SET):
        attr = f"{t.name}.{f.name}"
        if f.is_embedding:
            edge = DirectedEdge(
                uid, attr, value=Val(TypeID.VFLOAT, np.asarray(value, np.float32)),
                op=op,
            )
            apply_edge(txn, self.engine.schema, edge)
            return
        if not f.is_scalar:
            ct = self.types[f.type_name]
            for obj in _as_list(value):
                child_uid = self._upsert_object(txn, ct, obj, getattr(txn, '_created', None))
                apply_edge(
                    txn,
                    self.engine.schema,
                    DirectedEdge(uid, attr, value_id=child_uid, op=op),
                )
                if f.has_inverse:
                    apply_edge(
                        txn,
                        self.engine.schema,
                        DirectedEdge(
                            child_uid,
                            f"{ct.name}.{f.has_inverse}",
                            value_id=uid,
                            op=op,
                        ),
                    )
            return
        vals = value if (f.is_list and isinstance(value, list)) else [value]
        for v in vals:
            apply_edge(
                txn,
                self.engine.schema,
                DirectedEdge(uid, attr, value=_to_val(v, f), op=op),
            )

    def _upsert_object(self, txn, t: GqlType, obj: dict, created=None) -> int:
        """Create or reference an object: {id: "0x1"} references, otherwise
        create a new node (with @id dedup)."""
        if set(obj.keys()) == {"id"}:
            return int(obj["id"], 16)
        xf = t.xid_field()
        if xf and xf.name in obj:
            # look up existing by xid
            ex = Executor(txn.cache, self.engine.schema)
            found = ex._runner().run_root(
                FuncSpec(
                    name="eq", attr=f"{t.name}.{xf.name}", args=[obj[xf.name]]
                )
            )
            if len(found):
                uid = int(found[0])
                for k, v in obj.items():
                    if k in ("id", xf.name):
                        continue
                    self._set_field(txn, t, uid, t.fields[k], v)
                return uid
        uid = self.engine.zero.assign_uids(1)
        if created is not None:
            created.append(uid)
        apply_edge(
            txn,
            self.engine.schema,
            DirectedEdge(uid, "dgraph.type", value=Val(TypeID.STRING, t.name)),
        )
        for k, v in obj.items():
            if k == "id":
                continue
            f = t.fields.get(k)
            if f is None:
                raise GraphQLError(f"no field {k!r} on {t.name}")
            self._set_field(txn, t, uid, f, v)
        return uid

    def _add(self, t: GqlType, sel: Selection):
        auth = self._auth(t, "add")
        if auth is False:
            raise GraphQLError(f"unauthorized to add {t.name}")
        inputs = _as_list(sel.args.get("input", []))
        txn = self.engine.new_txn()
        created: List[int] = []
        txn.txn._created = created  # nested creates counted in numUids
        uids = [self._upsert_object(txn.txn, t, obj, created) for obj in inputs]
        if isinstance(auth, dict):
            # auth filter must reach every new node (post-mutation check,
            # ref add-rule semantics: newly added nodes are validated)
            gq = GraphQuery(attr="q")
            gq.func = FuncSpec(name="uid", args=list(uids))
            gq.filter = self._filter_tree(t, auth)
            gq.children = [GraphQuery(attr="uid", is_uid=True)]
            cache = txn.txn.cache
            ex = Executor(
                cache,
                self.engine.schema,
                vector_indexes=self.engine.vector_indexes,
            )
            nodes = ex.process([gq])
            ok = {int(u) for u in nodes[0].dest_uids}
            if not all(u in ok for u in uids):
                txn.discard()
                raise GraphQLError(f"unauthorized to add {t.name}")
        txn.commit()
        self._fire_webhook(t, "add", uids, sel)
        return self._payload(t, sel, uids, len(created))

    def _match_filter_uids(self, t: GqlType, fobj) -> List[int]:
        gq = GraphQuery(attr="q")
        gq.func = FuncSpec(name="type", attr=t.name)
        gq.filter = self._filter_tree(t, fobj)
        gq.children = [GraphQuery(attr="uid", is_uid=True)]
        return [int(o["uid"], 16) for o in self._run_block(gq)]

    def _update(self, t: GqlType, sel: Selection):
        inp = sel.args.get("input", {})
        fobj, allowed = self._with_auth_filter(t, inp.get("filter"), "update")
        if not allowed:
            raise GraphQLError(f"unauthorized to update {t.name}")
        uids = self._match_filter_uids(t, fobj)
        txn = self.engine.new_txn()
        for uid in uids:
            for k, v in (inp.get("set") or {}).items():
                f = t.fields.get(k)
                if f is None:
                    raise GraphQLError(f"no field {k!r}")
                self._set_field(txn.txn, t, uid, f, v)
            for k, v in (inp.get("remove") or {}).items():
                f = t.fields.get(k)
                if f is None:
                    raise GraphQLError(f"no field {k!r}")
                self._set_field(txn.txn, t, uid, f, v, op=OP_DEL)
        txn.commit()
        self._fire_webhook(t, "update", uids, sel)
        return self._payload(t, sel, uids, len(uids))

    def _delete(self, t: GqlType, sel: Selection):
        from dgraph_tpu.posting.mutation import delete_entity_attr

        fobj, allowed = self._with_auth_filter(
            t, sel.args.get("filter"), "delete"
        )
        if not allowed:
            raise GraphQLError(f"unauthorized to delete {t.name}")
        uids = self._match_filter_uids(t, fobj)
        txn = self.engine.new_txn()
        for uid in uids:
            for f in t.fields.values():
                if f.type_name == "ID":
                    continue
                delete_entity_attr(
                    txn.txn, self.engine.schema, uid, f"{t.name}.{f.name}"
                )
            delete_entity_attr(txn.txn, self.engine.schema, uid, "dgraph.type")
        txn.commit()
        self._fire_webhook(t, "delete", uids, sel)
        return self._payload(t, sel, uids, len(uids))


def _as_list(x):
    if x is None:
        return []
    return x if isinstance(x, list) else [x]


def _to_val(v, f: GqlField) -> Val:
    dtype = f.dql_type
    if dtype == "int":
        return Val(TypeID.INT, int(v))
    if dtype == "float":
        return Val(TypeID.FLOAT, float(v))
    if dtype == "bool":
        return Val(TypeID.BOOL, bool(v))
    if dtype == "datetime":
        from dgraph_tpu.types.types import parse_datetime

        return Val(TypeID.DATETIME, parse_datetime(str(v)))
    if dtype == "geo":
        if isinstance(v, dict) and "longitude" in v:
            v = {
                "type": "Point",
                "coordinates": [v["longitude"], v["latitude"]],
            }
        return Val(TypeID.GEO, v)
    return Val(TypeID.STRING, str(v))

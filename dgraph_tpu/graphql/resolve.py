"""GraphQL execution: generated API resolved onto the DQL executor.

Mirrors /root/reference/graphql/resolve (query_rewriter.go,
mutation_rewriter.go, resolver.go): for each SDL type T the API exposes
  getT(id/xid), queryT(filter, order, first, offset), aggregateT(filter),
  addT(input, upsert), updateT(input: {filter, set, remove}),
  deleteT(filter), querySimilarTByEmbedding(by, topK, vector)
and resolves them by building internal GraphQuery ASTs (not text) executed
by query.subgraph.Executor, with mutations applied through the
transactional path.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from dgraph_tpu.dql.parser import FilterTree, FuncSpec, GraphQuery, Order
from dgraph_tpu.graphql.parser import Operation, Selection, parse_operation
from dgraph_tpu.graphql.sdl import GqlField, GqlType, parse_sdl, to_dql_schema
from dgraph_tpu.posting.lists import LocalCache
from dgraph_tpu.posting.mutation import DirectedEdge, apply_edge
from dgraph_tpu.posting.pl import OP_DEL, OP_SET
from dgraph_tpu.query.outputjson import JsonEncoder
from dgraph_tpu.query.subgraph import Executor
from dgraph_tpu.types.types import TypeID, Val
from dgraph_tpu.x import config, keys

_FILTER_OPS = {
    "eq": "eq",
    "in": "eq",
    "le": "le",
    "lt": "lt",
    "ge": "ge",
    "gt": "gt",
    "between": "between",
    "anyofterms": "anyofterms",
    "allofterms": "allofterms",
    "anyoftext": "anyoftext",
    "alloftext": "alloftext",
    "regexp": "regexp",
    "near": "near",
    "within": "within",
    "contains": "contains",
    "intersects": "intersects",
}


def _gql_polygon_coords(p: dict) -> list:
    """GraphQL PolygonRef {coordinates: [{points: [{latitude,longitude}]}]}
    -> geojson-style [[[lon,lat], ...], ...] ring list."""
    return [
        [[pt["longitude"], pt["latitude"]] for pt in ring["points"]]
        for ring in p.get("coordinates", [])
    ]


def _gql_geo_to_geojson(v: dict) -> dict:
    if "longitude" in v:
        return {
            "type": "Point",
            "coordinates": [v["longitude"], v["latitude"]],
        }
    if "polygons" in v:
        return {
            "type": "MultiPolygon",
            "coordinates": [
                _gql_polygon_coords(p) for p in v["polygons"]
            ],
        }
    if "coordinates" in v:
        return {"type": "Polygon", "coordinates": _gql_polygon_coords(v)}
    return v


def _geojson_to_gql(g):
    """Stored geojson -> the GraphQL Point/Polygon/MultiPolygon shape
    (ref graphql/resolve completeGeoObject)."""
    if not isinstance(g, dict):
        return g
    t = g.get("type")
    c = g.get("coordinates")
    if t == "Point":
        return {"longitude": c[0], "latitude": c[1]}
    if t == "Polygon":
        return {
            "coordinates": [
                {
                    "points": [
                        {"longitude": p[0], "latitude": p[1]} for p in ring
                    ]
                }
                for ring in c
            ]
        }
    if t == "MultiPolygon":
        return {
            "polygons": [
                _geojson_to_gql({"type": "Polygon", "coordinates": pc})
                for pc in c
            ]
        }
    return g


class GraphQLError(Exception):
    pass


class _MutCtx:
    """Per-request mutation state (ref mutation_rewriter.go VarGenerator
    / xidMetadata): upsert flag, uids created, xids claimed by new nodes
    so in-request duplicates are rejected."""

    def __init__(self, upsert: bool = False):
        self.upsert = upsert
        self.upsert_auth = True  # add-rule verdict for upsert pre-checks
        self.now: Optional[str] = None  # one $now per request
        self.created: List[int] = []
        # (pred, xid-value) -> (new uid, the claiming input object)
        self.claimed: Dict[tuple, tuple] = {}


class GraphQLServer:
    def __init__(self, engine, sdl: str, lambda_url: Optional[str] = None):
        import threading

        from dgraph_tpu.graphql.auth import parse_authorization

        self.engine = engine
        self.types: Dict[str, GqlType] = parse_sdl(sdl)
        self.sdl = sdl
        self.auth_config = parse_authorization(sdl)
        self.closed_by_default = bool(
            self.auth_config and self.auth_config.closed_by_default
        )
        # --graphql lambda-url analog (ref x.LambdaUrl): explicit arg >
        # engine attr (set by the alpha CLI superflag) > env
        self.lambda_url = (
            lambda_url
            or getattr(engine, "graphql_lambda_url", None)
            or config.get("LAMBDA_URL")
        )
        self._tls = threading.local()  # per-request JWT claims
        self._validate_remote_customs()  # reject BEFORE mutating schema
        engine.alter(to_dql_schema(self.types))

    def _validate_remote_customs(self):
        """@custom(http: {graphql: ...}) fields introspect their remote
        endpoint at schema-update time and reject selections the remote
        can't serve (ref graphql/schema/remote.go validateRemoteGraphql
        — errors surface when the schema loads, not at first request).
        Set DGRAPH_TPU_SKIP_REMOTE_INTROSPECTION=1 to defer (air-gapped
        loads)."""
        if config.get("SKIP_REMOTE_INTROSPECTION"):
            return
        from dgraph_tpu.graphql.remote import (
            RemoteSchemaError,
            introspect_remote,
            validate_remote_graphql,
        )

        cache: Dict[str, dict] = {}
        for t in self.types.values():
            for f in t.fields.values():
                cfg = (f.custom or {}).get("http") or {}
                gql_op = cfg.get("graphql")
                if not gql_op:
                    continue
                url = cfg.get("url", "")
                try:
                    if url not in cache:
                        cache[url] = introspect_remote(url)
                    validate_remote_graphql(
                        cache[url],
                        gql_op,
                        f.type_name,
                        is_batch=cfg.get("mode") == "BATCH",
                    )
                except RemoteSchemaError as e:
                    raise GraphQLError(
                        f"resolving updateGQLSchema failed because "
                        f"input:{t.name}.{f.name}: {e}"
                    ) from e

    # ------------------------------------------------------------------
    # Entry
    # ------------------------------------------------------------------

    def execute(
        self,
        query: str,
        variables: Optional[Dict[str, Any]] = None,
        jwt_token: Optional[str] = None,
        claims: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        try:
            if claims is None and jwt_token and self.auth_config:
                from dgraph_tpu.graphql.auth import claims_from_jwt

                claims = claims_from_jwt(jwt_token, self.auth_config)
            self._tls.claims = claims or {}
            self._tls.auth_memo = {}  # fresh verdicts per request
            if (
                getattr(self, "closed_by_default", False)
                and claims is None
                and not jwt_token
            ):
                # Dgraph.Authorization ClosedByDefault: every request
                # needs a JWT (ref x/config.go + auth closed-mode tests)
                raise GraphQLError(
                    "a valid JWT is required but was not provided"
                )
            op = parse_operation(query, variables)
            data = {}
            for sel in op.selections:
                if op.kind == "mutation":
                    data[sel.key] = self._resolve_mutation(sel)
                else:
                    data[sel.key] = self._resolve_query(sel)
            return {"data": data}
        except Exception as e:  # noqa: BLE001 — GraphQL error envelope
            return {"data": None, "errors": [{"message": str(e)}]}

    # ------------------------------------------------------------------
    # Query resolution
    # ------------------------------------------------------------------

    def _type_for(self, sel_name: str, prefixes) -> GqlType:
        for pre in prefixes:
            if sel_name.startswith(pre):
                tname = sel_name[len(pre) :]
                t = self.types.get(tname)
                if t:
                    return t
        raise GraphQLError(f"unknown operation {sel_name!r}")

    def _claims(self) -> Dict[str, Any]:
        return getattr(self._tls, "claims", {}) or {}

    def _auth(self, t: GqlType, op: str):
        """True | False | filter-dict for the operation (@auth rules,
        ref graphql/resolve query_rewriter auth injection)."""
        from dgraph_tpu.graphql.auth import evaluate

        if t.auth is None:
            return True
        # per-request memo: the same (type, op) verdict is reused at
        # every nesting site (claims + snapshot are fixed per request)
        memo = getattr(self._tls, "auth_memo", None)
        if memo is None:
            memo = self._tls.auth_memo = {}
        key = (t.name, op)
        if key not in memo:
            memo[key] = evaluate(
                getattr(t.auth, op), self._claims(),
                rule_runner=self._run_auth_rule,
            )
        return memo[key]

    def _run_auth_rule(self, rule_text: str, claims, cache=None) -> List[str]:
        """Execute a deep @auth rule query with @cascade semantics and
        return the allowed root uids (the eager equivalent of the
        reference's uid-var + @cascade auth chains,
        auth_query_rewriting). cache pins the snapshot — mutation auth
        checks run against the uncommitted txn state."""
        op = parse_operation(rule_text, variables=dict(claims))
        sel = op.selections[0]
        t = self._type_for(sel.name, ["query", "get"])
        gq = GraphQuery(attr="q")
        gq.func = FuncSpec(name="type", attr=t.stored_name)
        fobj = sel.args.get("filter")
        if fobj:
            gq.filter = self._filter_tree(t, fobj)
        gq.cascade = True  # root @cascade prunes the whole subtree
        prev = getattr(self._tls, "in_auth_rule", False)
        self._tls.in_auth_rule = True
        try:
            gq.children = self._selection_children(t, sel.selections)
        finally:
            self._tls.in_auth_rule = prev
        gq.children.append(GraphQuery(attr="uid", is_uid=True))
        rows = self._run_block(gq, cache=cache)
        return [r["uid"] for r in rows if isinstance(r, dict) and "uid" in r]

    def _auth_allowed_uids(self, t: GqlType, auth_filter, uids, cache=None):
        """Subset of uids passing an auth filter dict, evaluated on the
        given snapshot (txn cache for mutation post-checks)."""
        if not uids:
            return set()
        gq = GraphQuery(attr="q")
        gq.func = FuncSpec(name="uid", args=list(uids))
        gq.filter = self._filter_tree(t, auth_filter)
        gq.children = [GraphQuery(attr="uid", is_uid=True)]
        rows = self._run_block(gq, cache=cache)
        return {int(r["uid"], 16) for r in rows}

    def _with_auth_filter(self, t: GqlType, fobj, op: str = "query"):
        """Merge the type's auth rule filter into a filter object. Returns
        (filter_obj, allowed)."""
        auth = self._auth(t, op)
        if auth is True:
            return fobj, True
        if auth is False:
            return fobj, False
        if not fobj:
            return auth, True
        return {"and": [fobj, auth]}, True

    def _resolve_query(self, sel: Selection):
        name = sel.name
        if name == "__schema" or name == "__type":
            from dgraph_tpu.graphql.introspection import resolve_introspection

            return resolve_introspection(self.types, sel)
        qt = self.types.get("Query")
        if qt is not None:
            f = qt.fields.get(name)
            if f is not None and f.custom is not None:
                return self._resolve_custom(f, sel)
            if f is not None and f.is_lambda:
                return self._resolve_lambda_root("Query", f, sel)
        if name == "_entities":
            return self._entities(sel)
        if name == "_service":
            return {
                s.key: self.sdl for s in sel.selections if s.name == "sdl"
            }
        if name.startswith("check") and name.endswith("Password"):
            t = self.types.get(name[len("check") : -len("Password")])
            if t is not None:
                return self._check_password(t, sel)
        if name.startswith("get"):
            t = self._type_for(name, ["get"])
            return self._get(t, sel)
        if name.startswith("querySimilar") and name.endswith("ByEmbedding"):
            tname = name[len("querySimilar") : -len("ByEmbedding")]
            t = self.types.get(tname)
            if not t:
                raise GraphQLError(f"unknown type {tname}")
            return self._similar(t, sel)
        if name.startswith("querySimilar") and name.endswith("ById"):
            tname = name[len("querySimilar") : -len("ById")]
            t = self.types.get(tname)
            if not t:
                raise GraphQLError(f"unknown type {tname}")
            return self._similar(t, sel, by_id=True)
        if name.startswith("query"):
            t = self._type_for(name, ["query"])
            return self._query_list(t, sel)
        if name.startswith("aggregate"):
            t = self._type_for(name, ["aggregate"])
            return self._aggregate(t, sel)
        raise GraphQLError(f"unknown query {name!r}")

    def _concrete(self, row_types, fallback: str) -> str:
        """The concrete (non-interface) type among a row's dgraph.type
        values — what __typename must report for interface/union
        results (ref outputnode_graphql.go)."""
        for n in row_types or []:
            tt = self.types.get(n) or self._by_stored().get(n)
            if tt is not None and tt.kind == "type":
                return tt.name
        return fallback

    def _add_typename(self, results, t: GqlType, sels: List[Selection]):
        """Post-encode shaping: prune inline-fragment fields that don't
        apply to a row's concrete type, inject __typename (concrete via
        the hidden __dgt fetch), drop __dgt."""
        for r in results:
            if isinstance(r, dict):
                self._shape_row(r, t, sels)
        return results

    def _shape_row(self, row: dict, t: GqlType, sels: List[Selection]):
        row.pop("__uid", None)
        row_types = row.pop("__dgt", None)
        if isinstance(row_types, str):
            row_types = [row_types]
        keep: Dict[str, tuple] = {}

        def collect(tt: GqlType, ss: List[Selection]):
            for s in ss:
                if s.name == "...":
                    ft = (
                        tt if not s.frag_on else self.types.get(s.frag_on)
                    )
                    if ft is None:
                        continue
                    # with no __dgt fetched (object-type parent) every
                    # fragment matched statically; otherwise the row's
                    # dgraph.type list (which includes interfaces)
                    # decides
                    frag_t = self.types.get(s.frag_on)
                    if (
                        not s.frag_on
                        or row_types is None
                        or s.frag_on in row_types
                        or (
                            frag_t is not None
                            and frag_t.stored_name in row_types
                        )
                    ):
                        collect(ft, s.selections)
                elif s.name == "__typename":
                    row[s.key] = self._concrete(row_types, tt.name)
                    keep.setdefault(s.key, (tt, s))
                elif (
                    s.name.endswith("Aggregate")
                    and s.name[: -len("Aggregate")] in tt.fields
                ):
                    if s.key in keep:
                        continue  # already computed (fragment overlap)
                    base_f = tt.fields[s.name[: -len("Aggregate")]]
                    ct = self.types.get(base_f.type_name)
                    if (
                        ct is not None
                        and self._auth(ct, "query") is False
                    ):
                        # deny-all child auth: null, not count 0 (the
                        # hidden fetch was never emitted)
                        row[s.key] = None
                        keep.setdefault(s.key, (tt, s))
                        continue
                    items = row.pop(f"__agg_{s.key}", None) or []
                    if not isinstance(items, list):
                        items = [items]
                    row[s.key] = _compute_child_agg(
                        s, items, base_f.type_name
                    )
                    keep.setdefault(s.key, (tt, s))
                else:
                    keep.setdefault(s.key, (tt, s))

        collect(t, sels)
        for k in list(row.keys()):
            if k not in keep and not k.startswith("__lp_"):
                row.pop(k)
        for k, (tt, s) in keep.items():
            v = row.get(k)
            f = tt.fields.get(s.name)
            if v is None or f is None:
                continue
            if f.type_name in ("Point", "Polygon", "MultiPolygon"):
                row[k] = (
                    [_geojson_to_gql(x) for x in v]
                    if isinstance(v, list)
                    else _geojson_to_gql(v)
                )
                continue
            if f.is_scalar:
                continue
            ct = self.types.get(f.type_name)
            if ct is None:
                continue
            if isinstance(v, list):
                for item in v:
                    if isinstance(item, dict):
                        self._shape_row(item, ct, s.selections)
            elif isinstance(v, dict):
                self._shape_row(v, ct, s.selections)

    def _resolve_custom(self, f: GqlField, sel: Selection):
        """@custom(http: {...}) resolver (ref graphql/schema/remote.go +
        resolve/http.go): substitute $args into the URL/body template,
        call the endpoint, project the selection over the JSON reply."""
        import json as _json
        import urllib.parse
        import urllib.request

        from dgraph_tpu.graphql.introspection import _project

        cfg = (f.custom or {}).get("http")
        if not cfg:
            raise GraphQLError(f"@custom field {f.name} has no http config")
        if cfg.get("graphql"):
            # remote-graphql mode (ref resolve/http.go graphql path):
            # POST {query, variables} and unwrap data.<opName>
            from dgraph_tpu.graphql.remote import _OP_RE

            import re as _re

            op_text = cfg["graphql"]
            for k, v in sel.args.items():
                lit = _gql_literal(v).replace("\\", "\\\\")
                op_text = _re.sub(rf"\$({k})\b", lit, op_text)
            # unsupplied optional args: drop `name: $var` pairs rather
            # than sending literal $var tokens to the remote
            op_text = _re.sub(r"\w+\s*:\s*\$\w+\s*,?", "", op_text)
            op_text = _re.sub(r"\(\s*\)", "", op_text)
            req = urllib.request.Request(
                cfg.get("url", ""),
                data=_json.dumps({"query": op_text}).encode(),
                method="POST",
                headers={"Content-Type": "application/json"},
            )
            try:
                with urllib.request.urlopen(req, timeout=10) as r:
                    payload = _json.loads(r.read() or b"null")
            except Exception as e:
                raise GraphQLError(
                    f"@custom graphql call failed: {e}"
                ) from e
            if payload.get("errors"):
                raise GraphQLError(str(payload["errors"]))
            m = _OP_RE.search(cfg["graphql"])
            data = (payload.get("data") or {}).get(
                m.group(2) if m else f.name
            )
            if sel.selections and isinstance(data, (dict, list)):
                return _project(data, sel.selections)
            return data
        url = cfg.get("url", "")
        for k, v in sel.args.items():
            url = url.replace(f"${k}", urllib.parse.quote(str(v)))
        method = str(cfg.get("method", "GET")).upper()
        body = None
        if cfg.get("body"):
            from dgraph_tpu.graphql.auth import _parse_gql_object, _substitute

            tmpl = _parse_gql_object(cfg["body"]) if isinstance(
                cfg["body"], str
            ) else cfg["body"]
            body = _json.dumps(_substitute(tmpl, sel.args)).encode()
        req = urllib.request.Request(url, data=body, method=method)
        if body is not None:
            req.add_header("Content-Type", "application/json")
        try:
            with urllib.request.urlopen(req, timeout=10) as r:
                payload = _json.loads(r.read() or b"null")
        except Exception as e:
            raise GraphQLError(f"@custom http call failed: {e}") from e
        if sel.selections and isinstance(payload, (dict, list)):
            return _project(payload, sel.selections)
        return payload

    # ------------------------------------------------------------------
    # @lambda (ref wrappers.go buildCustomDirectiveForLambda,
    # custom_http.go GetBodyForLambda)
    # ------------------------------------------------------------------

    def _lambda_post(self, body: dict):
        import json as _json
        import urllib.request

        if not self.lambda_url:
            raise GraphQLError(
                "@lambda field used but no lambda-url configured "
                "(--graphql lambda-url / DGRAPH_TPU_LAMBDA_URL)"
            )
        req = urllib.request.Request(
            self.lambda_url,
            data=_json.dumps(body).encode(),
            method="POST",
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            return _json.loads(r.read() or b"null")

    def _resolve_lambda_root(self, parent: str, f: GqlField, sel: Selection):
        """Query./Mutation.-level @lambda: POST {resolver, args} and return
        the lambda server's value, projected over the selection."""
        from dgraph_tpu.graphql.introspection import _project

        try:
            payload = self._lambda_post(
                {
                    "resolver": f"{parent}.{f.name}",
                    "args": sel.args,
                    "parents": None,
                    "authHeader": self._lambda_auth_header(),
                }
            )
        except GraphQLError:
            raise
        except Exception as e:
            raise GraphQLError(f"@lambda call failed: {e}") from e
        if sel.selections and isinstance(payload, (dict, list)):
            return _project(payload, sel.selections)
        return payload

    def _lambda_auth_header(self):
        cfg = self.auth_config
        if not cfg:
            return None
        return {"key": getattr(cfg, "header", None), "value": None}

    def _enrich_lambda_fields(
        self, t: GqlType, sels: List[Selection], rows: List[dict]
    ) -> None:
        """BATCH-mode @lambda on type fields: one POST per (type, field)
        with every row's scalar fields as `parents`; the response array
        aligns with parents (ref wrappers.go BATCH mode). Recurses into
        object-valued children; hidden __lp_ scalars are stripped."""
        if not rows:
            return
        # inline-fragment selections contribute their fields too: the
        # over-approximation (a fragment on a sibling type) is harmless
        # because _shape_row prunes non-applicable keys per row after
        sels = list(sels)
        for s in list(sels):
            if s.name == "...":
                ft = t if not s.frag_on else self.types.get(s.frag_on)
                if ft is not None:
                    sels.extend(s.selections)
        lam = [
            s
            for s in sels
            if t.fields.get(s.name) is not None and t.fields[s.name].is_lambda
        ]
        for s in sels:  # recurse into nested objects first
            f = t.fields.get(s.name)
            if f is None or f.is_scalar or f.is_lambda:
                continue
            ct = self.types.get(f.type_name)
            if ct is None:
                continue
            for row in rows:
                v = row.get(s.key)
                if isinstance(v, list):
                    self._enrich_lambda_fields(ct, s.selections, v)
                elif isinstance(v, dict):
                    self._enrich_lambda_fields(ct, s.selections, [v])
        if lam:
            parents = []
            for row in rows:
                p = {}
                for fn, fdef in t.fields.items():
                    if not fdef.is_scalar or fdef.is_lambda or fdef.custom:
                        continue
                    if fn in row:
                        p[fn] = row[fn]
                    elif f"__lp_{fn}" in row:
                        p[fn] = row[f"__lp_{fn}"]
                parents.append(p)
            for s in lam:
                try:
                    got = self._lambda_post(
                        {
                            "resolver": f"{t.name}.{s.name}",
                            "parents": parents,
                            "authHeader": self._lambda_auth_header(),
                        }
                    )
                except GraphQLError:
                    raise
                except Exception as e:
                    raise GraphQLError(f"@lambda call failed: {e}") from e
                if isinstance(got, list):
                    if len(got) != len(rows):
                        raise GraphQLError(
                            f"@lambda {t.name}.{s.name}: BATCH response has "
                            f"{len(got)} values for {len(rows)} parents"
                        )
                    vals = got
                else:
                    vals = [got] * len(rows)
                for row, v in zip(rows, vals):
                    row[s.key] = v
        for row in rows:
            for k in [k for k in row if k.startswith("__lp_")]:
                del row[k]

    def _run_block(self, gq: GraphQuery, cache=None) -> List[dict]:
        if cache is None:
            cache = LocalCache(
                self.engine.kv,
                self.engine.zero.read_ts(),
                mem=getattr(self.engine, "mem", None),
            )
        ex = Executor(
            cache, self.engine.schema, vector_indexes=self.engine.vector_indexes
        )
        nodes = ex.process([gq])
        enc = JsonEncoder(val_vars=ex.val_vars, schema=self.engine.schema)
        return enc.encode_blocks(nodes).get(gq.attr, [])

    def _merge_child_auth(self, ct: GqlType, child: GraphQuery):
        """Nested selections honor the CHILD type's query @auth rules
        (ref auth_query_rewriting: every traversal level gets its own
        uid-var auth filter — `Contact.adminTasks @filter(uid(...))`)."""
        if ct.kind == "union":
            return
        if getattr(self._tls, "in_auth_rule", False):
            return  # auth rule queries are not themselves auth-filtered
        auth = self._auth(ct, "query")
        if auth is True:
            return
        if auth is False:
            # matches nothing: uid-in-empty-set filter
            extra = FilterTree(func=FuncSpec(name="uid", args=[]))
        else:
            extra = self._filter_tree(ct, auth)
        child.filter = (
            extra
            if child.filter is None
            else FilterTree(op="and", children=[child.filter, extra])
        )

    def _selection_children(
        self, t: GqlType, sels: List[Selection]
    ) -> List[GraphQuery]:
        out = []
        has_lambda = False
        selected = set()
        need_dgt = t.kind in ("interface", "union") and any(
            s.name in ("...", "__typename") for s in sels
        )
        if need_dgt:
            # concrete-type dispatch for fragments/__typename: fetch
            # dgraph.type hidden; _shape_rows prunes with it
            out.append(GraphQuery(attr="dgraph.type", alias="__dgt"))
        for s in sels:
            if s.name == "...":
                # no type condition ('... { x }') means the enclosing type
                ft = t if not s.frag_on else self.types.get(s.frag_on)
                if ft is None or ft.kind not in ("type", "interface"):
                    raise GraphQLError(
                        f"fragment on unknown type {s.frag_on!r}"
                    )
                for c in self._selection_children(ft, s.selections):
                    if not any(
                        o.alias == c.alias and o.attr == c.attr
                        for o in out
                    ):
                        out.append(c)
                continue
            if (
                s.name.endswith("Aggregate")
                and s.name[: -len("Aggregate")] in t.fields
            ):
                # child-level aggregate field (ref gqlschema.go: every
                # object field f gets fAggregate(filter): visible as a
                # nested {count, <g>Min, ...} object). Fetch the child
                # edge hidden; _shape_row computes the aggregate.
                base = s.name[: -len("Aggregate")]
                bf = t.fields[base]
                ct = self.types.get(bf.type_name)
                hidden = GraphQuery(
                    attr=t.pred(base), alias=f"__agg_{s.key}"
                )
                if s.args.get("filter") and ct is not None:
                    hidden.filter = self._filter_tree(ct, s.args["filter"])
                if ct is not None:
                    if (
                        not getattr(self._tls, "in_auth_rule", False)
                        and self._auth(ct, "query") is False
                    ):
                        # deny-all child auth: the aggregate resolves
                        # null, NOT count 0 (ref auth_query_rewriting
                        # aggregate cases)
                        continue
                    self._merge_child_auth(ct, hidden)
                need = set()
                for a in s.selections:
                    for suffix in ("Min", "Max", "Sum", "Avg"):
                        if a.name.endswith(suffix):
                            need.add(a.name[: -len(suffix)])
                            break
                for fn in sorted(need):
                    if ct is not None and fn in ct.fields:
                        hidden.children.append(
                            GraphQuery(attr=ct.pred(fn), alias=fn)
                        )
                if not hidden.children:
                    hidden.children.append(
                        GraphQuery(attr="uid", is_uid=True, alias="uid")
                    )
                out.append(hidden)
                continue
            f = t.fields.get(s.name)
            if s.name == "__typename":
                continue  # injected post-encode (_shape_rows)
            if f is not None and f.is_lambda:
                has_lambda = True  # resolved post-query via the lambda URL
                continue
            if s.name == "id" or (f and f.type_name == "ID"):
                out.append(GraphQuery(attr="uid", is_uid=True, alias=s.key))
                continue
            if f is None:
                raise GraphQLError(f"no field {s.name!r} on type {t.name}")
            selected.add(s.name)
            child = GraphQuery(attr=t.pred(f.name), alias=s.key)
            if not f.is_scalar:
                ct = self.types.get(f.type_name)
                if ct is None:
                    raise GraphQLError(f"unknown type {f.type_name}")
                child.children = self._selection_children(ct, s.selections)
                # every object level carries uid (ref query_rewriter.go
                # injects dgraph.uid), so an entity whose requested
                # scalars are all absent still materializes as a row —
                # GraphQL returns it with null fields, DQL would omit it
                if not any(
                    c.alias == "__uid" for c in child.children
                ):
                    child.children.append(
                        GraphQuery(attr="uid", is_uid=True, alias="__uid")
                    )
                # per-field args (ref query_rewriter.go addArgumentsToField):
                # filter/order/first/offset apply to the edge expansion
                if s.args.get("filter"):
                    if ct.kind == "union":
                        child.filter = self._union_filter(
                            ct, s.args["filter"]
                        )
                    else:
                        child.filter = self._filter_tree(
                            ct, s.args["filter"]
                        )
                order = s.args.get("order") or {}
                self._apply_order(ct, child, order)
                if s.args.get("first") is not None:
                    child.first = s.args["first"]
                if s.args.get("offset") is not None:
                    child.offset = s.args["offset"]
                self._apply_cascade_dir(ct, s, child)
                self._merge_child_auth(ct, child)
            out.append(child)
        if has_lambda:
            # lambda parents carry ALL scalar fields of the type
            # (wrappers.go body template); fetch unselected ones hidden
            for fn, fdef in t.fields.items():
                if (
                    fdef.is_scalar
                    and not fdef.is_lambda
                    and not fdef.custom
                    and fdef.type_name != "ID"
                    and fn not in selected
                ):
                    out.append(
                        GraphQuery(
                            attr=t.pred(fn), alias=f"__lp_{fn}"
                        )
                    )
        # one fetch per (alias, attr): a field selected both plainly and
        # inside a matching fragment must not be fetched twice
        seen = set()
        dedup = []
        for c in out:
            key = (c.alias, c.attr)
            if key in seen:
                continue
            seen.add(key)
            dedup.append(c)
        return dedup

    def _union_filter(self, ut: GqlType, fobj: dict) -> Optional[FilterTree]:
        """Union member filter (ref query_rewriter.go buildUnionFilter):
        {memberTypes: [Dog, Parrot], dogFilter: {...}} -> OR over the
        named member types, each AND'd with its member filter when one
        is given. No memberTypes = all members."""
        members = _as_list(fobj.get("memberTypes") or ut.members)
        parts = []
        for mname in members:
            if mname not in ut.members:
                raise GraphQLError(
                    f"{mname} is not a member of union {ut.name}"
                )
            mt = self.types.get(mname)
            tf = FilterTree(func=FuncSpec(
                        name="type",
                        attr=mt.stored_name if mt else mname,
                    ))
            sub = fobj.get(mname[0].lower() + mname[1:] + "Filter")
            if sub and mt is not None:
                inner = self._filter_tree(mt, sub)
                if inner is not None:
                    tf = FilterTree(op="and", children=[tf, inner])
            parts.append(tf)
        if not parts:
            return None
        if len(parts) == 1:
            return parts[0]
        return FilterTree(op="or", children=parts)

    def _filter_tree(self, t: GqlType, fobj: dict) -> Optional[FilterTree]:
        """ref resolve/query_rewriter.go compileFilter: within one
        filter object the field comparisons and `and`/`not` clauses
        conjoin; an `or` clause disjoins with THAT conjunction —
        {f: X, or: {g: Y}} means (f=X) OR (g=Y), not AND."""
        parts: List[FilterTree] = []
        ors: List[FilterTree] = []
        for k, v in (fobj or {}).items():
            if k == "and":
                subs = [self._filter_tree(t, x) for x in _as_list(v)]
                parts.append(FilterTree(op="and", children=[s for s in subs if s]))
            elif k == "or":
                subs = [self._filter_tree(t, x) for x in _as_list(v)]
                ors.extend(s for s in subs if s)
            elif k == "not":
                sub = self._filter_tree(t, v)
                if sub:
                    parts.append(FilterTree(op="not", children=[sub]))
            elif k == "id":
                # ref query_rewriter.go convertIDs: unparseable or
                # out-of-range ids are silently dropped from the list
                uids = [
                    u for u in (_parse_uid(x) for x in _as_list(v))
                    if u is not None
                ]
                parts.append(
                    FilterTree(func=FuncSpec(name="uid", args=uids))
                )
            elif k == "has":
                for fname in _as_list(v):
                    f = t.fields.get(fname)
                    if f is None:
                        raise GraphQLError(f"no field {fname!r}")
                    parts.append(
                        FilterTree(
                            func=FuncSpec(name="has", attr=t.pred(fname))
                        )
                    )
            else:
                f = t.fields.get(k)
                if f is None:
                    raise GraphQLError(f"no field {k!r} on {t.name}")
                if f.type_name == "ID":
                    # an ID-named field (postID etc.) filters by uid,
                    # same as the generic "id" key
                    uids = [
                        u
                        for u in (_parse_uid(x) for x in _as_list(v))
                        if u is not None
                    ]
                    parts.append(
                        FilterTree(func=FuncSpec(name="uid", args=uids))
                    )
                    continue
                attr = t.pred(k)
                if not isinstance(v, dict):
                    v = {"eq": v}
                for opname, arg in v.items():
                    if arg is None:
                        # ref query_rewriter.go: {eq: null} matches
                        # nodes WITHOUT the predicate (NOT has); any
                        # other null-valued comparison is dropped
                        if opname == "eq":
                            parts.append(
                                FilterTree(
                                    op="not",
                                    children=[
                                        FilterTree(
                                            func=FuncSpec(
                                                name="has", attr=attr
                                            )
                                        )
                                    ],
                                )
                            )
                        continue
                    fn = _FILTER_OPS.get(opname)
                    if fn is None:
                        raise GraphQLError(f"bad filter op {opname!r}")
                    if opname == "in":
                        args = _as_list(arg)
                    elif opname == "between":
                        args = [arg.get("min"), arg.get("max")]
                    elif opname == "near":
                        c = arg.get("coordinate", {})
                        args = [
                            [c.get("longitude"), c.get("latitude")],
                            arg.get("distance"),
                        ]
                    elif opname in ("within", "intersects"):
                        if "multiPolygon" in arg:
                            args = [
                                [
                                    _gql_polygon_coords(p)
                                    for p in arg["multiPolygon"].get(
                                        "polygons", []
                                    )
                                ]
                            ]
                        else:
                            args = [
                                _gql_polygon_coords(arg.get("polygon", {}))
                            ]
                    elif opname == "contains":
                        if "point" in arg:
                            pt = arg["point"]
                            args = [
                                [pt.get("longitude"), pt.get("latitude")]
                            ]
                        else:
                            args = [
                                _gql_polygon_coords(arg.get("polygon", {}))
                            ]
                    elif opname == "regexp":
                        pat = str(arg)
                        if pat.startswith("/"):
                            end = pat.rindex("/")
                            args = [("regex", pat[1:end], pat[end + 1 :])]
                        else:
                            args = [("regex", pat, "")]
                    else:
                        args = [arg]
                    parts.append(
                        FilterTree(func=FuncSpec(name=fn, attr=attr, args=args))
                    )
        base: Optional[FilterTree]
        if not parts:
            base = None
        elif len(parts) == 1:
            base = parts[0]
        else:
            base = FilterTree(op="and", children=parts)
        for o in ors:
            base = (
                o
                if base is None
                else FilterTree(op="or", children=[base, o])
            )
        return base

    def _apply_cascade_dir(self, t: GqlType, sel: Selection, gq):
        """@cascade / @cascade(fields: [...]) on a field (ref
        query_rewriter.go addCascadeDirective)."""
        for dname, dargs in sel.directives:
            if dname != "cascade":
                continue
            gq.cascade = True
            for fn in _as_list(dargs.get("fields") or []):
                if fn == "id":
                    continue  # uid always present
                f = t.fields.get(fn)
                gq.cascade_fields.append(
                    t.pred(fn) if f is not None else fn
                )

    def _apply_order(self, t: GqlType, gq, order: dict):
        """order: {asc|desc: field, then: {...}} — nested `then` chains
        secondary sort keys (ref gqlschema.go order input synthesis)."""
        while order:
            if "asc" in order:
                gq.order.append(Order(attr=t.pred(order["asc"])))
            if "desc" in order:
                gq.order.append(Order(attr=t.pred(order["desc"]), desc=True))
            order = order.get("then") or {}

    def _query_list(self, t: GqlType, sel: Selection) -> List[dict]:
        fobj, allowed = self._with_auth_filter(t, sel.args.get("filter"))
        if not allowed:
            return []
        gq = GraphQuery(attr="q")
        gq.func = FuncSpec(name="type", attr=t.stored_name)
        gq.filter = self._filter_tree(t, fobj)
        if t.kind == "interface" and not getattr(
            self._tls, "in_auth_rule", False
        ):
            pre = self._match_filter_uids(t, fobj, "query")
            if pre is not None:
                # implementer auth applies BEFORE pagination (the
                # reference injects it into the query itself)
                gq.func = FuncSpec(name="uid", args=pre)
                gq.filter = None
        self._apply_cascade_dir(t, sel, gq)
        self._apply_order(t, gq, sel.args.get("order") or {})
        gq.first = sel.args.get("first")
        gq.offset = sel.args.get("offset")
        gq.children = self._selection_children(t, sel.selections)
        # rows materialize on uid even when every selected scalar is
        # absent (ref query_rewriter.go injects dgraph.uid at the root)
        if not any(c.alias == "__uid" for c in gq.children):
            gq.children.append(
                GraphQuery(attr="uid", is_uid=True, alias="__uid")
            )
        rows = self._run_block(gq)
        self._enrich_lambda_fields(t, sel.selections, rows)
        return self._add_typename(rows, t, sel.selections)

    def _entities(self, sel: Selection) -> List[dict]:
        """Apollo federation _entities(representations: [...]) (ref
        graphql/resolve entitiesQuery rewrite): group representations by
        __typename, fetch each batch by its @key field ordered asc."""
        reps = _as_list(sel.args.get("representations") or [])
        by_type: Dict[str, List[Any]] = {}
        for r in reps:
            tn = r.get("__typename")
            t = self.types.get(tn)
            if t is None or not t.key_field:
                raise GraphQLError(
                    f"unknown or keyless type in representation: {tn!r}"
                )
            by_type.setdefault(tn, []).append(r.get(t.key_field))
        # resolve each type batch (fetched orderasc by key, matching the
        # reference dgquery), then reorder to match the representations
        # argument positionally — Apollo merges results by index (ref
        # resolve/resolver.go:322 entitiesQueryCompletion). Duplicate keys
        # duplicate rows; but if ANY unique key resolved to no row the
        # reference returns the fetched rows as-is, unordered and
        # un-padded (resolver.go:394 — "This will end into an error at
        # the Gateway, so no need to order the result here").
        rows_by_key: Dict[tuple, dict] = {}
        fetched: List[dict] = []
        n_unique = 0
        for tn, keyvals in by_type.items():
            t = self.types[tn]
            n_unique += len(set(keyvals))
            gq = GraphQuery(attr="q")
            gq.func = FuncSpec(
                name="eq", attr=t.pred(t.key_field), args=keyvals
            )
            gq.order.append(Order(attr=t.pred(t.key_field)))
            gq.filter = FilterTree(
                func=FuncSpec(name="type", attr=t.stored_name)
            )
            frags = [
                s
                for s in sel.selections
                if s.name == "..." and s.frag_on in (tn, "")
            ]
            sels = [x for s in frags for x in s.selections]
            gq.children = self._selection_children(t, sels)
            gq.children.append(
                GraphQuery(attr=t.pred(t.key_field), alias="__key")
            )
            rows = self._run_block(gq)
            keys_ = [r.pop("__key", None) for r in rows]
            self._add_typename(rows, t, sels)
            fetched.extend(rows)
            for k, r in zip(keys_, rows):
                rows_by_key[(tn, k)] = r
        if len(fetched) < n_unique:
            return fetched
        out: List[Optional[dict]] = []
        for r in reps:
            tn = r.get("__typename")
            k = r.get(self.types[tn].key_field)
            out.append(rows_by_key.get((tn, k)))
        return out

    def _check_password(self, t: GqlType, sel: Selection) -> Optional[dict]:
        """checkTPassword(xid/id, <secretField>) -> T | null (ref
        query_rewriter.go passwordQuery: eq-root + checkPwd filter)."""
        sf = next(
            (f for f in t.fields.values() if f.is_secret), None
        )
        if sf is None:
            raise GraphQLError(f"{t.name} has no @secret field")
        pwd = sel.args.get(sf.name)
        gq = GraphQuery(attr="q")
        xf = t.xid_field()
        if xf is not None and xf.name in sel.args:
            gq.func = FuncSpec(
                name="eq", attr=t.pred(xf.name), args=[sel.args[xf.name]]
            )
        else:
            u = _parse_uid(sel.args.get("id"))
            if u is None:
                return None
            gq.func = FuncSpec(name="uid", args=[u])
        gq.filter = FilterTree(
            op="and",
            children=[
                FilterTree(func=FuncSpec(name="type", attr=t.stored_name)),
                FilterTree(
                    func=FuncSpec(
                        name="checkpwd",
                        attr=t.pred(sf.name),
                        args=[pwd],
                    )
                ),
            ],
        )
        gq.children = self._selection_children(t, sel.selections)
        res = self._run_block(gq)
        self._add_typename(res, t, sel.selections)
        return res[0] if res else None

    def _get(self, t: GqlType, sel: Selection) -> Optional[dict]:
        gq = GraphQuery(attr="q")
        idf = t.id_field()
        id_key = idf.name if idf is not None else "id"
        id_arg = sel.args.get(id_key, sel.args.get("id"))
        if id_arg is not None:
            u = _parse_uid(id_arg)
            if u is None:
                return None
            gq.func = FuncSpec(name="uid", args=[u])
            gq.filter = FilterTree(func=FuncSpec(name="type", attr=t.stored_name))
        else:
            xf = t.xid_field()
            if xf is None or xf.name not in sel.args:
                # ref rewrites an argless get to uid(0x0) — null result
                return None
            gq.func = FuncSpec(
                name="eq",
                attr=t.pred(xf.name),
                args=[sel.args[xf.name]],
            )
        auth = self._auth(t, "query")
        if auth is False:
            return None
        if isinstance(auth, dict):
            extra = self._filter_tree(t, auth)
            gq.filter = (
                extra
                if gq.filter is None
                else FilterTree(op="and", children=[gq.filter, extra])
            )
        self._apply_cascade_dir(t, sel, gq)
        gq.children = self._selection_children(t, sel.selections)
        if not any(c.alias == "__uid" for c in gq.children):
            gq.children.append(
                GraphQuery(attr="uid", is_uid=True, alias="__uid")
            )
        res = self._run_block(gq)
        if (
            res
            and t.kind == "interface"
            and not getattr(self._tls, "in_auth_rule", False)
        ):
            # getX through an interface honors implementer auth too
            u = int(res[0].get("__uid", "0x0"), 16)
            if not self._apply_interface_auth(t, [u], "query"):
                return None
        self._enrich_lambda_fields(t, sel.selections, res)
        self._add_typename(res, t, sel.selections)
        return res[0] if res else None

    def _aggregate(self, t: GqlType, sel: Selection) -> dict:
        """aggregateT(filter) { count fieldMin fieldMax fieldSum fieldAvg }
        (ref gqlschema.go aggregate type synthesis)."""
        fobj, allowed = self._with_auth_filter(t, sel.args.get("filter"))
        if not allowed:
            # denied aggregate resolves to null (ref `aggregateX()`)
            return None
        gq = GraphQuery(attr="q")
        gq.func = FuncSpec(name="type", attr=t.stored_name)
        gq.filter = self._filter_tree(t, fobj)
        if t.kind == "interface" and not getattr(
            self._tls, "in_auth_rule", False
        ):
            pre = self._match_filter_uids(t, fobj, "query")
            gq.func = FuncSpec(name="uid", args=pre)
            gq.filter = None
        count_keys = [s.key for s in sel.selections if s.name == "count"]
        count_key = count_keys[0] if count_keys else "count"
        gq.children = [GraphQuery(attr="uid", is_count=True, alias=count_key)]

        # map selections like ageMin/ageMax/ageSum/ageAvg to aggregators
        aggs = []  # (sel_key, field, op)
        for s in sel.selections:
            if s.name == "count":
                continue
            for suffix, op in (
                ("Min", "min"), ("Max", "max"), ("Sum", "sum"), ("Avg", "avg"),
            ):
                if s.name.endswith(suffix):
                    fname = s.name[: -len(suffix)]
                    f = t.fields.get(fname)
                    if f is not None and f.is_scalar:
                        aggs.append((s.key, fname, op))
                    break
        var_of = {}
        for i, (_, fname, _) in enumerate(aggs):
            if fname not in var_of:
                var_of[fname] = f"v{i}"
                gq.children.append(
                    GraphQuery(
                        attr=t.pred(fname), var_name=var_of[fname]
                    )
                )
        for key, fname, op in aggs:
            gq.children.append(
                GraphQuery(aggregator=op, val_var=var_of[fname], alias=key)
            )
        res = self._run_block(gq)
        out = {count_key: 0}
        for obj in res:
            out.update(obj)
        for k in count_keys[1:]:  # repeated count under other aliases
            out[k] = out.get(count_key, 0)
        wanted = {s.key for s in sel.selections}
        out = {k: v for k, v in out.items() if k in wanted}
        for s in sel.selections:
            if s.name == "__typename":
                # ref gqlschema.go names the result type TAggregateResult
                out[s.key] = f"{t.name}AggregateResult"
            else:  # absent aggregates -> null
                out.setdefault(s.key, None)
        return out

    def _similar(
        self, t: GqlType, sel: Selection, by_id: bool = False
    ) -> List[dict]:
        by = sel.args.get("by")
        topk = int(sel.args.get("topK", 10))
        import json as _json

        if by_id:
            # querySimilarTById: the query vector is the given node's
            # own embedding (ref query_rewriter.go rewriteVectorSearch
            # uid->vec var chain); results include the node itself
            u = _parse_uid(sel.args.get("id"))
            if u is None:
                return []
            probe = GraphQuery(attr="q")
            probe.func = FuncSpec(name="uid", args=[u])
            probe.children = [
                GraphQuery(attr=t.pred(by), alias="__v")
            ]
            got = self._run_block(probe)
            if not got or got[0].get("__v") is None:
                return []
            vec = got[0]["__v"]
        else:
            vec = sel.args.get("vector")
        gq = GraphQuery(attr="q")
        gq.func = FuncSpec(
            name="similar_to",
            attr=t.pred(by),
            args=[topk, _json.dumps(_as_list(vec))],
        )
        dist_sels = [
            s for s in sel.selections if s.name == "vector_distance"
        ]
        plain = [s for s in sel.selections if s.name != "vector_distance"]
        gq.children = self._selection_children(t, plain)
        if dist_sels:
            # fetch each hit's embedding hidden; distance computed here
            # (ref query_rewriter.go appends val(distance) the same way)
            gq.children.append(
                GraphQuery(attr=t.pred(by), alias="__simv")
            )
        rows = self._run_block(gq)
        dists = []
        if dist_sels:
            # the embedding's search metric picks the distance formula
            # (ref query_rewriter.go:669 distanceFormula)
            metric = "euclidean"
            bf = t.fields.get(by)
            for tok in bf.search if bf is not None else []:
                if tok in ("cosine", "dotproduct"):
                    metric = tok
            qv = np.asarray(_as_list(vec), np.float64)
            for r in rows:
                v = np.asarray(_as_list(r.pop("__simv", []) or []), np.float64)
                if v.size != qv.size or not v.size:
                    dists.append(None)
                elif metric == "cosine":
                    denom = float(
                        np.linalg.norm(v) * np.linalg.norm(qv)
                    )
                    dists.append(
                        1.0 - float(np.dot(v, qv)) / denom
                        if denom
                        else None
                    )
                elif metric == "dotproduct":
                    dists.append(1.0 - float(np.dot(v, qv)))
                else:
                    dists.append(float(np.sqrt(((v - qv) ** 2).sum())))
        self._enrich_lambda_fields(t, plain, rows)
        self._add_typename(rows, t, plain)
        for i, r in enumerate(rows):  # after shaping, it must survive
            for s in dist_sels:
                r[s.key] = dists[i]
        return rows

    # ------------------------------------------------------------------
    # Mutations (ref resolve/mutation_rewriter.go)
    # ------------------------------------------------------------------

    def _fire_webhook(self, t: GqlType, op: str, uids: List[int], sel: Selection):
        """@lambdaOnMutate fire-and-forget webhook (ref resolve/webhook.go
        sendWebhookEvent; payload shape webhookPayload/eventPayload)."""
        if not t.lambda_on_mutate.get(op) or not self.lambda_url:
            return
        event: Dict[str, Any] = {
            "__typename": t.name,
            "operation": op,
            "commitTs": 0,
        }
        root_uids = [f"0x{u:x}" for u in uids]
        if op == "add":
            event["add"] = {
                "rootUIDs": root_uids,
                "input": _as_list(sel.args.get("input", [])),
            }
        elif op == "update":
            inp = sel.args.get("input", {}) or {}
            event["update"] = {
                "rootUIDs": root_uids,
                "setPatch": inp.get("set"),
                "removePatch": inp.get("remove"),
            }
        else:
            event["delete"] = {"rootUIDs": root_uids}
        body = {"resolver": "$webhook", "event": event}

        import threading

        def post():
            try:
                self._lambda_post(body)
            except Exception:
                pass  # at-most-once, errors only logged by the reference too

        threading.Thread(target=post, daemon=True).start()

    def _resolve_mutation(self, sel: Selection):
        if getattr(self.engine, "draining", False):
            raise GraphQLError("the server is in draining mode")
        name = sel.name
        mt = self.types.get("Mutation")
        if mt is not None:
            f = mt.fields.get(name)
            if f is not None and f.custom is not None:
                return self._resolve_custom(f, sel)
            if f is not None and f.is_lambda:
                return self._resolve_lambda_root("Mutation", f, sel)
        if name.startswith("add"):
            return self._add(self._type_for(name, ["add"]), sel)
        if name.startswith("update"):
            return self._update(self._type_for(name, ["update"]), sel)
        if name.startswith("delete"):
            return self._delete(self._type_for(name, ["delete"]), sel)
        raise GraphQLError(f"unknown mutation {name!r}")

    def _payload(self, t: GqlType, sel: Selection, uids: List[int], num: int):
        out: Dict[str, Any] = {}
        for s in sel.selections:
            if s.name == "numUids":
                out[s.key] = num
            elif s.name == "msg":
                out[s.key] = "Deleted" if sel.name.startswith("delete") else "Ok"
            elif s.name.lower() == t.name.lower():
                gq = GraphQuery(attr="q")
                gq.func = FuncSpec(name="uid", args=uids)
                gq.children = self._selection_children(t, s.selections)
                rows = self._run_block(gq)
                self._enrich_lambda_fields(t, s.selections, rows)
                self._add_typename(rows, t, s.selections)
                out[s.key] = rows
        return out

    # -- mutation write path (ref graphql/resolve/mutation_rewriter.go) --

    def _edge_targets(self, txn, uid: int, attr: str) -> List[int]:
        from dgraph_tpu.x import keys as _keys

        return [
            int(u)
            for u in txn.cache.uids(_keys.DataKey(attr, uid))
        ]

    def _by_stored(self) -> dict:
        """stored dgraph.type name -> GqlType (for @dgraph(type:) maps)."""
        m = getattr(self, "_stored_map", None)
        if m is None:
            m = self._stored_map = {
                t.stored_name: t for t in self.types.values()
            }
        return m

    def _node_types(self, txn, uid: int) -> set:
        from dgraph_tpu.x import keys as _keys

        tkey = _keys.DataKey("dgraph.type", uid)
        return {str(p.val().value) for p in txn.cache.values(tkey)}

    def _node_is(self, txn, uid: int, t: GqlType) -> bool:
        tys = self._node_types(txn, uid)
        if t.stored_name in tys:
            return True
        return t.kind == "interface" and any(
            self.types[m].stored_name in tys
            for m in t.implementers
            if m in self.types
        )

    def _xid_lookup(self, txn, pred: str, value) -> List[int]:
        ex = Executor(txn.cache, self.engine.schema)
        found = ex._runner().run_root(
            FuncSpec(name="eq", attr=pred, args=[value])
        )
        return [int(u) for u in found]

    def _write_ref_edge(
        self, txn, t: GqlType, uid: int, f: GqlField, target: int, op=OP_SET
    ):
        """Write uid -[t.f]-> target keeping @hasInverse pairs coherent:
        the inverse edge is written too, and when either side is
        single-valued the stale partner edges are removed — exactly the
        delete set the reference rewriter emits (mutation_rewriter.go
        addInverseLink + the NOT-uid var cleanup blocks)."""
        attr = t.pred(f.name)
        st = self.engine.schema
        ct = self.types.get(f.type_name)
        g = (
            ct.fields.get(f.has_inverse)
            if (ct is not None and f.has_inverse)
            else None
        )
        if g is not None and op == OP_SET:
            inv_attr = ct.pred(g.name)
            if not f.is_list:
                for old in self._edge_targets(txn, uid, attr):
                    if old != target:
                        self._check_additional_delete_auth(txn, ct, old)
                        apply_edge(
                            txn, st,
                            DirectedEdge(old, inv_attr, value_id=uid, op=OP_DEL),
                        )
            if not g.is_list:
                for old_src in self._edge_targets(txn, target, inv_attr):
                    if old_src != uid:
                        self._check_additional_delete_auth(txn, t, old_src)
                        apply_edge(
                            txn, st,
                            DirectedEdge(
                                old_src, attr, value_id=target, op=OP_DEL
                            ),
                        )
            apply_edge(
                txn, st, DirectedEdge(target, inv_attr, value_id=uid, op=op)
            )
        elif g is not None and op == OP_DEL:
            apply_edge(
                txn, st,
                DirectedEdge(target, ct.pred(g.name), value_id=uid, op=OP_DEL),
            )
        apply_edge(txn, st, DirectedEdge(uid, attr, value_id=target, op=op))

    def _check_additional_delete_auth(self, txn, ct: GqlType, uid: int):
        """Re-pointing a reference strips the stale edge from a THIRD
        node — that node must pass its type's update rule (ref
        update_rewriter additional-deletes authorization:
        \"couldn't rewrite query for mutation ... because
        authorization failed\")."""
        if ct.auth is None or ct.auth.update is None:
            return
        from dgraph_tpu.graphql.auth import evaluate

        # deep rules run on the txn snapshot, like every mutation auth
        # check (the edge being re-pointed may already be in this txn)
        auth = evaluate(
            ct.auth.update,
            self._claims(),
            rule_runner=lambda r, c: self._run_auth_rule(
                r, c, cache=txn.cache
            ),
        )
        if auth is True:
            return
        ok = (
            set()
            if auth is False
            else self._auth_allowed_uids(ct, auth, [uid], cache=txn.cache)
        )
        if uid not in ok:
            raise GraphQLError(
                "couldn't rewrite query for mutation because "
                "authorization failed"
            )

    def _set_field(
        self, txn, t: GqlType, uid: int, f: GqlField, value,
        op=OP_SET, ctx=None,
    ):
        attr = t.pred(f.name)
        if f.is_embedding:
            edge = DirectedEdge(
                uid, attr, value=Val(TypeID.VFLOAT, np.asarray(value, np.float32)),
                op=op,
            )
            apply_edge(txn, self.engine.schema, edge)
            return
        if not f.is_scalar:
            ct = self.types[f.type_name]
            for i, obj in enumerate(_as_list(value)):
                if ct.kind == "union":
                    # union ref input: {dogRef: {...}} names the member
                    # (ref gqlschema.go union ref input synthesis)
                    if len(obj) != 1:
                        where = (
                            f"index `{i}`" if isinstance(value, list) else ""
                        )
                        raise GraphQLError(
                            f"value for field `{f.name}` in type "
                            f"`{t.name}` {where} must have exactly one "
                            f"child, found {len(obj)} children"
                        )
                    refk, obj = next(iter(obj.items()))
                    if not refk.endswith("Ref") or len(refk) <= 3:
                        raise GraphQLError(
                            f"bad union ref {refk!r} for {ct.name}"
                        )
                    mname = refk[:-3]
                    mname = mname[0].upper() + mname[1:]
                    if mname not in ct.members:
                        raise GraphQLError(
                            f"bad union ref {refk!r} for {ct.name}"
                        )
                    mt = self.types[mname]
                    child_uid = self._resolve_object(
                        txn, mt, obj, ctx=ctx, for_delete=(op == OP_DEL)
                    )
                    if child_uid is None:
                        continue
                    apply_edge(
                        txn,
                        self.engine.schema,
                        DirectedEdge(uid, attr, value_id=child_uid, op=op),
                    )
                    continue
                if op == OP_DEL and not isinstance(obj, dict):
                    continue
                child_uid = self._resolve_object(
                    txn, ct, obj, ctx=ctx, for_delete=(op == OP_DEL),
                    src_field=f,
                )
                if child_uid is None:
                    continue
                self._write_ref_edge(txn, t, uid, f, child_uid, op=op)
            return
        # @dgraph(pred: "Person.name@hi") fields write the base predicate
        # with a language tag (ref gqlschema.go language tag fields)
        lang = ""
        if "@" in attr:
            attr, lang = attr.split("@", 1)
        vals = value if (f.is_list and isinstance(value, list)) else [value]
        for v in vals:
            if v is None:
                continue
            apply_edge(
                txn,
                self.engine.schema,
                DirectedEdge(
                    uid, attr, value=_to_val(v, f), lang=lang, op=op
                ),
            )

    def _resolve_object(
        self, txn, t: GqlType, obj: dict, ctx=None,
        is_root=False, for_delete=False, src_field=None,
    ) -> Optional[int]:
        """Resolve one input object to a uid with the reference's
        existence semantics (mutation_rewriter.go RewriteQueries +
        Rewrite): uid refs must exist with the right type; xid refs
        link when found (extra fields ignored), error on root add
        (unless upsert, which updates), create otherwise. src_field is
        the edge we descended through — its inverse field inside obj is
        ignored (the parent link wins, ref rewriter inverse handling)."""
        ctx = ctx if ctx is not None else _MutCtx()
        # a SINGLE-VALUED inverse of the field we came through is
        # auto-satisfied by the parent link; user values for it are
        # dropped (ref add/082 goldens — list inverses still process)
        inv_name = None
        if src_field is not None and src_field.has_inverse:
            invf = t.fields.get(src_field.has_inverse)
            if invf is not None and not invf.is_list:
                inv_name = src_field.has_inverse
        xf0 = t.xid_field()
        idf = t.id_field()
        idname = idf.name if idf is not None else None
        if (
            idname
            and obj.get(idname) is not None
            and (xf0 is None or xf0.name != idname)
        ):
            # uid reference (extras, if any, are ignored — the reference
            # rewrites {postID: "0x123", ...} to a bare uid link)
            u = _parse_uid(obj[idname])
            if u is None:
                raise GraphQLError(
                    f"ID argument ({obj[idname]}) was not able to be parsed"
                )
            if not self._node_is(txn, u, t):
                if for_delete:
                    return None
                raise GraphQLError(
                    f'ID "{obj[idname]}" isn\'t a {t.name}'
                )
            return u
        # xid identity
        xids = [
            (f, obj[f.name])
            for f in t.fields.values()
            if f.is_id and f.name in obj and obj[f.name] is not None
            and f.name != inv_name
        ]
        for f, v in xids:
            if v == "":
                raise GraphQLError(
                    f"encountered an empty value for @id field "
                    f"`{t.pred(f.name)}`"
                )
        # in-request claimed xids: a repeat either links to the new node
        # or errors (ref xidMetadata.isDuplicateXid)
        for f, v in xids:
            key = (t.pred(f.name), str(v))
            if key not in ctx.claimed:
                continue
            prev_uid, prev_obj = ctx.claimed[key]
            if is_root:
                raise GraphQLError(f"duplicate XID found: {v}")
            if src_field is not None and src_field.has_inverse:
                ct = self.types.get(src_field.type_name)
                g = ct.fields.get(src_field.has_inverse) if ct else None
                if g is not None and not g.is_list:
                    raise GraphQLError(f"duplicate XID found: {v}")
            stripped = {k: x for k, x in obj.items() if k != inv_name}
            if (
                len(stripped) > 1
                and len(prev_obj) > 1
                and stripped != prev_obj
            ):
                raise GraphQLError(f"duplicate XID found: {v}")
            return prev_uid
        found = None
        for f, v in xids:
            hits = self._xid_lookup(txn, t.pred(f.name), v)
            if not hits:
                continue
            same = [
                h
                for h in hits
                if t.stored_name in self._node_types(txn, h)
            ]
            if len(same) > 1:
                raise GraphQLError(
                    "multiple nodes found for given xid values, "
                    "updation not possible"
                )
            if not same:
                # the value lives only on other types' nodes (shared
                # interface predicate): a conflict iff @id(interface:true)
                if f.id_interface:
                    owner = f.owner or t.name
                    raise GraphQLError(
                        f"id {v} already exists for field {f.name} in "
                        f"some other implementing type of interface "
                        f"{owner}"
                    )
                continue
            hit = same[0]
            if found is not None and hit != found:
                raise GraphQLError(
                    "multiple nodes found for given xid values, "
                    "updation not possible"
                )
            found, found_f, found_v = hit, f, v
        if for_delete:
            if not xids:
                # a remove reference must carry its identity (ref
                # rewriter: "field name cannot be empty")
                if xf0 is not None:
                    raise GraphQLError(
                        f"field {xf0.name} cannot be empty"
                    )
                raise GraphQLError(
                    f"id is not provided to remove a {t.name} reference"
                )
            return found
        if found is not None:
            if is_root and not ctx.upsert:
                raise GraphQLError(
                    f"id {found_v} already exists for field "
                    f"{found_f.name} inside type {t.name}"
                )
            if is_root and ctx.upsert:
                ua = ctx.upsert_auth
                if ua is False:
                    return found  # denied upsert: silent no-op
                if isinstance(ua, dict):
                    ok = self._auth_allowed_uids(t, ua, [found], cache=txn.cache)
                    if found not in ok:
                        return found
                self._apply_update_defaults(txn, t, found, obj, ctx)
                # every field is (re)written, xids included — the
                # reference's upsert setjson carries them all
                for k, v in obj.items():
                    if k == idname or v is None:
                        continue
                    fld = t.fields.get(k)
                    if fld is None:
                        raise GraphQLError(f"no field {k!r} on {t.name}")
                    self._set_field(txn, t, found, fld, v, ctx=ctx)
                return found
            # nested reference: link only, extra fields ignored
            return found
        if not xids and not is_root and src_field is not None:
            has_data = any(
                k for k in obj if k != inv_name
            )
            if xf0 is not None and not has_data:
                # a reference-shaped object with no identity at all
                raise GraphQLError(
                    f"field {xf0.name} cannot be empty"
                )
        # brand-new node: required (non-null) scalar fields must be
        # present (or defaulted) on creation
        for f in t.fields.values():
            if (
                f.non_null
                and f.is_scalar
                and not f.is_list
                and f.type_name != "ID"
                and not f.is_secret
                and obj.get(f.name) is None  # absent OR explicit null
                and f.default_add is None
                and f.name != inv_name
            ):
                raise GraphQLError(
                    f"type {t.name} requires a value for field "
                    f"{f.name}, but no value present"
                )
        uid = self.engine.zero.assign_uids(1)
        ctx.created.append(uid)
        for f, v in xids:
            ctx.claimed[(t.pred(f.name), str(v))] = (
                uid,
                {k: x for k, x in obj.items() if k != inv_name},
            )
        # a node is a member of its type AND every interface it
        # implements (ref mutation_rewriter.go — dgraph.type gets both,
        # so queryCharacter(func: type(Character)) finds Humans)
        for tyname in [
            t.stored_name,
            *(
                self.types[i].stored_name
                for i in t.interfaces
                if i in self.types
            ),
        ]:
            apply_edge(
                txn,
                self.engine.schema,
                DirectedEdge(
                    uid, "dgraph.type", value=Val(TypeID.STRING, tyname)
                ),
            )
        for k, v in obj.items():
            if k == idname and (xf0 is None or xf0.name != idname):
                continue  # virtual uid, no predicate — but a stored
                # @id key named 'id' (extended federation types) writes
            if k == inv_name:
                continue  # parent link wins over explicit inverse value
            f = t.fields.get(k)
            if f is None:
                raise GraphQLError(f"no field {k!r} on {t.name}")
            if v is None:
                continue
            self._set_field(txn, t, uid, f, v, ctx=ctx)
        # @default(add:) fills fields the input omitted
        for f in t.fields.values():
            if f.default_add is not None and obj.get(f.name) is None:
                self._set_field(
                    txn, t, uid, f,
                    self._default_value(f.default_add, ctx), ctx=ctx,
                )
        return uid

    def _default_value(self, spec: str, ctx=None):
        if spec == "$now":
            # ONE timestamp per mutation request (the reference stamps
            # the request time, not per-field wall clocks)
            if ctx is not None and ctx.now is not None:
                return ctx.now
            import datetime as _dt

            now = config.get("FAKE_NOW") or (
                _dt.datetime.now(_dt.timezone.utc)
                .strftime("%Y-%m-%dT%H:%M:%S.%f")[:-3]
                + "Z"
            )
            if ctx is not None:
                ctx.now = now
            return now
        return spec

    def _apply_update_defaults(self, txn, t: GqlType, uid: int, obj, ctx):
        """@default(update:) values auto-set on every update of a node
        (ref mutation_rewriter.go — update patches gain the defaults
        for fields the patch doesn't name)."""
        for f in t.fields.values():
            if f.default_update is not None and f.name not in obj:
                self._set_field(
                    txn, t, uid, f,
                    self._default_value(f.default_update, ctx), ctx=ctx,
                )

    def _add(self, t: GqlType, sel: Selection):
        inputs = _as_list(sel.args.get("input", []))
        txn = self.engine.new_txn()
        try:
            return self._add_in_txn(t, sel, inputs, txn)
        except Exception:
            if not txn.finished:
                txn.discard()  # release the start_ts (zero conflict GC)
            raise

    def _add_in_txn(self, t: GqlType, sel: Selection, inputs, txn):
        from dgraph_tpu.graphql.auth import evaluate

        ctx = _MutCtx(upsert=bool(sel.args.get("upsert")))
        # upserts pre-check the ADD rule against the existing node
        # (ref: the rewriter's upsert query carries the auth filter —
        # a denied upsert is a silent no-op, auth_add_test "Upsert Add
        # Mutation with RBAC false")
        ctx.upsert_auth = self._auth(t, "add") if ctx.upsert else True
        created = ctx.created
        uids = [
            self._resolve_object(txn.txn, t, obj, ctx=ctx, is_root=True)
            for obj in inputs
        ]
        # post-insert check: every CREATED node must satisfy its own
        # type's add rule, evaluated on the txn snapshot (ref
        # mutation resolver authorizeNewNodes — deep creates validate
        # against their types' rules too)
        by_type: Dict[str, List[int]] = {}
        for u in created:
            for tn in self._node_types(txn.txn, u):
                ct = self.types.get(tn) or self._by_stored().get(tn)
                if ct is not None and ct.kind == "type":
                    by_type.setdefault(ct.name, []).append(u)
        for tn, us in by_type.items():
            ct = self.types[tn]
            if ct.auth is None or ct.auth.add is None:
                continue
            auth = evaluate(
                ct.auth.add,
                self._claims(),
                rule_runner=lambda r, c: self._run_auth_rule(
                    r, c, cache=txn.txn.cache
                ),
            )
            if auth is True:
                continue
            ok = (
                set()
                if auth is False
                else self._auth_allowed_uids(
                    ct, auth, us, cache=txn.txn.cache
                )
            )
            if not all(u in ok for u in us):
                txn.discard()
                raise GraphQLError(
                    "mutation failed because authorization failed"
                )
        txn.commit()
        self._fire_webhook(t, "add", uids, sel)
        return self._payload(t, sel, uids, len(created))

    def _match_filter_uids(
        self, t: GqlType, fobj, op: str = "query"
    ) -> List[int]:
        gq = GraphQuery(attr="q")
        gq.func = FuncSpec(name="type", attr=t.stored_name)
        gq.filter = self._filter_tree(t, fobj)
        gq.children = [GraphQuery(attr="uid", is_uid=True)]
        uids = [int(o["uid"], 16) for o in self._run_block(gq)]
        return self._apply_interface_auth(t, uids, op)

    def _apply_interface_auth(
        self, t: GqlType, uids: List[int], op: str
    ) -> List[int]:
        """Operating on an INTERFACE applies the implementing types'
        own auth rules with OR semantics (ref auth rewriting:
        `uid(A_chain) OR uid(B_chain)` — a node passing ANY of its
        implementers' chains stays). For mutations, nodes belonging to
        no implementing type drop out entirely when implementer auth is
        in play (`ARoot ... @filter(uid(B_2))`)."""
        if t.kind != "interface" or not uids:
            return uids
        auth_impls = [
            impl
            for n in t.implementers
            if (impl := self.types.get(n)) is not None
            and impl.auth is not None
            and getattr(impl.auth, op, None) is not None
        ]
        if not auth_impls:
            return uids
        plain_impls = [
            impl
            for n in t.implementers
            if (impl := self.types.get(n)) is not None
            and impl not in auth_impls
        ]

        def impl_members(impl) -> set:
            gq = GraphQuery(attr="q")
            gq.func = FuncSpec(name="uid", args=list(uids))
            gq.filter = FilterTree(
                func=FuncSpec(name="type", attr=impl.stored_name)
            )
            gq.children = [GraphQuery(attr="uid", is_uid=True)]
            return {int(o["uid"], 16) for o in self._run_block(gq)}

        member: set = set()
        allowed: set = set()
        for impl in auth_impls:
            impl_uids = impl_members(impl)
            member |= impl_uids
            verdict = self._auth(impl, op)
            if verdict is True:
                allowed |= impl_uids
            elif verdict is not False:
                allowed |= self._auth_allowed_uids(
                    impl, verdict, sorted(impl_uids)
                )
        for impl in plain_impls:
            # implementers without rules keep their nodes (OR branch
            # with no auth filter)
            allowed |= impl_members(impl)
        if op in ("update", "delete"):
            # mutation targets come only from the implementer chains
            drop = set(uids) - allowed
        else:
            # queries keep interface-only nodes (they match no chain
            # but also no deny)
            drop = member - allowed
        if not drop:
            return uids
        return [u for u in uids if u not in drop]

    def _update(self, t: GqlType, sel: Selection):
        inp = sel.args.get("input", {})
        fobj, allowed = self._with_auth_filter(t, inp.get("filter"), "update")
        # a denied update matches nothing: empty payload, NOT an error
        # (ref auth_update_test "top level RBAC false": `x as updateLog()`)
        denied = not allowed
        # patch-shape validation happens before matching (the reference
        # rewriter rejects malformed patches even when the filter is
        # empty — e.g. a remove reference without its identity)
        self._validate_remove_patch(t, inp.get("remove"))
        uids = (
            []
            if denied
            else self._match_filter_uids(t, fobj, "update")
        )
        txn = self.engine.new_txn()
        try:
            return self._update_in_txn(t, sel, inp, uids, txn)
        except Exception:
            if not txn.finished:
                txn.discard()
            raise

    def _update_in_txn(self, t: GqlType, sel, inp, uids, txn):
        ctx = _MutCtx()
        from dgraph_tpu.posting.mutation import delete_entity_attr

        # the reference validates the patch at rewrite time, before it
        # knows what the filter matches — when nothing matches we still
        # run one discarded "probe" application so malformed patches
        # (duplicate xids, taken @id values) error identically
        probe = not uids
        for uid in uids or [0]:
            if inp.get("set") or inp.get("remove"):
                self._apply_update_defaults(
                    txn.txn, t, uid, inp.get("set") or {}, ctx
                )
            for k, v in (inp.get("set") or {}).items():
                f = t.fields.get(k)
                if f is None:
                    raise GraphQLError(f"no field {k!r}")
                if v is None:
                    continue
                if f.is_id and not isinstance(v, (dict, list)):
                    # writing an @id value that lives on ANOTHER node is
                    # rejected (ref update rewriter existence checks)
                    hits = self._xid_lookup(txn.txn, t.pred(k), v)
                    if any(h != uid for h in hits):
                        raise GraphQLError(
                            f"id {v} already exists for field {k} "
                            f"inside type {t.name}"
                        )
                self._set_field(txn.txn, t, uid, f, v, ctx=ctx)
            for k, v in (inp.get("remove") or {}).items():
                f = t.fields.get(k)
                if f is None:
                    raise GraphQLError(f"no field {k!r}")
                if v is None:
                    # remove {field: null}: drop the predicate outright
                    # (ref update rewriter — deletejson value null);
                    # language-tagged preds store under the base name
                    attr = t.pred(f.name).split("@", 1)[0]
                    for tgt in (
                        self._edge_targets(txn.txn, uid, attr)
                        if not f.is_scalar
                        else []
                    ):
                        self._write_ref_edge(
                            txn.txn, t, uid, f, tgt, op=OP_DEL
                        )
                    delete_entity_attr(
                        txn.txn, self.engine.schema, uid, attr
                    )
                    continue
                self._set_field(txn.txn, t, uid, f, v, op=OP_DEL, ctx=ctx)
        if probe:
            txn.discard()
            return self._payload(t, sel, [], 0)
        txn.commit()
        if uids:
            self._fire_webhook(t, "update", uids, sel)
        return self._payload(t, sel, uids, len(uids))

    def _validate_remove_patch(self, t: GqlType, patch):
        """A remove reference must carry id or @id identity (ref update
        rewriter: 'field name cannot be empty')."""
        for k, v in (patch or {}).items():
            f = t.fields.get(k)
            if f is None:
                raise GraphQLError(f"no field {k!r}")
            if f.is_scalar or v is None:
                continue
            ct = self.types.get(f.type_name)
            if ct is None or ct.kind == "union":
                continue
            idf = ct.id_field()
            for obj in _as_list(v):
                if not isinstance(obj, dict):
                    continue
                has_id = idf is not None and idf.name in obj
                has_xid = any(
                    g.is_id and obj.get(g.name) is not None
                    for g in ct.fields.values()
                )
                if not has_id and not has_xid:
                    xf0 = ct.xid_field()
                    if xf0 is not None:
                        raise GraphQLError(
                            f"field {xf0.name} cannot be empty"
                        )
                    raise GraphQLError(
                        f"id is not provided to remove a {ct.name} "
                        f"reference"
                    )

    def _delete(self, t: GqlType, sel: Selection):
        from dgraph_tpu.posting.mutation import delete_entity_attr

        fobj, allowed = self._with_auth_filter(
            t, sel.args.get("filter"), "delete"
        )
        # denied delete matches nothing (`x as deleteLog()`): no error
        uids = (
            []
            if not allowed
            else self._match_filter_uids(t, fobj, "delete")
        )
        txn = self.engine.new_txn()
        try:
            return self._delete_in_txn(t, sel, uids, txn)
        except Exception:
            if not txn.finished:
                txn.discard()
            raise

    def _delete_in_txn(self, t: GqlType, sel, uids, txn):
        from dgraph_tpu.posting.mutation import delete_entity_attr

        for uid in uids:
            for f in t.fields.values():
                if f.type_name == "ID":
                    continue
                attr = t.pred(f.name)
                if not f.is_scalar and f.has_inverse:
                    # unlink the other side of @hasInverse pairs (ref
                    # delete rewriter: `Post_2 as Author.posts` +
                    # deletejson {"uid":"uid(Post_2)","Post.author":…})
                    ct = self.types.get(f.type_name)
                    g = ct.fields.get(f.has_inverse) if ct else None
                    if g is not None:
                        for tgt in self._edge_targets(txn.txn, uid, attr):
                            apply_edge(
                                txn.txn,
                                self.engine.schema,
                                DirectedEdge(
                                    tgt, ct.pred(g.name),
                                    value_id=uid, op=OP_DEL,
                                ),
                            )
                delete_entity_attr(txn.txn, self.engine.schema, uid, attr)
            delete_entity_attr(txn.txn, self.engine.schema, uid, "dgraph.type")
        txn.commit()
        if uids:
            # no phantom events for denied/no-match deletes
            self._fire_webhook(t, "delete", uids, sel)
        return self._payload(t, sel, uids, len(uids))


def _compute_child_agg(
    sel: Selection, items: list, type_name: str = ""
) -> dict:
    """{count, <f>Min/Max/Sum/Avg} over a fetched child edge (the
    child-level aggregate fields of ref gqlschema.go)."""
    out = {}
    for a in sel.selections:
        if a.name == "count":
            out[a.key] = len(items)
            continue
        if a.name == "__typename":
            out[a.key] = f"{type_name}AggregateResult"
            continue
        for suffix, op in (
            ("Min", "min"),
            ("Max", "max"),
            ("Sum", "sum"),
            ("Avg", "avg"),
        ):
            if a.name.endswith(suffix):
                fname = a.name[: -len(suffix)]
                vals = [
                    it[fname]
                    for it in items
                    if isinstance(it, dict) and it.get(fname) is not None
                ]
                if not vals:
                    out[a.key] = None
                elif op == "min":
                    out[a.key] = min(vals)
                elif op == "max":
                    out[a.key] = max(vals)
                elif op == "sum":
                    out[a.key] = sum(vals)
                else:
                    out[a.key] = sum(vals) / len(vals)
                break
    return out


def _parse_uid(x):
    """uid within u64 range, else None (dropped). Base-0 semantics like
    the reference (query_rewriter.go convertIDs → strconv.ParseUint
    base 0): "17" is decimal, "0x11" is hex."""
    try:
        u = int(str(x), 0)
    except (ValueError, TypeError):
        return None
    # 0 is accepted like ParseUint (uid 0 simply matches no node)
    return u if 0 <= u < (1 << 64) else None


def _as_list(x):
    if x is None:
        return []
    return x if isinstance(x, list) else [x]


def _to_val(v, f: GqlField) -> Val:
    dtype = f.dql_type
    if dtype == "int":
        return Val(TypeID.INT, int(v))
    if dtype == "float":
        return Val(TypeID.FLOAT, float(v))
    if dtype == "bool":
        if isinstance(v, str):
            return Val(TypeID.BOOL, v.lower() == "true")
        return Val(TypeID.BOOL, bool(v))
    if dtype == "datetime":
        from dgraph_tpu.types.types import parse_datetime

        return Val(TypeID.DATETIME, parse_datetime(str(v)))
    if dtype == "geo":
        if isinstance(v, dict):
            v = _gql_geo_to_geojson(v)
        return Val(TypeID.GEO, v)
    if dtype == "password":
        from dgraph_tpu.types.types import convert

        return convert(Val(TypeID.STRING, str(v)), TypeID.PASSWORD)
    return Val(TypeID.STRING, str(v))


def _gql_literal(v) -> str:
    """Render a Python value as a GraphQL literal (NOT JSON: object keys
    are bare — a remote rejects {"name": ...}). Enum args can't be told
    apart from strings without the remote arg types, so enum-typed
    remote args must be passed as GraphQL variables by the schema
    author (documented limitation, like @custom DQL substitution)."""
    import json as _json

    if isinstance(v, bool):
        return "true" if v else "false"
    if v is None:
        return "null"
    if isinstance(v, (int, float)):
        return _json.dumps(v)
    if isinstance(v, str):
        return _json.dumps(v)
    if isinstance(v, list):
        return "[" + ", ".join(_gql_literal(x) for x in v) + "]"
    if isinstance(v, dict):
        return (
            "{"
            + ", ".join(
                f"{k}: {_gql_literal(x)}" for k, x in v.items()
            )
            + "}"
        )
    return _json.dumps(str(v))

"""Minimal GraphQL operation parser (queries + mutations with variables).

Stand-in for the reference's vendored gqlparser
(/root/reference/graphql/schema uses github.com/dgraph-io/gqlparser):
parses operations, selection sets, arguments (int/float/string/bool/enum/
list/object/variable), aliases, and variable definitions.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


class GqlParseError(Exception):
    pass


_TOKEN = re.compile(
    r"""
    (?P<ws>[\s,]+|\#[^\n]*)
  | (?P<string>"(?:\\.|[^"\\])*")
  | (?P<num>-?\d+\.\d+(?:[eE][+-]?\d+)?|-?\d+)
  | (?P<name>[_A-Za-z]\w*)
  | (?P<punct>\$|\(|\)|\{|\}|\[|\]|:|=|!|@|\.\.\.)
""",
    re.VERBOSE,
)


def _tokenize(s: str):
    out, pos = [], 0
    while pos < len(s):
        m = _TOKEN.match(s, pos)
        if not m:
            raise GqlParseError(f"unexpected char {s[pos]!r} at {pos}")
        if m.lastgroup != "ws":
            out.append((m.lastgroup, m.group(), pos))
        pos = m.end()
    out.append(("eof", "", len(s)))
    return out


@dataclass
class Selection:
    name: str
    alias: str = ""
    args: Dict[str, Any] = field(default_factory=dict)
    selections: List["Selection"] = field(default_factory=list)
    # inline fragment: name == "..." and frag_on holds the type
    # condition; its selections apply only to nodes of that type
    frag_on: str = ""
    # field directives other than @skip/@include (those are evaluated
    # at parse time since variables are already substituted): e.g.
    # ("cascade", {"fields": [...]})
    directives: List = field(default_factory=list)

    @property
    def key(self) -> str:
        return self.alias or self.name


@dataclass
class Operation:
    kind: str  # query | mutation
    name: str = ""
    var_defs: Dict[str, Any] = field(default_factory=dict)  # name -> default
    selections: List[Selection] = field(default_factory=list)


class _P:
    def __init__(self, toks):
        self.toks = toks
        self.i = 0

    def peek(self):
        if self.i >= len(self.toks):
            raise GqlParseError("unexpected end of query")
        return self.toks[self.i]

    def next(self):
        if self.i >= len(self.toks):
            raise GqlParseError("unexpected end of query")
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, text):
        t = self.next()
        if t[1] != text:
            raise GqlParseError(f"expected {text!r}, got {t[1]!r} at {t[2]}")
        return t

    def accept(self, text):
        if self.peek()[1] == text:
            self.i += 1
            return True
        return False


def _unquote(s: str) -> str:
    return re.sub(
        r"\\(.)",
        lambda m: {"n": "\n", "t": "\t", '"': '"', "\\": "\\"}.get(
            m.group(1), m.group(1)
        ),
        s[1:-1],
    )


def _parse_value(p: _P, variables: Dict[str, Any]):
    kind, text, pos = p.next()
    if text == "$":
        vname = p.next()[1]
        if vname not in variables:
            raise GqlParseError(f"undefined variable ${vname}")
        return variables[vname]
    if kind == "string":
        return _unquote(text)
    if kind == "num":
        return float(text) if ("." in text or "e" in text.lower()) else int(text)
    if text == "[":
        out = []
        while p.peek()[1] != "]":
            out.append(_parse_value(p, variables))
        p.expect("]")
        return out
    if text == "{":
        obj = {}
        while p.peek()[1] != "}":
            k = p.next()[1]
            p.expect(":")
            obj[k] = _parse_value(p, variables)
        p.expect("}")
        return obj
    if text == "true":
        return True
    if text == "false":
        return False
    if text == "null":
        return None
    if kind == "name":
        return text  # enum
    raise GqlParseError(f"bad value {text!r} at {pos}")


def _parse_args(p: _P, variables):
    args = {}
    if p.accept("("):
        while p.peek()[1] != ")":
            name = p.next()[1]
            p.expect(":")
            args[name] = _parse_value(p, variables)
        p.expect(")")
    return args


def _parse_directives(p: _P, variables):
    """Returns (keep, directives): @skip/@include evaluate immediately
    (variables are already substituted); the rest are returned."""
    keep = True
    out = []
    while p.accept("@"):
        dname = p.next()[1]
        dargs = _parse_args(p, variables)
        if dname == "skip":
            keep = keep and not dargs.get("if", False)
        elif dname == "include":
            keep = keep and bool(dargs.get("if", True))
        else:
            out.append((dname, dargs))
    return keep, out


def _parse_selection_set(p: _P, variables) -> List[Selection]:
    p.expect("{")
    out = []
    while not p.accept("}"):
        if p.accept("..."):
            nxt = p.peek()[1]
            if nxt == "on" or nxt == "{" or nxt == "@":
                # inline fragment; a missing type condition ('... { x }'
                # / '... @include(...) { x }') means "same type"
                cond = ""
                if nxt == "on":
                    p.next()
                    cond = p.next()[1]
                keep, dirs = _parse_directives(p, variables)
                sels = _parse_selection_set(p, variables)
                if keep:
                    sel = Selection(name="...", frag_on=cond)
                    sel.selections = sels
                    sel.directives = dirs
                    out.append(sel)
            else:  # named fragment spread — expanded after definitions
                fname = p.next()[1]
                keep, dirs = _parse_directives(p, variables)
                if keep:
                    sel = Selection(name="...", frag_on="")
                    sel.alias = f"__spread_{fname}"
                    sel.directives = dirs
                    out.append(sel)
            continue
        name = p.next()[1]
        sel = Selection(name=name)
        if p.accept(":"):
            sel.alias = name
            sel.name = p.next()[1]
        sel.args = _parse_args(p, variables)
        keep, sel.directives = _parse_directives(p, variables)
        if p.peek()[1] == "{":
            sel.selections = _parse_selection_set(p, variables)
        if keep:
            out.append(sel)
    return out


def _expand_spreads(
    sels: List[Selection], fragments, _stack=()
) -> List[Selection]:
    out = []
    for s in sels:
        if s.name == "..." and s.alias.startswith("__spread_"):
            fname = s.alias[len("__spread_") :]
            if fname in _stack:
                # the GraphQL spec rejects fragment cycles outright
                raise GqlParseError(f"fragment cycle through {fname!r}")
            frag = fragments.get(fname)
            if frag is None:
                raise GqlParseError(f"undefined fragment {fname!r}")
            cond, fsels = frag
            inline = Selection(name="...", frag_on=cond)
            inline.directives = s.directives
            inline.selections = _expand_spreads(
                fsels, fragments, _stack + (fname,)
            )
            out.append(inline)
        else:
            s.selections = _expand_spreads(s.selections, fragments, _stack)
            out.append(s)
    return out


def _skip_frag_directives(p) -> None:
    """Skip '@name(args)' directive tokens between a fragment's type
    condition and its '{' (legal GraphQL: 'fragment F on T @dir { … }')."""
    while p.peek()[1] == "@":
        p.next()
        p.next()  # directive name
        if p.peek()[1] == "(":
            depth = 0
            while True:
                tkn = p.next()[1]
                if tkn == "(":
                    depth += 1
                elif tkn == ")":
                    depth -= 1
                    if depth == 0:
                        break


def parse_operation(
    text: str, variables: Optional[Dict[str, Any]] = None
) -> Operation:
    variables = dict(variables or {})
    toks = _tokenize(text)
    p = _P(toks)
    kind = "query"
    name = ""
    fragments: Dict[str, tuple] = {}
    # Fragment definitions may precede the operation, but their bodies
    # can reference operation variables (incl. defaults declared in the
    # operation prologue) — so skip their token spans now and parse
    # them AFTER the variable definitions are known.
    leading: list = []  # (header_index,) spans to revisit
    while p.peek()[1] == "fragment":
        start = p.i
        p.next()
        p.next()  # name
        p.expect("on")
        p.next()  # type condition
        _skip_frag_directives(p)
        p.expect("{")
        depth = 1
        while depth:
            tkn = p.next()[1]
            if tkn == "{":
                depth += 1
            elif tkn == "}":
                depth -= 1
        leading.append(start)
    t = p.peek()
    if t[1] in ("query", "mutation"):
        kind = p.next()[1]
        if p.peek()[0] == "name":
            name = p.next()[1]
        if p.accept("("):
            # variable definitions: ($x: Type! = default)
            while p.peek()[1] != ")":
                p.expect("$")
                vname = p.next()[1]
                p.expect(":")
                # type: [ ]* Name with ! anywhere ([String!]! etc.)
                while p.peek()[1] in ("[",):
                    p.next()
                p.next()  # type name
                while p.peek()[1] in ("!", "]"):
                    p.next()
                if p.accept("="):
                    default = _parse_value(p, variables)
                    variables.setdefault(vname, default)
                if vname not in variables:
                    variables[vname] = None
            p.expect(")")
    # now parse the leading fragments with full variable knowledge
    for start in leading:
        fp = _P(toks)
        fp.i = start
        fp.next()
        fname = fp.next()[1]
        fp.expect("on")
        cond = fp.next()[1]
        _skip_frag_directives(fp)
        fragments[fname] = (
            cond,
            _parse_selection_set(fp, variables),
        )
    op = Operation(kind=kind, name=name)
    op.selections = _parse_selection_set(p, variables)
    # fragment definitions may follow the operation
    while p.peek()[1] == "fragment":
        p.next()
        fname = p.next()[1]
        p.expect("on")
        cond = p.next()[1]
        _skip_frag_directives(p)
        fragments[fname] = (cond, _parse_selection_set(p, variables))
    if p.peek()[0] != "eof":
        raise GqlParseError(f"trailing input at {p.peek()[2]}")
    op.selections = _expand_spreads(op.selections, fragments)
    return op

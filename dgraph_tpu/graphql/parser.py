"""Minimal GraphQL operation parser (queries + mutations with variables).

Stand-in for the reference's vendored gqlparser
(/root/reference/graphql/schema uses github.com/dgraph-io/gqlparser):
parses operations, selection sets, arguments (int/float/string/bool/enum/
list/object/variable), aliases, and variable definitions.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


class GqlParseError(Exception):
    pass


_TOKEN = re.compile(
    r"""
    (?P<ws>[\s,]+|\#[^\n]*)
  | (?P<string>"(?:\\.|[^"\\])*")
  | (?P<num>-?\d+\.\d+(?:[eE][+-]?\d+)?|-?\d+)
  | (?P<name>[_A-Za-z]\w*)
  | (?P<punct>\$|\(|\)|\{|\}|\[|\]|:|=|!|@|\.\.\.)
""",
    re.VERBOSE,
)


def _tokenize(s: str):
    out, pos = [], 0
    while pos < len(s):
        m = _TOKEN.match(s, pos)
        if not m:
            raise GqlParseError(f"unexpected char {s[pos]!r} at {pos}")
        if m.lastgroup != "ws":
            out.append((m.lastgroup, m.group(), pos))
        pos = m.end()
    out.append(("eof", "", len(s)))
    return out


@dataclass
class Selection:
    name: str
    alias: str = ""
    args: Dict[str, Any] = field(default_factory=dict)
    selections: List["Selection"] = field(default_factory=list)

    @property
    def key(self) -> str:
        return self.alias or self.name


@dataclass
class Operation:
    kind: str  # query | mutation
    name: str = ""
    var_defs: Dict[str, Any] = field(default_factory=dict)  # name -> default
    selections: List[Selection] = field(default_factory=list)


class _P:
    def __init__(self, toks):
        self.toks = toks
        self.i = 0

    def peek(self):
        if self.i >= len(self.toks):
            raise GqlParseError("unexpected end of query")
        return self.toks[self.i]

    def next(self):
        if self.i >= len(self.toks):
            raise GqlParseError("unexpected end of query")
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, text):
        t = self.next()
        if t[1] != text:
            raise GqlParseError(f"expected {text!r}, got {t[1]!r} at {t[2]}")
        return t

    def accept(self, text):
        if self.peek()[1] == text:
            self.i += 1
            return True
        return False


def _unquote(s: str) -> str:
    return re.sub(
        r"\\(.)",
        lambda m: {"n": "\n", "t": "\t", '"': '"', "\\": "\\"}.get(
            m.group(1), m.group(1)
        ),
        s[1:-1],
    )


def _parse_value(p: _P, variables: Dict[str, Any]):
    kind, text, pos = p.next()
    if text == "$":
        vname = p.next()[1]
        if vname not in variables:
            raise GqlParseError(f"undefined variable ${vname}")
        return variables[vname]
    if kind == "string":
        return _unquote(text)
    if kind == "num":
        return float(text) if ("." in text or "e" in text.lower()) else int(text)
    if text == "[":
        out = []
        while p.peek()[1] != "]":
            out.append(_parse_value(p, variables))
        p.expect("]")
        return out
    if text == "{":
        obj = {}
        while p.peek()[1] != "}":
            k = p.next()[1]
            p.expect(":")
            obj[k] = _parse_value(p, variables)
        p.expect("}")
        return obj
    if text == "true":
        return True
    if text == "false":
        return False
    if text == "null":
        return None
    if kind == "name":
        return text  # enum
    raise GqlParseError(f"bad value {text!r} at {pos}")


def _parse_args(p: _P, variables):
    args = {}
    if p.accept("("):
        while p.peek()[1] != ")":
            name = p.next()[1]
            p.expect(":")
            args[name] = _parse_value(p, variables)
        p.expect(")")
    return args


def _parse_selection_set(p: _P, variables) -> List[Selection]:
    p.expect("{")
    out = []
    while not p.accept("}"):
        name = p.next()[1]
        sel = Selection(name=name)
        if p.accept(":"):
            sel.alias = name
            sel.name = p.next()[1]
        sel.args = _parse_args(p, variables)
        while p.accept("@"):  # skip field directives
            p.next()
            _parse_args(p, variables)
        if p.peek()[1] == "{":
            sel.selections = _parse_selection_set(p, variables)
        out.append(sel)
    return out


def parse_operation(
    text: str, variables: Optional[Dict[str, Any]] = None
) -> Operation:
    variables = dict(variables or {})
    p = _P(_tokenize(text))
    kind = "query"
    name = ""
    t = p.peek()
    if t[1] in ("query", "mutation"):
        kind = p.next()[1]
        if p.peek()[0] == "name":
            name = p.next()[1]
        if p.accept("("):
            # variable definitions: ($x: Type! = default)
            while p.peek()[1] != ")":
                p.expect("$")
                vname = p.next()[1]
                p.expect(":")
                p.next()  # type name
                while p.peek()[1] in ("!", "[", "]"):
                    p.next()
                if p.accept("="):
                    default = _parse_value(p, variables)
                    variables.setdefault(vname, default)
                if vname not in variables:
                    variables[vname] = None
            p.expect(")")
    op = Operation(kind=kind, name=name)
    op.selections = _parse_selection_set(p, variables)
    if p.peek()[0] != "eof":
        raise GqlParseError(f"trailing input at {p.peek()[2]}")
    return op

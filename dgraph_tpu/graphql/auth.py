"""GraphQL @auth: JWT-gated, rule-filtered access to the generated API.

Mirrors /root/reference/graphql/schema/auth.go (directive parsing, the
`# Dgraph.Authorization` header config) + graphql/resolve/auth queries
(query_rewriter.go injecting auth filters): each type may carry

  @auth(
    query:  { rule: "{$ROLE: {eq: \"ADMIN\"}}" },          # RBAC rule
    add:    { rule: "query($U: String!) { queryT(filter: {owner: {eq: $U}}) { __typename } }" },
    update: { and: [ {rule: ...}, {rule: ...} ] },
    delete: { not: {rule: ...} },
  )

Rules come in two forms, like the reference:
  - RBAC: a JSON-ish object testing JWT claims directly — resolves to a
    hard True/False before touching the graph;
  - graph rules: a GraphQL query whose filter (with $VAR substituted from
    JWT claims) is ANDed into the operation's filter, so only nodes the
    rule reaches are visible/mutable.

The JWT config comes from the SDL's magic comment:
  # Dgraph.Authorization {"VerificationKey":"secret","Header":"X-App-Auth",
  #                       "Namespace":"https://app/claims","Algo":"HS256"}
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from dgraph_tpu.acl import jwt as jwtlib


class AuthError(Exception):
    pass


# ---------------------------------------------------------------------------
# Dgraph.Authorization config
# ---------------------------------------------------------------------------


@dataclass
class AuthConfig:
    verification_key: str
    header: str = "X-Dgraph-AuthToken"
    namespace: str = ""
    algo: str = "HS256"
    closed_by_default: bool = False  # every request needs a JWT


_AUTH_LINE = re.compile(r"#\s*Dgraph\.Authorization\s+(\{.*\})")


def parse_authorization(sdl: str) -> Optional[AuthConfig]:
    m = _AUTH_LINE.search(sdl)
    if not m:
        return None
    try:
        obj = json.loads(m.group(1))
    except json.JSONDecodeError as e:
        raise AuthError(f"bad Dgraph.Authorization JSON: {e}") from e
    if obj.get("Algo", "HS256") != "HS256":
        raise AuthError("only HS256 is supported")
    return AuthConfig(
        verification_key=obj["VerificationKey"],
        header=obj.get("Header", "X-Dgraph-AuthToken"),
        namespace=obj.get("Namespace", ""),
        algo=obj.get("Algo", "HS256"),
        closed_by_default=bool(obj.get("ClosedByDefault", False)),
    )


def claims_from_jwt(token: str, cfg: AuthConfig) -> Dict[str, Any]:
    """Verify + extract custom claims (namespace-nested per the spec).
    exp is honored when present; auth tokens without exp don't expire."""
    import time as _time

    claims = jwtlib.decode(token, cfg.verification_key.encode(), verify_exp=False)
    if "exp" in claims and claims["exp"] < _time.time():
        raise AuthError("token expired")
    if cfg.namespace and isinstance(claims.get(cfg.namespace), dict):
        merged = dict(claims)
        merged.update(claims[cfg.namespace])
        return merged
    return claims


# ---------------------------------------------------------------------------
# @auth rule trees
# ---------------------------------------------------------------------------


@dataclass
class AuthNode:
    kind: str  # rbac | filter | and | or | not
    # rbac
    claim: str = ""
    op: str = ""  # eq | in
    value: Any = None
    # filter: template filter object with "$VAR" placeholders
    filt: Optional[dict] = None
    children: List["AuthNode"] = field(default_factory=list)


@dataclass
class TypeAuth:
    query: Optional[AuthNode] = None
    add: Optional[AuthNode] = None
    update: Optional[AuthNode] = None
    delete: Optional[AuthNode] = None


_TRIPLE = re.compile(r'"""([\s\S]*?)"""')


def _untriple(s: str) -> str:
    return _TRIPLE.sub(lambda m: json.dumps(m.group(1)), s)


def _strip_comments(s: str) -> str:
    """Drop `# …` line comments outside string literals."""
    out = []
    in_str = False
    i = 0
    while i < len(s):
        ch = s[i]
        if in_str:
            if ch == '"':
                # closing quote unless preceded by an ODD number of
                # backslashes ("...\\" ends the string)
                bs = 0
                j = i - 1
                while j >= 0 and s[j] == "\\":
                    bs += 1
                    j -= 1
                if bs % 2 == 0:
                    in_str = False
            out.append(ch)
        elif ch == '"':
            in_str = True
            out.append(ch)
        elif ch == "#":
            while i < len(s) and s[i] != "\n":
                i += 1
            continue
        else:
            out.append(ch)
        i += 1
    return "".join(out)


def parse_auth_blob(blob: str) -> TypeAuth:
    """blob: the argument text inside @auth( ... )."""
    obj = _parse_gql_object("{" + _strip_comments(_untriple(blob)) + "}")
    ta = TypeAuth()
    for op in ("query", "add", "update", "delete"):
        if op in obj:
            setattr(ta, op, _rule_node(obj[op]))
    return ta


def _rule_node(obj: dict) -> AuthNode:
    if "and" in obj:
        return AuthNode(
            kind="and", children=[_rule_node(x) for x in obj["and"]]
        )
    if "or" in obj:
        return AuthNode(kind="or", children=[_rule_node(x) for x in obj["or"]])
    if "not" in obj:
        return AuthNode(kind="not", children=[_rule_node(obj["not"])])
    rule = obj.get("rule")
    if rule is None:
        raise AuthError(f"auth rule object needs rule/and/or/not: {obj!r}")
    rule = rule.strip()
    if rule.startswith("{"):
        rb = _parse_gql_object(rule)
        if len(rb) != 1:
            raise AuthError(f"RBAC rule must test one claim: {rule!r}")
        claim, cond = next(iter(rb.items()))
        if not claim.startswith("$"):
            raise AuthError(f"RBAC rule claim must be a $var: {rule!r}")
        if not isinstance(cond, dict) or len(cond) != 1:
            raise AuthError(f"RBAC rule needs one op: {rule!r}")
        op, val = next(iter(cond.items()))
        if op not in ("eq", "in", "regexp"):
            raise AuthError(f"RBAC op must be eq/in/regexp: {rule!r}")
        return AuthNode(kind="rbac", claim=claim[1:], op=op, value=val)
    # graph rule: query (...) { queryT(filter: {...}) { ... } }.
    # A root-only filter with a trivial body lifts straight into the
    # operation filter; anything deeper (nested filters / cascade-
    # significant selections) is kept as an executable rule query the
    # resolver runs with @cascade semantics (ref auth_query_rewriting's
    # uid-var + @cascade chains).
    if _is_root_only_rule(rule):
        m = re.search(r"filter\s*:", rule)
        if not m:
            return AuthNode(kind="filter", filt={})
        filt_src = _balanced_object(rule, rule.index("{", m.end()))
        return AuthNode(kind="filter", filt=_parse_gql_object(filt_src))
    return AuthNode(kind="gqlrule", value=rule)


def _is_root_only_rule(rule: str) -> bool:
    """True when the rule query's only structure is a root filter with a
    trivial (__typename/uid-only) body — the common fast path."""
    try:
        from dgraph_tpu.graphql.parser import parse_operation

        # probe-parse with every $var bound to a placeholder
        names = set(re.findall(r"\$(\w+)", rule))
        op = parse_operation(rule, variables={n: "0" for n in names})
    except Exception:
        return False
    if len(op.selections) != 1:
        return False
    root = op.selections[0]
    for s in root.selections:
        if s.selections or s.args or s.name not in ("__typename", "id", "uid"):
            return False
    return True


def evaluate(node: Optional[AuthNode], claims: Dict[str, Any], rule_runner=None):
    """Returns True (allow all), False (deny all), or a filter object to
    AND into the operation (the reference's auth-query injection).
    rule_runner(rule_text, claims) executes a deep rule query and
    returns the allowed uids (hex strings)."""
    if node is None:
        return True
    if node.kind == "rbac":
        got = claims.get(node.claim)
        if node.op == "eq":
            return got == node.value
        if node.op == "regexp":
            pat = str(node.value).strip("/")
            return bool(got is not None and re.search(pat, str(got)))
        vals = node.value if isinstance(node.value, list) else [node.value]
        return got in vals
    if node.kind == "filter":
        if not node.filt:
            return True
        try:
            return _substitute(node.filt, claims)
        except AuthError:
            # a rule whose JWT variable is missing simply fails —
            # deny THIS rule, not the request (ref auth_query_test
            # "Query with missing jwt variables")
            return False
    if node.kind == "gqlrule":
        if rule_runner is None:
            return False
        try:
            uids = rule_runner(node.value, claims)
        except Exception:  # noqa: BLE001 — missing claim/var => rule fails
            return False
        return {"id": list(uids)}
    parts = [evaluate(c, claims, rule_runner) for c in node.children]
    if node.kind == "and":
        if any(p is False for p in parts):
            return False
        filts = [p for p in parts if isinstance(p, dict)]
        if not filts:
            return True
        return filts[0] if len(filts) == 1 else {"and": filts}
    if node.kind == "or":
        if any(p is True for p in parts):
            return True
        filts = [p for p in parts if isinstance(p, dict)]
        if not filts:
            return False
        return filts[0] if len(filts) == 1 else {"or": filts}
    if node.kind == "not":
        p = parts[0]
        if isinstance(p, bool):
            return not p
        return {"not": p}
    raise AuthError(f"bad auth node {node.kind}")


def _substitute(obj, claims):
    if isinstance(obj, dict):
        return {k: _substitute(v, claims) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_substitute(x, claims) for x in obj]
    if isinstance(obj, str) and obj.startswith("$"):
        name = obj[1:]
        if name not in claims:
            raise AuthError(f"JWT claim {name!r} required by auth rule")
        return claims[name]
    return obj


# ---------------------------------------------------------------------------
# Tiny GraphQL-literal object parser (keys may be $names; values are
# strings/numbers/bools/lists/objects)
# ---------------------------------------------------------------------------


def _balanced_object(s: str, start: int) -> str:
    depth = 0
    i = start
    in_str = False
    while i < len(s):
        ch = s[i]
        if in_str:
            if ch == "\\":
                i += 2
                continue
            if ch == '"':
                in_str = False
        elif ch == '"':
            in_str = True
        elif ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
            if depth == 0:
                return s[start : i + 1]
        i += 1
    raise AuthError(f"unbalanced object at {start} in {s!r}")


_OBJ_TOKEN = re.compile(
    r"""[\s,]+
      | (?P<string>"(?:\\.|[^"\\])*")
      | (?P<num>-?\d+\.\d+|-?\d+)
      | (?P<name>\$?[_A-Za-z][\w.]*)
      | (?P<punct>\{|\}|\[|\]|:)
    """,
    re.VERBOSE,
)


def _parse_gql_object(src: str):
    toks: List[Tuple[str, str]] = []
    pos = 0
    while pos < len(src):
        m = _OBJ_TOKEN.match(src, pos)
        if not m:
            raise AuthError(f"bad char {src[pos]!r} in auth object")
        if m.lastgroup:
            toks.append((m.lastgroup, m.group()))
        pos = m.end()

    i = 0

    def parse_value():
        nonlocal i
        kind, text = toks[i]
        if kind == "punct" and text == "{":
            return parse_obj()
        if kind == "punct" and text == "[":
            i += 1
            out = []
            while toks[i] != ("punct", "]"):
                out.append(parse_value())
            i += 1
            return out
        i += 1
        if kind == "string":
            return json.loads(text)
        if kind == "num":
            return float(text) if "." in text else int(text)
        if kind == "name":
            if text == "true":
                return True
            if text == "false":
                return False
            if text == "null":
                return None
            return text  # enum or $var
        raise AuthError(f"unexpected token {text!r}")

    def parse_obj():
        nonlocal i
        assert toks[i] == ("punct", "{")
        i += 1
        out = {}
        while toks[i] != ("punct", "}"):
            kind, key = toks[i]
            if kind not in ("name", "string"):
                raise AuthError(f"bad object key {key!r}")
            if kind == "string":
                key = json.loads(key)
            i += 1
            if toks[i] != ("punct", ":"):
                raise AuthError(f"expected : after {key!r}")
            i += 1
            out[key] = parse_value()
        i += 1
        return out

    out = parse_obj()
    if i != len(toks):
        raise AuthError("trailing tokens in auth object")
    return out

from dgraph_tpu.graphql.resolve import GraphQLServer

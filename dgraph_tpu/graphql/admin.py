"""Admin GraphQL endpoint (/admin).

Mirrors /root/reference/graphql/admin (admin.go: the ops schema served at
/admin — health/state/getGQLSchema queries; updateGQLSchema, export,
backup, draining, shutdown, config mutations) resolved directly against
the engine, reusing the operation parser.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

from dgraph_tpu.graphql.parser import Operation, Selection, parse_operation

_START = time.time()


class AdminGraphQL:
    def __init__(self, engine):
        self.engine = engine

    def execute(self, query: str, variables: Optional[dict] = None) -> dict:
        try:
            op = parse_operation(query, variables)
            data: Dict[str, Any] = {}
            for sel in op.selections:
                if op.kind == "mutation":
                    data[sel.key] = self._mutation(sel)
                else:
                    data[sel.key] = self._query(sel)
            return {"data": data}
        except Exception as e:  # noqa: BLE001 — GraphQL error envelope
            return {"data": None, "errors": [{"message": str(e)}]}

    # -- queries -------------------------------------------------------------

    def _query(self, sel: Selection):
        if sel.name == "health":
            return [
                {
                    "instance": "alpha",
                    "status": "healthy",
                    "version": "0.1.0",
                    "uptime": int(time.time() - _START),
                }
            ]
        if sel.name == "state":
            return {
                "counter": self.engine.zero.max_assigned,
                "maxUID": self.engine.zero._max_uid,
                "groups": {
                    "1": {
                        "tablets": {
                            p: {"predicate": p}
                            for p in self.engine.schema.predicates()
                        }
                    }
                },
            }
        if sel.name == "getGQLSchema":
            gql = getattr(self.engine, "graphql", None)
            return {"schema": gql.sdl if gql else ""}
        if sel.name == "config":
            return {
                "cacheMb": getattr(self.engine, "cache_mb", 0),
                "logDQLRequest": False,
            }
        if sel.name == "task":
            from dgraph_tpu.admin import tasks

            tid = int(str(sel.args.get("input", {}).get("id", "0x0")), 16)
            st = tasks._queue_of(self.engine).status(tid)
            return st or {"status": "Unknown"}
        raise ValueError(f"unknown admin query {sel.name!r}")

    # -- mutations -----------------------------------------------------------

    def _mutation(self, sel: Selection):
        if sel.name == "updateGQLSchema":
            from dgraph_tpu.graphql import GraphQLServer

            sdl = sel.args.get("input", {}).get("set", {}).get("schema", "")
            self.engine.graphql = GraphQLServer(self.engine, sdl)
            return {"gqlSchema": {"schema": sdl}}
        if sel.name == "export":
            import tempfile

            from dgraph_tpu.admin import tasks

            dest = sel.args.get("input", {}).get(
                "destination", tempfile.mkdtemp(prefix="dgraph_export_")
            )
            tid = tasks.enqueue_export(self.engine, dest)
            st = tasks._queue_of(self.engine).wait(tid)
            return {
                "response": {
                    "code": st.get("status", "Unknown"),
                    "message": f"export to {dest}",
                },
                "taskId": f"{tid:#x}",
            }
        if sel.name == "backup":
            from dgraph_tpu.admin import tasks

            dest = sel.args.get("input", {}).get(
                "destination", "/tmp/dgraph_tpu_backup"
            )
            tid = tasks.enqueue_backup(self.engine, dest)
            st = tasks._queue_of(self.engine).wait(tid)
            return {
                "response": {
                    "code": st.get("status", "Unknown"),
                    "message": f"backup to {dest}",
                },
                "taskId": f"{tid:#x}",
            }
        if sel.name == "draining":
            enable = bool(sel.args.get("enable", True))
            self.engine.draining = enable
            return {
                "response": {
                    "code": "Success",
                    "message": f"draining mode set to {enable}",
                }
            }
        if sel.name == "shutdown":
            return {"response": {"code": "Success", "message": "Done"}}
        if sel.name == "config":
            cache = sel.args.get("input", {}).get("cacheMb")
            if cache is not None:
                self.engine.cache_mb = cache
            return {"response": {"code": "Success", "message": "Done"}}
        if sel.name == "addNamespace":
            from dgraph_tpu.admin.namespace import NamespaceManager

            pw = sel.args.get("input", {}).get("password", "password")
            ns = NamespaceManager(self.engine).create_namespace(pw)
            return {
                "namespaceId": ns,
                "message": f"Created namespace {ns}",
            }
        if sel.name == "deleteNamespace":
            from dgraph_tpu.admin.namespace import NamespaceManager

            ns = int(sel.args.get("input", {}).get("namespaceId", -1))
            NamespaceManager(self.engine).delete_namespace(ns)
            return {
                "namespaceId": ns,
                "message": f"Deleted namespace {ns}",
            }
        raise ValueError(f"unknown admin mutation {sel.name!r}")

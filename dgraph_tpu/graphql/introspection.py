"""GraphQL introspection (__schema / __type / __typename).

Mirrors the reference's introspection support (graphql/schema/
introspection.go serving the standard meta-schema over the generated
API): tools like GraphiQL and code generators issue __schema queries to
discover the synthesized Query/Mutation fields and object types. The
subset implemented covers the standard introspection query's shape:
kinds, fields, args, ofType chains, enum values.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from dgraph_tpu.graphql.sdl import _SCALARS, GqlField, GqlType

_SCALAR_NAMES = ["String", "Int", "Float", "Boolean", "ID", "DateTime", "Int64"]


def _named(name: str, kind: str) -> dict:
    return {"kind": kind, "name": name, "ofType": None}


def _non_null(inner: dict) -> dict:
    return {"kind": "NON_NULL", "name": None, "ofType": inner}


def _list_of(inner: dict) -> dict:
    return {"kind": "LIST", "name": None, "ofType": inner}


def _field_type(f: GqlField) -> dict:
    base_kind = "SCALAR" if f.type_name in _SCALARS else "OBJECT"
    t = _named(f.type_name, base_kind)
    if f.is_list:
        t = _list_of(_non_null(t) if f.non_null else t)
    elif f.non_null:
        t = _non_null(t)
    return t


def build_registry(types: Dict[str, GqlType]) -> Dict[str, dict]:
    """name -> full __Type description."""
    reg: Dict[str, dict] = {}
    for n in _SCALAR_NAMES:
        reg[n] = {
            "kind": "SCALAR",
            "name": n,
            "description": None,
            "fields": None,
            "enumValues": None,
            "inputFields": None,
            "interfaces": None,
            "possibleTypes": None,
        }
    for t in types.values():
        reg[t.name] = {
            "kind": "OBJECT",
            "name": t.name,
            "description": None,
            "fields": [
                {
                    "name": f.name,
                    "description": None,
                    "args": [],
                    "type": _field_type(f),
                    "isDeprecated": False,
                    "deprecationReason": None,
                }
                for f in t.fields.values()
            ],
            "enumValues": None,
            "inputFields": None,
            "interfaces": [],
            "possibleTypes": None,
        }
    # synthesized root types
    qfields = []
    mfields = []
    for t in types.values():
        obj = _named(t.name, "OBJECT")
        qfields.append({"name": f"get{t.name}", "args": [], "type": obj,
                        "description": None, "isDeprecated": False,
                        "deprecationReason": None})
        qfields.append({"name": f"query{t.name}", "args": [],
                        "type": _list_of(obj), "description": None,
                        "isDeprecated": False, "deprecationReason": None})
        qfields.append({"name": f"aggregate{t.name}", "args": [],
                        "type": _named(f"{t.name}AggregateResult", "OBJECT"),
                        "description": None, "isDeprecated": False,
                        "deprecationReason": None})
        mfields.append({"name": f"add{t.name}", "args": [],
                        "type": _named(f"Add{t.name}Payload", "OBJECT"),
                        "description": None, "isDeprecated": False,
                        "deprecationReason": None})
        mfields.append({"name": f"update{t.name}", "args": [],
                        "type": _named(f"Update{t.name}Payload", "OBJECT"),
                        "description": None, "isDeprecated": False,
                        "deprecationReason": None})
        mfields.append({"name": f"delete{t.name}", "args": [],
                        "type": _named(f"Delete{t.name}Payload", "OBJECT"),
                        "description": None, "isDeprecated": False,
                        "deprecationReason": None})
    reg["Query"] = {
        "kind": "OBJECT", "name": "Query", "description": None,
        "fields": qfields, "enumValues": None, "inputFields": None,
        "interfaces": [], "possibleTypes": None,
    }
    reg["Mutation"] = {
        "kind": "OBJECT", "name": "Mutation", "description": None,
        "fields": mfields, "enumValues": None, "inputFields": None,
        "interfaces": [], "possibleTypes": None,
    }
    return reg


def _project(value: Any, selections) -> Any:
    """Apply a GraphQL selection set to a plain dict-tree description."""
    if value is None or not selections:
        return value
    if isinstance(value, list):
        return [_project(v, selections) for v in value]
    out = {}
    for s in selections:
        if s.name == "__typename":
            out[s.key] = "__Type"
            continue
        v = value.get(s.name) if isinstance(value, dict) else None
        out[s.key] = _project(v, s.selections) if s.selections else v
    return out


def resolve_introspection(types: Dict[str, GqlType], sel) -> Any:
    reg = build_registry(types)
    if sel.name == "__type":
        t = reg.get(sel.args.get("name", ""))
        return _project(t, sel.selections) if t else None
    # __schema
    schema = {
        "queryType": {"name": "Query"},
        "mutationType": {"name": "Mutation"},
        "subscriptionType": None,
        "types": list(reg.values()),
        "directives": [],
    }
    return _project(schema, sel.selections)

"""GraphQL SDL schema: parse type definitions, generate the DQL mapping.

Mirrors /root/reference/graphql/schema/gqlschema.go (API synthesis from
SDL) + schemagen.go (SDL -> dgraph schema): each GraphQL type T with field
f becomes predicate `T.f`; @search(by:[...]) maps to @index tokenizers;
@id fields get @index(hash) @upsert; @hasInverse becomes @reverse pairs;
vector fields (`[Float!] @embedding @search(by:["hnsw"])`) map to
float32vector hnsw indexes.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional

_SCALARS = {
    "String": "string",
    "Int": "int",
    "Int64": "int",
    "Float": "float",
    "Boolean": "bool",
    "DateTime": "datetime",
    "ID": "uid",
    "Point": "geo",
}

_SEARCH_DEFAULT = {
    "string": ["term"],
    "int": ["int"],
    "float": ["float"],
    "bool": ["bool"],
    "datetime": ["year"],
    "geo": ["geo"],
}


@dataclass
class GqlField:
    name: str
    type_name: str  # GraphQL type, e.g. String, Person
    is_list: bool = False
    non_null: bool = False
    is_id: bool = False  # @id (external id) or ID type
    search: List[str] = field(default_factory=list)
    has_inverse: str = ""  # field name on target type
    is_embedding: bool = False
    is_scalar: bool = True
    custom: Optional[dict] = None  # @custom(http: {...}) config
    is_lambda: bool = False  # @lambda: resolved by the lambda server

    @property
    def dql_type(self) -> str:
        if self.is_embedding:
            return "float32vector"
        return _SCALARS.get(self.type_name, "uid")


@dataclass
class GqlType:
    name: str
    fields: Dict[str, GqlField] = field(default_factory=dict)
    auth: object = None  # graphql.auth.TypeAuth when @auth present
    # @lambdaOnMutate(add/update/delete) webhook switches
    # (ref gqlschema.go:292, resolve/webhook.go)
    lambda_on_mutate: Dict[str, bool] = field(default_factory=dict)

    def id_field(self) -> Optional[GqlField]:
        for f in self.fields.values():
            if f.type_name == "ID":
                return f
        return None

    def xid_field(self) -> Optional[GqlField]:
        for f in self.fields.values():
            if f.is_id:
                return f
        return None


_TYPE_RE = re.compile(
    r"type\s+(?P<name>\w+)\s*(?:implements\s+[\w&\s]+)?\{(?P<body>[^}]*)\}",
    re.DOTALL,
)
_FIELD_RE = re.compile(
    r"""(?P<name>\w+)\s*(?P<args>\((?:[^()]|\([^()]*\))*\))?\s*:\s*
    (?P<list>\[)?\s*(?P<type>\w+)\s*(?P<inner_nn>!)?\s*\]?\s*(?P<nn>!)?\s*
    (?P<directives>(?:@\w+(?:\((?:[^()]|\([^()]*\))*\))?\s*)*)""",
    re.VERBOSE,
)
_DIR_RE = re.compile(r"@(\w+)(?:\(((?:[^()]|\([^()]*\))*)\))?")


class SDLError(Exception):
    pass


def _extract_type_auth(sdl: str):
    """Pull type-header directives (between `type X` and its body `{`) out
    of the SDL so @auth blobs — which contain braces inside rule strings —
    don't break the type regex. Returns (cleaned_sdl, {type: auth_blob})."""
    blobs: Dict[str, str] = {}
    out = []
    pos = 0
    for m in re.finditer(r"\btype\s+(\w+)", sdl):
        name = m.group(1)
        i = m.end()
        in_str = None  # None | '"' | '"""'
        pdepth = 0  # directive args may contain braces; only the body `{`
        # at paren depth 0 ends the header
        while i < len(sdl):
            ch = sdl[i]
            if in_str:
                if in_str == '"""' and sdl.startswith('"""', i):
                    in_str = None
                    i += 3
                    continue
                if in_str == '"' and ch == '"' and sdl[i - 1] != "\\":
                    in_str = None
            elif sdl.startswith('"""', i):
                in_str = '"""'
                i += 3
                continue
            elif ch == '"':
                in_str = '"'
            elif ch == "(":
                pdepth += 1
            elif ch == ")":
                pdepth -= 1
            elif ch == "{" and pdepth == 0:
                break
            i += 1
        header = sdl[m.end() : i]
        am = re.search(r"@auth\s*\(", header)
        if am:
            # balanced-paren scan, quote-aware
            j = am.end()
            depth = 1
            in_str = None
            while j < len(header) and depth:
                ch = header[j]
                if in_str:
                    if in_str == '"""' and header.startswith('"""', j):
                        in_str = None
                        j += 3
                        continue
                    if in_str == '"' and ch == '"' and header[j - 1] != "\\":
                        in_str = None
                elif header.startswith('"""', j):
                    in_str = '"""'
                    j += 3
                    continue
                elif ch == '"':
                    in_str = '"'
                elif ch == "(":
                    depth += 1
                elif ch == ")":
                    depth -= 1
                j += 1
            blobs[name] = header[am.end() : j - 1]
            header = header[: am.start()] + header[j:]
        out.append(sdl[pos : m.end()])
        out.append(re.sub(r"@auth", "", header))
        pos = i
    out.append(sdl[pos:])
    return "".join(out), blobs


def _scan_bodies(sdl: str):
    """Extract (type_name, body_text) with quote- and brace-aware scanning
    — directive args may contain braces (@custom http configs, @auth
    rules), which a `[^}]*` regex body would truncate."""
    out = []
    for m in re.finditer(r"\btype\s+(\w+)[^{]*\{", sdl):
        name = m.group(1)
        i = m.end()
        depth = 1
        in_str = None
        start = i
        while i < len(sdl) and depth:
            ch = sdl[i]
            if in_str:
                if in_str == '"""' and sdl.startswith('"""', i):
                    in_str = None
                    i += 3
                    continue
                if in_str == '"' and ch == '"' and sdl[i - 1] != "\\":
                    in_str = None
            elif sdl.startswith('"""', i):
                in_str = '"""'
                i += 3
                continue
            elif ch == '"':
                in_str = '"'
            elif ch == "{":
                depth += 1
            elif ch == "}":
                depth -= 1
            i += 1
        out.append((name, sdl[start : i - 1]))
    return out


def parse_sdl(sdl: str) -> Dict[str, GqlType]:
    sdl, auth_blobs = _extract_type_auth(sdl)
    sdl = re.sub(r'"""[\s\S]*?"""', "", sdl)  # strip descriptions
    sdl = re.sub(r"#[^\n]*", "", sdl)
    # type-header @lambdaOnMutate switches (ref gqlschema.go:292)
    lom: Dict[str, Dict[str, bool]] = {}
    for m in re.finditer(r"\btype\s+(\w+)([^{]*)\{", sdl):
        dm = re.search(r"@lambdaOnMutate\s*\(([^)]*)\)", m.group(2))
        if dm:
            lom[m.group(1)] = {
                k: v.strip().lower() == "true"
                for k, v in re.findall(r"(\w+)\s*:\s*(\w+)", dm.group(1))
            }
    sdl = re.sub(r"@lambdaOnMutate\s*\([^)]*\)", "", sdl)
    types: Dict[str, GqlType] = {}
    for tname, body in _scan_bodies(sdl):
        t = GqlType(name=tname)
        t.lambda_on_mutate = lom.get(tname, {})
        if tname in auth_blobs:
            from dgraph_tpu.graphql.auth import parse_auth_blob

            t.auth = parse_auth_blob(auth_blobs[tname])
        matches = list(_FIELD_RE.finditer(body))
        if not matches and body.strip():
            raise SDLError(f"cannot parse fields of type {t.name}: {body!r}")
        # ensure nothing between fields went unparsed (newline- or
        # whitespace-separated declarations both allowed in SDL)
        leftover = body
        for fm in matches:
            leftover = leftover.replace(fm.group(0), "", 1)
        if leftover.strip():
            raise SDLError(
                f"cannot parse field(s) {leftover.strip()!r} in type {t.name}"
            )
        for fm in matches:
            f = GqlField(
                name=fm.group("name"),
                type_name=fm.group("type"),
                is_list=bool(fm.group("list")),
                non_null=bool(fm.group("nn") or fm.group("inner_nn")),
            )
            f.is_scalar = fm.group("type") in _SCALARS
            for dm in _DIR_RE.finditer(fm.group("directives") or ""):
                dname, dargs = dm.group(1), dm.group(2) or ""
                if dname == "id":
                    f.is_id = True
                elif dname == "search":
                    by = re.findall(r"\w+", dargs.split(":", 1)[1]) if ":" in dargs else []
                    f.search = [b.lower() for b in by] or ["__default__"]
                elif dname == "hasInverse":
                    iv = re.search(r"field\s*:\s*\"?(\w+)\"?", dargs)
                    if iv:
                        f.has_inverse = iv.group(1)
                elif dname == "embedding":
                    f.is_embedding = True
                    f.is_scalar = True
                elif dname == "custom":
                    from dgraph_tpu.graphql.auth import _parse_gql_object

                    f.custom = _parse_gql_object("{" + dargs + "}")
                elif dname == "lambda":
                    # internally @lambda is @custom against the configured
                    # lambda server (ref wrappers.go:699 comment); we keep
                    # the flag and build the POST in resolve.py
                    f.is_lambda = True
            t.fields[f.name] = f
        types[t.name] = t
    return types


def to_dql_schema(types: Dict[str, GqlType]) -> str:
    """Generate the internal schema text (ref schemagen.go)."""
    lines: List[str] = []
    for t in types.values():
        if t.name in ("Query", "Mutation"):
            continue  # virtual roots hold @custom resolvers, not data
        tfields = []
        for f in t.fields.values():
            if f.type_name == "ID":
                continue  # internal uid, no predicate
            if f.custom is not None or f.is_lambda:
                continue  # resolved remotely, never stored
            pred = f"{t.name}.{f.name}"
            tfields.append(pred)
            dtype = f.dql_type
            type_str = f"[{dtype}]" if (f.is_list and not f.is_embedding) else dtype
            directives = []
            if f.is_embedding:
                search = [s for s in f.search if s != "__default__"]
                metric = "euclidean"
                for s in search:
                    if s in ("euclidean", "cosine", "dotproduct"):
                        metric = s
                directives.append(f'@index(hnsw(metric:"{metric}"))')
            elif f.is_id:
                directives.append("@index(hash)")
                directives.append("@upsert")
            elif f.search:
                toks = []
                for s in f.search:
                    if s == "__default__":
                        toks.extend(_SEARCH_DEFAULT.get(dtype, ["term"]))
                    elif s == "regexp":
                        toks.append("trigram")
                    else:
                        toks.append(s)
                directives.append(f"@index({', '.join(dict.fromkeys(toks))})")
            if not f.is_scalar:
                if f.has_inverse:
                    directives.append("@reverse")
            d = (" " + " ".join(directives)) if directives else ""
            lines.append(f"<{pred}>: {type_str}{d} .")
        fl = "\n  ".join(tfields)
        lines.append(f"type {t.name} {{\n  {fl}\n}}")
    return "\n".join(lines)

"""GraphQL SDL schema: parse type definitions, generate the DQL mapping.

Mirrors /root/reference/graphql/schema/gqlschema.go (API synthesis from
SDL) + schemagen.go (SDL -> dgraph schema): each GraphQL type T with field
f becomes predicate `T.f`; @search(by:[...]) maps to @index tokenizers;
@id fields get @index(hash) @upsert; @hasInverse becomes @reverse pairs;
vector fields (`[Float!] @embedding @search(by:["hnsw"])`) map to
float32vector hnsw indexes.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional

_SCALARS = {
    "String": "string",
    "Int": "int",
    "Int64": "int",
    "Float": "float",
    "Boolean": "bool",
    "DateTime": "datetime",
    "ID": "uid",
    "Point": "geo",
    "Polygon": "geo",
    "MultiPolygon": "geo",
}

_SEARCH_DEFAULT = {
    "string": ["term"],
    "int": ["int"],
    "float": ["float"],
    "bool": ["bool"],
    "datetime": ["year"],
    "geo": ["geo"],
}


@dataclass
class GqlField:
    name: str
    type_name: str  # GraphQL type, e.g. String, Person
    is_list: bool = False
    non_null: bool = False
    is_id: bool = False  # @id (external id) or ID type
    search: List[str] = field(default_factory=list)
    has_inverse: str = ""  # field name on target type
    is_embedding: bool = False
    is_scalar: bool = True
    custom: Optional[dict] = None  # @custom(http: {...}) config
    is_lambda: bool = False  # @lambda: resolved by the lambda server
    # declaring type: a field inherited from an interface keeps the
    # interface's predicate (ref gqlschema.go — Human implements
    # Character stores Character.name, not Human.name)
    owner: str = ""
    is_enum: bool = False  # enum-typed: stored as string
    is_union: bool = False  # union-typed: uid edge, fragment-dispatched
    is_secret: bool = False  # @secret password field (never returned)
    # @dgraph(pred: "...") explicit predicate mapping; "~x" maps the
    # field onto x's reverse edge (ref gqlschema.go dgraph directive)
    dql_pred: str = ""
    # @default(add:/update: {value}) literals; "$now" = request time
    default_add: Optional[str] = None
    default_update: Optional[str] = None
    # @id(interface: true): unique interface-wide, not just per type
    id_interface: bool = False

    @property
    def dql_type(self) -> str:
        if self.is_embedding:
            return "float32vector"
        if self.is_secret:
            return "password"
        if self.is_enum:
            return "string"
        return _SCALARS.get(self.type_name, "uid")


@dataclass
class GqlType:
    name: str
    fields: Dict[str, GqlField] = field(default_factory=dict)
    auth: object = None  # graphql.auth.TypeAuth when @auth present
    # @lambdaOnMutate(add/update/delete) webhook switches
    # (ref gqlschema.go:292, resolve/webhook.go)
    lambda_on_mutate: Dict[str, bool] = field(default_factory=dict)
    kind: str = "type"  # type | interface | input | enum | union
    interfaces: List[str] = field(default_factory=list)  # implemented
    implementers: List[str] = field(default_factory=list)  # for interfaces
    enum_values: List[str] = field(default_factory=list)  # for enums
    members: List[str] = field(default_factory=list)  # for unions
    # Apollo federation: @key(fields: "x") + @extends (ref
    # graphql/schema apollo support; _entities resolver)
    key_field: str = ""
    is_extended: bool = False
    # @dgraph(type: "...") storage type-name override
    dgraph_name: str = ""

    @property
    def stored_name(self) -> str:
        return self.dgraph_name or self.name

    def pred(self, fname: str) -> str:
        """DQL predicate for a field: owner-qualified so interface
        fields share one predicate across implementing types;
        @dgraph(pred:) overrides entirely."""
        f = self.fields.get(fname)
        if f is not None and f.dql_pred:
            return f.dql_pred
        owner = (f.owner or self.name) if f else self.name
        return f"{owner}.{fname}"

    def id_field(self) -> Optional[GqlField]:
        for f in self.fields.values():
            if f.type_name == "ID":
                return f
        return None

    def xid_field(self) -> Optional[GqlField]:
        for f in self.fields.values():
            if f.is_id:
                return f
        return None


_TYPE_RE = re.compile(
    r"type\s+(?P<name>\w+)\s*(?:implements\s+[\w&\s]+)?\{(?P<body>[^}]*)\}",
    re.DOTALL,
)
_FIELD_RE = re.compile(
    r"""(?P<name>\w+)\s*(?P<args>\((?:[^()]|\([^()]*\))*\))?\s*:\s*
    (?P<list>\[)?\s*(?P<type>\w+)\s*(?P<inner_nn>!)?\s*\]?\s*(?P<nn>!)?\s*
    (?P<directives>(?:@\w+(?:[ \t]*\((?:[^()]|\([^()]*\))*\))?\s*)*)""",
    re.VERBOSE,
)
_DIR_RE = re.compile(r"@(\w+)(?:[ \t]*\(((?:[^()]|\([^()]*\))*)\))?")


class SDLError(Exception):
    pass


def _extract_type_auth(sdl: str):
    """Pull type-header directives (between `type X` and its body `{`) out
    of the SDL so @auth blobs — which contain braces inside rule strings —
    don't break the type regex. Returns (cleaned_sdl, {type: auth_blob})."""
    blobs: Dict[str, str] = {}
    out = []
    pos = 0
    for m in re.finditer(r"\b(?:type|interface)\s+(\w+)", sdl):
        name = m.group(1)
        i = m.end()
        in_str = None  # None | '"' | '"""'
        pdepth = 0  # directive args may contain braces; only the body `{`
        # at paren depth 0 ends the header
        while i < len(sdl):
            ch = sdl[i]
            if in_str:
                if in_str == '"""' and sdl.startswith('"""', i):
                    in_str = None
                    i += 3
                    continue
                if in_str == '"' and ch == '"' and sdl[i - 1] != "\\":
                    in_str = None
            elif sdl.startswith('"""', i):
                in_str = '"""'
                i += 3
                continue
            elif ch == '"':
                in_str = '"'
            elif ch == "(":
                pdepth += 1
            elif ch == ")":
                pdepth -= 1
            elif ch == "{" and pdepth == 0:
                break
            i += 1
        header = sdl[m.end() : i]
        am = re.search(r"@auth\s*\(", header)
        if am:
            # balanced-paren scan, quote-aware
            j = am.end()
            depth = 1
            in_str = None
            while j < len(header) and depth:
                ch = header[j]
                if in_str:
                    if in_str == '"""' and header.startswith('"""', j):
                        in_str = None
                        j += 3
                        continue
                    if in_str == '"' and ch == '"' and header[j - 1] != "\\":
                        in_str = None
                elif header.startswith('"""', j):
                    in_str = '"""'
                    j += 3
                    continue
                elif ch == '"':
                    in_str = '"'
                elif ch == "(":
                    depth += 1
                elif ch == ")":
                    depth -= 1
                j += 1
            blobs[name] = header[am.end() : j - 1]
            header = header[: am.start()] + header[j:]
        out.append(sdl[pos : m.end()])
        out.append(re.sub(r"@auth", "", header))
        pos = i
    out.append(sdl[pos:])
    return "".join(out), blobs


def _scan_bodies(sdl: str):
    """Extract (kind, type_name, header, body_text) with quote- and
    brace-aware scanning — directive args may contain braces (@custom
    http configs, @auth rules), which a `[^}]*` regex body would
    truncate."""
    out = []
    for m in re.finditer(
        r"\b(type|interface|input)\s+(\w+)([^{]*)\{", sdl
    ):
        kind, name, header = m.group(1), m.group(2), m.group(3)
        i = m.end()
        depth = 1
        in_str = None
        start = i
        while i < len(sdl) and depth:
            ch = sdl[i]
            if in_str:
                if in_str == '"""' and sdl.startswith('"""', i):
                    in_str = None
                    i += 3
                    continue
                if in_str == '"' and ch == '"' and sdl[i - 1] != "\\":
                    in_str = None
            elif sdl.startswith('"""', i):
                in_str = '"""'
                i += 3
                continue
            elif ch == '"':
                in_str = '"'
            elif ch == "{":
                depth += 1
            elif ch == "}":
                depth -= 1
            i += 1
        out.append((kind, name, header, sdl[start : i - 1]))
    return out


def parse_sdl(sdl: str) -> Dict[str, GqlType]:
    sdl, auth_blobs = _extract_type_auth(sdl)
    sdl = re.sub(r'"""[\s\S]*?"""', "", sdl)  # strip descriptions
    sdl = re.sub(r"#[^\n]*", "", sdl)
    # type-header @lambdaOnMutate switches (ref gqlschema.go:292)
    lom: Dict[str, Dict[str, bool]] = {}
    for m in re.finditer(r"\btype\s+(\w+)([^{]*)\{", sdl):
        dm = re.search(r"@lambdaOnMutate\s*\(([^)]*)\)", m.group(2))
        if dm:
            lom[m.group(1)] = {
                k: v.strip().lower() == "true"
                for k, v in re.findall(r"(\w+)\s*:\s*(\w+)", dm.group(1))
            }
    sdl = re.sub(r"@lambdaOnMutate\s*\([^)]*\)", "", sdl)
    types: Dict[str, GqlType] = {}
    # enum E { A B C } — values become string storage with hash search
    for m in re.finditer(r"\benum\s+(\w+)\s*\{([^}]*)\}", sdl):
        types[m.group(1)] = GqlType(
            name=m.group(1),
            kind="enum",
            enum_values=re.findall(r"\w+", m.group(2)),
        )
    # union U = A | B | C — a uid edge dispatched by inline fragments
    # members may span lines in leading-pipe style: after the first
    # member, every further member needs its '|', so the scan can't
    # swallow the next definition
    for m in re.finditer(
        r"\bunion\s+(\w+)\s*=\s*\|?\s*(\w+(?:\s*\|\s*\w+)*)", sdl
    ):
        types[m.group(1)] = GqlType(
            name=m.group(1),
            kind="union",
            members=re.findall(r"\w+", m.group(2)),
        )
    for kind, tname, header, body in _scan_bodies(sdl):
        t = GqlType(name=tname, kind=kind)
        im = re.search(r"\bimplements\s+([\w&\s]+)", header)
        if im:
            t.interfaces = re.findall(r"\w+", im.group(1))
        t.lambda_on_mutate = lom.get(tname, {})
        km = re.search(r'@key\s*\(\s*fields:\s*"(\w+)"', header)
        if km:
            t.key_field = km.group(1)
        if re.search(r"@extends\b", header):
            t.is_extended = True
        dm = re.search(r'@dgraph\s*\(\s*type:\s*"([^"]+)"', header)
        if dm:
            # type T @dgraph(type: "stored.name"): the node type name
            # in storage differs from the GraphQL name (ref
            # gqlschema.go dgraph directive on types)
            t.dgraph_name = dm.group(1)
        sm = re.search(r'@secret\s*\(\s*field:\s*"(\w+)"', header)
        if sm:
            # type T @secret(field: "pwd") stores a hashed password
            # predicate and generates checkTPassword (ref
            # gqlschema.go:280 secret directive)
            f = GqlField(
                name=sm.group(1), type_name="String", is_secret=True
            )
            t.fields[f.name] = f
        if tname in auth_blobs:
            from dgraph_tpu.graphql.auth import parse_auth_blob

            t.auth = parse_auth_blob(auth_blobs[tname])
        matches = list(_FIELD_RE.finditer(body))
        if not matches and body.strip():
            raise SDLError(f"cannot parse fields of type {t.name}: {body!r}")
        # ensure nothing between fields went unparsed (newline- or
        # whitespace-separated declarations both allowed in SDL)
        leftover = body
        for fm in matches:
            leftover = leftover.replace(fm.group(0), "", 1)
        if leftover.strip():
            raise SDLError(
                f"cannot parse field(s) {leftover.strip()!r} in type {t.name}"
            )
        for fm in matches:
            f = GqlField(
                name=fm.group("name"),
                type_name=fm.group("type"),
                is_list=bool(fm.group("list")),
                non_null=bool(fm.group("nn") or fm.group("inner_nn")),
            )
            f.is_scalar = fm.group("type") in _SCALARS
            for dm in _DIR_RE.finditer(fm.group("directives") or ""):
                dname, dargs = dm.group(1), dm.group(2) or ""
                if dname == "id":
                    f.is_id = True
                    # @id(interface: true): unique across ALL types
                    # implementing the declaring interface (ref
                    # gqlschema.go idDirective interface arg)
                    if re.search(r"interface\s*:\s*true", dargs):
                        f.id_interface = True
                elif dname == "search":
                    by = re.findall(r"\w+", dargs.split(":", 1)[1]) if ":" in dargs else []
                    f.search = [b.lower() for b in by] or ["__default__"]
                elif dname == "hasInverse":
                    iv = re.search(r"field\s*:\s*\"?(\w+)\"?", dargs)
                    if iv:
                        f.has_inverse = iv.group(1)
                elif dname == "embedding":
                    f.is_embedding = True
                    f.is_scalar = True
                elif dname == "default":
                    # @default(add: {value: "x"}, update: {value: "y"})
                    # (ref gqlschema.go defaultDirective — values are
                    # strings, converted by field type; "$now" = now)
                    am = re.search(
                        r'add\s*:\s*\{\s*value\s*:\s*"([^"]*)"', dargs
                    )
                    um = re.search(
                        r'update\s*:\s*\{\s*value\s*:\s*"([^"]*)"', dargs
                    )
                    if am:
                        f.default_add = am.group(1)
                    if um:
                        f.default_update = um.group(1)
                elif dname == "custom":
                    from dgraph_tpu.graphql.auth import _parse_gql_object

                    f.custom = _parse_gql_object("{" + dargs + "}")
                elif dname == "lambda":
                    # internally @lambda is @custom against the configured
                    # lambda server (ref wrappers.go:699 comment); we keep
                    # the flag and build the POST in resolve.py
                    f.is_lambda = True
                elif dname == "dgraph":
                    pm = re.search(r'pred\s*:\s*"([^"]+)"', dargs)
                    if pm:
                        f.dql_pred = pm.group(1).strip("<>").replace(
                            "~<", "~"
                        )
            t.fields[f.name] = f
        types[t.name] = t
    # an extended type's @external ID key comes from another federation
    # service: it is STORED as an indexed string predicate, not a uid
    # (ref schemagen apollo handling — eq(Astronaut.id, ...) queries)
    for t in types.values():
        if t.is_extended and t.key_field:
            f = t.fields.get(t.key_field)
            if f is not None and f.type_name == "ID":
                f.type_name = "String"
                f.is_scalar = True
                f.is_id = True
    # second pass: enum/union field marking, interface inheritance
    for t in types.values():
        for f in t.fields.values():
            ft = types.get(f.type_name)
            if ft is not None and ft.kind == "enum":
                f.is_enum = True
                f.is_scalar = True
            elif ft is not None and ft.kind == "union":
                f.is_union = True
                f.is_scalar = False
    # @hasInverse pairs are two-way: writing through EITHER side keeps
    # both edges (ref mutation_rewriter.go addInverseLink). Propagate
    # BEFORE interface-field inheritance so implementers inherit the
    # back-pointer, and again after for pairs declared on implementers.
    def _propagate_inverse():
        for t in types.values():
            for f in t.fields.values():
                if not f.has_inverse or f.is_scalar:
                    continue
                ft = types.get(f.type_name)
                if ft is None:
                    continue
                g = ft.fields.get(f.has_inverse)
                if g is not None and not g.has_inverse:
                    g.has_inverse = f.name

    _propagate_inverse()
    for t in types.values():
        if t.kind != "type":
            continue
        for iname in t.interfaces:
            it = types.get(iname)
            if it is None or it.kind != "interface":
                raise SDLError(
                    f"type {t.name} implements unknown interface {iname}"
                )
            it.implementers.append(t.name)
            # inherited fields keep the interface's predicate; the
            # interface's declaration is authoritative even when the
            # implementing type redeclares the field (ref gqlschema.go)
            for f in it.fields.values():
                g = GqlField(**{**f.__dict__, "search": list(f.search)})
                g.owner = iname
                t.fields[f.name] = g
    # @dgraph(type: "stored") types default their unmapped fields to
    # "<stored>.<field>" (ref schemagen.go — the directives e2e data
    # stores myPost.title for `type Post @dgraph(type: "myPost")`)
    for t in types.values():
        for f in t.fields.values():
            if f.dql_pred or f.type_name == "ID":
                continue
            owner = types.get(f.owner) if f.owner else t
            owner = owner or t
            if owner.dgraph_name:
                f.dql_pred = f"{owner.dgraph_name}.{f.name}"
    _propagate_inverse()
    # interface @auth rules apply to implementers too, AND-combined
    # with the type's own rules (ref graphql/schema auth inheritance)
    from dgraph_tpu.graphql.auth import AuthNode, TypeAuth

    for t in types.values():
        if t.kind != "type" or not t.interfaces:
            continue
        for iname in t.interfaces:
            it = types.get(iname)
            if it is None or it.auth is None:
                continue
            if t.auth is None:
                t.auth = TypeAuth()
            for op in ("query", "add", "update", "delete"):
                mine = getattr(t.auth, op)
                theirs = getattr(it.auth, op)
                if theirs is None:
                    continue
                if mine is None:
                    setattr(t.auth, op, theirs)
                else:
                    setattr(
                        t.auth, op,
                        AuthNode(kind="and", children=[theirs, mine]),
                    )
    return types


def to_dql_schema(types: Dict[str, GqlType]) -> str:
    """Generate the internal schema text (ref schemagen.go). Interfaces
    emit their own predicates; implementing types list the inherited
    (interface-owned) predicates in their type definition but do not
    re-emit them."""
    lines: List[str] = []
    # predicates referenced through "~x" reverse mappings need @reverse
    # on their forward declaration
    need_reverse = {
        f.dql_pred[1:]
        for t in types.values()
        for f in t.fields.values()
        if f.dql_pred.startswith("~")
    }
    emitted = set()
    for t in types.values():
        if t.name in ("Query", "Mutation"):
            continue  # virtual roots hold @custom resolvers, not data
        if t.kind in ("enum", "union", "input"):
            continue  # no storage of their own
        tfields = []
        for f in t.fields.values():
            if f.type_name == "ID":
                continue  # internal uid, no predicate
            if f.custom is not None or f.is_lambda:
                continue  # resolved remotely, never stored
            pred = t.pred(f.name)
            if pred.startswith("~"):
                continue  # rides the forward predicate's @reverse
            tfields.append(pred)
            if f.owner and f.owner != t.name:
                continue  # inherited: the interface emits the predicate
            if pred in emitted:
                continue  # @dgraph(pred) shared across types
            emitted.add(pred)
            dtype = f.dql_type
            type_str = f"[{dtype}]" if (f.is_list and not f.is_embedding) else dtype
            directives = []
            if f.is_embedding:
                search = [s for s in f.search if s != "__default__"]
                metric = "euclidean"
                for s in search:
                    if s in ("euclidean", "cosine", "dotproduct"):
                        metric = s
                directives.append(f'@index(hnsw(metric:"{metric}"))')
            elif f.is_id:
                directives.append("@index(hash)")
                directives.append("@upsert")
            elif f.search:
                toks = []
                for s in f.search:
                    if s == "__default__":
                        if f.is_enum:
                            # ref gqlschema.go defaultSearches: enum=hash
                            toks.append("hash")
                        else:
                            toks.extend(_SEARCH_DEFAULT.get(dtype, ["term"]))
                    elif s == "regexp":
                        toks.append("trigram")
                    else:
                        toks.append(s)
                directives.append(f"@index({', '.join(dict.fromkeys(toks))})")
            if not f.is_scalar:
                if f.has_inverse or pred in need_reverse:
                    directives.append("@reverse")
            d = (" " + " ".join(directives)) if directives else ""
            lines.append(f"<{pred}>: {type_str}{d} .")
        fl = "\n  ".join(tfields)
        lines.append(f"type {t.stored_name} {{\n  {fl}\n}}")
    return "\n".join(lines)

"""Remote-schema introspection + validation for @custom graphql fields.

Mirrors /root/reference/graphql/schema/remote.go: at schema-update time
every `@custom(http: {graphql: "..."})` field introspects the remote
endpoint (introspectRemoteSchema:40) and validates the operation
against what the remote actually serves (validateRemoteGraphql:227):
the query/mutation must exist, its return type must match the field's
(list-wrapped for batch mode), required remote arguments must be
supplied, and argument/return type names must resolve in the remote
schema. Invalid selections are rejected at schema-update time, not at
first request.
"""

from __future__ import annotations

import json
import re
import urllib.request
from typing import Dict, Optional

# the standard GraphQL introspection query, trimmed to what validation
# reads (remote.go introspectionQuery:86)
_TYPE_REF = "kind name ofType { kind name ofType { kind name ofType { kind name } } }"
INTROSPECTION_QUERY = f"""
query {{
  __schema {{
    queryType {{ name }}
    mutationType {{ name }}
    types {{
      kind
      name
      fields {{
        name
        args {{ name type {{ {_TYPE_REF} }} }}
        type {{ {_TYPE_REF} }}
      }}
      inputFields {{ name type {{ {_TYPE_REF} }} }}
    }}
  }}
}}
"""


class RemoteSchemaError(ValueError):
    pass


def introspect_remote(
    url: str, headers: Optional[Dict[str, str]] = None, timeout: float = 10.0
) -> dict:
    """POST the introspection query; returns the __schema dict
    (introspectRemoteSchema — POST urls must carry no query params)."""
    if "?" in url:
        raise RemoteSchemaError(
            f"POST method cannot have query parameters in url: {url}"
        )
    body = json.dumps({"query": INTROSPECTION_QUERY}).encode()
    req = urllib.request.Request(
        url, data=body, method="POST",
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            payload = json.loads(r.read())
    except Exception as e:
        raise RemoteSchemaError(
            f"unable to introspect remote schema at {url}: {e}"
        ) from e
    schema = (payload.get("data") or {}).get("__schema")
    if not schema:
        raise RemoteSchemaError(
            f"remote introspection at {url} returned no __schema"
        )
    return schema


def _type_str(t: Optional[dict]) -> str:
    """Render an introspected type ref as a GraphQL type string."""
    if not t:
        return ""
    kind = t.get("kind")
    if kind == "NON_NULL":
        return _type_str(t.get("ofType")) + "!"
    if kind == "LIST":
        return "[" + _type_str(t.get("ofType")) + "]"
    return t.get("name") or ""


def _named_type(t: Optional[dict]) -> str:
    while t and not t.get("name"):
        t = t.get("ofType")
    return (t or {}).get("name") or ""


_OP_RE = re.compile(r"\b(query|mutation)\b[^{]*\{\s*(\w+)\s*(\(([^)]*)\))?")


def validate_remote_graphql(
    remote_schema: dict,
    graphql_text: str,
    field_type: str,
    is_batch: bool = False,
) -> None:
    """validateRemoteGraphql:227 — the given operation must exist on the
    remote with a matching return type, all required remote args
    supplied, and referenced type names present in the remote schema."""
    m = _OP_RE.search(graphql_text)
    if not m:
        raise RemoteSchemaError(
            f"could not parse @custom graphql operation: {graphql_text!r}"
        )
    op_kind, op_name, _, arg_src = m.group(1), m.group(2), m.group(3), m.group(4)

    root = (remote_schema.get(f"{op_kind}Type") or {}).get("name")
    if not root:
        raise RemoteSchemaError(
            f"remote schema doesn't have any {op_kind}s."
        )
    types = {t["name"]: t for t in remote_schema.get("types") or []}
    root_t = types.get(root)
    if root_t is None:
        raise RemoteSchemaError(f"remote schema has no type {root}")

    remote_field = next(
        (f for f in root_t.get("fields") or [] if f["name"] == op_name),
        None,
    )
    if remote_field is None:
        raise RemoteSchemaError(
            f"{op_kind} `{op_name}` is not present in remote schema."
        )

    expected = f"[{field_type}]" if is_batch else field_type
    got = _type_str(remote_field.get("type"))
    if _strip_nn(got) != _strip_nn(expected):
        raise RemoteSchemaError(
            f"found return type mismatch for {op_kind} `{op_name}`, "
            f"expected `{expected}`, got `{got}`."
        )

    # every referenced named type must exist remotely
    ret_name = _named_type(remote_field.get("type"))
    if ret_name and ret_name not in types:
        raise RemoteSchemaError(
            f"remote schema doesn't have any type named {ret_name}."
        )

    given_args = set()
    for part in (arg_src or "").split(","):
        part = part.strip()
        if part and ":" in part:
            given_args.add(part.split(":", 1)[0].strip())
    for arg in remote_field.get("args") or []:
        required = (arg.get("type") or {}).get("kind") == "NON_NULL"
        if required and arg["name"] not in given_args:
            raise RemoteSchemaError(
                f"argument `{arg['name']}` in {op_kind} `{op_name}` is "
                f"missing, it is required by remote {op_kind}."
            )
        aname = _named_type(arg.get("type"))
        if aname and aname not in types:
            raise RemoteSchemaError(
                f"remote schema doesn't have any type named {aname}."
            )


def _strip_nn(s: str) -> str:
    return s.replace("!", "")

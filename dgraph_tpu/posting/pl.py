"""Posting lists: MVCC layered edge/value storage per (predicate, uid) key.

Mirrors /root/reference/posting/list.go semantics with a simplified layer
model (SURVEY.md §7.2):

  - a *rollup* record is the complete immutable state at some commit ts —
    UID edges as a block-compressed UidPack (codec/uidpack.py) plus value
    postings (ref list.go:66 `plist` with UidPack + postings),
  - *delta* records are per-txn changes written at their commit ts
    (ref posting/mvcc.go:266 CommitToDisk),
  - a read at `read_ts` walks KV versions newest->oldest until a rollup,
    then applies the deltas above it in ts order
    (ref posting/mvcc.go:641 ReadPostingList),
  - rollup() recompacts layers into a new rollup record
    (ref list.go:1416 Rollup; incremental trigger posting/mvcc.go:41).

Value postings use the reference's uid conventions: a scalar value posting
has uid VALUE_UID (math.MaxUint64, ref posting/index.go fingerprinting); a
language-tagged or list value posting uses a 64-bit fingerprint of the
lang/value so multiple values coexist in one sorted list.
"""

from __future__ import annotations

import hashlib
import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from dgraph_tpu.codec import uidpack
from dgraph_tpu.types.types import TypeID, Val, from_binary, to_binary
from dgraph_tpu.utils.farmhash import (
    fingerprint64 as _farm_fp,
    go_value_binary,
)

OP_SET = 1
OP_DEL = 2

# multi-part list threshold: a rollup whose uid set exceeds this is split
# into part records under keys.SplitKey (ref posting/list.go:44 maxListSize,
# rollup re-split list.go:1590). Tunable for tests / memory budgets; the
# native bulk reduce (loaders/bulk2.py) reads the same registry knob.
from dgraph_tpu.x import config as _config

MAX_PART_UIDS = int(_config.get("MAX_PART_UIDS"))

VALUE_UID = (1 << 64) - 1  # plain scalar value posting


class CorruptRecordError(ValueError):
    """A stored posting record failed structural validation (truncated or
    corrupt bytes) — raised instead of silently decoding garbage
    (mirrors the strict checks in codec/uidpack.deserialize)."""


def fingerprint64(data: bytes) -> int:
    h = hashlib.blake2b(data, digest_size=8).digest()
    v = struct.unpack("<Q", h)[0]
    return v or 1  # avoid uid 0


def lang_uid(lang: str) -> int:
    """Posting uid for a language-tagged value: farm.Fingerprint64 of the
    bare lang tag (ref posting/list.go:826) — the reference accepts the
    lang-vs-value collision this implies, so we must too: posting order
    (= JSON list order) is fingerprint order."""
    if not lang:
        return VALUE_UID
    return _farm_fp(lang.encode("utf-8"))


def value_uid(stored: "Val") -> int:
    """Posting uid for a list-predicate value: farm.Fingerprint64 of the
    value's GO-marshaled bytes (ref posting/list.go:831 + the conversion
    in types/conversion.go Marshal). Matching the reference's hash over
    the reference's bytes makes list-value JSON ordering bit-exact."""
    return _farm_fp(go_value_binary(stored.tid, stored.value))


@dataclass(slots=True)
class Posting:
    uid: int
    op: int = OP_SET
    value: Optional[bytes] = None  # None => pure uid edge
    value_type: TypeID = TypeID.DEFAULT
    lang: str = ""
    facets: Dict[str, bytes] = field(default_factory=dict)
    facet_types: Dict[str, TypeID] = field(default_factory=dict)

    @property
    def is_value(self) -> bool:
        return self.value is not None

    def val(self) -> Val:
        return from_binary(self.value_type, self.value)

    def get_facets(self) -> Dict[str, Val]:
        return {
            k: from_binary(self.facet_types.get(k, TypeID.DEFAULT), v)
            for k, v in self.facets.items()
        }


# ---------------------------------------------------------------------------
# Record serialization (KV value bytes).
# ---------------------------------------------------------------------------

KIND_ROLLUP = 0
KIND_DELTA = 1


def _enc_posting(p: Posting, out: List[bytes]):
    flags = (1 if p.is_value else 0) | (p.op << 1)
    out.append(struct.pack("<BQB", flags, p.uid, int(p.value_type)))
    lang = p.lang.encode("utf-8")
    out.append(struct.pack("<B", len(lang)))
    out.append(lang)
    v = p.value if p.value is not None else b""
    out.append(struct.pack("<I", len(v)))
    out.append(v)
    out.append(struct.pack("<H", len(p.facets)))
    for k in sorted(p.facets):
        kb = k.encode("utf-8")
        fv = p.facets[k]
        out.append(
            struct.pack(
                "<BBH", len(kb), int(p.facet_types.get(k, TypeID.DEFAULT)), len(fv)
            )
        )
        out.append(kb)
        out.append(fv)


def encode_posting_bytes(p: Posting) -> bytes:
    """One posting in the record wire layout (the bulk loader's spill-run
    payload format — shared with native/bulkload.cpp)."""
    out: List[bytes] = []
    _enc_posting(p, out)
    return b"".join(out)


def decode_posting_bytes(data: bytes) -> Posting:
    p, _ = _dec_posting(data, 0)
    return p


def _need(data: bytes, pos: int, n: int):
    if pos + n > len(data):
        raise CorruptRecordError(
            f"posting record truncated: need {n} bytes at {pos}, have {len(data)}"
        )


def _dec_posting(data: bytes, pos: int) -> Tuple[Posting, int]:
    _need(data, pos, 11)
    flags, uid, tid = struct.unpack_from("<BQB", data, pos)
    pos += 10
    (llen,) = struct.unpack_from("<B", data, pos)
    pos += 1
    _need(data, pos, llen)
    lang = data[pos : pos + llen].decode("utf-8")
    pos += llen
    _need(data, pos, 4)
    (vlen,) = struct.unpack_from("<I", data, pos)
    pos += 4
    _need(data, pos, vlen)
    value = data[pos : pos + vlen]
    pos += vlen
    _need(data, pos, 2)
    (nf,) = struct.unpack_from("<H", data, pos)
    pos += 2
    facets: Dict[str, bytes] = {}
    ftypes: Dict[str, TypeID] = {}
    for _ in range(nf):
        _need(data, pos, 4)
        klen, ftid, fvlen = struct.unpack_from("<BBH", data, pos)
        pos += 4
        _need(data, pos, klen + fvlen)
        k = data[pos : pos + klen].decode("utf-8")
        pos += klen
        facets[k] = data[pos : pos + fvlen]
        ftypes[k] = TypeID(ftid)
        pos += fvlen
    is_value = flags & 1
    p = Posting(
        uid=uid,
        op=(flags >> 1) & 0x3,
        value=value if is_value else None,
        value_type=TypeID(tid),
        lang=lang,
        facets=facets,
        facet_types=ftypes,
    )
    return p, pos


def encode_rollup(
    pack,
    postings: List[Posting],
    split_starts: Optional[List[int]] = None,
) -> bytes:
    """Main rollup record. When `split_starts` is non-empty the pack holds
    only value/facet postings' context — the uid set lives in part records
    (one per start uid) under keys.SplitKey(main_key, start).

    `pack` is a UidPack or pre-serialized pack bytes (bulk fast path)."""
    pb = pack if isinstance(pack, bytes) else uidpack.serialize(pack)
    out = [struct.pack("<BI", KIND_ROLLUP, len(pb)), pb]
    out.append(struct.pack("<I", len(postings)))
    for p in postings:
        _enc_posting(p, out)
    ss = split_starts or []
    out.append(struct.pack("<I", len(ss)))
    for st in ss:
        out.append(struct.pack("<Q", st))
    return b"".join(out)


def encode_delta(postings: List[Posting]) -> bytes:
    out = [struct.pack("<BI", KIND_DELTA, len(postings))]
    for p in postings:
        _enc_posting(p, out)
    return b"".join(out)


def encode_deltas(deltas: Dict[bytes, List[Posting]]):
    """Batched delta encode for a whole txn's write set: returns
    [(key, delta_record_bytes)] for every non-empty key (in write-set
    order), byte-identical to per-key encode_delta. The common
    scalar/uid posting shapes (no facets, no lang) encode through ONE
    native call across keys (codec.cpp enc_delta_records); keys
    holding facet/lang postings take the Python encoder PER KEY, so a
    single rich edge never disables the kernel for the whole txn."""
    from dgraph_tpu import native

    from dgraph_tpu.utils.observe import METRICS

    items = [(k, p) for k, p in deltas.items() if p]
    if not items:
        return []
    if not native.NATIVE_AVAILABLE:
        METRICS.inc("mutation_native_fallback_total", len(items))
        METRICS.inc(
            'mutation_native_fallback_total{reason="no_native"}',
            len(items),
        )
        return [(k, encode_delta(p)) for k, p in items]
    fast: List[int] = []  # indices into items taking the native kernel
    out: List = [None] * len(items)
    rich = 0
    for i, (k, posts) in enumerate(items):
        if any(p.facets or p.lang for p in posts):
            out[i] = (k, encode_delta(posts))
            rich += 1
        else:
            fast.append(i)
    if rich:
        # the per-key Python encoder ran: kernel-coverage regression
        # signal for the encode stage (keys, not edges, here)
        METRICS.inc("mutation_native_fallback_total", rich)
        METRICS.inc(
            'mutation_native_fallback_total{reason="rich_posting"}', rich
        )
    if fast:
        recs = _encode_deltas_native([items[i] for i in fast])
        if recs is None:  # native call unavailable after all
            METRICS.inc("mutation_native_fallback_total", len(fast))
            METRICS.inc(
                'mutation_native_fallback_total{reason="no_native"}',
                len(fast),
            )
            for i in fast:
                out[i] = (items[i][0], encode_delta(items[i][1]))
        else:
            for j, i in enumerate(fast):
                out[i] = (items[i][0], recs[j])
    return out


def _encode_deltas_native(items):
    """One-call encode of fast-shape postings (caller pre-filtered:
    no facets, no lang); returns the per-key record list or None when
    the native library is unavailable. Inputs assemble through plain
    lists converted to arrays in bulk — per-element numpy stores would
    cost more than the native call saves."""
    from dgraph_tpu import native

    counts: List[int] = []
    flags: List[int] = []
    uids: List[int] = []
    tids: List[int] = []
    vlens: List[int] = []
    vals: List[bytes] = []
    for _k, posts in items:
        counts.append(len(posts))
        for p in posts:
            v = p.value
            flags.append((1 if v is not None else 0) | (p.op << 1))
            uids.append(p.uid)
            tids.append(int(p.value_type))
            if v is not None:
                vlens.append(len(v))
                vals.append(v)
            else:
                vlens.append(0)
    return native.enc_delta_records(
        np.array(counts, np.int64),
        np.frombuffer(bytes(flags), np.uint8),
        np.array(uids, np.uint64),
        np.frombuffer(bytes(tids), np.uint8),
        np.array(vlens, np.int64),
        b"".join(vals),
    )


def decode_record(data: bytes):
    """Returns (kind, pack_or_None, postings, split_starts)."""
    _need(data, 0, 5)
    kind, n = struct.unpack_from("<BI", data, 0)
    if kind not in (KIND_ROLLUP, KIND_DELTA):
        raise CorruptRecordError(f"unknown record kind {kind}")
    pos = 5
    if kind == KIND_ROLLUP:
        _need(data, pos, n)
        pack = uidpack.deserialize(data[pos : pos + n])
        pos += n
        _need(data, pos, 4)
        (cnt,) = struct.unpack_from("<I", data, pos)
        pos += 4
        postings = []
        for _ in range(cnt):
            p, pos = _dec_posting(data, pos)
            postings.append(p)
        splits: List[int] = []
        if pos < len(data):  # records from before splits lack the tail
            _need(data, pos, 4)
            (ns,) = struct.unpack_from("<I", data, pos)
            pos += 4
            _need(data, pos, 8 * ns)
            for i in range(ns):
                splits.append(struct.unpack_from("<Q", data, pos)[0])
                pos += 8
        return KIND_ROLLUP, pack, postings, splits
    postings = []
    for _ in range(n):
        p, pos = _dec_posting(data, pos)
        postings.append(p)
    return KIND_DELTA, None, postings, []


def rollup_writes(
    key: bytes, uids: np.ndarray, posts: List[Posting], ts: int
) -> List[Tuple[bytes, int, bytes]]:
    """KV writes for a full rollup of `key` with the given uid set —
    split into part records when oversized (used by the bulk loader's
    reduce phase and tablet-move streaming; same split layout as
    PostingList.rollup)."""
    uids = np.asarray(uids, np.uint64)
    if len(uids) <= MAX_PART_UIDS:
        return [
            (key, ts, encode_rollup(uidpack.serialize_uids(uids), list(posts)))
        ]
    from dgraph_tpu.x import keys as _keys

    per = max(1, MAX_PART_UIDS // 2)
    writes: List[Tuple[bytes, int, bytes]] = []
    starts: List[int] = []
    for i in range(0, len(uids), per):
        chunk = uids[i : i + per]
        starts.append(int(chunk[0]))
        writes.append(
            (
                _keys.SplitKey(key, int(chunk[0])),
                ts,
                encode_rollup(uidpack.encode(chunk), []),
            )
        )
    empty = uidpack.encode(np.zeros((0,), np.uint64))
    writes.append(
        (key, ts, encode_rollup(empty, list(posts), split_starts=starts))
    )
    return writes


# ---------------------------------------------------------------------------
# PostingList: reconstruct-at-ts + mutate + rollup.
# ---------------------------------------------------------------------------


class PostingList:
    """A posting list reconstructed at a read timestamp.

    Layers, like ref posting/list.go:66: `pack`+`value_postings` form the
    immutable layer; `deltas` (commit_ts-ordered) are the committed mutable
    layer; uncommitted postings for the reading txn are merged by LocalCache.
    """

    def __init__(
        self,
        key: bytes,
        pack: Optional[uidpack.UidPack] = None,
        value_postings: Optional[List[Posting]] = None,
        deltas: Optional[List[Tuple[int, List[Posting]]]] = None,
        min_ts: int = 0,
    ):
        self.key = key
        self.pack = pack or uidpack.encode(np.zeros((0,), np.uint64))
        self.value_postings = value_postings or []
        # committed deltas above the rollup, ascending commit_ts
        self.deltas = deltas or []
        self.min_ts = min_ts  # ts of the rollup layer
        # newest version ts this list was built from — the identity used by
        # the device pack cache (key, latest_ts); 0 = empty/unknown
        self.latest_ts = max((ts for ts, _ in self.deltas), default=min_ts)
        self._uids_cache: Optional[np.ndarray] = None
        # multi-part list: per-part uid packs in ascending start-uid order
        # (the main record's pack is empty then; ref posting/list.go:519
        # pIterator walking split parts)
        self.part_packs: List[uidpack.UidPack] = []
        self.split_starts: List[int] = []
        # compressed-domain read state: merged multi-part view (block-array
        # concat, no decode) + decoded-block cache for the block-skip set
        # ops (ops/packed_setops.py). Both live on the PostingList, so a
        # commit invalidates them together with the list itself (MemoryLayer
        # drops the entry; DeviceCache mirrors the same invalidation).
        self._merged_pack: Optional[uidpack.UidPack] = None
        self._block_cache: Dict[int, np.ndarray] = {}
        self._has_uid_deltas: Optional[bool] = None

    # -- compressed-domain access -------------------------------------------

    # decoded-block cache bound: 4096 blocks ≈ 1M UIDs ≈ 8 MB per hot list
    BLOCK_CACHE_MAX = 4096

    def merged_pack(self) -> uidpack.UidPack:
        """The full uid set as ONE UidPack — the main pack, or the
        multi-part parts concatenated at the block level WITHOUT decoding
        (parts hold disjoint ascending ranges, so their block arrays chain
        into a valid pack). This is the operand the block-skip set ops
        consume; part packs are no longer eagerly decoded just to exist."""
        if self._merged_pack is None:
            if self.part_packs:
                self._merged_pack = uidpack.merge_packs(self.part_packs)
            else:
                self._merged_pack = self.pack
        return self._merged_pack

    def has_uid_deltas(self) -> bool:
        """True when committed deltas touch the uid set (value-only deltas
        leave the packed view exact)."""
        if self._has_uid_deltas is None:
            self._has_uid_deltas = any(
                not p.is_value for _, posts in self.deltas for p in posts
            )
        return self._has_uid_deltas

    def packed(self) -> Optional[uidpack.UidPack]:
        """The uid set as a UidPack when the compressed view is exact —
        None when committed uid deltas exist (the packed layers are stale
        then and callers must take the decoded path)."""
        if self.has_uid_deltas():
            return None
        return self.merged_pack()

    def decode_blocks(
        self, pack: uidpack.UidPack, idxs: np.ndarray
    ) -> np.ndarray:
        """Partial decoder with a per-list block cache: repeated traversals
        hitting the same candidate blocks stop re-decoding. `pack` must be
        this list's merged_pack() (the cache keys are its block indices)."""
        idxs = np.asarray(idxs, np.int64)
        if idxs.size == 0:
            return np.zeros((0,), np.uint64)
        missing = [int(i) for i in idxs if int(i) not in self._block_cache]
        tmp: Dict[int, np.ndarray] = {}
        if missing:
            decoded = uidpack.decode_blocks(
                pack, np.asarray(missing, np.int64)
            )
            pos = 0
            for bi in missing:
                c = int(pack.counts[bi])
                tmp[bi] = decoded[pos : pos + c]
                pos += c
            # cache-full: still serve cached blocks, just don't grow —
            # a hot list at the cap keeps its cache useful
            if len(self._block_cache) + len(tmp) <= self.BLOCK_CACHE_MAX:
                self._block_cache.update(tmp)
        parts = []
        for i in idxs:
            got = self._block_cache.get(int(i))
            parts.append(got if got is not None else tmp[int(i)])
        return parts[0] if len(parts) == 1 else np.concatenate(parts)

    # -- construction from KV versions --------------------------------------

    @classmethod
    def from_versions(
        cls,
        key: bytes,
        versions: List[Tuple[int, bytes]],
        kv=None,
        read_ts: Optional[int] = None,
    ) -> "PostingList":
        """versions: (ts, record) newest first (KV.versions contract).

        When the rollup layer is split (multi-part list), `kv`/`read_ts`
        are used to fetch the part records; without them a split list
        raises (callers with KV access — LocalCache, MemoryLayer, rollups —
        always pass them)."""
        deltas: List[Tuple[int, List[Posting]]] = []
        pack = None
        value_postings: List[Posting] = []
        min_ts = 0
        splits: List[int] = []
        for ts, rec in versions:
            kind, pk, posts, ss = decode_record(rec)
            if kind == KIND_DELTA:
                deltas.append((ts, posts))
            else:
                pack = pk
                value_postings = posts
                min_ts = ts
                splits = ss
                break
        deltas.reverse()  # ascending commit_ts
        pl = cls(
            key,
            pack=pack,
            value_postings=value_postings,
            deltas=deltas,
            min_ts=min_ts,
        )
        if splits:
            if kv is None:
                raise CorruptRecordError(
                    "split posting list needs KV access to read parts"
                )
            from dgraph_tpu.x import keys as _keys

            rts = read_ts if read_ts is not None else min_ts
            pl.split_starts = list(splits)
            for st in splits:
                got = kv.get(_keys.SplitKey(key, st), max(rts, min_ts))
                if got is None:
                    raise CorruptRecordError(
                        f"missing split part start={st} for key {key!r}"
                    )
                _, ppack, _, _ = decode_record(got[1])
                pl.part_packs.append(ppack)
        return pl

    # -- reads ---------------------------------------------------------------

    def adopt_uids(self, uids: np.ndarray) -> None:
        """Install an externally decoded uid set as the memoized
        materialization (level-batched reads decode N lists' packs into one
        flat buffer and hand each list back its slice). Only valid for a
        list whose packed view is exact — callers check has_uid_deltas()
        first; the adopted array must equal what uids() would compute.
        The slice keeps its level buffer alive; total retention matches
        per-list copies while the whole cohort stays cached (one commit
        drops them together via MemoryLayer invalidation)."""
        if self._uids_cache is None:
            self._uids_cache = uids

    def uids(self, extra_deltas: Optional[List[Posting]] = None) -> np.ndarray:
        """Materialized sorted u64 uid set (ref list.go:1758 Uids).

        The no-extra-deltas result is memoized: a PostingList is immutable
        once constructed, and MemoryLayer shares it across queries — without
        this, every traversal level re-decodes the pack."""
        if extra_deltas is None and self._uids_cache is not None:
            return self._uids_cache
        out = self._compute_uids(extra_deltas)
        if extra_deltas is None:
            self._uids_cache = out
        return out

    def _compute_uids(self, extra_deltas: Optional[List[Posting]]) -> np.ndarray:
        # one partial-decoder pass over the merged block view — multi-part
        # lists no longer decode every part pack through its own per-pack
        # call, and packed-path readers that never call uids() decode
        # nothing at all here
        base = uidpack.decode(self.merged_pack())
        # last-writer-wins per uid across layers in commit order
        final_op: Dict[int, int] = {}
        for _, posts in self.deltas:
            for p in posts:
                if not p.is_value:
                    final_op[p.uid] = p.op
        for p in extra_deltas or []:
            if not p.is_value:
                final_op[p.uid] = p.op
        if not final_op:
            return base
        adds = [u for u, op in final_op.items() if op == OP_SET]
        dels = [u for u, op in final_op.items() if op == OP_DEL]
        if dels:
            base = np.setdiff1d(
                base, np.array(dels, np.uint64), assume_unique=False
            )
        if adds:
            base = np.union1d(base, np.array(adds, np.uint64))
        return base.astype(np.uint64)

    def _merged_postings(
        self, extra_deltas: Optional[List[Posting]] = None
    ) -> Dict[int, Posting]:
        """uid -> winning posting (last writer wins by layer order)."""
        merged: Dict[int, Posting] = {p.uid: p for p in self.value_postings}
        for _, posts in self.deltas:
            for p in posts:
                merged[p.uid] = p
        for p in extra_deltas or []:
            merged[p.uid] = p
        return merged

    def get_value(
        self, lang: str = "", extra_deltas=None
    ) -> Optional[Val]:
        """Scalar value read (ref list.go Value/ValueForTag)."""
        merged = self._merged_postings(extra_deltas)
        p = merged.get(lang_uid(lang))
        if p is not None and p.op != OP_DEL and p.is_value:
            return p.val()
        if not lang:
            # fall back to any language (ref list.go:1990 ValueWithLockHeld)
            for uid in sorted(merged):
                p = merged[uid]
                if p.op != OP_DEL and p.is_value:
                    return p.val()
        return None

    def get_all_values(self, extra_deltas=None) -> List[Posting]:
        """All live value postings (list predicates / lang variants),
        posting-uid ascending — with farm-fingerprint uids this reproduces
        the reference's list-value JSON ordering exactly (posting lists
        iterate uid order, ref list.go Iterate)."""
        merged = self._merged_postings(extra_deltas)
        return [
            merged[uid]
            for uid in sorted(merged)
            if merged[uid].op != OP_DEL and merged[uid].is_value
        ]

    def is_empty(self, extra_deltas=None) -> bool:
        return (
            len(self.uids(extra_deltas)) == 0
            and not self.get_all_values(extra_deltas)
        )

    # -- rollup --------------------------------------------------------------

    def rollup(self) -> Tuple[bytes, int, List[Tuple[int, bytes]]]:
        """Compact all layers into a fresh rollup record.

        Returns (main_record_bytes, ts, parts) where parts is
        [(start_uid, part_record_bytes)] — non-empty when the uid set
        exceeds MAX_PART_UIDS and the list splits (ref posting/list.go:1416
        Rollup + :1590 splitUpList re-split; part keys via keys.SplitKey).
        Uid-edge postings that carry facets are kept alongside the pack
        (the pack stores only the uid set; facets live on the posting).
        """
        uids = self.uids()
        posts = self.get_all_values()
        live = set(int(u) for u in uids)
        merged = self._merged_postings()
        for uid in sorted(merged):
            p = merged[uid]
            if not p.is_value and p.op != OP_DEL and p.facets and uid in live:
                posts.append(p)
        ts = max(
            [self.min_ts] + [t for t, _ in self.deltas]
        )
        if len(uids) <= MAX_PART_UIDS:
            return encode_rollup(uidpack.encode(uids), posts), ts, []
        # split: half-threshold parts so in-place growth has headroom
        # before the next re-split (mirrors the reference's size targets)
        per = max(1, MAX_PART_UIDS // 2)
        parts: List[Tuple[int, bytes]] = []
        starts: List[int] = []
        for i in range(0, len(uids), per):
            chunk = uids[i : i + per]
            starts.append(int(chunk[0]))
            parts.append(
                (int(chunk[0]), encode_rollup(uidpack.encode(chunk), []))
            )
        empty = uidpack.encode(np.zeros((0,), np.uint64))
        return encode_rollup(empty, posts, split_starts=starts), ts, parts

from dgraph_tpu.posting.pl import Posting, PostingList, OP_SET, OP_DEL, VALUE_UID
from dgraph_tpu.posting.lists import LocalCache, Txn

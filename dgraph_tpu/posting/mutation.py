"""Edge application with index maintenance: the mutation write path.

Mirrors /root/reference/posting/index.go: AddMutationWithIndex (:585) —
apply a DirectedEdge to the data key, and maintain the index keys
(addIndexMutations :84), reverse edges (:276), and count index (:431)
according to the predicate's schema.

An edge is (entity uid, attr, value_id target | typed value, lang, facets,
op). Value changes first delete the old value's index tokens, then insert
the new ones (ref index.go:497 addMutationHelper's current-value read).
"""

from __future__ import annotations

import struct
from typing import List, Optional

from dgraph_tpu.posting import colwrite
from dgraph_tpu.posting.lists import LocalCache, Txn
from dgraph_tpu.posting.pl import (
    OP_DEL,
    OP_SET,
    VALUE_UID,
    Posting,
    lang_uid,
    value_uid,
)
from dgraph_tpu.schema.schema import SchemaUpdate, State
from dgraph_tpu.tok.tok import build_tokens
from dgraph_tpu.types.types import TypeID, Val, convert, to_binary
from dgraph_tpu.utils import observe
from dgraph_tpu.x import keys


class DirectedEdge:
    """Ref protos/pb.proto DirectedEdge."""

    __slots__ = (
        "entity",
        "attr",
        "value",
        "value_type",
        "value_id",
        "lang",
        "facets",
        "op",
        "ns",
        "fresh",
    )

    def __init__(
        self,
        entity: int,
        attr: str,
        value: Optional[Val] = None,
        value_id: Optional[int] = None,
        lang: str = "",
        facets=None,
        op: int = OP_SET,
        ns: int = keys.GALAXY_NS,
        fresh: bool = False,
    ):
        self.entity = entity
        self.attr = attr
        self.value = value
        self.value_id = value_id
        self.lang = lang
        self.facets = facets or {}
        self.op = op
        self.ns = ns
        # `fresh` marks a subject whose uid was leased by THIS request
        # (a blank node): its (entity, pred) keys cannot hold committed
        # values, so the batched apply path skips the deindex read —
        # byte-identical outcome, the serial path just reads emptiness
        self.fresh = fresh


def _facet_bytes(facets) -> tuple[dict, dict]:
    fb, ft = {}, {}
    for k, v in (facets or {}).items():
        if not isinstance(v, Val):
            raise TypeError("facets must be types.Val")
        fb[k] = to_binary(v)
        ft[k] = v.tid
    return fb, ft


def apply_edge(
    txn: Txn, st: State, edge: DirectedEdge, update_schema: bool = True
) -> None:
    """Apply one edge to the txn's local cache with index maintenance."""
    if getattr(txn, "col", None) is not None:
        # direct per-edge entry on a columnar txn: pending columns must
        # land first (same-key ordering), and the txn goes serial
        if txn.col.pending:
            colwrite.count_fallback("direct", 1)
        colwrite.materialize(txn)
    su = st.get(edge.attr)
    if su is None:
        if not update_schema:
            raise ValueError(f"no schema for predicate {edge.attr!r}")
        tid = (
            TypeID.UID
            if edge.value_id is not None
            else (edge.value.tid if edge.value else TypeID.DEFAULT)
        )
        su = st.ensure_default(edge.attr, tid)

    # per-tablet traffic accounting (the rebalancer's mutation signal);
    # fast-path edges in apply_edges are counted there instead
    if observe.tablet_traffic_enabled():
        observe.TABLETS.note_write(edge.ns, edge.attr, 1)

    data_key = keys.DataKey(edge.attr, edge.entity, edge.ns)
    cache = txn.cache

    if su.is_uid or edge.value_id is not None:
        _apply_uid_edge(txn, su, edge, data_key)
    else:
        _apply_value_edge(txn, su, edge, data_key)

    if su.count:
        _update_count_index(txn, su, edge, data_key)


def ingest_vectors(vector_indexes, deltas) -> None:
    """Vector-index ingestion at commit (factory seam, ref
    tok/index/index.go boundary): ONE implementation for every engine's
    post-commit hook. No-op without vector predicates — the per-key
    parse is measurable on the write path."""
    if not vector_indexes:
        return
    for key, posts in deltas.items():
        pk = keys.parse_key(key)
        vidx = vector_indexes.get(pk.attr)
        if vidx is not None and pk.is_data:
            for p in posts:
                if p.is_value and p.op == OP_SET:
                    vidx.insert(pk.uid, p.val().value)
                elif p.op == OP_DEL:
                    vidx.remove(pk.uid)


def apply_edges(
    txn: Txn, st: State, edges: List[DirectedEdge],
    update_schema: bool = True,
) -> None:
    """Batched edge application. On a columnar txn (colwrite.maybe_enable)
    the whole call is first offered to the columnar collector — edges
    land as arrays for the commit-time native batch_apply kernel instead
    of Posting objects. Any ineligible edge falls the call back: the
    collected columns replay through the serial path (byte-identical),
    then this call runs through the Python path — serial, or partitioned
    by predicate across the exec-worker pool when wide enough
    (_apply_edges_sharded; posting lists of distinct predicates live
    under distinct keys, so shards commute)."""
    if not edges:
        return
    col = getattr(txn, "col", None)
    if col is not None:
        reason = col.try_collect(txn, st, edges, update_schema)
        if reason is None:
            return
        colwrite.count_fallback(reason, len(edges))
        colwrite.materialize(txn)
    _apply_edges_fallback(txn, st, edges, update_schema)


def _apply_edges_fallback(
    txn: Txn, st: State, edges: List[DirectedEdge],
    update_schema: bool = True,
) -> None:
    """Python application of a batch the columnar path declined:
    predicate-sharded across the exec pool when the batch is wide
    enough, else the serial bulk path."""
    shards = _shard_plan(edges)
    if shards is None:
        _apply_edges_serial(txn, st, edges, update_schema)
    else:
        _apply_edges_sharded(txn, st, edges, shards, update_schema)


def shard_assign(n_groups: int, nshards: int) -> List[int]:
    """The (ns, attr)-disjoint shard rule, shared between the
    thread-sharded residual apply below and the apply-shard worker
    processes (worker/applyshard.py): group i — in first-appearance
    order — lands on shard i % nshards. One definition, so the two
    planes can never partition the same batch differently."""
    return [i % nshards for i in range(n_groups)]


def _shard_plan(edges) -> Optional[List[List[DirectedEdge]]]:
    """Partition a batch by predicate into shard worklists, or None to
    run serially. APPLY_SHARDS forces a width (tests/chaos); otherwise
    sharding engages only past APPLY_SHARD_MIN_EDGES with EXEC_WORKERS
    threads configured. Per-(ns, attr) edge order is preserved inside a
    shard; shards touch disjoint predicates, hence disjoint keys
    (data/index/reverse/count keys all embed the attr)."""
    from dgraph_tpu.x import config

    forced = int(config.get("APPLY_SHARDS"))
    if forced == 1 or len(edges) < 2:
        return None
    workers = forced if forced > 0 else int(config.get("EXEC_WORKERS"))
    if workers < 2:
        return None
    if forced <= 0 and len(edges) < int(
        config.get("APPLY_SHARD_MIN_EDGES")
    ):
        return None
    by_attr: dict = {}
    for e in edges:
        by_attr.setdefault((e.ns, e.attr), []).append(e)
    if len(by_attr) < 2:
        return None
    nshards = min(workers, len(by_attr))
    shards: List[List[DirectedEdge]] = [[] for _ in range(nshards)]
    assign = shard_assign(len(by_attr), nshards)
    for i, group in enumerate(by_attr.values()):
        shards[assign[i]].extend(group)
    return shards


class _OverlayDeltas:
    """Shard-local delta map layered over the txn's base deltas: reads
    see base + local (earlier serial calls in this txn may have touched
    the same predicate), writes go local only — the merge barrier moves
    them into the base in shard-index order."""

    __slots__ = ("base", "local")

    def __init__(self, base):
        self.base = base
        self.local: dict = {}

    def get(self, key, default=None):
        b = self.base.get(key)
        l = self.local.get(key)
        if b and l:
            return b + l
        return l or b or default

    def setdefault(self, key, default):
        # add_delta's accessor: appends must stay shard-local
        loc = self.local.get(key)
        if loc is None:
            loc = self.local[key] = []
        return loc

    def __contains__(self, key):
        return key in self.local or key in self.base


class _ShardCache(LocalCache):
    """LocalCache view for one apply shard: shares the txn's kv /
    read_ts / memlayer (thread-safe), private posting-list memo and
    delta overlay."""

    def __init__(self, base: LocalCache):
        self.kv = base.kv
        self.read_ts = base.read_ts
        self.mem = base.mem
        self._plists = {}
        self.deltas = _OverlayDeltas(base.deltas)


class _ShardTxn:
    """Txn facade a shard worker writes through: buffers conflict-key
    calls for deterministic replay at the merge barrier."""

    __slots__ = ("cache", "start_ts", "cks")

    def __init__(self, base: Txn, cache: _ShardCache):
        self.cache = cache
        self.start_ts = base.start_ts
        self.cks: List[tuple] = []

    def add_conflict_key(self, key: bytes, extra: bytes = b""):
        self.cks.append((key, extra))


def _apply_edges_sharded(
    txn: Txn, st: State, edges, shards, update_schema: bool
) -> None:
    """Run the shard worklists through _apply_edges_serial on private
    cache overlays — shard 0 on this thread, the rest on the exec pool
    — then merge deterministically in shard-index order (append-order
    inside a key is all the layered store observes, and shards never
    share keys). Any shard error discards every overlay and replays the
    ORIGINAL batch serially on the main txn, reproducing the serial
    path's partial-application-then-raise semantics exactly (per-tablet
    traffic gets counted twice on that path — an accounting smudge, not
    a correctness issue)."""
    from dgraph_tpu.query.subgraph import _expand_pool, _submit_bounded

    nshards = len(shards)
    caches = [_ShardCache(txn.cache) for _ in range(nshards)]
    stxns = [_ShardTxn(txn, c) for c in caches]
    pool = _expand_pool(nshards)
    futs = []
    for k in range(1, nshards):
        futs.append(
            (
                k,
                _submit_bounded(
                    pool, nshards, _apply_edges_serial,
                    stxns[k], st, shards[k], update_schema,
                ),
            )
        )
    err = None
    try:
        _apply_edges_serial(stxns[0], st, shards[0], update_schema)
    except Exception as ex:
        err = ex  # still join the pool shards before acting
    for k, f in futs:
        try:
            if f is None:  # pool at its backpressure bound: run inline
                _apply_edges_serial(stxns[k], st, shards[k], update_schema)
            else:
                f.result()
        except Exception as ex:
            if err is None:
                err = ex
    if err is not None:
        _apply_edges_serial(txn, st, edges, update_schema)
        return
    base = txn.cache.deltas
    for k in range(nshards):
        for key, posts in caches[k].deltas.local.items():
            base.setdefault(key, []).extend(posts)
        for key, extra in stxns[k].cks:
            txn.add_conflict_key(key, extra)
    observe.METRICS.inc("mutation_sharded_apply_total")


def _apply_edges_serial(
    txn: Txn, st: State, edges: List[DirectedEdge],
    update_schema: bool = True,
) -> None:
    """Batched edge application: semantically identical to calling
    apply_edge per edge in order, but the common live-ingest shape —
    scalar value SET with no lang/facets on a non-list, non-count
    predicate, writing a (entity, pred) key no other edge in the batch
    touches — runs through bulk machinery:

      - ONE values_many pass reads every such key's current postings
        (the deindex check) instead of a KV read per edge;
      - term tokens for ASCII strings come from ONE native call
        (codec.cpp tok_terms_ascii), exact/int/bool tokens from direct
        formatters — build_tokens only runs for the long tail;
      - tokenizer objects are the schema entry's cached list.

    Reordering is safe exactly because fast-path keys are
    batch-exclusive: edges sharing a data key (and every rich shape)
    fall back to apply_edge in their original relative order, index
    postings for one uid always come from that uid's own (excluded)
    data-key edges, and per-key delta order is all the layered store
    observes. Keys holding live prior values also fall back (the
    deindex-old-tokens path)."""
    if len(edges) < 2:
        for e in edges:
            apply_edge(txn, st, e, update_schema)
        return
    # classes: 0 slow (apply_edge in order), 1 fast scalar value,
    # 2 fast list-uid SET (append-only postings, order-free)
    infos = []
    key_owners: dict = {}
    key_mixed: dict = {}  # dk -> a non-class-2 edge touches it
    st_get = st.get
    for e in edges:
        su = st_get(e.attr)
        if su is None:
            if not update_schema:
                raise ValueError(f"no schema for predicate {e.attr!r}")
            tid = (
                TypeID.UID
                if e.value_id is not None
                else (e.value.tid if e.value else TypeID.DEFAULT)
            )
            su = st.ensure_default(e.attr, tid)
        dk = keys.DataKey(e.attr, e.entity, e.ns)
        if (
            e.value_id is None
            and not su.is_uid
            and e.value is not None
            and e.op == OP_SET
            and not e.facets
            and not e.lang
            and not su.is_list
            and not su.count
        ):
            cls = 1
        elif (
            e.value_id is not None
            and su.is_list
            and e.op == OP_SET
            and not e.facets
            and not su.count
        ):
            # list-uid SET postings append commutatively (two SETs on
            # one key land as independent final_op entries), so these
            # may even share a data key with each other — just not
            # with any slower-class edge
            cls = 2
        else:
            cls = 0
        key_owners[dk] = key_owners.get(dk, 0) + 1
        if cls != 2:
            key_mixed[dk] = True
        infos.append((e, su, dk, cls))
    fast = [
        i
        for i, (_e, _su, dk, cls) in enumerate(infos)
        if cls == 1 and key_owners[dk] == 1
    ]
    stored: dict = {}
    if fast:
        # the deindex check (does the key hold live prior values?) is
        # only needed where deindexing could happen at all — preds WITH
        # tokenizers (serial apply_edge reads under the same guard) —
        # and never for a `fresh` subject with no txn-local delta
        # (a uid leased this request has no committed values to read)
        need_read = [
            i
            for i in fast
            if infos[i][1].tokenizers
            and not (
                infos[i][0].fresh
                and infos[i][2] not in txn.cache.deltas
            )
        ]
        old_by_idx: dict = {}
        if need_read:
            oldvals = txn.cache.values_many(
                [infos[i][2] for i in need_read]
            )
            old_by_idx = dict(zip(need_read, oldvals))
        kept = []
        for i in fast:
            if old_by_idx.get(i):
                continue  # live prior values: deindex path, per-edge
            e, su, _dk, _cls = infos[i]
            try:
                stored[i] = (
                    convert(e.value, su.value_type)
                    if su.value_type != TypeID.DEFAULT
                    else e.value
                )
            except Exception:
                continue  # conversion error: re-raised by apply_edge
            kept.append(i)
        fast = kept
    tokens = _bulk_tokens(infos, fast, stored)
    fastset = set(fast)
    add_delta = txn.cache.add_delta
    add_ck = txn.add_conflict_key
    # fast-path edges never reach apply_edge (which counts itself):
    # aggregate their per-tablet traffic here, one note per predicate
    traffic = observe.tablet_traffic_enabled()
    wcounts: dict = {}
    for i, (e, su, dk, cls) in enumerate(infos):
        if i in fastset:
            if traffic:
                wcounts[(e.ns, e.attr)] = (
                    wcounts.get((e.ns, e.attr), 0) + 1
                )
            sv = stored[i]
            add_delta(
                dk,
                Posting(
                    uid=VALUE_UID,
                    op=OP_SET,
                    value=to_binary(sv),
                    value_type=sv.tid,
                ),
            )
            add_ck(dk if su.upsert else dk + b"#v")
            for tokb in tokens.get(i, ()):
                ikey = keys.IndexKey(e.attr, tokb, e.ns)
                add_delta(ikey, Posting(uid=e.entity, op=OP_SET))
                if su.upsert:
                    add_ck(ikey)
        elif cls == 2 and dk not in key_mixed:
            if traffic:
                wcounts[(e.ns, e.attr)] = (
                    wcounts.get((e.ns, e.attr), 0) + 1
                )
            # fast list-uid SET: no reads, append-only postings — the
            # same deltas _apply_uid_edge produces for this shape
            add_delta(dk, Posting(uid=e.value_id, op=OP_SET))
            add_ck(
                dk if su.upsert else dk + b"#u",
                str(e.value_id).encode(),
            )
            if su.directive_reverse:
                rk = keys.ReverseKey(e.attr, e.value_id, e.ns)
                add_delta(rk, Posting(uid=e.entity, op=OP_SET))
                add_ck(rk, str(e.entity).encode())
        else:
            apply_edge(txn, st, e, update_schema)
    for (ns, attr), n in wcounts.items():
        observe.TABLETS.note_write(ns, attr, n)


def _bulk_tokens(infos, fast, stored) -> dict:
    """edge index -> index token list for the fast-path edges: native
    bulk term tokenization for ASCII strings, direct formatters for
    exact/int/bool, build_tokens for anything else."""
    from dgraph_tpu import native
    from dgraph_tpu.tok.tok import (
        BoolTokenizer,
        ExactTokenizer,
        IntTokenizer,
        TermTokenizer,
    )

    tokens: dict = {i: [] for i in fast}
    term_idx: List[int] = []
    term_vals: List[bytes] = []
    term_ident = 0
    for i in fast:
        _e, su, _dk, _el = infos[i]
        sv = stored[i]
        for t in su.tokenizer_objs():
            if isinstance(t, TermTokenizer) and sv.tid == TypeID.STRING:
                s = str(sv.value)
                if s.isascii() and native.NATIVE_AVAILABLE:
                    term_idx.append(i)
                    term_vals.append(s.encode("utf-8"))
                    term_ident = t.identifier
                    continue
            elif isinstance(t, ExactTokenizer) and sv.tid == TypeID.STRING:
                tokens[i].append(
                    t.prefix() + str(sv.value).encode("utf-8")
                )
                continue
            elif isinstance(t, IntTokenizer) and sv.tid == TypeID.INT:
                tokens[i].append(
                    t.prefix()
                    + struct.pack(
                        ">Q", (int(sv.value) + (1 << 63)) & ((1 << 64) - 1)
                    )
                )
                continue
            elif isinstance(t, BoolTokenizer) and sv.tid == TypeID.BOOL:
                tokens[i].append(
                    t.prefix() + (b"\x01" if sv.value else b"\x00")
                )
                continue
            tokens[i].extend(build_tokens(sv, [t]))
    if term_idx:
        got = native.tok_terms_ascii(term_vals, term_ident)
        if got is None:
            for i, vb in zip(term_idx, term_vals):
                tokens[i].extend(
                    build_tokens(stored[i], [TermTokenizer()])
                )
        else:
            for i, toks in zip(term_idx, got):
                tokens[i].extend(toks)
    return tokens


def _apply_uid_edge(txn: Txn, su: SchemaUpdate, edge: DirectedEdge, data_key):
    if edge.value_id is None:
        raise ValueError(f"predicate {edge.attr!r} expects a uid edge")
    if not su.is_list and edge.op == OP_SET:
        # single-valued uid predicate: a set REPLACES the target (ref
        # worker/mutation.go — non-list uid preds hold one value; the
        # GraphQL rewriter relies on this when re-pointing references)
        for old in txn.cache.uids(data_key):
            if int(old) != edge.value_id:
                txn.cache.add_delta(data_key, Posting(uid=int(old), op=OP_DEL))
                if su.directive_reverse:
                    rk = keys.ReverseKey(edge.attr, int(old), edge.ns)
                    txn.cache.add_delta(
                        rk, Posting(uid=edge.entity, op=OP_DEL)
                    )
    p = Posting(uid=edge.value_id, op=edge.op)
    fb, ft = _facet_bytes(edge.facets)
    p.facets, p.facet_types = fb, ft
    txn.cache.add_delta(data_key, p)
    txn.add_conflict_key(data_key if su.upsert else data_key + b"#u",
                         str(edge.value_id).encode())

    if su.directive_reverse:
        rkey = keys.ReverseKey(edge.attr, edge.value_id, edge.ns)
        rp = Posting(uid=edge.entity, op=edge.op)
        rp.facets, rp.facet_types = fb, ft
        txn.cache.add_delta(rkey, rp)
        txn.add_conflict_key(rkey, str(edge.entity).encode())


def _apply_value_edge(txn: Txn, su: SchemaUpdate, edge: DirectedEdge, data_key):
    if edge.value is None:
        raise ValueError(f"predicate {edge.attr!r}: missing value")
    # convert to the schema's storage type (ref mutation.go ValidateAndConvert)
    stored = (
        convert(edge.value, su.value_type)
        if su.value_type != TypeID.DEFAULT
        else edge.value
    )
    vbytes = to_binary(stored)

    if su.is_list:
        puid = value_uid(stored)
    else:
        puid = lang_uid(edge.lang if su.lang else "")

    tokenizers = su.tokenizer_objs()

    # deindex old value(s) being overwritten
    if tokenizers:
        if su.is_list:
            old_posts = (
                [p for p in txn.cache.values(data_key) if p.uid == puid]
                if edge.op == OP_DEL
                else []
            )
        else:
            old_posts = [
                p
                for p in txn.cache.values(data_key)
                if p.uid == puid
            ]
        for old in old_posts:
            for tokb in build_tokens(old.val(), tokenizers, lang=old.lang):
                ikey = keys.IndexKey(edge.attr, tokb, edge.ns)
                txn.cache.add_delta(
                    ikey, Posting(uid=edge.entity, op=OP_DEL)
                )
                txn.add_conflict_key(ikey)

    p = Posting(
        uid=puid,
        op=edge.op,
        value=vbytes,
        value_type=stored.tid,
        lang=edge.lang,
    )
    fb, ft = _facet_bytes(edge.facets)
    p.facets, p.facet_types = fb, ft
    txn.cache.add_delta(data_key, p)
    # value writes always conflict at (entity, pred) granularity; @upsert
    # additionally conflicts on index keys (ref posting/list.go:842)
    txn.add_conflict_key(data_key if su.upsert else data_key + b"#v")

    if tokenizers and edge.op == OP_SET:
        for tokb in build_tokens(stored, tokenizers, lang=edge.lang):
            ikey = keys.IndexKey(edge.attr, tokb, edge.ns)
            txn.cache.add_delta(ikey, Posting(uid=edge.entity, op=OP_SET))
            if su.upsert:
                txn.add_conflict_key(ikey)

    # vector index maintenance handled by models/ at commit (factory seam,
    # ref tok/index/index.go boundary); the engine registers vector preds.


def _update_count_index(txn: Txn, su: SchemaUpdate, edge: DirectedEdge, data_key):
    """Maintain @count index: move entity between count buckets
    (ref posting/index.go:431 updateCount)."""
    before = len(txn.cache.uids(data_key))
    # Note: this runs *after* add_delta, so 'before' includes the new edge;
    # recompute prior count from ops in this txn is simplified: we recount
    # from the cache (correct because deltas are applied in order).
    after = before
    prior = after - (1 if edge.op == OP_SET else -1)
    if prior >= 0:
        okey = keys.CountKey(edge.attr, prior, False, edge.ns)
        txn.cache.add_delta(okey, Posting(uid=edge.entity, op=OP_DEL))
    nkey = keys.CountKey(edge.attr, after, False, edge.ns)
    txn.cache.add_delta(nkey, Posting(uid=edge.entity, op=OP_SET))


def delete_entity_attr(txn: Txn, st: State, entity: int, attr: str, ns=keys.GALAXY_NS):
    """S P * deletion: drop all postings of (entity, attr)
    (ref posting/index.go deleteEntries path for star deletes)."""
    if getattr(txn, "col", None) is not None:
        # the star delete reads current postings: collected columns for
        # this (entity, attr) must be visible as deltas first
        from dgraph_tpu.posting import colwrite

        if txn.col.pending:
            colwrite.count_fallback("delete_star", 1)
        colwrite.materialize(txn)
    su = st.get(attr)
    data_key = keys.DataKey(attr, entity, ns)
    tokenizers = su.tokenizer_objs() if su else []
    for p in txn.cache.values(data_key):
        for tokb in build_tokens(p.val(), tokenizers, lang=p.lang):
            ikey = keys.IndexKey(attr, tokb, ns)
            txn.cache.add_delta(ikey, Posting(uid=entity, op=OP_DEL))
    for uid in txn.cache.uids(data_key):
        txn.cache.add_delta(data_key, Posting(uid=int(uid), op=OP_DEL))
        if su and su.directive_reverse:
            rkey = keys.ReverseKey(attr, int(uid), ns)
            txn.cache.add_delta(rkey, Posting(uid=entity, op=OP_DEL))
    for p in txn.cache.values(data_key):
        txn.cache.add_delta(
            data_key,
            Posting(uid=p.uid, op=OP_DEL, value=p.value, value_type=p.value_type),
        )
    txn.add_conflict_key(data_key)

"""Edge application with index maintenance: the mutation write path.

Mirrors /root/reference/posting/index.go: AddMutationWithIndex (:585) —
apply a DirectedEdge to the data key, and maintain the index keys
(addIndexMutations :84), reverse edges (:276), and count index (:431)
according to the predicate's schema.

An edge is (entity uid, attr, value_id target | typed value, lang, facets,
op). Value changes first delete the old value's index tokens, then insert
the new ones (ref index.go:497 addMutationHelper's current-value read).
"""

from __future__ import annotations

from typing import List, Optional

from dgraph_tpu.posting.lists import LocalCache, Txn
from dgraph_tpu.posting.pl import (
    OP_DEL,
    OP_SET,
    Posting,
    lang_uid,
    value_uid,
)
from dgraph_tpu.schema.schema import SchemaUpdate, State
from dgraph_tpu.tok.tok import build_tokens
from dgraph_tpu.types.types import TypeID, Val, convert, to_binary
from dgraph_tpu.x import keys


class DirectedEdge:
    """Ref protos/pb.proto DirectedEdge."""

    __slots__ = (
        "entity",
        "attr",
        "value",
        "value_type",
        "value_id",
        "lang",
        "facets",
        "op",
        "ns",
    )

    def __init__(
        self,
        entity: int,
        attr: str,
        value: Optional[Val] = None,
        value_id: Optional[int] = None,
        lang: str = "",
        facets=None,
        op: int = OP_SET,
        ns: int = keys.GALAXY_NS,
    ):
        self.entity = entity
        self.attr = attr
        self.value = value
        self.value_id = value_id
        self.lang = lang
        self.facets = facets or {}
        self.op = op
        self.ns = ns


def _facet_bytes(facets) -> tuple[dict, dict]:
    fb, ft = {}, {}
    for k, v in (facets or {}).items():
        if not isinstance(v, Val):
            raise TypeError("facets must be types.Val")
        fb[k] = to_binary(v)
        ft[k] = v.tid
    return fb, ft


def apply_edge(
    txn: Txn, st: State, edge: DirectedEdge, update_schema: bool = True
) -> None:
    """Apply one edge to the txn's local cache with index maintenance."""
    su = st.get(edge.attr)
    if su is None:
        if not update_schema:
            raise ValueError(f"no schema for predicate {edge.attr!r}")
        tid = (
            TypeID.UID
            if edge.value_id is not None
            else (edge.value.tid if edge.value else TypeID.DEFAULT)
        )
        su = st.ensure_default(edge.attr, tid)

    data_key = keys.DataKey(edge.attr, edge.entity, edge.ns)
    cache = txn.cache

    if su.is_uid or edge.value_id is not None:
        _apply_uid_edge(txn, su, edge, data_key)
    else:
        _apply_value_edge(txn, su, edge, data_key)

    if su.count:
        _update_count_index(txn, su, edge, data_key)


def _apply_uid_edge(txn: Txn, su: SchemaUpdate, edge: DirectedEdge, data_key):
    if edge.value_id is None:
        raise ValueError(f"predicate {edge.attr!r} expects a uid edge")
    if not su.is_list and edge.op == OP_SET:
        # single-valued uid predicate: a set REPLACES the target (ref
        # worker/mutation.go — non-list uid preds hold one value; the
        # GraphQL rewriter relies on this when re-pointing references)
        for old in txn.cache.uids(data_key):
            if int(old) != edge.value_id:
                txn.cache.add_delta(data_key, Posting(uid=int(old), op=OP_DEL))
                if su.directive_reverse:
                    rk = keys.ReverseKey(edge.attr, int(old), edge.ns)
                    txn.cache.add_delta(
                        rk, Posting(uid=edge.entity, op=OP_DEL)
                    )
    p = Posting(uid=edge.value_id, op=edge.op)
    fb, ft = _facet_bytes(edge.facets)
    p.facets, p.facet_types = fb, ft
    txn.cache.add_delta(data_key, p)
    txn.add_conflict_key(data_key if su.upsert else data_key + b"#u",
                         str(edge.value_id).encode())

    if su.directive_reverse:
        rkey = keys.ReverseKey(edge.attr, edge.value_id, edge.ns)
        rp = Posting(uid=edge.entity, op=edge.op)
        rp.facets, rp.facet_types = fb, ft
        txn.cache.add_delta(rkey, rp)
        txn.add_conflict_key(rkey, str(edge.entity).encode())


def _apply_value_edge(txn: Txn, su: SchemaUpdate, edge: DirectedEdge, data_key):
    if edge.value is None:
        raise ValueError(f"predicate {edge.attr!r}: missing value")
    # convert to the schema's storage type (ref mutation.go ValidateAndConvert)
    stored = (
        convert(edge.value, su.value_type)
        if su.value_type != TypeID.DEFAULT
        else edge.value
    )
    vbytes = to_binary(stored)

    if su.is_list:
        puid = value_uid(stored)
    else:
        puid = lang_uid(edge.lang if su.lang else "")

    tokenizers = su.tokenizer_objs()

    # deindex old value(s) being overwritten
    if tokenizers:
        if su.is_list:
            old_posts = (
                [p for p in txn.cache.values(data_key) if p.uid == puid]
                if edge.op == OP_DEL
                else []
            )
        else:
            old_posts = [
                p
                for p in txn.cache.values(data_key)
                if p.uid == puid
            ]
        for old in old_posts:
            for tokb in build_tokens(old.val(), tokenizers, lang=old.lang):
                ikey = keys.IndexKey(edge.attr, tokb, edge.ns)
                txn.cache.add_delta(
                    ikey, Posting(uid=edge.entity, op=OP_DEL)
                )
                txn.add_conflict_key(ikey)

    p = Posting(
        uid=puid,
        op=edge.op,
        value=vbytes,
        value_type=stored.tid,
        lang=edge.lang,
    )
    fb, ft = _facet_bytes(edge.facets)
    p.facets, p.facet_types = fb, ft
    txn.cache.add_delta(data_key, p)
    # value writes always conflict at (entity, pred) granularity; @upsert
    # additionally conflicts on index keys (ref posting/list.go:842)
    txn.add_conflict_key(data_key if su.upsert else data_key + b"#v")

    if tokenizers and edge.op == OP_SET:
        for tokb in build_tokens(stored, tokenizers, lang=edge.lang):
            ikey = keys.IndexKey(edge.attr, tokb, edge.ns)
            txn.cache.add_delta(ikey, Posting(uid=edge.entity, op=OP_SET))
            if su.upsert:
                txn.add_conflict_key(ikey)

    # vector index maintenance handled by models/ at commit (factory seam,
    # ref tok/index/index.go boundary); the engine registers vector preds.


def _update_count_index(txn: Txn, su: SchemaUpdate, edge: DirectedEdge, data_key):
    """Maintain @count index: move entity between count buckets
    (ref posting/index.go:431 updateCount)."""
    before = len(txn.cache.uids(data_key))
    # Note: this runs *after* add_delta, so 'before' includes the new edge;
    # recompute prior count from ops in this txn is simplified: we recount
    # from the cache (correct because deltas are applied in order).
    after = before
    prior = after - (1 if edge.op == OP_SET else -1)
    if prior >= 0:
        okey = keys.CountKey(edge.attr, prior, False, edge.ns)
        txn.cache.add_delta(okey, Posting(uid=edge.entity, op=OP_DEL))
    nkey = keys.CountKey(edge.attr, after, False, edge.ns)
    txn.cache.add_delta(nkey, Posting(uid=edge.entity, op=OP_SET))


def delete_entity_attr(txn: Txn, st: State, entity: int, attr: str, ns=keys.GALAXY_NS):
    """S P * deletion: drop all postings of (entity, attr)
    (ref posting/index.go deleteEntries path for star deletes)."""
    su = st.get(attr)
    data_key = keys.DataKey(attr, entity, ns)
    tokenizers = su.tokenizer_objs() if su else []
    for p in txn.cache.values(data_key):
        for tokb in build_tokens(p.val(), tokenizers, lang=p.lang):
            ikey = keys.IndexKey(attr, tokb, ns)
            txn.cache.add_delta(ikey, Posting(uid=entity, op=OP_DEL))
    for uid in txn.cache.uids(data_key):
        txn.cache.add_delta(data_key, Posting(uid=int(uid), op=OP_DEL))
        if su and su.directive_reverse:
            rkey = keys.ReverseKey(attr, int(uid), ns)
            txn.cache.add_delta(rkey, Posting(uid=entity, op=OP_DEL))
    for p in txn.cache.values(data_key):
        txn.cache.add_delta(
            data_key,
            Posting(uid=p.uid, op=OP_DEL, value=p.value, value_type=p.value_type),
        )
    txn.add_conflict_key(data_key)

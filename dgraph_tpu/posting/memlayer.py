"""MemoryLayer: shared read cache for decoded posting lists.

Mirrors /root/reference/posting/mvcc.go:387 MemoryLayer (ristretto-backed
cache keyed by key bytes): decoding a posting list (KV versions -> record
parse -> UidPack decode) is the host-side hot cost of every traversal
level. This cache keeps *decoded* PostingLists keyed by (key, newest
version ts) so repeated reads — including the same predicate reached from
different query roots — skip straight to the materialized form.

Invalidation mirrors the reference (mvcc.go:510 updates on commit): the
engine calls `invalidate(keys)` with every committed key. Entries also
self-validate by comparing the KV's newest version ts, so even a missed
invalidation only costs a re-decode, never staleness.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, Iterable, Optional, Tuple

from dgraph_tpu.posting.pl import PostingList


class MemoryLayer:
    def __init__(self, max_entries: Optional[int] = None):
        if max_entries is None:
            # must exceed the touched-key count of one large traversal
            # level or the LRU thrashes (a 5M-edge 2-hop touches ~140k
            # lists); decoded entries are small, ~300B typical
            from dgraph_tpu.x import config

            max_entries = int(config.get("MEMLAYER_ENTRIES"))
        self.max_entries = max_entries
        self._lock = threading.Lock()
        # key -> (newest_version_ts, PostingList); LRU by insertion order
        self._cache: "OrderedDict[bytes, Tuple[int, PostingList]]" = (
            OrderedDict()
        )
        self.hits = 0
        self.misses = 0

    @staticmethod
    def _fast_state(kv, read_ts: int):
        """(seq, complete) for the no-revalidation fast path. An entry is
        reusable WITHOUT a per-key probe by a reader at R2 iff:
          - the KV's global mutation counter hasn't moved since the entry
            was built (store content identical), AND
          - the entry was a COMPLETE view when built — its creation
            read_ts covered every version in the store
            (max_write_ts <= creation read_ts), AND
          - R2 >= the entry's creation read_ts.
        The completeness condition closes the race where a query holding
        an older read_ts caches a partial view after a newer commit."""
        fn = getattr(kv, "mut_seq", None)
        if fn is None:
            return None, False
        mx = getattr(kv, "max_write_ts", None)
        return fn(), (mx is not None and mx() <= read_ts)

    @staticmethod
    def _fast_hit(ent, seq, read_ts: int) -> bool:
        return (
            seq is not None
            and ent[2] == seq
            and ent[4]
            and read_ts >= ent[3]
        )

    def read(self, kv, key: bytes, read_ts: int) -> PostingList:
        """Read-through: returns a PostingList valid at read_ts.

        Cached entries are keyed by the newest version <= read_ts, so a
        reader at an older ts never sees future versions. The version list
        is fetched ONCE and the cache key derives from it — deriving it
        from a separate earlier kv.get would race concurrent commits and
        cache future versions under an old ts. Complete entries skip the
        probe while the store is unchanged (_fast_state)."""
        seq, complete = self._fast_state(kv, read_ts)
        with self._lock:
            got = self._cache.get(key)
            if got is not None and self._fast_hit(got, seq, read_ts):
                self._cache.move_to_end(key)
                self.hits += 1
                return got[1]
        versions = kv.versions(key, read_ts)
        newest_ts = versions[0][0] if versions else 0
        with self._lock:
            got = self._cache.get(key)
            if got is not None and got[0] == newest_ts:
                self._cache[key] = (newest_ts, got[1], seq, read_ts, complete)
                self._cache.move_to_end(key)
                self.hits += 1
                return got[1]
        self.misses += 1
        pl = PostingList.from_versions(key, versions, kv=kv, read_ts=read_ts)
        with self._lock:
            self._cache[key] = (newest_ts, pl, seq, read_ts, complete)
            self._cache.move_to_end(key)
            while len(self._cache) > self.max_entries:
                self._cache.popitem(last=False)
        return pl

    def read_many(self, kv, keys, read_ts: int) -> dict:
        """Batched read-through: one kv.versions_batch for every key (the
        LSM backend probes each table monotonically instead of per-key).
        Returns {key: PostingList}. Falls back to per-key read when the
        backend has no batch API."""
        keys = list(dict.fromkeys(keys))  # dedupe: decode each key once
        vb = getattr(kv, "versions_batch", None)
        if vb is None:
            return {k: self.read(kv, k, read_ts) for k in keys}
        seq, complete = self._fast_state(kv, read_ts)
        out = {}
        need = []
        with self._lock:
            for k in keys:
                ent = self._cache.get(k)
                if ent is not None and self._fast_hit(ent, seq, read_ts):
                    self._cache.move_to_end(k)
                    self.hits += 1
                    out[k] = ent[1]
                else:
                    need.append(k)
        if not need:
            return out
        got = vb(need, read_ts)
        to_store = []
        with self._lock:
            for k in need:
                versions = got.get(k, [])
                newest_ts = versions[0][0] if versions else 0
                ent = self._cache.get(k)
                if ent is not None and ent[0] == newest_ts:
                    self._cache[k] = (newest_ts, ent[1], seq, read_ts, complete)
                    self._cache.move_to_end(k)
                    self.hits += 1
                    out[k] = ent[1]
                else:
                    out[k] = None  # decode outside the lock
                    to_store.append((k, newest_ts, versions))
        # one decode loop outside the lock, then ONE lock acquisition to
        # publish the whole level's entries (level-batched fan-out: the
        # per-key lock round-trips dominated wide levels)
        decoded = []
        for k, newest_ts, versions in to_store:
            pl = PostingList.from_versions(
                k, versions, kv=kv, read_ts=read_ts
            )
            out[k] = pl
            decoded.append((k, newest_ts, pl))
        if decoded:
            with self._lock:
                self.misses += len(decoded)
                for k, newest_ts, pl in decoded:
                    self._cache[k] = (
                        newest_ts, pl, seq, read_ts, complete
                    )
                    self._cache.move_to_end(k)
                while len(self._cache) > self.max_entries:
                    self._cache.popitem(last=False)
        return out

    def invalidate(self, keys: Iterable[bytes]):
        keys = list(keys)
        with self._lock:
            for k in keys:
                self._cache.pop(k, None)
        # the device (HBM) operand cache mirrors this invalidation
        from dgraph_tpu.query.dispatch import DISPATCHER

        DISPATCHER.device_cache.invalidate(keys)

    def invalidate_prefix(self, prefixes: Iterable[bytes]):
        """Drop every cached entry whose key starts with any prefix —
        the tablet-move/drop-attr invalidation: only the moved
        predicate's data/split/index entries go; an unrelated
        predicate's decoded lists survive (the old movers cleared the
        whole layer)."""
        pfx = tuple(bytes(p) for p in prefixes)
        if not pfx:
            return
        with self._lock:
            hit = [k for k in self._cache if k.startswith(pfx)]
            for k in hit:
                del self._cache[k]
        from dgraph_tpu.query.dispatch import DISPATCHER

        DISPATCHER.device_cache.invalidate_prefix(pfx)

    def clear(self):
        with self._lock:
            self._cache.clear()

    def stats(self) -> dict:
        return {
            "entries": len(self._cache),
            "hits": self.hits,
            "misses": self.misses,
        }

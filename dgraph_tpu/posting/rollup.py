"""Incremental rollups: bound delta-chain length per key.

Mirrors /root/reference/posting/mvcc.go (incrRollupi:41, Process:158): keys
whose committed delta chains exceed a threshold are compacted into a fresh
rollup record and old versions dropped, keeping reads O(1)-ish in layers.
Runs on demand (rollup_all) or as a background thread (RollupDaemon — the
incremental rollup goroutine analog).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from dgraph_tpu.posting.pl import KIND_DELTA, PostingList, decode_record
from dgraph_tpu.x import keys


def rollup_key(kv, key: bytes, read_ts: int) -> bool:
    """Compact one key's layers; returns True if a rollup was written.

    Oversized lists split into part records (keys.SplitKey) and re-split on
    every rollup (ref posting/list.go:1590 splitUpList); parts dropped by a
    re-split are deleted."""
    versions = kv.versions(key, read_ts)
    n_deltas = 0
    for _, rec in versions:
        kind = rec[0]
        if kind == KIND_DELTA:
            n_deltas += 1
        else:
            break
    if n_deltas == 0:
        return False
    pl = PostingList.from_versions(key, versions, kv=kv, read_ts=read_ts)
    old_starts = set(pl.split_starts)
    rec, ts, parts = pl.rollup()
    new_starts = set()
    for start, prec in parts:
        pk = keys.SplitKey(key, start)
        kv.put(pk, ts, prec)
        kv.delete_below(pk, ts)
        new_starts.add(start)
    for start in old_starts - new_starts:
        kv.delete_below(keys.SplitKey(key, start), ts + 1)
    kv.put(key, ts, rec)
    kv.delete_below(key, ts)
    return True


def rollup_all(server, min_deltas: int = 2) -> int:
    """Compact every key whose delta chain is >= min_deltas. Returns the
    number of keys rolled up (ref Rollup stream in draft.go rollup op)."""
    ts = server.zero.read_ts()
    rolled = 0
    todo = []
    for key, vers in server.kv.iterate_versions(b"", ts):
        try:
            keys.parse_key(key)
        except Exception:
            continue  # non-graph meta keys (counters, checkpoints)
        n = 0
        for _, rec in vers:
            if rec[:1] and rec[0] == KIND_DELTA:
                n += 1
            else:
                break
        if n >= min_deltas:
            todo.append(key)
    for key in todo:
        if rollup_key(server.kv, key, ts):
            rolled += 1
    return rolled


class RollupDaemon:
    """Background incremental rollup (ref posting/mvcc.go:92 goroutine)."""

    def __init__(self, server, interval_s: float = 5.0, min_deltas: int = 4):
        self.server = server
        self.interval = interval_s
        self.min_deltas = min_deltas
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.rolled_total = 0

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval):
            try:
                # race-ok: single-writer stats counter — only this daemon
                # thread increments; readers see a GIL-atomic int
                self.rolled_total += rollup_all(self.server, self.min_deltas)
            except Exception:
                pass  # rollups are best-effort; next tick retries

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)

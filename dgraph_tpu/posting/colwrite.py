"""Columnar batch-apply: the native group-commit mutation write path.

The serial write path (posting/mutation.apply_edges) builds Posting
objects per edge into txn.cache.deltas and serializes them per key at
commit (posting/pl.encode_deltas). That leaves tokenization, key
construction and record grouping as per-edge Python work under the GIL
— PR 11's own profiling pinned the residual mutation cost there.

This module collects the dominant edge shapes — scalar-value SET on a
non-list predicate (exact/int/bool/term indexes) and list-uid SET
(incl. @reverse) — into columnar arrays *instead of* postings. At
commit, a group-commit leader flattens every batch member's columns
into ONE native call (codec.cpp batch_apply) that fuses tokenization,
index/reverse key emission and delta-record encoding, returning
ready-to-put (key, record) pairs for a single kv.put_batch. Records
are byte-identical to the serial path's encode_delta output.

Correctness rules (all enforced here, fuzz-verified byte-for-byte in
tests/test_batch_apply.py):

  - ALL-OR-NOTHING PER TXN: columnar columns and Python deltas never
    coexist. Any ineligible edge (delete, lang, facets, rich
    tokenizer, live prior value needing deindex, ...) first
    *materializes* the collected columns back through the serial
    apply path, then proceeds serially — so delete-before-set
    ordering and the one-record-per-(key, commit_ts) MVCC invariant
    (MemKV overwrites same-ts puts) both survive.
  - In-txn reads materialize first: the engines' query/upsert entry
    points call txn.materialize_cols() before executing, so
    read-your-writes semantics are unchanged.
  - Conflict keys are computed at collect time in Python (the oracle
    needs them before the kernel runs); @upsert predicates with index
    tokenizers fall back (their conflict set includes index keys only
    the kernel would know).
  - Engines only enable collection when no commit-time consumer needs
    Posting objects (CDC, subscriptions, vector indexes); the commit
    entry re-checks and materializes if one appeared mid-txn.
"""

from __future__ import annotations

from array import array
from typing import Dict, List, Tuple

from dgraph_tpu.posting.pl import OP_SET
from dgraph_tpu.types.types import TypeID, convert, to_binary
from dgraph_tpu.utils import observe
from dgraph_tpu.utils.observe import METRICS
from dgraph_tpu.x import config, keys

# predicate tokenization plan bits (mirrored in codec.cpp batch_apply)
PF_REVERSE = 1
PF_EXACT = 2
PF_INT = 4
PF_BOOL = 8
PF_TERM = 16
_PF_TOKS = PF_EXACT | PF_INT | PF_BOOL | PF_TERM


def count_fallback(reason: str, n_edges: int) -> None:
    """One escape from the columnar path: aggregate + per-reason
    counters (the kernel-coverage regression signal)."""
    METRICS.inc("mutation_native_fallback_total", n_edges)
    METRICS.inc(
        f'mutation_native_fallback_total{{reason="{reason}"}}', n_edges
    )


class _Pred:
    """Per-(ns, attr) columnar plan: key prefix + tokenizer flag bits +
    identifier bytes, resolved once per predicate per txn (revalidated
    when the schema entry object changes mid-txn)."""

    __slots__ = (
        "su", "attr", "ns", "pid", "prefix", "flags", "idents",
        "upsert", "scalar_ok", "scalar_reason", "uid_ok", "uid_reason",
        "est_scalar", "est_uid",
    )

    def __init__(self, su, attr: str, ns: int, pid: int):
        from dgraph_tpu.tok.tok import (
            BoolTokenizer,
            ExactTokenizer,
            IntTokenizer,
            TermTokenizer,
        )

        self.su = su
        self.attr = attr
        self.ns = ns
        self.pid = pid
        self.prefix = keys.PredicatePrefix(attr, ns)
        self.upsert = bool(su.upsert)
        flags = 0
        idents = bytearray(4)
        scalar_ok, scalar_reason = True, ""
        uid_ok, uid_reason = True, ""
        if su.count:
            scalar_ok, scalar_reason = False, "count"
            uid_ok, uid_reason = False, "count"
        if su.is_uid:
            # a typed-value edge on a uid predicate is an error shape;
            # the serial path raises it with the right message
            scalar_ok, scalar_reason = False, "shape"
            if not su.is_list:
                # single-valued uid SET replaces the target (a read)
                uid_ok, uid_reason = False, "uid_single"
            if su.directive_reverse:
                flags |= PF_REVERSE
        else:
            uid_ok, uid_reason = False, "shape"
            if su.is_list:
                scalar_ok, scalar_reason = False, "list"
            else:
                for t in su.tokenizer_objs():
                    if (
                        isinstance(t, ExactTokenizer)
                        and su.value_type == TypeID.STRING
                    ):
                        flags |= PF_EXACT
                        idents[0] = t.identifier
                    elif (
                        isinstance(t, IntTokenizer)
                        and su.value_type == TypeID.INT
                    ):
                        flags |= PF_INT
                        idents[1] = t.identifier
                    elif (
                        isinstance(t, BoolTokenizer)
                        and su.value_type == TypeID.BOOL
                    ):
                        flags |= PF_BOOL
                        idents[2] = t.identifier
                    elif (
                        isinstance(t, TermTokenizer)
                        and su.value_type == TypeID.STRING
                    ):
                        flags |= PF_TERM
                        idents[3] = t.identifier
                    else:
                        # fulltext/trigram/hash/... or a tokenizer-type
                        # mismatch: the long tail stays Python
                        scalar_ok, scalar_reason = False, "tok"
                        break
                if scalar_ok and self.upsert and (flags & _PF_TOKS):
                    # @upsert conflicts on index keys — which only the
                    # kernel would produce, too late for the oracle
                    scalar_ok, scalar_reason = False, "upsert_index"
        self.flags = flags
        self.idents = bytes(idents)
        self.scalar_ok, self.scalar_reason = scalar_ok, scalar_reason
        self.uid_ok, self.uid_reason = uid_ok, uid_reason
        ntok = bin(flags & (PF_EXACT | PF_INT | PF_BOOL)).count("1")
        self.est_scalar = 1 + ntok + (2 if flags & PF_TERM else 0)
        self.est_uid = 1 + (1 if flags & PF_REVERSE else 0)


class ColumnarWriteSet:
    """Per-txn columnar collection of fast-shape edges (in place of
    txn.cache.deltas postings). Collection is all-or-nothing per
    apply_edges call; the original edges are retained so any later
    ineligible operation can replay them byte-identically through the
    serial path (materialize)."""

    __slots__ = (
        "shapes", "entities", "pids", "objects", "vtypes", "voffs",
        "vblob",
        "_preds", "pred_list", "_scalar_seen", "_chunks", "nposts_est",
    )

    def __init__(self):
        # columns are the cheap typed buffers native.batch_apply takes
        # by raw address — C-typed appends at collect, zero conversion
        # at the kernel call (the per-commit fixed cost is the enemy)
        self.shapes = bytearray()  # 0 scalar-value SET, 1 list-uid SET
        self.entities = array("Q")
        self.pids = array("i")
        self.objects = array("Q")  # uid-shape target (else 0)
        self.vtypes = bytearray()  # stored TypeID (scalar), else 0
        self.voffs = array("q", (0,))  # CSR offsets into vblob
        self.vblob = bytearray()  # to_binary bytes (scalar shapes)
        self._preds: Dict[Tuple[int, str], _Pred] = {}
        self.pred_list: List[_Pred] = []
        # scalar (ns, attr, entity) keys already collected: a second
        # write to a tokenized key needs the deindex read path
        self._scalar_seen: set = set()
        self._chunks: List[tuple] = []  # (st, edges, update_schema)
        self.nposts_est = 0

    @property
    def pending(self) -> bool:
        return bool(self._chunks)

    def _pred_for(self, su, attr: str, ns: int) -> _Pred:
        ck = (ns, attr)
        p = self._preds.get(ck)
        if p is not None and p.su is su:
            return p
        # new predicate — or the schema entry was replaced mid-txn:
        # already-collected edges keep their old plan under the old pid
        p = _Pred(su, attr, ns, len(self.pred_list))
        self._preds[ck] = p
        self.pred_list.append(p)
        return p

    def try_collect(self, txn, st, edges, update_schema: bool):
        """Collect a whole apply_edges call, or explain why not.

        Returns None when every edge was collected (conflict keys
        added, columns appended); otherwise a fallback reason string
        and NO state was modified — the caller materializes and runs
        the serial path. Single staged pass: columns build in local
        typed buffers and land with bulk extends on success (this is
        per-edge GIL work on the commit fast path — every attribute
        lookup here is paid tens of thousands of times per second)."""
        if txn.cache.deltas:
            # sticky serial: Python deltas exist (a prior materialize
            # or slow-path call) — mixing would double-write keys at
            # one commit_ts (MemKV same-ts puts overwrite)
            return "mixed_txn"
        st_get = st.get
        preds_get = self._preds.get
        scalar_seen = self._scalar_seen
        data_key = keys.DataKey
        default_tid = TypeID.DEFAULT
        sh = bytearray()
        en = array("Q")
        pi = array("i")
        ob = array("Q")
        vt = bytearray()
        vb = bytearray()
        vo = array("q")
        vbase = len(self.vblob)
        cks: List[tuple] = []  # staged add_conflict_key arg tuples
        seen_add: List[tuple] = []  # staged _scalar_seen additions
        probe = []  # data keys pending the live-prior-values read
        call_scalar: set = set()
        nposts = 0
        for e in edges:
            if e.op != OP_SET:
                return "delete"
            if e.facets:
                return "facets"
            if e.lang:
                return "lang"
            attr = e.attr
            ns = e.ns
            su = st_get(attr)
            if su is None:
                if not update_schema:
                    return "schema"  # serial path raises the error
                tid = (
                    TypeID.UID
                    if e.value_id is not None
                    else (e.value.tid if e.value else default_tid)
                )
                su = st.ensure_default(attr, tid)
            pred = preds_get((ns, attr))
            if pred is None or pred.su is not su:
                pred = self._pred_for(su, attr, ns)
            entity = e.entity
            if e.value_id is not None:
                if not pred.uid_ok:
                    return pred.uid_reason
                obj = int(e.value_id)
                sh.append(1)
                en.append(entity)
                pi.append(pred.pid)
                ob.append(obj)
                vt.append(0)
                vo.append(vbase + len(vb))
                dk = data_key(attr, entity, ns)
                cks.append((
                    dk if pred.upsert else dk + b"#u",
                    str(obj).encode(),
                ))
                if pred.flags & PF_REVERSE:
                    cks.append((
                        keys.ReverseKey(attr, obj, ns),
                        str(entity).encode(),
                    ))
                nposts += pred.est_uid
                continue
            value = e.value
            if value is None:
                return "shape"  # serial path raises the error
            if not pred.scalar_ok:
                return pred.scalar_reason
            vt_id = su.value_type
            try:
                stored = (
                    convert(value, vt_id)
                    if vt_id != default_tid
                    else value
                )
                vbytes = to_binary(stored)
            except Exception:
                return "convert"  # serial path raises the error
            flags = pred.flags
            if flags & PF_TERM and not str(stored.value).isascii():
                return "ascii"  # unicode terms: Python tokenizer
            skey = (ns, attr, entity)
            if skey in call_scalar:
                # serial demotes shared-key edges to the per-edge loop
                return "shared_key"
            call_scalar.add(skey)
            dk = data_key(attr, entity, ns)
            if flags & _PF_TOKS:
                if skey in scalar_seen:
                    # overwriting an earlier columnar write needs the
                    # deindex-old-tokens path
                    return "deindex"
                if not e.fresh:
                    probe.append(dk)
            sh.append(0)
            en.append(entity)
            pi.append(pred.pid)
            ob.append(0)
            vt.append(int(stored.tid))
            vb += vbytes
            vo.append(vbase + len(vb))
            cks.append((dk if pred.upsert else dk + b"#v",))
            seen_add.append(skey)
            nposts += pred.est_scalar
        if probe:
            # the deindex check: keys holding live prior values must
            # delete old index tokens first (serial-path territory)
            oldvals = txn.cache.values_many(probe)
            if any(oldvals):
                return "deindex"
        # every edge is eligible — commit the call atomically
        add_ck = txn.add_conflict_key
        for args in cks:
            add_ck(*args)
        self.shapes += sh
        self.entities += en
        self.pids += pi
        self.objects += ob
        self.vtypes += vt
        self.vblob += vb
        self.voffs += vo
        scalar_seen.update(seen_add)
        self.nposts_est += nposts
        self._chunks.append((st, list(edges), update_schema))
        return None

    def take_chunks(self) -> List[tuple]:
        """Drain for materialize: returns the collected (st, edges,
        update_schema) calls and resets every column."""
        chunks = self._chunks
        self._chunks = []
        self.shapes = bytearray()
        self.entities = array("Q")
        self.pids = array("i")
        self.objects = array("Q")
        self.vtypes = bytearray()
        self.voffs = array("q", (0,))
        self.vblob = bytearray()
        self._scalar_seen = set()
        self.nposts_est = 0
        # pred plans stay cached: pids are only meaningful to columns
        return chunks

    def fence_keys(self) -> List[bytes]:
        """One representative data key per collected predicate — what
        the tablet-move fence check parses attrs from (the columns
        carry no concrete keys until the kernel runs)."""
        return [
            keys.DataKey(p.attr, 0, p.ns)
            for p in self.pred_list
        ]

    def note_traffic(self) -> None:
        """Per-tablet mutation accounting at encode time (the serial
        path counts per edge at apply time)."""
        if not observe.tablet_traffic_enabled():
            return
        counts: Dict[int, int] = {}
        for pid in self.pids:
            counts[pid] = counts.get(pid, 0) + 1
        for pid, n in counts.items():
            p = self.pred_list[pid]
            observe.TABLETS.note_write(p.ns, p.attr, n)


# ---------------------------------------------------------------------------
# Engine integration
# ---------------------------------------------------------------------------


def columnar_ok(engine) -> bool:
    """May this engine's commits consume columnar write sets right now?
    Checked at txn creation AND again at commit (a CDC sink or vector
    index registered mid-txn forces a materialize): every commit-time
    consumer of Posting objects must be absent."""
    from dgraph_tpu import native

    if not native.NATIVE_AVAILABLE or not bool(config.get("BATCH_APPLY")):
        return False
    if getattr(engine, "_cdc", None) is not None:
        return False
    if getattr(engine, "_subscriptions", None) is not None:
        return False
    if getattr(engine, "vector_indexes", None):
        return False
    return True


def maybe_enable(txn, engine) -> None:
    """Attach a columnar write set to a fresh engine txn when the
    batch-apply path is available."""
    if columnar_ok(engine):
        txn.col = ColumnarWriteSet()


def commit_guard(txn, engine) -> None:
    """Commit-entry check: if a consumer that needs Posting objects
    appeared after the txn was created (CDC, subscriptions, vector
    index), fall back to the serial representation now."""
    col = getattr(txn, "col", None)
    if col is not None and col.pending and not columnar_ok(engine):
        count_fallback("engine", len(col.shapes))
        materialize(txn)


def materialize(txn) -> None:
    """Replay collected calls through the serial apply path into
    txn.cache.deltas (byte-identical outcome), disabling further
    collection for this txn (sticky: deltas are now non-empty)."""
    col = txn.col
    if col is None:
        return
    txn.col = None  # replay must not re-collect
    if not col.pending:
        return
    from dgraph_tpu.posting.mutation import _apply_edges_fallback

    chunks = col.take_chunks()
    for st, edges, update_schema in chunks:
        _apply_edges_fallback(txn, st, edges, update_schema)


# ---------------------------------------------------------------------------
# Commit-time encode (the kernel call)
# ---------------------------------------------------------------------------


def _pred_blobs(pred_tab: List[_Pred]):
    """(pp_blob, pp_offs, pflags, pidents) for a pred table."""
    pp_offs = array("q", (0,))
    parts = []
    pos = 0
    for p in pred_tab:
        parts.append(p.prefix)
        pos += len(p.prefix)
        pp_offs.append(pos)
    return (
        b"".join(parts),
        pp_offs,
        bytes(p.flags for p in pred_tab),
        b"".join(p.idents for p in pred_tab),
    )


def flatten_colsets(colsets: List[ColumnarWriteSet]):
    """The merged batch arrays the kernel (and the apply-shard
    processes' wire payload) consume: ((m_offs, shapes, entities,
    pids, objects, vtypes, voffs, vblob), pred_tab) with the members'
    pred ids remapped onto one deduplicated pred table. Single-colset
    calls (serial commits, 1-member batches) pass the collected
    buffers straight through — zero concatenation."""
    if len(colsets) == 1:
        cs = colsets[0]
        return (
            (
                array("q", (0, len(cs.shapes))), cs.shapes,
                cs.entities, cs.pids, cs.objects, cs.vtypes,
                cs.voffs, cs.vblob,
            ),
            cs.pred_list,
        )
    merged: Dict[tuple, int] = {}
    pred_tab = []
    remaps: List[List[int]] = []
    for cs in colsets:
        remap = []
        for p in cs.pred_list:
            mk = (p.ns, p.attr, p.flags, p.idents, p.prefix)
            b = merged.get(mk)
            if b is None:
                b = merged[mk] = len(pred_tab)
                pred_tab.append(p)
            remap.append(b)
        remaps.append(remap)
    m_offs = array("q", (0,))
    shapes = bytearray()
    entities = array("Q")
    pids = array("i")
    objects = array("Q")
    vtypes = bytearray()
    voffs = array("q", (0,))
    vblob = bytearray()
    for cs, remap in zip(colsets, remaps):
        shapes += cs.shapes
        entities += cs.entities
        if remap == list(range(len(remap))):
            pids += cs.pids  # members usually share one pred order
        else:
            pids.extend(remap[p] for p in cs.pids)
        objects += cs.objects
        vtypes += cs.vtypes
        base = len(vblob)
        vblob += cs.vblob
        if base:
            voffs.extend(v + base for v in cs.voffs[1:])
        else:
            voffs += cs.voffs[1:]
        m_offs.append(len(shapes))
    return (
        (m_offs, shapes, entities, pids, objects, vtypes, voffs, vblob),
        pred_tab,
    )


def _run_kernel(colsets: List[ColumnarWriteSet]):
    """Flatten the colsets (members of one group-commit batch) into the
    batch arrays and run ONE codec.cpp batch_apply call. Returns the
    wrapper's raw result plus the merged pred table, or None when the
    native library refuses."""
    from dgraph_tpu import native

    flat, pred_tab = flatten_colsets(colsets)
    pp_blob, pp_offs, pflags, pidents = _pred_blobs(pred_tab)
    res = native.batch_apply(
        *flat, pp_blob, pp_offs, pflags, pidents,
    )
    if res is None:
        return None
    return res, pred_tab


def _encode_colsets(colsets: List[ColumnarWriteSet]):
    """Per-colset [(key, record, attr)] lists plus per-colset
    (keys, stats_rows, n_postings) side info, or None when the kernel
    is unavailable (caller materializes). With DGRAPH_TPU_APPLY_PROCS
    workers live, the kernel runs in the apply-shard processes
    (worker/applyshard.py) — same result shape, byte-identical pairs;
    any escape from that plane falls through to the in-process call
    below (exact serial semantics, counted per reason)."""
    from dgraph_tpu.worker import applyshard

    pool = applyshard.maybe_pool()
    if pool is not None:
        got = pool.encode(colsets)
        if got is not None:
            METRICS.inc("mutation_batch_apply_total")
            METRICS.inc(
                "mutation_batch_apply_edges_total",
                sum(len(cs.shapes) for cs in colsets),
            )
            return got
    got = _run_kernel(colsets)
    if got is None:
        return None
    (
        n_pairs, keys_blob, key_offs, recs_blob, rec_offs,
        member, pred, kinds, counts,
    ), pred_tab = got
    kidx = keys.KIND_INDEX
    attrs = [p.attr for p in pred_tab]
    plens = [len(p.prefix) + 1 for p in pred_tab]
    out = []
    side = []
    pos = 0
    for mi in range(len(colsets)):
        end = pos
        while end < n_pairs and member[end] == mi:
            end += 1
        pairs = []
        pappend = pairs.append
        mkeys = []
        kappend = mkeys.append
        stats_rows = []
        for i in range(pos, end):
            key = keys_blob[key_offs[i]:key_offs[i + 1]]
            pid = pred[i]
            pappend((key, recs_blob[rec_offs[i]:rec_offs[i + 1]],
                     attrs[pid]))
            kappend(key)
            if kinds[i] == kidx:
                stats_rows.append(
                    (attrs[pid], key[plens[pid]:], counts[i])
                )
        out.append(pairs)
        side.append((mkeys, stats_rows, sum(counts[pos:end])))
        pos = end
    METRICS.inc("mutation_batch_apply_total")
    METRICS.inc(
        "mutation_batch_apply_edges_total",
        sum(len(cs.shapes) for cs in colsets),
    )
    return out, side


def encode_txn(txn) -> List[Tuple[bytes, bytes, str]]:
    """Serial-commit encode of one txn's columnar write set: returns
    ready-to-put (key, record, attr) triples and stamps the side
    channels (col_keys for invalidation, col_stats for the selectivity
    sketch, col_nposts for the postings-written metric). Falls back to
    materialize (returning []) when the kernel refuses — the caller's
    ordinary deltas path then handles everything."""
    col = getattr(txn, "col", None)
    if col is None or not col.pending:
        return []
    got = _encode_colsets([col])
    if got is None:
        count_fallback("kernel", len(col.shapes))
        materialize(txn)
        return []
    out, side = got
    mkeys, stats_rows, nposts = side[0]
    txn.col_keys = mkeys
    txn.col_stats = stats_rows
    txn.col_nposts = nposts
    col.note_traffic()
    col.take_chunks()  # consumed
    return out[0]


def batch_encode(members) -> Dict[object, List[Tuple[bytes, bytes, str]]]:
    """Group-commit leader encode: ALL committed members' columnar
    write sets through ONE kernel call. Returns {member: [(key,
    record, attr)]} for members that had columns (stamping the same
    per-txn side channels as encode_txn); members whose colsets had to
    materialize simply keep their Python deltas and are absent."""
    live = [
        m
        for m in members
        if getattr(m.txn, "col", None) is not None and m.txn.col.pending
    ]
    if not live:
        return {}
    got = _encode_colsets([m.txn.col for m in live])
    if got is None:
        for m in live:
            count_fallback("kernel", len(m.txn.col.shapes))
            materialize(m.txn)
        return {}
    out, side = got
    result = {}
    for m, pairs, (mkeys, stats_rows, nposts) in zip(live, out, side):
        m.txn.col_keys = mkeys
        m.txn.col_stats = stats_rows
        m.txn.col_nposts = nposts
        m.txn.col.note_traffic()
        m.txn.col.take_chunks()  # consumed
        result[m] = pairs
    return result


def fence_keys(txn) -> List[bytes]:
    """Keys the tablet-move fence check should parse for this txn:
    Python delta keys plus one synthetic data key per columnar
    predicate."""
    ks = list(txn.cache.deltas)
    col = getattr(txn, "col", None)
    if col is not None and col.pending:
        ks.extend(col.fence_keys())
    return ks


def feed_col_stats(stats, txn) -> None:
    """Index-posting counts from the kernel's output into the
    selectivity sketch — what cmsketch.feed_stats does for Python
    deltas."""
    rows = getattr(txn, "col_stats", None)
    if rows:
        for attr, term, n in rows:
            stats.record(attr, term, n)

"""Transaction-local posting cache + Txn object.

Mirrors /root/reference/posting/lists.go:63 LocalCache (per-txn view that
layers uncommitted deltas over the store) and posting/oracle.go:40 Txn.
Commit writes one delta record per touched key at the commit ts
(ref posting/mvcc.go:266 CommitToDisk).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from dgraph_tpu.posting.pl import (
    Posting,
    PostingList,
    encode_delta,
    fingerprint64,
)
from dgraph_tpu.storage.kv import KV


class LocalCache:
    """Per-txn read-through cache with uncommitted delta overlay.

    When a shared MemoryLayer is provided, decoded lists are reused across
    transactions/queries (ref posting/mvcc.go MemoryLayer)."""

    def __init__(self, kv: KV, read_ts: int, mem=None):
        self.kv = kv
        self.read_ts = read_ts
        self.mem = mem
        self._plists: Dict[bytes, PostingList] = {}
        self.deltas: Dict[bytes, List[Posting]] = {}

    def get(self, key: bytes) -> PostingList:
        pl = self._plists.get(key)
        if pl is None:
            if self.mem is not None:
                pl = self.mem.read(self.kv, key, self.read_ts)
            else:
                pl = PostingList.from_versions(
                    key,
                    self.kv.versions(key, self.read_ts),
                    kv=self.kv,
                    read_ts=self.read_ts,
                )
            self._plists[key] = pl
        return pl

    def prefetch(self, keys_list) -> None:
        """Batch-read many posting lists ahead of a per-key loop (level-
        batched fan-out, uid_in probes). On the LSM backend this becomes
        one monotone multi-key probe per table instead of a seek per key
        (ref badger iterator prefetch / MultiGet)."""
        if self.mem is None:
            return
        missing = [k for k in keys_list if k not in self._plists]
        if len(missing) < 16:
            return
        self._plists.update(
            self.mem.read_many(self.kv, missing, self.read_ts)
        )

    # -- reads (uncommitted deltas visible to this txn) ----------------------

    def uids(self, key: bytes) -> np.ndarray:
        return self.get(key).uids(self.deltas.get(key))

    def uids_tok(self, key: bytes):
        """(uids, version token). The token is the posting list's device-
        cache identity (key, latest_ts) — None when this txn has local
        deltas on the key (the materialized view is txn-private then)."""
        pl = self.get(key)
        extra = self.deltas.get(key)
        uids = pl.uids(extra)
        tok = None if extra else (key, pl.latest_ts)
        return uids, tok

    def packed_operand(self, key: bytes):
        """The posting list as a compressed-domain dispatcher operand
        (query/dispatch.PackedOperand), or None when any uid delta —
        committed or txn-local — makes the packed layers stale. Carries the
        list's block-cached partial decoder, so candidate blocks decode
        once per list per commit epoch."""
        extra = self.deltas.get(key)
        if extra and any(not p.is_value for p in extra):
            return None
        pl = self.get(key)
        pack = pl.packed()
        if pack is None:
            return None
        from dgraph_tpu.query.dispatch import PackedOperand

        return PackedOperand(
            pack,
            decode_fn=pl.decode_blocks,
            uids=pl._uids_cache,
            uids_fn=pl.uids,
        )

    def value(self, key: bytes, lang: str = ""):
        return self.get(key).get_value(lang, self.deltas.get(key))

    def values(self, key: bytes) -> List[Posting]:
        return self.get(key).get_all_values(self.deltas.get(key))

    def has(self, key: bytes) -> bool:
        return not self.get(key).is_empty(self.deltas.get(key))

    def edge_facets(self, key: bytes):
        """Facets per target uid for a uid-edge list (ref facets on
        pb.Posting; used by @facets projection/filtering)."""
        merged = self.get(key)._merged_postings(self.deltas.get(key))
        out = {}
        for uid, p in merged.items():
            if not p.is_value and p.facets and p.op == 1:  # OP_SET
                out[uid] = p.get_facets()
        return out

    # -- writes --------------------------------------------------------------

    def add_delta(self, key: bytes, p: Posting):
        self.deltas.setdefault(key, []).append(p)


class Txn:
    """A read-write transaction (ref posting/oracle.go:40 Txn)."""

    def __init__(self, kv: KV, start_ts: int, mem=None):
        self.start_ts = start_ts
        self.cache = LocalCache(kv, start_ts, mem=mem)
        self.conflict_keys: set[int] = set()
        self.committed = False
        self.aborted = False

    def add_conflict_key(self, key: bytes, extra: bytes = b""):
        """Fingerprint written keys for oracle conflict detection
        (ref posting/list.go:842 GetConflictKey)."""
        self.conflict_keys.add(fingerprint64(key + b"|" + extra))

    def write_deltas(self, kv: KV, commit_ts: int):
        """Persist all pending deltas at commit_ts (CommitToDisk)."""
        for key, posts in self.cache.deltas.items():
            if posts:
                kv.put(key, commit_ts, encode_delta(posts))

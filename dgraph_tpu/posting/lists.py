"""Transaction-local posting cache + Txn object.

Mirrors /root/reference/posting/lists.go:63 LocalCache (per-txn view that
layers uncommitted deltas over the store) and posting/oracle.go:40 Txn.
Commit writes one delta record per touched key at the commit ts
(ref posting/mvcc.go:266 CommitToDisk).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from dgraph_tpu.posting.pl import (
    Posting,
    PostingList,
    encode_delta,
    fingerprint64,
)
from dgraph_tpu.storage.kv import KV
from dgraph_tpu.utils.observe import METRICS


class ReadCounters:
    """Process-wide cache round-trip accounting (level_batch_read_calls
    benchmark + fan-out observability). Plain unsynchronized ints: point
    reads are the hottest call sites in the engine, so a lock per
    increment (METRICS.inc) is not acceptable there; a lost increment
    under racing threads is noise, not corruption. `publish()` mirrors
    the totals into the Prometheus registry as gauges."""

    __slots__ = ("point_reads", "batch_reads", "batch_read_keys")

    def __init__(self):
        self.point_reads = 0
        self.batch_reads = 0
        self.batch_read_keys = 0

    def snapshot(self) -> dict:
        return {
            "point_reads": self.point_reads,
            "batch_reads": self.batch_reads,
            "batch_read_keys": self.batch_read_keys,
        }

    def publish(self):
        METRICS.set_gauge("cache_point_reads", float(self.point_reads))
        METRICS.set_gauge("cache_batch_reads", float(self.batch_reads))
        METRICS.set_gauge(
            "cache_batch_read_keys", float(self.batch_read_keys)
        )


READ_COUNTERS = ReadCounters()


def cache_tier_snapshot(mem=None) -> dict:
    """Cache-tier counter snapshot for the EXPLAIN `cache` block (one
    shared mapping — the entry points diff two of these around a debug
    query). Process-wide counters: under concurrent queries a delta
    attributes a class of work, not an exact per-query count."""
    out = READ_COUNTERS.snapshot()
    if mem is not None:
        out["memlayer_hits"] = mem.hits
        out["memlayer_misses"] = mem.misses
    return out


class LocalCache:
    """Per-txn read-through cache with uncommitted delta overlay.

    When a shared MemoryLayer is provided, decoded lists are reused across
    transactions/queries (ref posting/mvcc.go MemoryLayer)."""

    def __init__(self, kv: KV, read_ts: int, mem=None):
        self.kv = kv
        self.read_ts = read_ts
        self.mem = mem
        self._plists: Dict[bytes, PostingList] = {}
        self.deltas: Dict[bytes, List[Posting]] = {}

    def get(self, key: bytes) -> PostingList:
        pl = self._plists.get(key)
        if pl is None:
            if self.mem is not None:
                pl = self.mem.read(self.kv, key, self.read_ts)
            else:
                pl = PostingList.from_versions(
                    key,
                    self.kv.versions(key, self.read_ts),
                    kv=self.kv,
                    read_ts=self.read_ts,
                )
            self._plists[key] = pl
        return pl

    def prefetch(self, keys_list) -> None:
        """Batch-read many posting lists ahead of a per-key loop (level-
        batched fan-out, uid_in probes). On the LSM backend this becomes
        one monotone multi-key probe per table instead of a seek per key
        (ref badger iterator prefetch / MultiGet)."""
        if self.mem is None:
            return
        missing = [k for k in keys_list if k not in self._plists]
        if len(missing) < 16:
            return
        self._plists.update(
            self.mem.read_many(self.kv, missing, self.read_ts)
        )

    # -- reads (uncommitted deltas visible to this txn) ----------------------

    def uids(self, key: bytes) -> np.ndarray:
        READ_COUNTERS.point_reads += 1
        return self.get(key).uids(self.deltas.get(key))

    def uids_tok(self, key: bytes):
        """(uids, version token). The token is the posting list's device-
        cache identity (key, latest_ts) — None when this txn has local
        deltas on the key (the materialized view is txn-private then)."""
        READ_COUNTERS.point_reads += 1
        pl = self.get(key)
        extra = self.deltas.get(key)
        uids = pl.uids(extra)
        tok = None if extra else (key, pl.latest_ts)
        return uids, tok

    # -- level-batched reads (one task per (predicate, level)) ---------------

    def _resolve_many(self, keys_list) -> None:
        """Materialize PostingLists for every key in ONE memlayer pass
        (single lock acquisition + one versions_batch LSM probe) instead
        of N read-throughs."""
        missing = [k for k in keys_list if k not in self._plists]
        if not missing:
            return
        if self.mem is not None:
            self._plists.update(
                self.mem.read_many(self.kv, missing, self.read_ts)
            )
        else:
            for k in missing:
                if k not in self._plists:
                    self.get(k)

    def uids_many(self, keys_list):
        """Batched uid read for a whole traversal level: returns
        (flat, offsets, toks) where row i = flat[offsets[i]:offsets[i+1]]
        is key i's sorted uid set and toks[i] is its device-cache version
        token ((key, latest_ts), None when txn-local deltas exist).

        One memlayer/LSM pass resolves every list; all-committed no-delta
        packs then decode through ONE native pass (codec.cpp
        packs_decode_many) into the shared flat buffer — each list adopts
        its slice as the memoized materialization, so later point reads
        stay free. Lists with uid deltas fall back to the layered path."""
        from dgraph_tpu.codec import uidpack

        n = len(keys_list)
        READ_COUNTERS.batch_reads += 1
        READ_COUNTERS.batch_read_keys += n
        self._resolve_many(keys_list)
        rows: list = [None] * n
        toks: list = [None] * n
        batch = []  # (row index, PostingList) pending the one-pass decode
        for i, k in enumerate(keys_list):
            pl = self._plists.get(k)
            if pl is None:
                pl = self.get(k)
            extra = self.deltas.get(k)
            if not extra:
                toks[i] = (k, pl.latest_ts)
                if pl._uids_cache is not None:
                    rows[i] = pl._uids_cache
                elif not pl.has_uid_deltas():
                    batch.append((i, pl))
                else:
                    rows[i] = pl.uids(None)
            else:
                rows[i] = pl.uids(extra)
        if batch:
            flat_b, offs_b = uidpack.decode_packs(
                [pl.merged_pack() for _, pl in batch]
            )
            for j, (i, pl) in enumerate(batch):
                row = flat_b[offs_b[j] : offs_b[j + 1]]
                pl.adopt_uids(row)
                rows[i] = row
        from dgraph_tpu.query.ragged import pack_rows

        flat, offsets = pack_rows(rows)
        METRICS.inc("level_batch_read_bytes", int(flat.nbytes))
        return flat, offsets, toks

    def values_many(self, keys_list):
        """Batched value-posting read: one memlayer/LSM pass for the whole
        level, then the per-list merge (values are heterogeneous posting
        objects — the batched KV probe is the win, not the merge loop).
        Returns a list aligned with keys_list."""
        READ_COUNTERS.batch_reads += 1
        READ_COUNTERS.batch_read_keys += len(keys_list)
        self._resolve_many(keys_list)
        return [
            self.get(k).get_all_values(self.deltas.get(k))
            for k in keys_list
        ]

    def packed_operand(self, key: bytes):
        """The posting list as a compressed-domain dispatcher operand
        (query/dispatch.PackedOperand), or None when any uid delta —
        committed or txn-local — makes the packed layers stale. Carries the
        list's block-cached partial decoder, so candidate blocks decode
        once per list per commit epoch."""
        extra = self.deltas.get(key)
        if extra and any(not p.is_value for p in extra):
            return None
        pl = self.get(key)
        pack = pl.packed()
        if pack is None:
            return None
        from dgraph_tpu.query.dispatch import PackedOperand

        return PackedOperand(
            pack,
            decode_fn=pl.decode_blocks,
            uids=pl._uids_cache,
            uids_fn=pl.uids,
        )

    def value(self, key: bytes, lang: str = ""):
        READ_COUNTERS.point_reads += 1
        return self.get(key).get_value(lang, self.deltas.get(key))

    def values(self, key: bytes) -> List[Posting]:
        READ_COUNTERS.point_reads += 1
        return self.get(key).get_all_values(self.deltas.get(key))

    def has(self, key: bytes) -> bool:
        return not self.get(key).is_empty(self.deltas.get(key))

    def edge_facets(self, key: bytes):
        """Facets per target uid for a uid-edge list (ref facets on
        pb.Posting; used by @facets projection/filtering)."""
        merged = self.get(key)._merged_postings(self.deltas.get(key))
        out = {}
        for uid, p in merged.items():
            if not p.is_value and p.facets and p.op == 1:  # OP_SET
                out[uid] = p.get_facets()
        return out

    # -- writes --------------------------------------------------------------

    def add_delta(self, key: bytes, p: Posting):
        self.deltas.setdefault(key, []).append(p)


class Txn:
    """A read-write transaction (ref posting/oracle.go:40 Txn)."""

    def __init__(self, kv: KV, start_ts: int, mem=None):
        self.start_ts = start_ts
        self.cache = LocalCache(kv, start_ts, mem=mem)
        self.conflict_keys: set[int] = set()
        self.committed = False
        self.aborted = False
        # columnar write set (posting/colwrite): engines attach one via
        # colwrite.maybe_enable when the native batch-apply path may
        # consume this txn's writes at commit; None = classic deltas
        self.col = None

    def add_conflict_key(self, key: bytes, extra: bytes = b""):
        """Fingerprint written keys for oracle conflict detection
        (ref posting/list.go:842 GetConflictKey)."""
        self.conflict_keys.add(fingerprint64(key + b"|" + extra))

    def materialize_cols(self):
        """Read-your-writes hook: convert any collected columnar edges
        back into Python deltas before this txn reads its own writes
        (query / upsert entry points call this)."""
        if self.col is not None:
            from dgraph_tpu.posting import colwrite

            if self.col.pending:
                colwrite.count_fallback("read", len(self.col.shapes))
            colwrite.materialize(self)

    def pending_postings(self) -> int:
        """Postings this txn will write at commit (admission control's
        write-size signal): Python deltas plus the columnar estimate."""
        n = sum(len(p) for p in self.cache.deltas.values())
        if self.col is not None:
            n += self.col.nposts_est
        return n

    def write_deltas(self, kv: KV, commit_ts: int):
        """Persist all pending deltas at commit_ts (CommitToDisk)."""
        if self.col is not None and self.col.pending:
            from dgraph_tpu.posting import colwrite

            for key, rec, _attr in colwrite.encode_txn(self):
                kv.put(key, commit_ts, rec)
        for key, posts in self.cache.deltas.items():
            if posts:
                kv.put(key, commit_ts, encode_delta(posts))

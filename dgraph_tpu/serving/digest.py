"""Query digest store — per-(namespace, shape) aggregate statistics.

The pg_stat_statements analog for the flight recorder: every query that
passes an entry point (`Server.query`, `ProcCluster.query`) is folded
into an aggregate row keyed on the plan-cache normalized shape
(`plancache.normalize`: the dql token stream with literals replaced by
`?`) crossed with the resolved namespace. A row accumulates calls,
errors, a latency histogram on the shared `_BUCKETS` ladder, result
rows/bytes, plan/result-cache hits, and the packed-kernel counter
deltas the profile scope already computes — so after a latency spike
the *shapes* responsible are readable from `/debug/digests` without a
rerun.

Capacity is bounded (DGRAPH_TPU_DIGEST_SHAPES distinct rows, LRU).
Eviction never loses counts: the evicted row is folded into a sticky
per-namespace ``other`` bucket (a bare ``other`` can never collide
with a real shape — real shapes contain braces and spaces), so
per-namespace totals stay exact under shape churn.

Accounting is observation-only: `record()` mutates only this store, so
query results are byte-identical with the store on or off (the A/B
gate `bench.py --obs-sanity` enforces it). The hot path pays one
enabled-check plus one dict update under a short lock; METRICS is
never called while the store's lock is held (lock-order discipline).

Cluster merge: every process serves its local rows over the
``debug.digests`` RPC; `merge_rows()` sums same-keyed rows bucket-wise
so `ProcCluster.merged_digests()` (and `dgraph-tpu top`) shows cluster
totals whose call counts equal the sum of per-process scrapes.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, Iterable, List, Optional, Tuple

from dgraph_tpu.utils.observe import _BUCKETS, METRICS
from dgraph_tpu.x import config

# sticky eviction bucket; real shapes always contain braces/spaces
OTHER_SHAPE = "other"

# numeric fields summed on merge/fold (histogram counts handled apart)
_SUM_FIELDS = (
    "calls", "errors", "lat_sum", "rows", "bytes",
    "plan_hits", "result_hits", "setop_pairs", "setop_packed",
)


class DigestEntry:
    __slots__ = (
        "calls", "errors", "lat_sum", "lat_counts", "rows", "bytes",
        "plan_hits", "result_hits", "setop_pairs", "setop_packed",
    )

    def __init__(self):
        self.calls = 0
        self.errors = 0
        self.lat_sum = 0.0
        self.lat_counts = [0] * (len(_BUCKETS) + 1)
        self.rows = 0
        self.bytes = 0
        self.plan_hits = 0
        self.result_hits = 0
        self.setop_pairs = 0
        self.setop_packed = 0

    def fold(self, other: "DigestEntry") -> None:
        self.calls += other.calls
        self.errors += other.errors
        self.lat_sum += other.lat_sum
        for i, c in enumerate(other.lat_counts):
            self.lat_counts[i] += c
        self.rows += other.rows
        self.bytes += other.bytes
        self.plan_hits += other.plan_hits
        self.result_hits += other.result_hits
        self.setop_pairs += other.setop_pairs
        self.setop_packed += other.setop_packed


class DigestStore:
    """Bounded LRU of (namespace, shape) -> DigestEntry. Thread-safe;
    nothing blocking (and no METRICS call) runs under its lock."""

    def __init__(self, capacity: Optional[int] = None):
        self._lock = threading.Lock()
        self._capacity = capacity
        self._rows: "OrderedDict[Tuple[str, str], DigestEntry]" = (
            OrderedDict()
        )

    def capacity(self) -> int:
        if self._capacity is not None:
            return max(1, int(self._capacity))
        return max(1, int(config.get("DIGEST_SHAPES")))

    @staticmethod
    def enabled() -> bool:
        return bool(config.get("DIGEST"))

    def record(
        self,
        ns: str,
        shape: Optional[str],
        seconds: float,
        rows: int = 0,
        nbytes: int = 0,
        error: bool = False,
        plan_hit: bool = False,
        result_hit: bool = False,
        setop_pairs: int = 0,
        setop_packed: int = 0,
    ) -> None:
        """Fold one query observation into its aggregate row. A query
        whose text does not lex (shape None) accrues to `other`."""
        if not self.enabled():
            return
        key = (str(ns), shape if shape else OTHER_SHAPE)
        cap = self.capacity()
        evicted = 0
        with self._lock:
            e = self._rows.get(key)
            if e is None:
                e = self._rows[key] = DigestEntry()
            else:
                self._rows.move_to_end(key)
            e.calls += 1
            if error:
                e.errors += 1
            e.lat_sum += seconds
            i = len(_BUCKETS)
            for j, b in enumerate(_BUCKETS):
                if seconds <= b:
                    i = j
                    break
            e.lat_counts[i] += 1
            e.rows += int(rows)
            e.bytes += int(nbytes)
            if plan_hit:
                e.plan_hits += 1
            if result_hit:
                e.result_hits += 1
            e.setop_pairs += int(setop_pairs)
            e.setop_packed += int(setop_packed)
            while len(self._rows) > cap:
                old_key, old = self._rows.popitem(last=False)
                sink_key = (old_key[0], OTHER_SHAPE)
                if sink_key == old_key:
                    # `other` itself hit the LRU head: reinsert hottest
                    self._rows[old_key] = old
                    self._rows.move_to_end(old_key, last=True)
                    if len(self._rows) <= cap:
                        break
                    old_key, old = self._rows.popitem(last=False)
                    sink_key = (old_key[0], OTHER_SHAPE)
                sink = self._rows.get(sink_key)
                if sink is None:
                    sink = self._rows[sink_key] = DigestEntry()
                sink.fold(old)
                evicted += 1
        if evicted:
            METRICS.inc("digest_evicted_total", evicted)

    def snapshot(self) -> List[dict]:
        """All rows as plain dicts, sorted by latency share (lat_sum
        desc) — the wire/JSON form debug.digests serves. Also publishes
        the digest_shapes gauge (scrape-time, like tablet_traffic)."""
        with self._lock:
            rows = [
                {
                    "ns": ns,
                    "shape": shape,
                    "calls": e.calls,
                    "errors": e.errors,
                    "lat_sum": e.lat_sum,
                    "lat_counts": list(e.lat_counts),
                    "rows": e.rows,
                    "bytes": e.bytes,
                    "plan_hits": e.plan_hits,
                    "result_hits": e.result_hits,
                    "setop_pairs": e.setop_pairs,
                    "setop_packed": e.setop_packed,
                }
                for (ns, shape), e in self._rows.items()
            ]
        METRICS.set_gauge("digest_shapes", len(rows))
        rows.sort(key=lambda r: (-r["lat_sum"], r["ns"], r["shape"]))
        return rows

    def totals(self) -> Dict[str, float]:
        """Store-wide aggregates — what qps_loadgen stamps into BENCH
        rows: total calls/errors/latency plus the top shape's latency
        share (0 when empty)."""
        rows = self.snapshot()
        calls = sum(r["calls"] for r in rows)
        lat = sum(r["lat_sum"] for r in rows)
        top_share = (rows[0]["lat_sum"] / lat) if rows and lat > 0 else 0.0
        return {
            "shapes": float(len(rows)),
            "calls": float(calls),
            "errors": float(sum(r["errors"] for r in rows)),
            "lat_sum": lat,
            "top_shape_lat_share": top_share,
        }

    def reset(self) -> None:
        with self._lock:
            self._rows.clear()


def merge_rows(row_lists: Iterable[List[dict]]) -> List[dict]:
    """Sum same-keyed rows from several per-process snapshots (bucket-
    wise for the histogram) — merged call counts equal the sum of the
    per-process scrapes by construction."""
    merged: Dict[Tuple[str, str], dict] = {}
    for rows in row_lists:
        for r in rows or []:
            key = (str(r.get("ns", "")), str(r.get("shape", "")))
            m = merged.get(key)
            if m is None:
                m = merged[key] = {
                    "ns": key[0],
                    "shape": key[1],
                    "lat_counts": [0] * (len(_BUCKETS) + 1),
                }
                for f in _SUM_FIELDS:
                    m[f] = 0
            for f in _SUM_FIELDS:
                m[f] += r.get(f, 0)
            for i, c in enumerate(r.get("lat_counts") or []):
                if i < len(m["lat_counts"]):
                    m["lat_counts"][i] += c
    out = list(merged.values())
    out.sort(key=lambda r: (-r["lat_sum"], r["ns"], r["shape"]))
    return out


# process-wide store, like METRICS/TRACER/TABLETS — entry points feed
# it directly and attach_debug_surface serves it without plumbing
DIGESTS = DigestStore()

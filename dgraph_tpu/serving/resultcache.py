"""Snapshot-keyed whole-response result cache (ROADMAP open item 2).

Production traffic from millions of users is highly repetitive: the
same query shapes with the same hot literal bindings arrive over and
over between commits. PR 7's plan cache only skips *parsing*; this
cache skips execution and encode outright by serving the response's
wire bytes from a bounded LRU keyed on

    (normalized plan shape, literal bindings, query variables,
     namespace, snapshot watermark)

Correctness rests on the PR 7/11 snapshot-watermark proof: the
engine's `_snapshot_ts` is published only after a commit's deltas are
written and advances in commit-ts order, so any two reads covering the
SAME watermark observe identical stores — the executed response bytes
are a pure function of (query text, variables, namespace, watermark).
A commit (or alter) advances the watermark, which changes every key:
no cached result can ever be served past a watermark advance, with no
explicit invalidation sweep needed (stale-watermark entries age out of
the LRU; commit-epoch invalidation already covers the plan cache).

What is stored is only the response `data` wire bytes (the RawJson /
RawData `.raw` arena output) — entries are immutable `bytes`; hits
rebuild the response shell per `want` (a fresh RawJson, or a RawData
around `json.loads`, the same parse-back the stream path performs on a
miss), so callers can never mutate a cached entry.

Eligibility is decided at the entry points (api/server.py,
worker/harness.py): watermark reads only (caller-pinned read_ts never
caches), no ACL (per-user visibility would need per-claims keys),
clean completions only (no truncated/degraded/partial responses), and
EXPLAIN/debug queries always execute (the plan tree is the point) but
record the would-hit tier in `extensions.plan.result_cache`.

Default OFF (DGRAPH_TPU_RESULT_CACHE_SIZE=0), like the other
serving-front gates (ADMISSION, BATCH_WINDOW_US); the BENCH_QPS
reuse sweep A/Bs it against the same build with the knob zeroed.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Dict, Optional, Tuple

from dgraph_tpu.utils.observe import METRICS
from dgraph_tpu.x import config


class ResultCache:
    """Bounded LRU of response wire bytes keyed on (shape, literals,
    vars, ns, watermark). Thread-safe; nothing blocking runs under its
    lock (entries are prebuilt bytes)."""

    def __init__(self, size: Optional[int] = None, ttl_s: Optional[float] = None,
                 max_bytes: Optional[int] = None):
        self._lock = threading.Lock()
        self._size = size
        self._ttl = ttl_s
        self._max_bytes = max_bytes
        # key -> (raw bytes, monotonic insert time)
        self._entries: "OrderedDict[tuple, Tuple[bytes, float]]" = (
            OrderedDict()
        )
        self._bytes = 0  # payload bytes currently held
        self.hits = 0
        self.misses = 0

    def capacity(self) -> int:
        if self._size is not None:
            return max(0, int(self._size))
        return max(0, int(config.get("RESULT_CACHE_SIZE")))

    def byte_capacity(self) -> int:
        """Byte bound on stored payloads; 0 = entry count only. A
        response cache sized in 'entries' alone is unbounded in the
        dimension that matters (a wide fan-out response is MBs)."""
        if self._max_bytes is not None:
            return max(0, int(self._max_bytes))
        return max(0, int(config.get("RESULT_CACHE_BYTES")))

    def ttl_s(self) -> float:
        if self._ttl is not None:
            return max(0.0, float(self._ttl))
        return max(0.0, float(config.get("RESULT_CACHE_TTL_S")))

    @staticmethod
    def key(
        shape: str,
        literals: tuple,
        variables,
        ns: int,
        watermark: int,
        epoch: int = 0,
    ) -> tuple:
        """`epoch` is the engine's commit epoch (plan-cache epoch,
        bumped by every commit AND alter): it closes the one hole
        watermark keying leaves — an alter, or a commit racing an
        alter's watermark jump, can change visible semantics without
        the watermark distinguishing before from after. Keys carry
        both, so an entry is reachable only at an unchanged store AND
        an unchanged schema/commit epoch."""
        vk = (
            ()
            if not variables
            else tuple(sorted((str(k), repr(v)) for k, v in variables.items()))
        )
        return (
            shape, tuple(literals or ()), vk, int(ns),
            int(watermark), int(epoch),
        )

    # -- lookups --------------------------------------------------------------

    def get(self, key: tuple) -> Optional[bytes]:
        """Cached wire bytes for this exact (binding, watermark), or
        None. Counts result_cache_{hit,miss}_total — call only for
        ELIGIBLE lookups so the metrics describe the reuse regime."""
        ttl = self.ttl_s()
        now = time.monotonic()
        with self._lock:
            got = self._entries.get(key)
            if got is not None and ttl and now - got[1] > ttl:
                del self._entries[key]
                self._bytes -= len(got[0])
                got = None
            if got is None:
                self.misses += 1
                METRICS.inc("result_cache_miss_total")
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            METRICS.inc("result_cache_hit_total")
            return got[0]

    def peek(self, key: tuple) -> bool:
        """Presence probe without serving, counters, or LRU touch —
        the EXPLAIN would-hit tier (debug queries always execute)."""
        ttl = self.ttl_s()
        with self._lock:
            got = self._entries.get(key)
            if got is None:
                return False
            return not (ttl and time.monotonic() - got[1] > ttl)

    def put(self, key: tuple, raw: bytes) -> None:
        cap = self.capacity()
        bcap = self.byte_capacity()
        if cap == 0 or not isinstance(raw, (bytes, bytearray)):
            return
        raw = bytes(raw)
        if bcap and len(raw) > bcap:
            return  # one giant response must not flush the whole LRU
        with self._lock:
            old = self._entries.get(key)
            if old is not None:
                self._bytes -= len(old[0])
            self._entries[key] = (raw, time.monotonic())
            self._entries.move_to_end(key)
            self._bytes += len(raw)
            while len(self._entries) > cap or (
                bcap and self._bytes > bcap
            ):
                _, (dropped, _t) = self._entries.popitem(last=False)
                self._bytes -= len(dropped)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    def stats(self) -> Dict[str, float]:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "hits": self.hits,
                "misses": self.misses,
            }


def rebuild_data(raw: bytes, want: str):
    """Response `data` shell around cached wire bytes: a fresh RawJson
    (want="raw"), or a RawData around json.loads — the SAME parse-back
    the stream encoder performs on a miss, so hit and miss responses
    are structurally as well as byte identical. A fresh object per hit
    means callers can never mutate the cached entry."""
    from dgraph_tpu.query.streamjson import RawData, RawJson

    if want == "raw":
        return RawJson(raw)
    import json

    return RawData(json.loads(raw), raw)


def hit_response(
    raw: bytes,
    want: str,
    parsing_ns: int,
    assign_ns: int,
    processing_ns: int,
    watermark: int,
) -> dict:
    """The full cache-hit response shell — ONE implementation for both
    entry points (api/server.Server.query, ProcCluster.query) so the
    hit shape can never drift between engines. The latency parts
    partition the wall clock at the caller, so total is their sum
    (encoding is 0: no bytes were produced on a hit)."""
    out = {"data": rebuild_data(raw, want)}
    out["extensions"] = {
        "server_latency": {
            "parsing_ns": int(parsing_ns),
            "assign_timestamp_ns": int(assign_ns),
            "processing_ns": int(processing_ns),
            "encoding_ns": 0,
            "total_ns": int(parsing_ns) + int(assign_ns) + int(processing_ns),
        },
        # the response contract promises an extensions.profile block on
        # every query (consumers index into it unguarded): a hit did no
        # execution, so the attribution is the empty QueryProfile shape
        "profile": {
            "level_tasks": [],
            "rpc": [],
            "kernel": {},
            "events": {},
            "encode": {},
            "exec_pool": {"max_queue_depth": 0},
        },
        "result_cache": {"hit": True, "watermark": int(watermark)},
    }
    return out

"""Parsed-query plan cache keyed on normalized query shape.

High-QPS traffic repeats a small set of query *shapes* with varying
literal values. `normalize()` tokenizes the query with the dql lexer
and strips every literal token (strings, numbers, regexes) out of the
shape key — so two textually different queries that differ only in
values (or whitespace/comments) share one shape. The cache is a
two-level structure:

  shape  -> ShapeEntry     (LRU over shapes, DGRAPH_TPU_PLAN_CACHE_SIZE)
  ShapeEntry.variants:
    (literals, query-vars) -> parsed blocks   (bounded per shape)

A variant hit returns the cached GraphQuery tree directly — parse is
skipped entirely. Reuse without copying is safe because the executor
never mutates the parsed tree (it builds ExecNodes beside it; expand/
recurse construct *new* GraphQuery children) — a regression test runs
one cached tree through the executor repeatedly and asserts identical
output. A shape hit with a new literal binding still re-parses (one
miss) but accrues to the same per-shape statistics.

Commit-epoch invalidation: every commit/alter bumps the engine epoch;
an entry stamped with an older epoch is discarded on access. Parse
output is data-independent today, so this is deliberately conservative
— the cache contract is "no plan survives a commit unrevalidated",
which keeps the door open for stats-fed planning decisions to move
into the cached plan without a correctness cliff. Read-heavy steady
state (the serving regime this cache exists for) is unaffected.

Per-shape statistics (hits and a latency EWMA fed by the entry points)
are the admission controller's cost model: a shape that has been
observed slow admits as expensive *before* it runs.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from dgraph_tpu.utils.observe import METRICS
from dgraph_tpu.x import config

# literal token kinds stripped from the shape (dql/parser.py tokenizer)
_LITERAL_KINDS = frozenset({"string", "num", "regex"})
# distinct literal bindings cached per shape before LRU eviction
_VARIANTS_PER_SHAPE = 16
# EWMA weight of the newest cost observation
_COST_ALPHA = 0.2


def normalize(text: str) -> Optional[Tuple[str, Tuple[str, ...]]]:
    """(shape, literals) for a query: the dql token stream with literal
    tokens replaced by `?` (joined with single spaces), plus the raw
    literal texts in source order. None when the text does not lex —
    the caller falls through to a plain parse for the real error."""
    from dgraph_tpu.dql.parser import ParseError, tokenize

    try:
        toks = tokenize(text)
    except ParseError:
        return None
    shape: List[str] = []
    lits: List[str] = []
    for t in toks:
        if t.kind in _LITERAL_KINDS:
            shape.append("?")
            lits.append(t.text)
        elif t.kind != "eof":
            shape.append(t.text)
    return " ".join(shape), tuple(lits)


class ShapeEntry:
    __slots__ = ("epoch", "variants", "hits", "misses", "cost_ms")

    def __init__(self, epoch: int):
        self.epoch = epoch
        # (literals, vars_key) -> parsed blocks
        self.variants: "OrderedDict[tuple, list]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.cost_ms: Optional[float] = None  # latency EWMA


class PlanCache:
    """LRU plan cache + per-shape cost statistics. Thread-safe; nothing
    blocking runs under its lock (parse happens at the call sites)."""

    def __init__(self, size: Optional[int] = None):
        self._lock = threading.Lock()
        self._size = size
        self._shapes: "OrderedDict[str, ShapeEntry]" = OrderedDict()
        self.epoch = 0

    def capacity(self) -> int:
        """Configured shape capacity; 0 = caching (and the per-shape
        cost stats built on it) disabled."""
        if self._size is not None:
            return max(0, int(self._size))
        return max(0, int(config.get("PLAN_CACHE_SIZE")))

    _capacity = capacity  # internal alias

    @staticmethod
    def _vars_key(variables) -> tuple:
        if not variables:
            return ()
        return tuple(sorted((str(k), repr(v)) for k, v in variables.items()))

    # -- lookups -------------------------------------------------------------

    def get(self, shape: str, literals: tuple, variables=None):
        """Cached parsed blocks for this exact binding, or None. Counts
        plan_cache_{hit,miss}_total; epoch-stale entries are dropped."""
        cap = self._capacity()
        vk = self._vars_key(variables)
        with self._lock:
            e = self._shapes.get(shape)
            if e is not None and e.epoch != self.epoch:
                # commit-epoch invalidation: plans don't survive a
                # commit; the shape's cost stats do (they describe the
                # shape, not the snapshot)
                e.variants.clear()
                e.epoch = self.epoch
            if cap == 0 or e is None:
                if e is not None:
                    e.misses += 1
                METRICS.inc("plan_cache_miss_total")
                return None
            self._shapes.move_to_end(shape)
            blocks = e.variants.get((literals, vk))
            if blocks is None:
                e.misses += 1
                METRICS.inc("plan_cache_miss_total")
                return None
            e.variants.move_to_end((literals, vk))
            e.hits += 1
            METRICS.inc("plan_cache_hit_total")
            return blocks

    def put(self, shape: str, literals: tuple, blocks, variables=None):
        cap = self._capacity()
        if cap == 0:
            return
        vk = self._vars_key(variables)
        with self._lock:
            e = self._shapes.get(shape)
            if e is None:
                e = self._shapes[shape] = ShapeEntry(self.epoch)
            elif e.epoch != self.epoch:
                e.variants.clear()
                e.epoch = self.epoch
            self._shapes.move_to_end(shape)
            e.variants[(literals, vk)] = blocks
            e.variants.move_to_end((literals, vk))
            while len(e.variants) > _VARIANTS_PER_SHAPE:
                e.variants.popitem(last=False)
            while len(self._shapes) > cap:
                self._shapes.popitem(last=False)

    # -- invalidation ---------------------------------------------------------

    def invalidate(self) -> None:
        """Bump the commit epoch: every cached plan is stale (dropped
        lazily on next access). Called from the commit and alter paths."""
        with self._lock:
            self.epoch += 1

    # -- statistics (admission's cost model) ----------------------------------

    def observe_cost(self, shape: str, took_ms: float) -> None:
        """Feed one completed execution's latency into the shape's EWMA
        (creates the stats-bearing entry even when plans aren't cached)."""
        with self._lock:
            e = self._shapes.get(shape)
            if e is None:
                cap = self._capacity()
                if cap == 0:
                    return
                e = self._shapes[shape] = ShapeEntry(self.epoch)
                while len(self._shapes) > cap:
                    self._shapes.popitem(last=False)
            if e.cost_ms is None:
                e.cost_ms = float(took_ms)
            else:
                e.cost_ms += _COST_ALPHA * (float(took_ms) - e.cost_ms)

    def estimated_cost_ms(self, shape: Optional[str]) -> Optional[float]:
        if shape is None:
            return None
        with self._lock:
            e = self._shapes.get(shape)
            return None if e is None else e.cost_ms

    def stats(self) -> Dict[str, float]:
        with self._lock:
            return {
                "shapes": len(self._shapes),
                "hits": sum(e.hits for e in self._shapes.values()),
                "misses": sum(e.misses for e in self._shapes.values()),
                "epoch": self.epoch,
            }

"""ServingFront: the per-engine bundle of plan cache, result cache,
micro-batcher, and admission controller.

One instance per engine (api/server.Server, worker/harness.ProcCluster).
The entry points drive it in four places:

    blocks, shape, lits = front.parse(q, variables)  # plan cache
    ticket = front.admit(shape, blocks)         # admission gate (raises)
    ...result-cache probe (shape, lits, watermark), else execute with
       batcher=front.batcher_for(cache)...
    front.finish(ticket, shape, took_ms, slow)  # stats + release

`on_commit()` hooks the engine's commit/alter paths: it bumps the plan
cache epoch so no cached plan survives a commit unrevalidated. The
result cache needs no hook — its keys carry the snapshot watermark,
which every commit/alter advances (serving/resultcache.py).
"""

from __future__ import annotations

from typing import Optional, Tuple

from dgraph_tpu.serving.admission import AdmissionController, Ticket
from dgraph_tpu.serving.microbatch import MicroBatcher, window_us
from dgraph_tpu.serving.plancache import PlanCache, normalize
from dgraph_tpu.serving.resultcache import ResultCache


class ServingFront:
    def __init__(self, stats=None, schema_fn=None, last_commit_fn=None):
        self.plan_cache = PlanCache()
        # snapshot-keyed whole-response reuse (watermark-keyed; off by
        # default via DGRAPH_TPU_RESULT_CACHE_SIZE=0)
        self.results = ResultCache()
        # schema_fn: a getter, so engines that rebind their schema
        # wholesale (drop_all) are always read fresh
        self.admission = AdmissionController(
            plan_cache=self.plan_cache, stats=stats, schema_fn=schema_fn
        )
        # last_commit_fn: the engine's last-commit watermark (published
        # before the commit's apply barrier) — the batcher's snapshot
        # identity; None = exact-ts grouping (never coalesces)
        self.batcher = MicroBatcher(
            inflight_fn=self.admission.inflight_count,
            last_commit_fn=last_commit_fn,
        )

    # -- plan cache -----------------------------------------------------------

    def parse(
        self, q: str, variables=None, info: Optional[dict] = None
    ) -> Tuple[list, Optional[str], Optional[tuple]]:
        """dql.parse through the plan cache. Returns (blocks, shape,
        literals); shape is None when the query doesn't lex (parse
        raises the real error) — such queries bypass both caches. The
        literal tuple is the result cache's binding component (shape +
        literals + variables reconstruct the query modulo whitespace).
        With the plan cache disabled (PLAN_CACHE_SIZE=0) but the
        result cache on, normalization still runs — the result cache
        needs the shape key; with BOTH disabled the second tokenize is
        skipped outright.

        `info`, when given (debug/EXPLAIN requests), is filled with the
        plan-cache outcome: {"hit": bool, "shape": normalized-key,
        "enabled": bool} — the entry point folds it into
        extensions.plan."""
        from dgraph_tpu import dql

        plan_on = self.plan_cache.capacity() > 0
        if not plan_on and self.results.capacity() == 0:
            if info is not None:
                info.update(enabled=False, hit=False, shape=None)
            return dql.parse(q, variables), None, None
        norm = normalize(q)
        if norm is None:
            if info is not None:
                info.update(enabled=plan_on, hit=False, shape=None)
            return dql.parse(q, variables), None, None
        shape, literals = norm
        if not plan_on:
            if info is not None:
                info.update(enabled=False, hit=False, shape=shape)
            return dql.parse(q, variables), shape, literals
        blocks = self.plan_cache.get(shape, literals, variables)
        hit = blocks is not None
        if blocks is None:
            blocks = dql.parse(q, variables)
            self.plan_cache.put(shape, literals, blocks, variables)
        if info is not None:
            info.update(enabled=True, hit=hit, shape=shape)
        return blocks, shape, literals

    # -- result cache ---------------------------------------------------------

    def result_probe(
        self, shape, literals, variables, ns: int, watermark: int,
        debug: bool = False,
    ):
        """Key + lookup for one result-cache-ELIGIBLE query — callers
        gate the entry-point-specific conditions first (no pinned
        read_ts, no ACL, cluster not degraded). Returns (key, raw_hit,
        would_hit): key None when the cache is off, the query didn't
        normalize, or nothing has committed yet; debug probes presence
        WITHOUT serving (EXPLAIN always executes). One implementation
        for both engines so key composition can never drift between
        them."""
        rc = self.results
        if shape is None or not watermark or rc.capacity() == 0:
            return None, None, False
        key = rc.key(
            shape, literals, variables, int(ns), int(watermark),
            epoch=self.plan_cache.epoch,
        )
        if debug:
            return key, None, rc.peek(key)
        return key, rc.get(key), False

    # -- admission ------------------------------------------------------------

    def admit(self, shape: Optional[str], blocks=None) -> Ticket:
        return self.admission.admit(shape, blocks)

    def admit_write(self, n_edges: int) -> Ticket:
        """Admission for the commit path: writes cost tokens out of the
        same in-flight budget queries draw from (raises the retryable
        TooManyRequestsError over budget). Pair with release_write."""
        return self.admission.admit_write(n_edges)

    def release_write(self, ticket: Ticket) -> None:
        self.admission.release(ticket)

    def finish(
        self,
        ticket: Optional[Ticket],
        shape: Optional[str],
        took_ms: float,
        slow: bool = False,
    ) -> None:
        """End-of-query bookkeeping. Callers pass shape=None for
        anything that did NOT run to clean completion (shed, error,
        budget-truncated) — those latencies describe the failure mode,
        not the shape, and would decay the cost EWMA exactly when
        admission depends on it. A degraded-admission query's slowness
        likewise must not refresh the saturation signal that degraded
        it (self-latch), so its `slow` is suppressed."""
        if shape is not None:
            self.plan_cache.observe_cost(shape, took_ms)
        if slow and (ticket is None or not ticket.degrade):
            self.admission.note_slow()
        if ticket is not None:
            self.admission.release(ticket)

    def degrade_budget_s(self) -> float:
        """The bounded time budget a degraded-admission query runs
        under: the slow-query threshold (a degraded query must never
        itself become a slow query)."""
        from dgraph_tpu.x import config

        return max(0.01, float(config.get("SLOW_QUERY_MS")) / 1e3)

    # -- read-plane context ---------------------------------------------------

    def read_context(self):
        """Fresh per-query ReadContext for the resilient read plane:
        ONE RetryBudget (DGRAPH_TPU_READ_RETRY_BUDGET tokens) that every
        group-read retry and hedge of the query draws from, plus the
        leaderless-serving notes the entry point surfaces as the
        `degraded: leaderless` extension. Budget 0 disables budgeting
        (never exhausted)."""
        from dgraph_tpu.conn.retry import RetryBudget
        from dgraph_tpu.worker.remote import ReadContext
        from dgraph_tpu.x import config

        n = int(config.get("READ_RETRY_BUDGET"))
        return ReadContext(budget=RetryBudget(n) if n > 0 else None)

    # -- micro-batcher --------------------------------------------------------

    def batcher_for(self, cache) -> Optional[MicroBatcher]:
        """The batcher, or None when batching is off or this cache is
        ineligible (txn-local deltas make its reads private). Window 0
        must restore today's exact path, so the executor sees no
        batcher at all then."""
        if window_us() <= 0 or cache.deltas:
            return None
        return self.batcher

    # -- invalidation ----------------------------------------------------------

    def on_commit(self) -> None:
        self.plan_cache.invalidate()

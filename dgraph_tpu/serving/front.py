"""ServingFront: the per-engine bundle of plan cache, micro-batcher,
and admission controller.

One instance per engine (api/server.Server, worker/harness.ProcCluster).
The entry points drive it in four places:

    blocks, shape = front.parse(q, variables)   # plan cache
    ticket = front.admit(shape, blocks)         # admission gate (raises)
    ...execute with batcher=front.batcher_for(cache)...
    front.finish(ticket, shape, took_ms, slow)  # stats + release

`on_commit()` hooks the engine's commit/alter paths: it bumps the plan
cache epoch so no cached plan survives a commit unrevalidated.
"""

from __future__ import annotations

from typing import Optional, Tuple

from dgraph_tpu.serving.admission import AdmissionController, Ticket
from dgraph_tpu.serving.microbatch import MicroBatcher, window_us
from dgraph_tpu.serving.plancache import PlanCache, normalize


class ServingFront:
    def __init__(self, stats=None, schema_fn=None, last_commit_fn=None):
        self.plan_cache = PlanCache()
        # schema_fn: a getter, so engines that rebind their schema
        # wholesale (drop_all) are always read fresh
        self.admission = AdmissionController(
            plan_cache=self.plan_cache, stats=stats, schema_fn=schema_fn
        )
        # last_commit_fn: the engine's last-commit watermark (published
        # before the commit's apply barrier) — the batcher's snapshot
        # identity; None = exact-ts grouping (never coalesces)
        self.batcher = MicroBatcher(
            inflight_fn=self.admission.inflight_count,
            last_commit_fn=last_commit_fn,
        )

    # -- plan cache -----------------------------------------------------------

    def parse(
        self, q: str, variables=None, info: Optional[dict] = None
    ) -> Tuple[list, Optional[str]]:
        """dql.parse through the plan cache. Returns (blocks, shape);
        shape is None when the query doesn't lex (parse raises the real
        error) — such queries bypass the cache. With the cache disabled
        (PLAN_CACHE_SIZE=0) the normalization pass — a second full
        tokenize per query — is skipped outright (the shape would feed
        nothing: cost stats are disabled with the cache).

        `info`, when given (debug/EXPLAIN requests), is filled with the
        plan-cache outcome: {"hit": bool, "shape": normalized-key,
        "enabled": bool} — the entry point folds it into
        extensions.plan."""
        from dgraph_tpu import dql

        if self.plan_cache.capacity() == 0:
            if info is not None:
                info.update(enabled=False, hit=False, shape=None)
            return dql.parse(q, variables), None
        norm = normalize(q)
        if norm is None:
            if info is not None:
                info.update(enabled=True, hit=False, shape=None)
            return dql.parse(q, variables), None
        shape, literals = norm
        blocks = self.plan_cache.get(shape, literals, variables)
        hit = blocks is not None
        if blocks is None:
            blocks = dql.parse(q, variables)
            self.plan_cache.put(shape, literals, blocks, variables)
        if info is not None:
            info.update(enabled=True, hit=hit, shape=shape)
        return blocks, shape

    # -- admission ------------------------------------------------------------

    def admit(self, shape: Optional[str], blocks=None) -> Ticket:
        return self.admission.admit(shape, blocks)

    def admit_write(self, n_edges: int) -> Ticket:
        """Admission for the commit path: writes cost tokens out of the
        same in-flight budget queries draw from (raises the retryable
        TooManyRequestsError over budget). Pair with release_write."""
        return self.admission.admit_write(n_edges)

    def release_write(self, ticket: Ticket) -> None:
        self.admission.release(ticket)

    def finish(
        self,
        ticket: Optional[Ticket],
        shape: Optional[str],
        took_ms: float,
        slow: bool = False,
    ) -> None:
        """End-of-query bookkeeping. Callers pass shape=None for
        anything that did NOT run to clean completion (shed, error,
        budget-truncated) — those latencies describe the failure mode,
        not the shape, and would decay the cost EWMA exactly when
        admission depends on it. A degraded-admission query's slowness
        likewise must not refresh the saturation signal that degraded
        it (self-latch), so its `slow` is suppressed."""
        if shape is not None:
            self.plan_cache.observe_cost(shape, took_ms)
        if slow and (ticket is None or not ticket.degrade):
            self.admission.note_slow()
        if ticket is not None:
            self.admission.release(ticket)

    def degrade_budget_s(self) -> float:
        """The bounded time budget a degraded-admission query runs
        under: the slow-query threshold (a degraded query must never
        itself become a slow query)."""
        from dgraph_tpu.x import config

        return max(0.01, float(config.get("SLOW_QUERY_MS")) / 1e3)

    # -- micro-batcher --------------------------------------------------------

    def batcher_for(self, cache) -> Optional[MicroBatcher]:
        """The batcher, or None when batching is off or this cache is
        ineligible (txn-local deltas make its reads private). Window 0
        must restore today's exact path, so the executor sees no
        batcher at all then."""
        if window_us() <= 0 or cache.deltas:
            return None
        return self.batcher

    # -- invalidation ----------------------------------------------------------

    def on_commit(self) -> None:
        self.plan_cache.invalidate()

"""Token-based admission control for the query entry points.

The failure mode admission exists for: offered load crosses the
service capacity, queues build, every query's latency grows without
bound, and the server ends up doing work for clients that have long
since timed out. The policy here is the standard one — bound the work
in flight, shed the excess *fast* with a retryable error, and when the
slow-query signal says the server is already saturated, degrade
(bounded budget, partial response) rather than queue.

Cost model: one admitted query consumes `cost` tokens out of
`DGRAPH_TPU_MAX_INFLIGHT`. Cost is estimated BEFORE execution from
what the serving front already knows:

  - the plan cache's per-shape latency EWMA (a shape observed at 80ms
    admits as 8x the cost of an 10ms shape), normalized so a
    cheap-or-unknown shape costs 1 token;
  - StatsHolder selectivity of the root function (an eq() whose index
    term matches millions of uids is charged more than a point
    lookup) — the same sketch that drives the packed-kernel crossover;
  - real executor backpressure: the exec-worker pool's queue depth
    (query/subgraph.pool_backpressure) is added on top, so admission
    tightens exactly when the pool is the bottleneck instead of
    guessing from counts alone.

Shedding raises TooManyRequestsError (`too_many_requests`) — mapped to
HTTP 429 by the front-ends and marked retryable so clients back off
and retry (conn/retry.retrying_call). Degradation is decided here but
executed by the caller: `Ticket.degrade` tells the entry point to run
with a bounded time budget and return a partial/degraded response on
budget exhaustion (PR 3's partial-result shape) instead of joining the
queue at full budget.

The in-flight gauge is tracked even when admission is off
(DGRAPH_TPU_ADMISSION=0): the micro-batcher uses it to skip the window
when the server is idle.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from dgraph_tpu.utils.observe import METRICS
from dgraph_tpu.x import config

# saturation signal: this many slow queries inside the sliding window
_SLOW_WINDOW_S = 10.0
_SLOW_SATURATED = 5
# pool queue depth at/above which admission degrades new arrivals
_QUEUE_SATURATED = 8


class TooManyRequestsError(Exception):
    """Admission gate refusal: the server is over its in-flight budget.
    Retryable — clients should back off and resend (HTTP 429)."""

    code = "too_many_requests"
    retryable = True


class Ticket:
    __slots__ = ("cost", "degrade")

    def __init__(self, cost: float, degrade: bool):
        self.cost = cost
        self.degrade = degrade


class AdmissionController:
    def __init__(self, plan_cache=None, stats=None, schema_fn=None):
        self._lock = threading.Lock()
        self.plan_cache = plan_cache
        self.stats = stats  # StatsHolder (selectivity sketch)
        # schema GETTER, not the State object: engines rebind their
        # schema wholesale (Server.alter drop_all), and a captured
        # reference would consult the dropped schema forever
        self.schema_fn = schema_fn
        self.inflight_cost = 0.0
        self.inflight = 0
        self._slow_at: deque = deque()  # monotonic stamps of slow queries

    # -- config ---------------------------------------------------------------

    @staticmethod
    def enabled() -> bool:
        return bool(config.get("ADMISSION"))

    @staticmethod
    def max_inflight() -> float:
        return max(1.0, float(config.get("MAX_INFLIGHT")))

    # -- cost estimation ------------------------------------------------------

    def estimate_cost(self, shape: Optional[str], blocks=None) -> float:
        """Tokens this query is expected to consume (>= 1)."""
        cost = 1.0
        if self.plan_cache is not None:
            ms = self.plan_cache.estimated_cost_ms(shape)
            if ms is not None:
                # 10ms of observed latency per token
                cost = max(cost, ms / 10.0)
        if self.stats is not None and blocks:
            try:
                cost += self._selectivity_cost(blocks)
            except Exception:
                pass  # stats are advisory; never fail admission on them
        return cost

    def _selectivity_cost(self, blocks) -> float:
        """Extra tokens from StatsHolder root-function selectivity: eq()
        args are keyed the same way the index feeds the sketch (the
        predicate's own tokenizers), +1 token per 100k estimated uids."""
        from dgraph_tpu.tok.tok import build_tokens
        from dgraph_tpu.types.types import TypeID, Val

        schema = self.schema_fn() if self.schema_fn is not None else None
        extra = 0.0
        for b in blocks:
            fn = getattr(b, "func", None)
            if fn is None or fn.name != "eq" or not fn.attr:
                continue
            su = schema.get(fn.attr) if schema is not None else None
            if su is None:
                continue
            tokenizers = su.tokenizer_objs()
            for a in fn.args:
                if isinstance(a, tuple):
                    continue  # val(x) args have no static selectivity
                try:
                    toks = build_tokens(
                        Val(TypeID.STRING, str(a)), tokenizers
                    )
                except Exception:
                    continue
                n = max(
                    (self.stats.estimate(fn.attr, t) for t in toks),
                    default=0,
                )
                if n:
                    extra += min(64.0, n / 1e5)
        return extra

    # -- saturation signal ----------------------------------------------------

    def note_slow(self) -> None:
        """Called by the entry points when a query crossed the
        slow-query threshold (the slow-query log's signal)."""
        now = time.monotonic()
        with self._lock:
            self._slow_at.append(now)
            while self._slow_at and self._slow_at[0] < now - _SLOW_WINDOW_S:
                self._slow_at.popleft()

    def saturated(self) -> bool:
        """True when the slow-query log or the exec pool's queue says
        the server is already past its comfortable operating point."""
        now = time.monotonic()
        with self._lock:
            while self._slow_at and self._slow_at[0] < now - _SLOW_WINDOW_S:
                self._slow_at.popleft()
            slow = len(self._slow_at)
        if slow >= _SLOW_SATURATED:
            return True
        from dgraph_tpu.query.subgraph import pool_backpressure

        queued, _ = pool_backpressure()
        return queued >= _QUEUE_SATURATED

    # -- the gate -------------------------------------------------------------

    def admit(self, shape: Optional[str], blocks=None) -> Ticket:
        """Admit one query or raise TooManyRequestsError. Always call
        `release(ticket)` in a finally block."""
        cost = self.estimate_cost(shape, blocks)
        enabled = self.enabled()
        # the saturation signal is advisory and reads its own state, so
        # it is sampled OUTSIDE the budget lock; the budget check and
        # the charge happen in ONE lock hold — a burst of concurrent
        # arrivals must not all pass the check before any of them
        # charges (that would blow the budget exactly under the load
        # the gate exists for)
        degrade = enabled and self.saturated()
        self._charge(cost, enabled, "retry with backoff")
        if degrade:
            METRICS.inc("admission_degraded_total")
        return Ticket(cost, degrade)

    def _charge(self, cost: float, enabled: bool, retry_hint: str) -> None:
        """The locked check-and-charge shared by the query and write
        gates: the budget check and the charge happen in ONE lock hold
        (a burst of arrivals must not all pass the check before any of
        them charges)."""
        with self._lock:
            if enabled:
                limit = self.max_inflight()
                if self.inflight_cost + cost > limit and self.inflight > 0:
                    METRICS.inc("admission_shed_total")
                    raise TooManyRequestsError(
                        f"server over in-flight budget "
                        f"({self.inflight_cost:.0f}+{cost:.0f} > "
                        f"{limit:.0f} tokens); {retry_hint}"
                    )
            self.inflight += 1
            self.inflight_cost += cost
            METRICS.set_gauge("admission_inflight_queries", self.inflight)

    # one token per this many postings in a write's delta set (a small
    # txn costs 1 token like a cheap query; a bulk-ish live ingest
    # charges proportionally — writes compete for the same budget
    # instead of riding under the gate while queries are shed)
    _EDGES_PER_TOKEN = 50.0

    def admit_write(self, n_edges: int) -> Ticket:
        """Admit one commit or raise TooManyRequestsError (retryable,
        HTTP 429). Writes charge the SAME in-flight token budget as
        queries: under overload a server that sheds reads but admits
        unlimited mutations just moves the queue to the write path.
        Always call `release(ticket)` in a finally block."""
        cost = max(1.0, float(n_edges) / self._EDGES_PER_TOKEN)
        self._charge(cost, self.enabled(), "retry the commit with backoff")
        return Ticket(cost, False)

    def release(self, ticket: Ticket) -> None:
        with self._lock:
            self.inflight = max(0, self.inflight - 1)
            self.inflight_cost = max(0.0, self.inflight_cost - ticket.cost)
            METRICS.set_gauge("admission_inflight_queries", self.inflight)

    def inflight_count(self) -> int:
        return self.inflight

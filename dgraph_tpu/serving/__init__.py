"""High-QPS serving front: cross-query micro-batching, plan cache,
admission control (ROADMAP open item 3).

Millions of users means thousands of concurrent *small* queries, not one
big one. The per-query machinery below this package (level-batched task
fan-out, zero-decode set-op kernels) made one dispatch cheap and
amortizable; this package amortizes it *across* queries:

  MicroBatcher   — holds concurrent same-shape (predicate, level) tasks
                   from different in-flight queries for a bounded window
                   and coalesces them into ONE vectorized read over a
                   shared ragged (flat_uids, offsets) buffer, demuxing
                   per-query row slices on return (serving/microbatch.py).

  PlanCache      — parsed-query cache keyed on the normalized query
                   shape (dql token stream with literal values stripped),
                   LRU-bounded, commit-epoch invalidated, with per-shape
                   cost statistics that feed admission control
                   (serving/plancache.py).

  AdmissionController — token-based admission gate at the query entry
                   points: tracks in-flight cost, sheds over-limit
                   traffic fast with a retryable too_many_requests
                   error, and degrades (bounded budget + partial
                   response) instead of queueing when the slow-query
                   signal says the server is saturated
                   (serving/admission.py).

  ResultCache    — snapshot-keyed whole-response reuse: wire bytes
                   served from a bounded LRU keyed on (normalized
                   shape, literal bindings, variables, namespace,
                   snapshot watermark, commit epoch) — provably
                   byte-identical until a commit advances the
                   watermark (serving/resultcache.py, PR 15).

  ServingFront   — the per-engine bundle of the four, constructed by
                   api/server.Server and worker/harness.ProcCluster
                   (serving/front.py). Also mints the per-query
                   ReadContext (read_context()) for the resilient read
                   plane: one shared retry/hedge RetryBudget per query
                   plus the leaderless-serving notes that become the
                   `degraded: leaderless` extension (worker/remote.py).
"""

from dgraph_tpu.serving.admission import (  # noqa: F401
    AdmissionController,
    TooManyRequestsError,
)
from dgraph_tpu.serving.front import ServingFront  # noqa: F401
from dgraph_tpu.serving.microbatch import MicroBatcher  # noqa: F401
from dgraph_tpu.serving.plancache import PlanCache, normalize  # noqa: F401
from dgraph_tpu.serving.resultcache import ResultCache  # noqa: F401

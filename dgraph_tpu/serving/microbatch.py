"""Cross-query micro-batcher for (predicate, level) tasks.

Every query level in this engine is already ONE vectorized task (PR 2:
`LocalCache.uids_many` / `values_many` — a single MemoryLayer pass plus
one native decode of the whole level into a ragged `(flat, offsets)`
buffer). Under high QPS many concurrent queries issue the *same-shape*
task — same predicate, same read snapshot — within microseconds of each
other, each paying the fixed dispatch cost (memlayer lock pass, native
call marshaling, decode setup) separately.

The MicroBatcher coalesces them *behind the running dispatch* (the
natural-batching shape, not an artificial delay): a task whose group
key is idle dispatches IMMEDIATELY — zero added latency — while a task
arriving during an in-flight same-key dispatch opens (or joins) the
NEXT batch, which fires as soon as the runner completes (bounded by
`DGRAPH_TPU_BATCH_WINDOW_US`, the cap on how long a batch waits behind
its runner; 0 disables the batcher entirely — callers never reach
submit). Under load, same-shape arrivals therefore pile into combined
dispatches exactly when dispatches are the bottleneck; when the server
is idle nothing ever waits. The batch leader runs ONE combined read
over the concatenation of every member's keys and demuxes per-member
row slices of the shared ragged buffer — row i of a combined
`uids_many` is computed exactly as row i of a solo call, so the
demuxed slices are byte-identical to what each member would have read
alone (the same argument test_parallel_exec.py makes for the worker
pool: a pure performance knob).

Group keys bind members to one read SNAPSHOT, not one read timestamp:
every query allocates a fresh read_ts, so keying on the ts would never
coalesce anything. Instead the engine exposes its last-commit
watermark (`last_commit_fn`, published BEFORE the commit's apply
barrier): two queries whose read timestamps both cover the same
watermark see byte-identical stores — any commit between their
timestamps would have advanced the watermark before either of them got
past the read_ts apply-wait, and any commit after the younger token
read carries a commit_ts above both timestamps (timestamps are
allocated monotonically) and is invisible to both. A watermark ABOVE a
query's read_ts means the snapshot is genuinely ts-dependent; that
query falls back to exact-ts grouping (no coalescing, never
incorrectness). The argument covers only FRESH engine-allocated
timestamps — caller-pinned read_ts queries never receive a batcher at
the entry points — and inherits the oracle's own caveat: a read_ts
issued after the bounded applied-wait gave up (30s, staleness over
deadlock) already reads best-effort; coalescing such queries keeps
them consistent with each other. Only delta-free caches are eligible
(the executor routes txn-snapshot reads around the batcher), so any
member's cache can execute the combined read for all of them.

Locking: two small, strictly-layered domains. The batcher lock guards
the group/runner maps and group MEMBERSHIP (joins, close, snapshot) —
only ever held for pointer work, never across a wait or a read. Each
group owns an independent Condition guarding its RESULT hand-off
(done/go/results/error); every wait happens under that cv with the
batcher lock already released, and wakeups stay scoped to one group's
waiters (a shared condvar was a measurable thundering herd at 16
clients). The combined read — the blocking, native-calling part —
runs under no lock at all, so the lock-discipline analyzer passes this
module with no allowlist entry.

Tracing: the leader wraps the combined read in a `batch_dispatch` span
carrying every member's traceparent as span links (`link.N` attrs).
Each member still records its own `level_task` span under its own
query's trace — one trace per query survives coalescing; the links are
how a coalesced dispatch is attributed to all of its queries.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from dgraph_tpu.utils.observe import METRICS, TRACER, format_traceparent
from dgraph_tpu.x import config


def window_us() -> int:
    """Current batching window (µs); 0 = batcher off. Re-read per call
    so tests and operators can flip it without rebuilding engines."""
    return max(0, int(config.get("BATCH_WINDOW_US")))


class _Group:
    """One open coalescing group. Membership fields (members, contexts,
    closed) are guarded by the BATCHER lock; hand-off fields (done, go,
    results, error) by the group's own `cv` — waiters never hold the
    batcher lock (see the module docstring's locking contract)."""

    __slots__ = (
        "cv", "members", "caches", "contexts", "closed", "done", "go",
        "results", "error",
    )

    def __init__(self):
        self.cv = threading.Condition()
        self.members: List[list] = []  # per-member keys_list
        self.caches: List[object] = []  # per-member LocalCache
        self.contexts: List[Optional[object]] = []  # member trace ctxs
        self.closed = False  # no further joins (leader is dispatching)
        self.done = False  # results/error populated
        self.go = False  # the running dispatch ahead of us finished
        self.results: List[object] = []
        self.error: Optional[BaseException] = None


# members per batch before new arrivals dispatch on their own: a batch
# the width of the whole client population convoys every thread onto
# one dispatch and releases them in a stampede — worse tail latency
# than the dispatch it saved (measured at 16 closed-loop clients)
_MAX_MEMBERS = 4


class MicroBatcher:
    """Behind-the-runner coalescer for level-task reads.

    `inflight_fn` reports the engine's in-flight query count (the
    admission controller's gauge): with zero or one query in flight the
    batcher steps aside entirely (direct path, not even a lock touch
    beyond the count read), so an idle server and `BATCH_WINDOW_US=0`
    behave identically."""

    def __init__(
        self,
        inflight_fn: Optional[Callable[[], int]] = None,
        last_commit_fn: Optional[Callable[[], int]] = None,
    ):
        self._lock = threading.Lock()
        self._pending: Dict[tuple, _Group] = {}
        self._running: Dict[tuple, int] = {}  # key -> dispatches in flight
        self._inflight_fn = inflight_fn
        self._last_commit_fn = last_commit_fn

    def _snapshot_token(self, cache) -> tuple:
        """Snapshot identity of a delta-free cache: the engine's
        last-commit watermark when it is covered by this cache's
        read_ts (see the module docstring for why that is sound), else
        the exact read_ts (sound but never coalesces)."""
        if self._last_commit_fn is not None:
            snap = int(self._last_commit_fn())
            if snap <= cache.read_ts:
                return ("commit", snap)
        return ("ts", cache.read_ts)

    # -- public read API ----------------------------------------------------

    @staticmethod
    def _kv_identity(cache):
        """Store identity for the group key: kvs may advertise a stable
        `coalesce_key` (per-query RemoteKV facades over one cluster are
        read-interchangeable); otherwise object identity."""
        return getattr(cache.kv, "coalesce_key", None) or id(cache.kv)

    def read_uids(self, attr: str, cache, keys_list: list):
        """Coalesced `cache.uids_many(keys_list)`: returns the member's
        own (flat, offsets, toks) slice of the combined level read."""
        key = (
            "uids", attr, self._kv_identity(cache), id(cache.mem),
            self._snapshot_token(cache),
        )
        return self._submit(
            key, cache, keys_list, self._run_uids, self._split_uids
        )

    def read_values(self, attr: str, cache, keys_list: list):
        """Coalesced `cache.values_many(keys_list)`: returns the
        member's aligned postings lists."""
        key = (
            "values", attr, self._kv_identity(cache), id(cache.mem),
            self._snapshot_token(cache),
        )
        return self._submit(
            key, cache, keys_list, self._run_values, self._split_values
        )

    def read_similar(self, attr: str, cache, index, qvec, k: int):
        """Coalesced plain (unfiltered) `similar_to`: concurrent vector
        searches against the same index, same k, same snapshot become
        ONE `index.search_batch` dispatch; each member gets its own row.
        Rows of a batch are scored independently by the same kernels
        (models/vector.py search_one), so the demuxed row is
        byte-identical to the member's solo search — the same argument
        read_uids makes for level reads. k joins the group key (a
        combined dispatch has one k); the snapshot token binds members
        to one store state, which covers the index too: vector-index
        mutations happen at commit apply, behind the same watermark."""
        key = (
            "similar", attr, self._kv_identity(cache), id(index), int(k),
            self._snapshot_token(cache),
        )

        def run(_cache, all_vecs):
            return index.search_batch(np.stack(all_vecs), k)

        def split(combined, spans):
            return [combined[r0:r1] for r0, r1 in spans]

        return self._submit(key, cache, [qvec], run, split)[0]

    # -- combined executors (leader only, lock NOT held) ----------------------

    @staticmethod
    def _run_uids(cache, all_keys: list):
        return cache.uids_many(all_keys)

    @staticmethod
    def _run_values(cache, all_keys: list):
        return cache.values_many(all_keys)

    @staticmethod
    def _split_uids(combined, spans: List[Tuple[int, int]]):
        flat, offs, toks = combined
        out = []
        for r0, r1 in spans:
            base = offs[r0]
            out.append(
                (
                    flat[base : offs[r1]],
                    offs[r0 : r1 + 1] - base,
                    toks[r0:r1],
                )
            )
        return out

    @staticmethod
    def _split_values(combined, spans: List[Tuple[int, int]]):
        return [combined[r0:r1] for r0, r1 in spans]

    # -- core ----------------------------------------------------------------

    @staticmethod
    def _note_plan(members: int) -> None:
        """EXPLAIN capture: this member's coalescing outcome (solo vs
        coalesced dispatch and the batch width) — debug queries only."""
        from dgraph_tpu.utils.observe import current_plan

        plan = current_plan()
        if plan is not None:
            plan.note_microbatch(members)

    def _submit(self, key, cache, keys_list, run, split):
        win = window_us()
        inflight = (
            self._inflight_fn() if self._inflight_fn is not None else 0
        )
        if win <= 0 or inflight <= 1:
            # off switch / nobody to coalesce with: today's direct path
            self._note_plan(1)
            return run(cache, keys_list)
        lead = False
        with self._lock:
            g = self._pending.get(key)
            if (
                g is not None
                and not g.closed
                and len(g.members) < _MAX_MEMBERS
            ):
                # a batch is already forming behind the running
                # dispatch: join it (membership under the batcher
                # lock), then wait for its leader on the group cv
                idx = len(g.members)
                g.members.append(keys_list)
                g.caches.append(cache)
                g.contexts.append(TRACER.current_context())
            elif g is not None or not self._running.get(key):
                # idle key — dispatch IMMEDIATELY, the batcher adds
                # zero latency when there is nothing to coalesce with —
                # or the forming batch is already full: dispatch alone
                # rather than grow the convoy (correct either way;
                # dispatches for one key may overlap freely)
                self._running[key] = self._running.get(key, 0) + 1
                g = None
            else:
                # a same-key dispatch is in flight: open the next batch
                # and lead it; it fires the moment the runner completes
                # (the window only caps how long we wait for that)
                lead = True
                g = _Group()
                g.members.append(keys_list)
                g.caches.append(cache)
                g.contexts.append(TRACER.current_context())
                self._pending[key] = g
        if g is not None and not lead:
            # follower: batcher lock released; wait on the group cv —
            # but never past the follower's OWN ambient deadline (a
            # stalled leader must not convert a tight-deadline query
            # into a full-budget one). On expiry, bail out to a solo
            # read at the same snapshot; the group slice is ignored.
            from dgraph_tpu.conn.retry import current_deadline

            dl = current_deadline()
            bailed = False
            with g.cv:
                while not g.done:
                    if dl is not None and dl.expired():
                        bailed = True
                        break
                    g.cv.wait(
                        timeout=(
                            None
                            if dl is None
                            else max(0.001, min(0.05, dl.remaining()))
                        )
                    )
            if bailed:
                self._note_plan(1)
                return run(cache, keys_list)
            if g.error is not None:
                # the LEADER failed (its deadline, its RPC fault) — that
                # must not fail healthy members that would have
                # succeeded solo; re-read alone at the same snapshot
                # and let any genuine store error surface as our own
                self._note_plan(1)
                return run(cache, keys_list)
            self._note_plan(len(g.results))
            return g.results[idx]
        if g is not None:
            # batch leader: wait (bounded) for the runner ahead of us,
            # then close the group and take over the key
            end = time.monotonic() + win / 1e6
            with g.cv:
                while not g.go:
                    remaining = end - time.monotonic()
                    if remaining <= 0:
                        break
                    g.cv.wait(timeout=remaining)
            with self._lock:
                g.closed = True
                if self._pending.get(key) is g:
                    del self._pending[key]
                self._running[key] = self._running.get(key, 0) + 1
                members = list(g.members)
        try:
            if g is None:
                self._note_plan(1)
                return run(cache, keys_list)
            spans: List[Tuple[int, int]] = []
            row = 0
            for m in members:
                spans.append((row, row + len(m)))
                row += len(m)
            all_keys = [k for m in members for k in m]
            # partial-read degradation (PR 3) must reach every member:
            # the combined read runs on the LEADER's kv, so any group
            # it finds unreachable is copied to the other members' kvs
            # before their entry points inspect degraded_groups
            lead_dg = getattr(cache.kv, "degraded_groups", None)
            pre_dg = set(lead_dg) if lead_dg is not None else set()
            try:
                if len(members) > 1:
                    METRICS.inc("batch_coalesced_total", len(members))
                    attrs = {
                        "members": len(members), "rows": len(all_keys)
                    }
                    for i, ctx in enumerate(g.contexts):
                        if ctx is not None:
                            attrs[f"link.{i}"] = format_traceparent(ctx)
                    with TRACER.span("batch_dispatch", **attrs):
                        combined = run(cache, all_keys)
                else:
                    combined = run(cache, all_keys)
                results = split(combined, spans)
                if lead_dg is not None:
                    new_dg = set(lead_dg) - pre_dg
                    if new_dg:
                        for mc in g.caches:
                            mdg = getattr(
                                mc.kv, "degraded_groups", None
                            )
                            if mdg is not None and mc.kv is not cache.kv:
                                mdg.update(new_dg)
            except BaseException as exc:
                with g.cv:
                    g.error = exc
                    g.done = True
                    g.cv.notify_all()
                raise
            with g.cv:
                g.results = results
                g.done = True
                g.cv.notify_all()
            self._note_plan(len(members))
            return results[0]
        finally:
            # hand the key to the batch that formed behind us
            with self._lock:
                n = self._running.get(key, 1) - 1
                if n > 0:
                    self._running[key] = n
                else:
                    self._running.pop(key, None)
                nxt = self._pending.get(key)
                if nxt is not None and nxt.closed:
                    nxt = None
            if nxt is not None:
                with nxt.cv:
                    if not nxt.go:
                        nxt.go = True
                        nxt.cv.notify_all()

"""Raft-replicated Zero: the coordinator as a consensus state machine.

Mirrors /root/reference/dgraph/cmd/zero (raft-backed Zero quorum:
zero/raft.go applies proposals to the shared state, oracle.go decides
commits, assign.go leases in blocks, zero.go:680 ShouldServe assigns
tablets): every coordinator decision — timestamp/uid leases, tablet
assignment, commit-or-abort — is a raft proposal applied deterministically
on every Zero replica, so the cluster survives Zero crashes and restarts
with no lost leases or split-brain commit decisions.

The state machine is deterministic: `commit` re-runs conflict detection
inside apply, so every replica reaches the same verdict. The client-side
wrapper (`ReplicatedZero`) keeps the ZeroLite interface (begin_txn /
read_ts / commit(track)/applied / assign_uids), leasing timestamps in
blocks (assign.go's lease batching) so the common path doesn't pay one
consensus round per timestamp.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from dgraph_tpu.conn.retry import poll_policy
from dgraph_tpu.raft.raft import RaftNode
from dgraph_tpu.zero.zero import TxnConflictError


class ZeroStateMachine:
    """Deterministic coordinator state, mutated only by raft apply."""

    def __init__(self):
        self.max_ts = 0
        self.max_uid = 1
        self.commits: Dict[int, int] = {}  # conflict fp -> commit_ts
        self.aborted: Set[int] = set()
        self.tablets: Dict[str, int] = {}
        # in-flight tablet moves: pred -> {src, dst, phase, read_ts}.
        # The durable move journal (worker/tabletmove.py): every phase
        # transition is a raft op, so a coordinator death at any
        # boundary leaves a recoverable record in the quorum.
        self.moves: Dict[str, dict] = {}
        self.n_groups = 1
        # proposal results keyed by (proposer, req_id): the proposing
        # node's wrapper reads its own result after apply
        self.results: Dict[Tuple[int, int], object] = {}
        # start_ts -> final commit/abort verdict. A txn's verdict is
        # decided EXACTLY once: a commit op re-proposed through a
        # different server (e.g. the first server applied it but timed
        # out waiting, so the client retried elsewhere with a fresh
        # request id) returns the recorded verdict instead of re-running
        # conflict detection — which could flip commit into abort.
        self.txn_verdicts: Dict[int, tuple] = {}

    def apply(self, op: tuple):
        kind = op[0]
        if kind == "config":
            self.n_groups = int(op[1])
            return None
        _, proposer, req_id, *args = op
        key = (proposer, req_id)
        if key in self.results:
            # a client that re-proposed across a leader change: the first
            # committed copy decided; re-applying (e.g. a commit op) would
            # flip the verdict (dedup, ref zero proposal keys)
            return self.results[key]
        out = self._apply_inner(kind, args)
        self.results[(proposer, req_id)] = out
        # bound the results map: entries are read once by the proposer
        if len(self.results) > 10_000:
            self.results.clear()
        return out

    def _apply_inner(self, kind: str, args):
        if kind == "lease_ts":
            (count,) = args
            first = self.max_ts + 1
            self.max_ts += count
            return first
        if kind == "lease_uid":
            (count,) = args
            first = self.max_uid + 1
            self.max_uid += count
            return first
        if kind == "commit":
            start_ts, cks = args
            return self._commit_one(start_ts, cks)
        if kind == "commit_batch":
            # ONE consensus round deciding N txns, verdicts per member
            # (an aborted member never fails its batchmates). Members
            # decide in list order — the serial order back-to-back
            # "commit" ops would have produced — and each verdict is
            # recorded in txn_verdicts, so a member re-proposed solo
            # (or in a different batch) after a lost ack replays its
            # original verdict instead of re-running conflict checks.
            (batch,) = args
            items = batch["b"] if isinstance(batch, dict) else batch
            return [
                self._commit_one(int(start_ts), cks)
                for start_ts, cks in items
            ]
        if kind == "abort":
            (start_ts,) = args
            self.aborted.add(start_ts)
            self.txn_verdicts.setdefault(start_ts, ("abort", 0))
            return ("ok",)
        if kind == "tablet":
            (pred,) = args
            gid = self.tablets.get(pred)
            if gid is None:
                load = {g: 0 for g in range(1, self.n_groups + 1)}
                for g in self.tablets.values():
                    load[g] = load.get(g, 0) + 1
                gid = min(load, key=lambda g: (load[g], g))
                self.tablets[pred] = gid
            return gid
        if kind == "move_tablet":
            pred, gid = args
            self.tablets[pred] = int(gid)
            return ("ok",)
        if kind == "move_begin":
            pred, src, dst, read_ts = args
            self.moves[pred] = {
                "src": int(src), "dst": int(dst),
                "phase": "copy", "read_ts": int(read_ts),
            }
            return ("ok",)
        if kind == "move_fence":
            (pred,) = args
            m = self.moves.get(pred)
            if m is not None and m["phase"] == "copy":
                self.moves[pred] = dict(m, phase="fence")
            return ("ok",)
        if kind == "move_flip":
            # the atomic ownership change: tablets[pred]=dst and the
            # journal advancing to the drop phase land in ONE apply
            # (idempotent: recovery re-asserts it)
            (pred,) = args
            m = self.moves.get(pred)
            if m is not None:
                self.tablets[pred] = int(m["dst"])
                self.moves[pred] = dict(m, phase="drop")
            return ("ok",)
        if kind == "move_clear":
            (pred,) = args
            self.moves.pop(pred, None)
            return ("ok",)
        if kind == "moves":
            # linearizable journal read: riding the raft log means the
            # answer reflects every committed transition — recovery
            # decisions from a lagging follower's state could roll
            # back a move whose flip already committed
            return {p: dict(m) for p, m in self.moves.items()}
        if kind == "gc":
            (floor,) = args
            for ck in [c for c, ts in self.commits.items() if ts <= floor]:
                del self.commits[ck]
            self.aborted = {t for t in self.aborted if t >= floor}
            self.txn_verdicts = {
                t: v for t, v in self.txn_verdicts.items() if t >= floor
            }
            return ("ok",)
        raise ValueError(f"unknown zero op {kind!r}")

    def _commit_one(self, start_ts: int, cks) -> tuple:
        """Deterministic per-txn commit-or-abort (shared by the solo
        and batched ops)."""
        prior = self.txn_verdicts.get(start_ts)
        if prior is not None:
            return prior
        if start_ts in self.aborted:
            return ("abort", 0)
        for ck in cks:
            if self.commits.get(ck, 0) > start_ts:
                self.aborted.add(start_ts)
                return self._record_verdict(
                    start_ts, ("abort", self.commits[ck])
                )
        self.max_ts += 1
        for ck in cks:
            self.commits[ck] = self.max_ts
        return self._record_verdict(start_ts, ("commit", self.max_ts))

    def _record_verdict(self, start_ts: int, verdict: tuple) -> tuple:
        self.txn_verdicts[start_ts] = verdict
        if len(self.txn_verdicts) > 20_000:
            # deterministic bound (applied at the same op on every
            # replica): keep the newest half by start_ts
            cut = sorted(self.txn_verdicts)[len(self.txn_verdicts) // 2]
            self.txn_verdicts = {
                t: v for t, v in self.txn_verdicts.items() if t >= cut
            }
        return verdict

    # -- snapshot support ----------------------------------------------------

    def dump(self) -> bytes:
        import pickle

        return pickle.dumps(
            (
                self.max_ts,
                self.max_uid,
                self.commits,
                self.aborted,
                self.tablets,
                self.n_groups,
                self.txn_verdicts,
                self.moves,
            )
        )

    def load(self, blob: bytes):
        import pickle

        state = pickle.loads(blob)
        (
            self.max_ts,
            self.max_uid,
            self.commits,
            self.aborted,
            self.tablets,
            self.n_groups,
        ) = state[:6]
        # snapshots from before verdict dedup carry 6 fields; before
        # the move journal, 7
        self.txn_verdicts = state[6] if len(state) > 6 else {}
        self.moves = state[7] if len(state) > 7 else {}
        self.results = {}


class ZeroReplica:
    """One Zero raft member: state machine + raft node."""

    def __init__(self, node_id: int, peer_ids: List[int], net, wal=None,
                 compact_every: int = 0):
        self.id = node_id
        self.net = net
        self.sm = ZeroStateMachine()
        net.register(node_id)
        self.raft = RaftNode(
            node_id,
            peer_ids,
            net,
            lambda idx, data: self.sm.apply(tuple(data)),
            wal=wal,
            snapshot_cb=self.sm.dump,
            restore_cb=lambda blob, idx: self.sm.load(blob),
            compact_every=compact_every,
        )


class ReplicatedZero:
    """ZeroLite-compatible client over a quorum of ZeroReplica nodes.

    Timestamps lease in blocks (TS_BLOCK) from consensus and are handed
    out locally; every other decision (uids, commits, tablets) is one
    proposal. The read_ts visibility barrier (pending commits) is
    client-side volatile state, exactly like the oracle's MaxAssigned
    wait — it gates reads, not safety."""

    TS_BLOCK = 128

    def __init__(self, replicas: List[ZeroReplica], pump=None):
        self.replicas = replicas
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._req_id = 0
        self._ts_next = 0
        self._ts_end = -1  # exhausted
        # highest commit_ts this client observed: block remnants below it
        # are stale for snapshot purposes (a "fresh" ts must order after
        # every acknowledged commit, like Zero's Timestamps() contract)
        self._floor = 0
        self._active: Set[int] = set()
        self._pending: Set[int] = set()
        self._client_id = 10_000 + id(self) % 10_000

    # -- consensus plumbing --------------------------------------------------

    def _leader(self, timeout: float = 5.0) -> ZeroReplica:
        deadline = time.time() + timeout
        poll = poll_policy(0.002)
        while time.time() < deadline:
            down = getattr(self.replicas[0].net, "down", set())
            live = [
                r
                for r in self.replicas
                if r.raft.is_leader() and r.id not in down
            ]
            if live:
                # highest term wins: a partitioned stale leader lingers
                # until it hears the new term
                return max(live, key=lambda r: r.raft.term)
            poll.sleep(1)
        raise TimeoutError("no zero leader")

    def _propose(self, kind: str, *args, timeout: float = 10.0):
        """Propose and wait until OUR replica set applies it; read the
        deterministic result from the leader's state machine."""
        with self._lock:
            self._req_id += 1
            rid = self._req_id
        op = (kind, self._client_id, rid, *args)
        key = (self._client_id, rid)
        deadline = time.time() + timeout
        while time.time() < deadline:
            leader = self._leader(timeout=max(0.01, deadline - time.time()))
            if not leader.raft.propose(op):
                continue
            # bounded wait per attempt: if leadership flips mid-flight we
            # re-propose; the state machine dedups by (client, req_id)
            attempt_end = min(deadline, time.time() + 1.5)
            apply_poll = poll_policy(0.001)
            while time.time() < attempt_end:
                if key in leader.sm.results:
                    return leader.sm.results[key]
                # the op may have committed via a NEW leader
                for r in self.replicas:
                    if key in r.sm.results and r.raft.is_leader():
                        return r.sm.results[key]
                apply_poll.sleep(1)
        raise TimeoutError(f"zero proposal {kind} timed out")

    # -- ZeroLite interface --------------------------------------------------

    def next_ts(self, count: int = 1) -> int:
        with self._lock:
            if (
                count == 1
                and self._ts_next <= self._ts_end
                and self._ts_next > self._floor
            ):
                ts = self._ts_next
                self._ts_next += 1
                return ts
        if count == 1:
            first = self._propose("lease_ts", self.TS_BLOCK)
            with self._lock:
                self._ts_next = first + 1
                self._ts_end = first + self.TS_BLOCK - 1
                return first
        return self._propose("lease_ts", count)

    def begin_txn(self) -> int:
        # waits out in-flight commits below the start ts, like
        # read_ts(): a txn snapshot must be complete or SSI misses the
        # lost update (see zero/zero.py begin_txn)
        from dgraph_tpu.zero.zero import wait_applied_below

        ts = self.next_ts()
        with self._cv:
            self._active.add(ts)
            wait_applied_below(self._cv, self._pending, ts)
        return ts

    def read_ts(self) -> int:
        from dgraph_tpu.zero.zero import wait_applied_below

        ts = self.next_ts()
        with self._cv:
            wait_applied_below(self._cv, self._pending, ts)
        return ts

    def assign_uids(self, count: int) -> int:
        return self._propose("lease_uid", count)

    @property
    def max_assigned(self) -> int:
        try:
            return self._leader(timeout=1.0).sm.max_ts
        except TimeoutError:
            return max(r.sm.max_ts for r in self.replicas)

    @property
    def _max_uid(self) -> int:
        try:
            return self._leader(timeout=1.0).sm.max_uid
        except TimeoutError:
            return max(r.sm.max_uid for r in self.replicas)

    def commit(self, start_ts: int, conflict_keys, track: bool = False) -> int:
        verdict = self._propose("commit", start_ts, sorted(conflict_keys))
        with self._lock:
            self._active.discard(start_ts)
        if verdict[0] == "abort":
            with self._lock:
                self._floor = max(self._floor, verdict[1])
            raise TxnConflictError(
                f"conflict (committed at {verdict[1]} > start {start_ts})"
            )
        commit_ts = verdict[1]
        with self._lock:
            self._floor = max(self._floor, commit_ts)
            if track:
                self._pending.add(commit_ts)
        # opportunistic conflict-map GC below the oldest active txn
        with self._lock:
            floor = min(self._active) if self._active else None
        if floor is not None:
            try:
                self._propose("gc", floor, timeout=1.0)
            except TimeoutError:
                pass
        return commit_ts

    def commit_batch(self, items, track: bool = False):
        """ONE consensus round deciding N txns (group commit): returns
        a ("commit", ts) / ("abort", last_ts) verdict per member in
        order — an aborted member never fails its batchmates."""
        payload = [
            [int(s), sorted(int(c) for c in cks)] for s, cks in items
        ]
        verdicts = self._propose("commit_batch", {"b": payload})
        with self._lock:
            for (s, _), v in zip(items, verdicts):
                self._active.discard(int(s))
                if int(v[1]):
                    self._floor = max(self._floor, int(v[1]))
                if v[0] == "commit" and track:
                    self._pending.add(int(v[1]))
            floor = min(self._active) if self._active else None
        if floor is not None:
            try:
                self._propose("gc", floor, timeout=1.0)
            except TimeoutError:
                pass
        return [tuple(v) for v in verdicts]

    def applied(self, commit_ts: int):
        with self._cv:
            self._pending.discard(commit_ts)
            self._cv.notify_all()

    def abort(self, start_ts: int):
        with self._lock:
            self._active.discard(start_ts)
        try:
            self._propose("abort", start_ts, timeout=2.0)
        except TimeoutError:
            pass  # aborts are advisory bookkeeping

    # -- tablet ops (ZeroService face) ---------------------------------------

    def should_serve(self, pred: str) -> int:
        return int(self._propose("tablet", pred))

    def move_tablet(self, pred: str, gid: int):
        self._propose("move_tablet", pred, gid)

    # -- move journal (worker/tabletmove.py phase driver) --------------------

    def move_begin(self, pred: str, src: int, dst: int, read_ts: int):
        self._propose("move_begin", pred, int(src), int(dst), int(read_ts))

    def move_fence(self, pred: str):
        self._propose("move_fence", pred)

    def move_flip(self, pred: str):
        self._propose("move_flip", pred)

    def move_clear(self, pred: str):
        self._propose("move_clear", pred)

    @property
    def moves(self) -> Dict[str, dict]:
        # journal reads drive DESTRUCTIVE recovery decisions, so they
        # go through consensus like the writes — never a follower's
        # possibly-stale state machine
        return dict(self._propose("moves"))

    @property
    def tablets(self) -> Dict[str, int]:
        try:
            return dict(self._leader(timeout=1.0).sm.tablets)
        except TimeoutError:
            return dict(self.replicas[0].sm.tablets)

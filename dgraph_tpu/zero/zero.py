"""Zero-lite: timestamp/UID leasing and the transaction oracle.

Single-process implementation of the five operations the reference
abstracts behind the ZeroHooks seam for embedded deployments
(/root/reference/hooks/config.go:23): lease timestamps, lease UIDs,
commit-or-abort with conflict detection, namespace ids, membership.
The distributed Zero service (Raft-replicated, delta streams —
ref dgraph/cmd/zero/oracle.go) builds on the same core in parallel/.

Conflict rule (ref dgraph/cmd/zero/oracle.go:72 hasConflict): a txn T
commits iff no conflict-key it writes was committed by another txn with
commit_ts in (T.start_ts, now]. SSI at predicate+entity granularity via
key fingerprints.

Visibility rule (ref worker/oracle MaxAssigned / WaitForTs): a commit_ts
is handed out *before* its deltas are written; a reader leasing a fresh
read_ts must not observe a gap where commit_ts < read_ts but the deltas
are not yet in the KV. `commit()` therefore records the ts as pending and
`read_ts()` blocks until every pending commit below it is `applied()`.

Conflict-state GC (ref dgraph/cmd/zero/oracle.go:125 purgeBelow): the
fingerprint->commit_ts map is purged below the minimum active start ts.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Set


class TxnConflictError(Exception):
    """Transaction aborted due to write conflict (ref x/error ErrConflict)."""


def wait_applied_below(cv, pending, ts: int, deadline: float = 30.0) -> None:
    """Block — with `cv` HELD by the caller — until every pending
    commit below `ts` has applied its deltas, or the bound expires (a
    crashed writer costs staleness, never a deadlock). ONE
    implementation for the begin_txn/read_ts visibility barriers of
    all three oracle clients (ZeroLite, ReplicatedZero, RemoteZero)."""
    import time as _t

    while pending and min(pending) < ts and deadline > 0:
        t0 = _t.monotonic()
        cv.wait(timeout=min(1.0, deadline))
        deadline -= _t.monotonic() - t0


class ZeroLite:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._max_ts = 0
        self._max_uid = 1  # uid 0 invalid, uid 1 reserved (ref assign.go)
        # conflict key fingerprint -> last commit_ts
        self._commits: Dict[int, int] = {}
        self._aborted: Set[int] = set()
        # start_ts of open (registered) transactions — GC watermark
        self._active: Set[int] = set()
        # commit_ts assigned but whose deltas are not yet applied to the KV
        self._pending: Set[int] = set()

    # -- leases (ref dgraph/cmd/zero/assign.go:69 lease) ---------------------

    def next_ts(self, count: int = 1) -> int:
        """Lease `count` timestamps; returns the first."""
        with self._lock:
            first = self._max_ts + 1
            self._max_ts += count
            return first

    def begin_txn(self) -> int:
        """Lease a start ts and register the txn as active (for conflict-map
        GC). Pair with commit()/abort().

        Like read_ts(), WAITS until every commit below the leased ts has
        applied its deltas (ref worker/oracle WaitForTs on a txn's start
        ts): a txn reading at a start ts that predates an in-flight
        commit's WRITES but postdates its commit_ts would read a stale
        snapshot that SSI cannot catch — its conflict check compares
        against commit timestamps BELOW its start, so the lost update
        would commit. The group-commit pipeline widens that in-flight
        window enough to hit in practice (bank-suite verified)."""
        with self._cv:
            self._max_ts += 1
            ts = self._max_ts
            self._active.add(ts)
            wait_applied_below(self._cv, self._pending, ts)
            return ts

    def read_ts(self) -> int:
        """A fresh read timestamp (linearizable read point): waits until all
        commits below it have had their deltas applied, so the snapshot at
        this ts is complete (ref worker/oracle.go WaitForTs). The wait is
        bounded — a crashed writer costs staleness, never a deadlock."""
        with self._cv:
            self._max_ts += 1
            ts = self._max_ts
            wait_applied_below(self._cv, self._pending, ts)
            return ts

    def assign_uids(self, count: int) -> int:
        """Lease `count` uids; returns the first (ref assign.go:176)."""
        with self._lock:
            first = self._max_uid + 1
            self._max_uid += count
            return first

    @property
    def max_assigned(self) -> int:
        return self._max_ts

    # -- commit (ref dgraph/cmd/zero/oracle.go:421 CommitOrAbort) ------------

    def commit(self, start_ts: int, conflict_keys, track: bool = False) -> int:
        """Returns commit_ts, or raises TxnConflictError. With track=True the
        commit is registered as pending and the caller MUST call
        applied(commit_ts) once deltas are written (fresh readers block on
        it); track=False is for single-writer callers that write deltas
        before any reader can observe the ts."""
        with self._lock:
            self._active.discard(start_ts)
            for ck in conflict_keys:
                last = self._commits.get(ck, 0)
                if last > start_ts:
                    self._aborted.add(start_ts)
                    self._gc_locked()
                    raise TxnConflictError(
                        f"conflict on key fingerprint {ck:#x} "
                        f"(committed at {last} > start {start_ts})"
                    )
            self._max_ts += 1
            commit_ts = self._max_ts
            for ck in conflict_keys:
                self._commits[ck] = commit_ts
            if track:
                self._pending.add(commit_ts)
            self._gc_locked()
            return commit_ts

    def commit_batch(self, items, track: bool = False):
        """Batched commit-or-abort: ONE oracle exchange for N members.
        `items` is [(start_ts, conflict_keys), ...]; returns a verdict
        per member — ("commit", commit_ts) or ("abort", last_commit_ts)
        — so one aborted member never fails its batchmates. Members are
        decided in list order under one lock hold, which is exactly the
        serial order the per-txn path would have produced: an earlier
        member's commit aborts a later same-key member whose start_ts
        predates it, just as back-to-back commit() calls would."""
        out = []
        with self._lock:
            for start_ts, conflict_keys in items:
                self._active.discard(start_ts)
                last = 0
                for ck in conflict_keys:
                    got = self._commits.get(ck, 0)
                    if got > start_ts:
                        last = got
                        break
                if last:
                    self._aborted.add(start_ts)
                    out.append(("abort", last))
                    continue
                self._max_ts += 1
                commit_ts = self._max_ts
                for ck in conflict_keys:
                    self._commits[ck] = commit_ts
                if track:
                    self._pending.add(commit_ts)
                out.append(("commit", commit_ts))
            self._gc_locked()
        return out

    def applied(self, commit_ts: int):
        """Deltas for commit_ts are in the KV; unblock readers."""
        with self._cv:
            self._pending.discard(commit_ts)
            self._cv.notify_all()

    def abort(self, start_ts: int):
        with self._lock:
            self._aborted.add(start_ts)
            self._active.discard(start_ts)
            self._gc_locked()

    def _gc_locked(self):
        """Purge conflict state below the oldest active txn's start ts
        (ref zero/oracle.go purgeBelow): an entry with commit_ts <= every
        active start_ts can never abort anyone again. Only runs when at
        least one txn is registered — with an empty registry we cannot
        know whether an unregistered reader/writer (low-level next_ts
        users) still needs the entries."""
        if not self._active:
            return
        floor = min(self._active)
        if self._commits:
            for ck in [ck for ck, cts in self._commits.items() if cts <= floor]:
                del self._commits[ck]
        if self._aborted:
            self._aborted = {ts for ts in self._aborted if ts >= floor}

"""Zero-lite: timestamp/UID leasing and the transaction oracle.

Single-process implementation of the five operations the reference
abstracts behind the ZeroHooks seam for embedded deployments
(/root/reference/hooks/config.go:23): lease timestamps, lease UIDs,
commit-or-abort with conflict detection, namespace ids, membership.
The distributed Zero service (Raft-replicated, delta streams —
ref dgraph/cmd/zero/oracle.go) builds on the same core in parallel/.

Conflict rule (ref dgraph/cmd/zero/oracle.go:72 hasConflict): a txn T
commits iff no conflict-key it writes was committed by another txn with
commit_ts in (T.start_ts, now]. SSI at predicate+entity granularity via
key fingerprints.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Set


class TxnConflictError(Exception):
    """Transaction aborted due to write conflict (ref x/error ErrConflict)."""


class ZeroLite:
    def __init__(self):
        self._lock = threading.Lock()
        self._max_ts = 0
        self._max_uid = 1  # uid 0 invalid, uid 1 reserved (ref assign.go)
        # conflict key fingerprint -> last commit_ts
        self._commits: Dict[int, int] = {}
        self._aborted: Set[int] = set()

    # -- leases (ref dgraph/cmd/zero/assign.go:69 lease) ---------------------

    def next_ts(self, count: int = 1) -> int:
        """Lease `count` timestamps; returns the first."""
        with self._lock:
            first = self._max_ts + 1
            self._max_ts += count
            return first

    def read_ts(self) -> int:
        """A fresh read timestamp (linearizable read point)."""
        return self.next_ts()

    def assign_uids(self, count: int) -> int:
        """Lease `count` uids; returns the first (ref assign.go:176)."""
        with self._lock:
            first = self._max_uid + 1
            self._max_uid += count
            return first

    @property
    def max_assigned(self) -> int:
        return self._max_ts

    # -- commit (ref dgraph/cmd/zero/oracle.go:421 CommitOrAbort) ------------

    def commit(self, start_ts: int, conflict_keys) -> int:
        """Returns commit_ts, or raises TxnConflictError."""
        with self._lock:
            for ck in conflict_keys:
                last = self._commits.get(ck, 0)
                if last > start_ts:
                    self._aborted.add(start_ts)
                    raise TxnConflictError(
                        f"conflict on key fingerprint {ck:#x} "
                        f"(committed at {last} > start {start_ts})"
                    )
            self._max_ts += 1
            commit_ts = self._max_ts
            for ck in conflict_keys:
                self._commits[ck] = commit_ts
            return commit_ts

    def abort(self, start_ts: int):
        with self._lock:
            self._aborted.add(start_ts)

"""Coordinator-side client for an OS-process Zero quorum.

Same ZeroLite-compatible face as zero/replicated.ReplicatedZero, but the
quorum members are zero_process.py servers reached over conn/rpc —
leases, commit verdicts and tablet decisions are zero.exec RPCs routed to
the Zero leader with not-leader retry (ref the alphas' Zero gRPC client,
worker/zero.go).
"""

from __future__ import annotations

import threading
import json
import time
from typing import Dict, List, Optional, Set, Tuple

from dgraph_tpu.conn.retry import RetryPolicy, effective_deadline
from dgraph_tpu.conn.rpc import RpcError, RpcPool
from dgraph_tpu.zero.zero import TxnConflictError


class RemoteZero:
    TS_BLOCK = 128
    retry = RetryPolicy(base=0.02, cap=0.5)

    def __init__(self, rpc_addrs: List[Tuple[str, int]], pool: RpcPool):
        self.addrs = [tuple(a) for a in rpc_addrs]
        self.pool = pool
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._ts_next = 0
        self._ts_end = -1
        self._floor = 0
        self._active: Set[int] = set()
        self._pending: Set[int] = set()
        self._leader: Optional[Tuple[str, int]] = None

    # -- rpc plumbing --------------------------------------------------------

    def _state(self, addr) -> dict:
        got = self.pool.call(addr, "zero.state", timeout=2.0)
        return json.loads(got.state_json)

    def _exec(self, kind: str, *args, timeout: float = 15.0, batch=None):
        """Leader-routed Zero op. Runs under the ambient deadline (see
        conn/retry.py), retries with full-jitter backoff instead of a
        fixed 50ms sleep, and sends `idem=True`: a reconnect-and-resend
        of a lease/commit/abort dedupes in the server's idempotency LRU
        rather than re-proposing (a double-applied commit could flip a
        verdict; a double-applied lease leaks a block). `batch` carries
        the typed ZeroCommitBatch body of the batched commit op."""
        dl = effective_deadline(timeout)
        last = "no zero leader"
        attempt = 0
        while not dl.expired():
            order = (
                [self._leader] + [a for a in self.addrs if a != self._leader]
                if self._leader
                else list(self.addrs)
            )
            wait_s = dl.clamp(5.0, floor=0.1)
            for addr in order:
                try:
                    from dgraph_tpu.conn.messages import ZeroExec

                    out = self.pool.call(
                        addr,
                        "zero.exec",
                        ZeroExec(
                            op=kind,
                            args_json=json.dumps(
                                {"args": list(args), "timeout": wait_s}
                            ).encode(),
                            commit_batch=batch,
                        ),
                        timeout=wait_s + 3.0,
                        idem=True,
                        deadline=dl,
                    )
                except RpcError as e:
                    last = str(e)
                    continue
                if out.get("ok"):
                    self._leader = addr
                    return out["result"]
                if out.get("not_leader"):
                    self._leader = None
                last = f"{addr}: {out}"
            attempt += 1
            self.retry.sleep(attempt, dl)
        raise TimeoutError(f"zero.exec {kind} failed: {last}")

    # -- ZeroLite face -------------------------------------------------------

    def next_ts(self, count: int = 1) -> int:
        with self._lock:
            if (
                count == 1
                and self._ts_next <= self._ts_end
                and self._ts_next > self._floor
            ):
                ts = self._ts_next
                self._ts_next += 1
                return ts
        if count == 1:
            first = self._exec("lease_ts", self.TS_BLOCK)
            with self._lock:
                self._ts_next = first + 1
                self._ts_end = first + self.TS_BLOCK - 1
                return first
        return self._exec("lease_ts", count)

    def begin_txn(self) -> int:
        # waits out in-flight commits below the start ts, like
        # read_ts(): a txn snapshot must be complete or SSI misses the
        # lost update (see zero/zero.py begin_txn)
        from dgraph_tpu.zero.zero import wait_applied_below

        ts = self.next_ts()
        with self._cv:
            self._active.add(ts)
            wait_applied_below(self._cv, self._pending, ts)
        return ts

    def read_ts(self) -> int:
        from dgraph_tpu.zero.zero import wait_applied_below

        ts = self.next_ts()
        with self._cv:
            wait_applied_below(self._cv, self._pending, ts)
        return ts

    def assign_uids(self, count: int) -> int:
        return self._exec("lease_uid", count)

    @property
    def max_assigned(self) -> int:
        for addr in self.addrs:
            try:
                return int(self._state(addr)["max_ts"])
            except RpcError:
                continue
        return 0

    @property
    def _max_uid(self) -> int:
        for addr in self.addrs:
            try:
                return int(
                    self._state(addr)["max_uid"]
                )
            except RpcError:
                continue
        return 1

    def commit(self, start_ts: int, conflict_keys, track: bool = False) -> int:
        verdict = self._exec("commit", start_ts, sorted(conflict_keys))
        with self._lock:
            self._active.discard(start_ts)
        if verdict[0] == "abort":
            with self._lock:
                self._floor = max(self._floor, int(verdict[1]))
            raise TxnConflictError(
                f"conflict (committed at {verdict[1]} > start {start_ts})"
            )
        commit_ts = int(verdict[1])
        with self._lock:
            self._floor = max(self._floor, commit_ts)
            if track:
                self._pending.add(commit_ts)
        return commit_ts

    def commit_batch(self, items, track: bool = False):
        """ONE zero.exec round trip deciding N txns (the group-commit
        oracle exchange): verdicts come back per member, so an aborted
        member never fails its batchmates. The batch body rides typed
        (conn/messages.ZeroCommitBatch), not through args_json."""
        from dgraph_tpu.conn.messages import ZeroCommitBatch, ZeroCommitReq

        batch = ZeroCommitBatch(
            txns=[
                ZeroCommitReq(
                    start_ts=int(s),
                    cks=sorted(int(c) for c in cks),
                )
                for s, cks in items
            ]
        )
        verdicts = self._exec("commit_batch", batch=batch)
        with self._lock:
            for (s, _), v in zip(items, verdicts):
                self._active.discard(int(s))
                if int(v[1]):
                    self._floor = max(self._floor, int(v[1]))
                if v[0] == "commit" and track:
                    self._pending.add(int(v[1]))
        return [tuple(v) for v in verdicts]

    def applied(self, commit_ts: int):
        with self._cv:
            self._pending.discard(commit_ts)
            self._cv.notify_all()

    def abort(self, start_ts: int):
        with self._lock:
            self._active.discard(start_ts)
        try:
            self._exec("abort", start_ts, timeout=3.0)
        except TimeoutError:
            pass

    # -- tablet ops ----------------------------------------------------------

    def should_serve(self, pred: str) -> int:
        return int(self._exec("tablet", pred))

    def move_tablet(self, pred: str, gid: int):
        self._exec("move_tablet", pred, int(gid))

    # -- move journal (worker/tabletmove.py phase driver) --------------------

    def move_begin(self, pred: str, src: int, dst: int, read_ts: int):
        self._exec("move_begin", pred, int(src), int(dst), int(read_ts))

    def move_fence(self, pred: str):
        self._exec("move_fence", pred)

    def move_flip(self, pred: str):
        self._exec("move_flip", pred)

    def move_clear(self, pred: str):
        self._exec("move_clear", pred)

    @property
    def moves(self) -> Dict[str, dict]:
        # linearizable (leader-routed raft op): journal reads drive
        # destructive recovery — a follower's stale state could roll
        # back a move whose flip already committed
        return {
            p: dict(m) for p, m in self._exec("moves").items()
        }

    @property
    def tablets(self) -> Dict[str, int]:
        for addr in self.addrs:
            try:
                return dict(
                    self._state(addr)["tablets"]
                )
            except RpcError:
                continue
        return {}

"""Standalone Zero replica process (ref dgraph/cmd/zero run.go: the Zero
quorum as its own servers).

One OS process hosts one Zero raft member: the deterministic coordinator
state machine (zero/replicated.py), TCP raft among the quorum, a raft
WAL, and an RPC surface the cluster coordinator calls:

  zero.exec  {kind, args} — leader-only: propose the op, wait for local
             apply, return the deterministic result (non-leaders answer
             {not_leader: true})
  zero.state — {is_leader, term, max_ts, max_uid, tablets}

Run: python -m dgraph_tpu.zero.zero_process <config.json>
config: {"node_id": 901, "replica_ids": [901,902,903],
         "raft_addrs": {"901": ["127.0.0.1", p], ...},
         "rpc_addr": ["127.0.0.1", p], "data_dir": "..."|null,
         "n_groups": 2}
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Optional

from dgraph_tpu.conn.rpc import RpcServer
from dgraph_tpu.raft.raft import RaftNode
from dgraph_tpu.raft.tcp import TcpNetwork
from dgraph_tpu.raft.wal import RaftWal
from dgraph_tpu.zero.replicated import ZeroStateMachine


class ZeroProcess:
    def __init__(self, cfg: dict):
        self.node_id = int(cfg["node_id"])
        self.replica_ids = [int(x) for x in cfg["replica_ids"]]
        raft_addrs = {int(k): tuple(v) for k, v in cfg["raft_addrs"].items()}
        data_dir: Optional[str] = cfg.get("data_dir")
        self.sm = ZeroStateMachine()
        self.sm.n_groups = int(cfg.get("n_groups", 1))
        wal = None
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            wal = RaftWal(
                os.path.join(data_dir, f"zeroraft_{self.node_id}"),
                sync=bool(cfg.get("wal_sync", True)),
            )
        self.net = TcpNetwork(raft_addrs)
        self.net.register(self.node_id)
        self._apply_cv = threading.Condition()
        self.raft = RaftNode(
            self.node_id,
            self.replica_ids,
            self.net,
            self._apply,
            wal=wal,
            snapshot_cb=self.sm.dump,
            restore_cb=lambda blob, idx: self.sm.load(blob),
            compact_every=int(cfg.get("compact_every", 2048)),
            election_timeout=(400, 800),
            heartbeat=100,
        )
        self._req_id = 0
        host, port = cfg["rpc_addr"]
        self.rpc = RpcServer(
            host, int(port), instance=f"zero-{self.node_id}"
        )
        self.rpc.register("zero.exec", self._h_exec)
        self.rpc.register("zero.state", self._h_state)
        from dgraph_tpu.utils.observe import attach_debug_surface

        self._debug_http, self.debug_port = attach_debug_surface(self.rpc)
        self._stop = threading.Event()

    def _apply(self, idx: int, data):
        with self._apply_cv:
            self.sm.apply(tuple(data) if isinstance(data, list) else data)
            self._apply_cv.notify_all()

    def _h_state(self, a):
        from dgraph_tpu.conn.messages import ZeroState

        return ZeroState(
            state_json=json.dumps(
                {
                    "is_leader": self.raft.is_leader(),
                    "term": self.raft.term,
                    "max_ts": self.sm.max_ts,
                    "max_uid": self.sm.max_uid,
                    "tablets": self.sm.tablets,
                    "moves": self.sm.moves,
                }
            ).encode()
        )

    def _h_exec(self, m):
        """Leader-only propose + wait (the coordinator's consensus op)."""
        from dgraph_tpu.conn.messages import ZeroExec

        if isinstance(m, ZeroExec):
            a = json.loads(m.args_json)
            kind = m.op  # the typed field is authoritative
        else:
            a = m
            kind = a["kind"]
        if not self.raft.is_leader():
            return {"not_leader": True, "hint": self.raft.leader_id}
        with self._apply_cv:
            self._req_id += 1
            rid = self._req_id
        if (
            isinstance(m, ZeroExec)
            and m.commit_batch is not None
            and m.commit_batch.txns
        ):
            # typed batched-commit body (group commit): the nested
            # (start_ts, cks-list) shape never rides args_json, so the
            # scalar-list normalizer below can't mangle it
            args = [
                {
                    "b": [
                        [int(t.start_ts), [int(c) for c in t.cks]]
                        for t in m.commit_batch.txns
                    ]
                }
            ]
        else:
            args = a.get("args") or []
            # JSON round-trip turns tuples/ints-as-keys; normalize args
            args = [
                [int(x) for x in v] if isinstance(v, list) else v
                for v in args
            ]
        op = (kind, self.node_id, rid, *args)
        if not self.raft.propose(op):
            return {"not_leader": True, "hint": self.raft.leader_id}
        key = (self.node_id, rid)
        deadline = time.time() + float(a.get("timeout", 10.0))
        with self._apply_cv:
            while key not in self.sm.results:
                if not self._apply_cv.wait(timeout=0.1) and time.time() > deadline:
                    return {"timeout": True}
        out = self.sm.results[key]
        return {"ok": True, "result": out}

    def run_forever(self):
        self.rpc.start()
        now = 0
        while not self._stop.is_set():
            now += 20
            self.raft.tick(now)
            # apply_cb runs inside tick; wake exec waiters even when the
            # apply happened on this tick thread
            time.sleep(0.005)

    def stop(self):
        self._stop.set()
        self.rpc.close()
        self.net.close()
        if self.raft.wal is not None:
            self.raft.wal.close()


def main():
    with open(sys.argv[1]) as f:
        cfg = json.load(f)
    from dgraph_tpu.conn import faults
    from dgraph_tpu.utils import observe

    observe.init_from_env(instance=f"zero-{cfg.get('node_id')}")
    plan = faults.init_from_env()
    if plan is not None:
        print(
            f"[faults] zero {cfg.get('node_id')}: chaos plan active "
            f"seed={plan.seed} rules={len(plan.rules)}",
            file=sys.stderr, flush=True,
        )
    proc = ZeroProcess(cfg)
    try:
        proc.run_forever()
    except KeyboardInterrupt:
        pass
    finally:
        proc.stop()


if __name__ == "__main__":
    main()

from dgraph_tpu.zero.zero import ZeroLite, TxnConflictError

"""Access control: users, groups, predicate-level rules, JWT sessions.

Mirrors /root/reference/edgraph/access.go (+ worker/acl_cache.go): users
and groups are stored *as graph data* in the cluster itself (predicates
dgraph.xid, dgraph.password, dgraph.user.group, dgraph.acl.rule /
dgraph.rule.predicate / dgraph.rule.permission); login issues an
access+refresh JWT pair; per-request authorization checks the union of the
user's groups' rules at predicate granularity (READ=4, WRITE=2, MODIFY=1);
members of the `guardians` group bypass checks; the bootstrap superuser is
`groot` (access.go:417-531).

Multi-tenancy: each namespace has its own user/group universe (keys are
namespaced); guardians of the galaxy (ns 0) administer namespaces.
"""

from __future__ import annotations

import hashlib
import os
import time
from typing import Dict, List, Optional, Set

from dgraph_tpu.acl import jwt
from dgraph_tpu.posting.lists import LocalCache, Txn
from dgraph_tpu.posting.mutation import DirectedEdge, apply_edge
from dgraph_tpu.posting.pl import OP_DEL
from dgraph_tpu.types.types import TypeID, Val
from dgraph_tpu.x import keys

READ = 4
WRITE = 2
MODIFY = 1


class Permission:
    READ = READ
    WRITE = WRITE
    MODIFY = MODIFY


class AclError(Exception):
    pass


_ACL_SCHEMA = """
dgraph.xid: string @index(exact) @upsert .
dgraph.password: password .
dgraph.user.group: [uid] @reverse .
dgraph.acl.rule: [uid] .
dgraph.rule.predicate: string @index(exact) .
dgraph.rule.permission: int .
"""

GROOT = "groot"
GUARDIANS = "guardians"
_ACCESS_TTL = 6 * 3600
_REFRESH_TTL = 30 * 24 * 3600


def _hash_password(pw: str, salt: bytes) -> bytes:
    return hashlib.pbkdf2_hmac("sha256", pw.encode(), salt, 10_000)


class AclManager:
    def __init__(self, server, secret: Optional[bytes] = None):
        self.server = server
        self.secret = secret or os.urandom(32)
        self._ensure_schema()

    # -- bootstrap (ref access.go:417 initializeAcl) -------------------------

    def _ensure_schema(self):
        self.server.alter(_ACL_SCHEMA)

    def bootstrap(self, ns: int = keys.GALAXY_NS, groot_password: str = "password"):
        """Create groot + guardians if missing."""
        if self._uid_of_xid(GUARDIANS, ns) is None:
            g_uid = self._create_node(ns, GUARDIANS, kind="group")
        else:
            g_uid = self._uid_of_xid(GUARDIANS, ns)
        if self._uid_of_xid(GROOT, ns) is None:
            u_uid = self._create_node(
                ns, GROOT, kind="user", password=groot_password
            )
            txn = self.server.new_txn()
            apply_edge(
                txn.txn,
                self.server.schema,
                DirectedEdge(u_uid, "dgraph.user.group", value_id=g_uid, ns=ns),
            )
            txn.commit()

    def _create_node(self, ns, xid, kind, password: Optional[str] = None) -> int:
        uid = self.server.zero.assign_uids(1)
        txn = self.server.new_txn()
        apply_edge(
            txn.txn,
            self.server.schema,
            DirectedEdge(uid, "dgraph.xid", value=Val(TypeID.STRING, xid), ns=ns),
        )
        if password is not None:
            salt = os.urandom(16)  # stored alongside the hash
            ph = salt + _hash_password(password, salt)
            apply_edge(
                txn.txn,
                self.server.schema,
                DirectedEdge(
                    uid,
                    "dgraph.password",
                    value=Val(TypeID.PASSWORD, ph.hex()),
                    ns=ns,
                ),
            )
        txn.commit()
        return uid

    # -- lookups ---------------------------------------------------------------

    def _cache(self) -> LocalCache:
        return LocalCache(
            self.server.kv,
            self.server.zero.read_ts(),
            mem=getattr(self.server, "mem", None),
        )

    def _uid_of_xid(self, xid: str, ns: int) -> Optional[int]:
        cache = self._cache()
        tok = b"\x02" + xid.encode()
        uids = cache.uids(keys.IndexKey("dgraph.xid", tok, ns))
        return int(uids[0]) if len(uids) else None

    def _groups_of(self, uid: int, ns: int) -> List[int]:
        cache = self._cache()
        return [
            int(g)
            for g in cache.uids(keys.DataKey("dgraph.user.group", uid, ns))
        ]

    def _xid_of(self, uid: int, ns: int) -> str:
        v = self._cache().value(keys.DataKey("dgraph.xid", uid, ns))
        return str(v.value) if v else ""

    # -- user/group admin (ref graphql/admin ACL resolvers) ----------------------

    def add_user(self, xid: str, password: str, ns: int = keys.GALAXY_NS) -> int:
        if self._uid_of_xid(xid, ns) is not None:
            raise AclError(f"user {xid!r} exists")
        return self._create_node(ns, xid, "user", password)

    def add_group(self, xid: str, ns: int = keys.GALAXY_NS) -> int:
        if self._uid_of_xid(xid, ns) is not None:
            raise AclError(f"group {xid!r} exists")
        return self._create_node(ns, xid, "group")

    def add_user_to_group(self, user: str, group: str, ns: int = keys.GALAXY_NS):
        u, g = self._uid_of_xid(user, ns), self._uid_of_xid(group, ns)
        if u is None or g is None:
            raise AclError("unknown user or group")
        txn = self.server.new_txn()
        apply_edge(
            txn.txn,
            self.server.schema,
            DirectedEdge(u, "dgraph.user.group", value_id=g, ns=ns),
        )
        txn.commit()

    def set_rule(
        self, group: str, predicate: str, perm: int, ns: int = keys.GALAXY_NS
    ):
        g = self._uid_of_xid(group, ns)
        if g is None:
            raise AclError(f"unknown group {group!r}")
        rule_uid = self.server.zero.assign_uids(1)
        txn = self.server.new_txn()
        apply_edge(
            txn.txn,
            self.server.schema,
            DirectedEdge(g, "dgraph.acl.rule", value_id=rule_uid, ns=ns),
        )
        apply_edge(
            txn.txn,
            self.server.schema,
            DirectedEdge(
                rule_uid,
                "dgraph.rule.predicate",
                value=Val(TypeID.STRING, predicate),
                ns=ns,
            ),
        )
        apply_edge(
            txn.txn,
            self.server.schema,
            DirectedEdge(
                rule_uid,
                "dgraph.rule.permission",
                value=Val(TypeID.INT, perm),
                ns=ns,
            ),
        )
        txn.commit()

    # -- login (ref access.go:42 Login) ------------------------------------------

    def login(
        self, user: str, password: str, ns: int = keys.GALAXY_NS
    ) -> Dict[str, str]:
        uid = self._uid_of_xid(user, ns)
        if uid is None:
            raise AclError("invalid username or password")
        stored = self._cache().value(keys.DataKey("dgraph.password", uid, ns))
        if stored is None:
            raise AclError("invalid username or password")
        raw = bytes.fromhex(str(stored.value))
        salt, want = raw[:16], raw[16:]
        import hmac as _hmac

        if not _hmac.compare_digest(_hash_password(password, salt), want):
            raise AclError("invalid username or password")
        now = int(time.time())
        groups = [self._xid_of(g, ns) for g in self._groups_of(uid, ns)]
        access = jwt.encode(
            {
                "userid": user,
                "namespace": ns,
                "groups": groups,
                "exp": now + _ACCESS_TTL,
                "typ": "access",
            },
            self.secret,
        )
        refresh = jwt.encode(
            {"userid": user, "namespace": ns, "exp": now + _REFRESH_TTL,
             "typ": "refresh"},
            self.secret,
        )
        return {"accessJwt": access, "refreshJwt": refresh}

    def refresh(self, refresh_jwt: str) -> Dict[str, str]:
        claims = jwt.decode(refresh_jwt, self.secret)
        if claims.get("typ") != "refresh":
            raise AclError("not a refresh token")
        user, ns = claims["userid"], claims.get("namespace", 0)
        uid = self._uid_of_xid(user, ns)
        if uid is None:
            raise AclError("user deleted")
        now = int(time.time())
        groups = [self._xid_of(g, ns) for g in self._groups_of(uid, ns)]
        access = jwt.encode(
            {"userid": user, "namespace": ns, "groups": groups,
             "exp": now + _ACCESS_TTL, "typ": "access"},
            self.secret,
        )
        return {"accessJwt": access, "refreshJwt": refresh_jwt}

    # -- authorization (ref access.go:620 authorizePreds) -------------------------

    def claims(self, access_jwt: str) -> dict:
        c = jwt.decode(access_jwt, self.secret)
        if c.get("typ") != "access":
            raise AclError("not an access token")
        return c

    def _perms_for(self, claims: dict) -> Optional[Dict[str, int]]:
        """None => guardian (all access). Else predicate -> permission bits."""
        ns = claims.get("namespace", 0)
        if GUARDIANS in claims.get("groups", []):
            return None
        cache = self._cache()
        perms: Dict[str, int] = {}
        for gname in claims.get("groups", []):
            g = self._uid_of_xid(gname, ns)
            if g is None:
                continue
            for rule in cache.uids(keys.DataKey("dgraph.acl.rule", g, ns)):
                p = cache.value(
                    keys.DataKey("dgraph.rule.predicate", int(rule), ns)
                )
                m = cache.value(
                    keys.DataKey("dgraph.rule.permission", int(rule), ns)
                )
                if p is not None and m is not None:
                    pred = str(p.value)
                    perms[pred] = perms.get(pred, 0) | int(m.value)
        return perms

    def readable_preds(self, claims: dict) -> Optional[Set[str]]:
        """Set of predicates the caller may READ, or None for guardians
        (used to filter expand(_all_), ref graphql auth filtering)."""
        perms = self._perms_for(claims)
        if perms is None:
            return None
        return {p for p, m in perms.items() if m & READ} | {"dgraph.type"}

    def is_guardian(self, access_jwt: Optional[str]) -> bool:
        if access_jwt is None:
            return False
        try:
            claims = self.claims(access_jwt)
        except Exception:
            return False
        return GUARDIANS in claims.get("groups", [])

    def authorize_preds(
        self, access_jwt: str, preds: List[str], need: int, claims=None
    ) -> None:
        """Raise AclError if any predicate lacks `need` permission."""
        if claims is None:
            claims = self.claims(access_jwt)
        perms = self._perms_for(claims)
        if perms is None:
            return  # guardian
        for pred in preds:
            if pred.startswith("dgraph."):
                # non-guardians may only READ dgraph.type (needed by
                # type()/expand); ACL internals (dgraph.password,
                # dgraph.acl.rule, ...) are guardian-only like the reference
                if need == READ and pred == "dgraph.type":
                    continue
                raise AclError(
                    f"only guardians may access {pred!r}"
                )
            if not (perms.get(pred, 0) & need):
                raise AclError(
                    f"unauthorized to {'read' if need == READ else 'write'} "
                    f"predicate {pred!r}"
                )

from dgraph_tpu.acl.acl import AclManager, AclError, Permission

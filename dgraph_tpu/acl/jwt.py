"""Minimal JWT (HS256) — stdlib only.

Stand-in for the golang-jwt dependency used by
/root/reference/edgraph/access.go (access+refresh token pair with
namespace + groups claims)."""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
from typing import Optional


class JwtError(Exception):
    pass


def _b64(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64(s: str) -> bytes:
    pad = "=" * (-len(s) % 4)
    return base64.urlsafe_b64decode(s + pad)


def encode(claims: dict, secret: bytes) -> str:
    header = _b64(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    payload = _b64(json.dumps(claims, separators=(",", ":")).encode())
    msg = f"{header}.{payload}".encode()
    sig = _b64(hmac.new(secret, msg, hashlib.sha256).digest())
    return f"{header}.{payload}.{sig}"


def decode(token: str, secret: bytes, verify_exp: bool = True) -> dict:
    try:
        header, payload, sig = token.split(".")
    except ValueError:
        raise JwtError("malformed token") from None
    msg = f"{header}.{payload}".encode()
    want = _b64(hmac.new(secret, msg, hashlib.sha256).digest())
    if not hmac.compare_digest(want, sig):
        raise JwtError("bad signature")
    claims = json.loads(_unb64(payload))
    if verify_exp and claims.get("exp", 0) < time.time():
        raise JwtError("token expired")
    return claims

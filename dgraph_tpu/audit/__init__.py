from dgraph_tpu.audit.audit import AuditLog

"""Audit logging: append-only (optionally encrypted) request log.

Mirrors /root/reference/audit/ (interceptor.go:65,97 + audit.go:127
rolling encrypted logs): every API request is recorded as one JSON line
{ts, user, ns, endpoint, req_body, status}; files roll at max_bytes; with
an encryption key each line is AES-CTR sealed (enc/enc.py).
"""

from __future__ import annotations

import base64
import json
import os
import threading
import time
from typing import Optional


class AuditLog:
    def __init__(
        self,
        out_dir: str,
        key: Optional[bytes] = None,
        max_bytes: int = 10 * 1024 * 1024,
    ):
        os.makedirs(out_dir, exist_ok=True)
        self.dir = out_dir
        self.key = key
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        self._seq = 0
        self._open()

    def _open(self):
        self.path = os.path.join(self.dir, f"audit-{self._seq:04d}.log")
        self._f = open(self.path, "ab")

    def _roll_if_needed(self):
        if self._f.tell() >= self.max_bytes:
            self._f.close()
            self._seq += 1
            self._open()

    def record(
        self,
        endpoint: str,
        user: str = "",
        ns: int = 0,
        body: str = "",
        status: str = "OK",
    ):
        entry = {
            "ts": time.time(),
            "endpoint": endpoint,
            "user": user,
            "namespace": ns,
            "body": body[:4096],
            "status": status,
        }
        line = json.dumps(entry, separators=(",", ":")).encode()
        if self.key is not None:
            from dgraph_tpu.enc.enc import encrypt_stream

            line = base64.b64encode(encrypt_stream(line, self.key))
        with self._lock:
            self._f.write(line + b"\n")
            self._f.flush()
            self._roll_if_needed()

    def read_all(self) -> list:
        """Decrypt + parse all audit entries (ops tooling)."""
        out = []
        for fname in sorted(os.listdir(self.dir)):
            if not fname.startswith("audit-"):
                continue
            with open(os.path.join(self.dir, fname), "rb") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    if self.key is not None:
                        from dgraph_tpu.enc.enc import decrypt_stream

                        line = decrypt_stream(base64.b64decode(line), self.key)
                    out.append(json.loads(line))
        return out

    def close(self):
        self._f.close()

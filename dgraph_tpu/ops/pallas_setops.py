"""Pallas TPU kernel for the membership hot loop.

The XLA path (ops/setops.py) lowers membership to searchsorted — binary
search with gathers, which the TPU executes but does not love. This kernel
reformulates small-side membership as a *compare-all sweep*: the query set
(<=128 uids, one VREG lane row) is compared against every 8x128 tile of the
big sorted list with pure VPU broadcasting — zero gathers, zero
data-dependent control flow. For the dominant fan-out shape (tiny src list
vs huge posting list, the reference's IntersectWith ratio>32 regime,
algo/uidlist.go:156) the sweep is bandwidth-bound at HBM speed, which is
the roofline for this op.

The kernel is written BATCH-AWARE (grid = (batch, b_tiles), block specs
indexed by batch) rather than as a vmapped single example: Pallas TPU
lowering rejects the Squeezed SMEM blocks that jax.vmap produces for the
scalar length operand (found the first time the kernel ran compiled on a
real v5e — interpret mode accepts them).

Grid: for each batch row, one step per b-tile; the hit-mask accumulates
across steps via output revisiting (out block index is constant in the
tile dimension). TPU grids iterate the last axis fastest, so the
`step == 0` init runs before that row's accumulation.

Correctness is validated in interpret mode on CPU (tests). The dispatcher
uses this path for intersect buckets with <=128-element small sides when
DGRAPH_TPU_PALLAS=1 (query/dispatch.py).
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128
SUBLANE = 8
TILE = LANE * SUBLANE  # 1024 u32 per b-tile


def _default_interpret() -> bool:
    """Pallas TPU kernels only run compiled on real TPUs; everywhere else
    use interpret mode. Resolved from the live backend (the env var can
    disagree with the configured platform, e.g. under the test conftest)."""
    import jax

    return jax.default_backend() != "tpu"


def _member_kernel(lb_ref, a_ref, b_ref, out_ref):
    """One grid step: OR membership hits of batch row i's queries (1,128)
    against its b tile (8,128).

    b-lane validity is computed from the global flat index vs lb (no
    sentinel collisions possible — 0xFFFFFFFF stays a legal uid)."""
    i = pl.program_id(0)
    step = pl.program_id(1)

    @pl.when(step == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    a = a_ref[0, 0]  # (LANE,)
    b = b_ref[0]  # (SUBLANE, LANE)
    base = step * TILE
    flat = (
        base
        + jax.lax.broadcasted_iota(jnp.int32, (SUBLANE, LANE), 0) * LANE
        + jax.lax.broadcasted_iota(jnp.int32, (SUBLANE, LANE), 1)
    )
    # validity folded in as an i32 multiply: Mosaic cannot insert a minor
    # dim on 1-bit vectors (valid[:, :, None] fails to compile), and the
    # accumulator is i32 for the same reason
    vmask = (flat < lb_ref[i]).astype(jnp.int32)
    # compare-all: (SUBLANE, LANE, 1) vs (1, 1, LANE) -> any over b axes
    eq = (b[:, :, None] == a[None, None, :]).astype(jnp.int32)
    hits = (eq * vmask[:, :, None]).max(axis=(0, 1))
    out_ref[:] = jnp.maximum(out_ref[:], hits[None, None, :])


@functools.partial(jax.jit, static_argnames=("interpret",))
def _membership_padded(LB, A128, Bp, interpret: bool = False):
    """A128: (n, LANE) u32; Bp: (n, nb*SUBLANE, LANE) u32 row-major tiles;
    LB: (n,) i32 valid lengths. Returns (n, LANE) bool hit masks."""
    n, nbs, _ = Bp.shape
    nb = nbs // SUBLANE
    # (1, 1, LANE) blocks: TPU lowering requires the last two block dims
    # divisible by (8, 128) OR equal to the array dims — a leading
    # singleton axis makes the (1, LANE) row block legal
    out = pl.pallas_call(
        _member_kernel,
        grid=(n, nb),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, LANE), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, SUBLANE, LANE), lambda i, j: (i, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, LANE), lambda i, j: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, 1, LANE), jnp.int32),
        interpret=interpret,
    )(jnp.asarray(LB, jnp.int32), A128[:, None, :], Bp)
    return out[:, 0, :] != 0


def membership_batch(A, LA, B, LB, interpret=None):
    """Batched membership masks: A (n, pa<=128) u32 sorted rows (padded
    with UINT32_MAX), B (n, pb) u32 sorted rows, lengths LA/LB. Returns
    (n, pa) bool — True where A[i,j] occurs in B[i, :LB[i]]."""
    if interpret is None:
        interpret = _default_interpret()
    n, pa = A.shape
    if pa > LANE:
        raise ValueError(f"pallas membership path is for <=128 queries, got {pa}")
    pb = B.shape[1]
    if pb == 0:
        return jnp.zeros((n, pa), jnp.bool_)
    A_l = jnp.pad(A, ((0, 0), (0, LANE - pa)))
    Bp = jnp.pad(B, ((0, 0), (0, (-pb) % TILE)))
    Bp = Bp.reshape(n, -1, LANE)
    hits = _membership_padded(LB, A_l, Bp, interpret=interpret)
    la_mask = (
        jax.lax.broadcasted_iota(jnp.int32, (n, pa), 1)
        < jnp.asarray(LA, jnp.int32)[:, None]
    )
    return hits[:, :pa] & la_mask


def intersect_batch(A, LA, B, LB, interpret=None):
    """Batched pallas intersect with the same (out, cnt) contract as
    jax.vmap(setops.intersect) — the dispatcher's bucket entry point."""
    from dgraph_tpu.ops import setops

    keep = membership_batch(A, LA, B, LB, interpret=interpret)
    return jax.vmap(setops.compact)(A, keep)


def membership(a, la, b, lb, interpret=None):
    """Single-example membership (<=128 queries) — test/compat shim over
    the batched kernel."""
    mask = membership_batch(
        a[None, :], jnp.asarray([la]), b[None, :], jnp.asarray([lb]),
        interpret=interpret,
    )
    return mask[0]


def intersect(a, la, b, lb, interpret=None):
    """Pallas-backed intersect for small a (uses sort-based compaction)."""
    from dgraph_tpu.ops import setops

    keep = membership(a, la, b, lb, interpret=interpret)
    return setops.compact(a, keep)

"""Pallas TPU kernel for the membership hot loop.

The XLA path (ops/setops.py) lowers membership to searchsorted — binary
search with gathers, which the TPU executes but does not love. This kernel
reformulates small-side membership as a *compare-all sweep*: the query set
(<=128 uids, one VREG lane row) is compared against every 8x128 tile of the
big sorted list with pure VPU broadcasting — zero gathers, zero
data-dependent control flow. For the dominant fan-out shape (tiny src list
vs huge posting list, the reference's IntersectWith ratio>32 regime,
algo/uidlist.go:156) the sweep is bandwidth-bound at HBM speed, which is
the roofline for this op.

Grid: one step per b-tile; the hit-mask accumulates across steps via
output revisiting (out block index is constant). Early-block skipping by
base comparison is left to the caller's block structure (codec blocks are
range-partitioned, so the engine only feeds tiles overlapping [a_min,
a_max]).

Correctness is validated in interpret mode on CPU (tests). The dispatcher
uses this path for intersect buckets with <=128-element small sides when
DGRAPH_TPU_PALLAS=1 (query/dispatch.py); default remains the XLA
searchsorted path until the sweep is profiled on real hardware.
"""

from __future__ import annotations

import functools
import os

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128
SUBLANE = 8
TILE = LANE * SUBLANE  # 1024 u32 per b-tile

def _default_interpret() -> bool:
    """Pallas TPU kernels only run compiled on real TPUs; everywhere else
    use interpret mode. Resolved from the live backend (the env var can
    disagree with the configured platform, e.g. under the test conftest)."""
    import jax

    return jax.default_backend() != "tpu"



def _member_kernel(lb_ref, a_ref, b_ref, out_ref):
    """One grid step: OR membership hits of a (1,128) against b tile (8,128).

    b-lane validity is computed from the global flat index vs lb (no
    sentinel collisions possible — 0xFFFFFFFF stays a legal uid)."""
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    a = a_ref[:]  # (1, LANE)
    b = b_ref[:]  # (SUBLANE, LANE)
    base = step * TILE
    flat = (
        base
        + jax.lax.broadcasted_iota(jnp.int32, (SUBLANE, LANE), 0) * LANE
        + jax.lax.broadcasted_iota(jnp.int32, (SUBLANE, LANE), 1)
    )
    valid = flat < lb_ref[0]
    # compare-all: (SUBLANE, LANE, 1) vs (1, 1, LANE) -> any over b axes
    eq = (b[:, :, None] == a[0][None, None, :]) & valid[:, :, None]
    hits = eq.any(axis=(0, 1))
    out_ref[:] = out_ref[:] | hits[None, :]


@functools.partial(jax.jit, static_argnames=("interpret",))
def membership_small(a128, b_padded, lb, interpret: bool = False):
    """mask over a128 (shape (128,) uint32) against b_padded (shape (N,)
    uint32, N a multiple of 1024); b validity = index < lb."""
    nb = b_padded.shape[0] // TILE
    a2 = a128.reshape(1, LANE)
    b2 = b_padded.reshape(nb * SUBLANE, LANE)
    out = pl.pallas_call(
        _member_kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, LANE), lambda i: (0, 0)),
            pl.BlockSpec((SUBLANE, LANE), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, LANE), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, LANE), jnp.bool_),
        interpret=interpret,
    )(jnp.asarray([lb], jnp.int32), a2, b2)
    return out[0]


def membership(a, la, b, lb, interpret=None):
    """Drop-in replacement for setops.membership when len(a) <= 128.

    Handles the sentinel-collision case (0xFFFFFFFF is a legal uid) by
    masking on explicit lengths like the XLA path.
    """
    if interpret is None:
        interpret = _default_interpret()
    n = a.shape[0]
    if n > LANE:
        raise ValueError(f"pallas membership path is for <=128 queries, got {n}")
    if b.shape[0] == 0:
        # zero grid steps would leave the output uninitialized
        return jnp.zeros((n,), jnp.bool_)
    a_l = jnp.pad(a, (0, LANE - n))
    m = b.shape[0]
    b_p = jnp.pad(b, (0, (-m) % TILE))
    hits = membership_small(a_l, b_p, lb, interpret=interpret)
    return hits[:n] & (jnp.arange(n) < la)


def intersect(a, la, b, lb, interpret=None):
    """Pallas-backed intersect for small a (uses sort-based compaction)."""
    from dgraph_tpu.ops import setops

    keep = membership(a, la, b, lb, interpret=interpret)
    return setops.compact(a, keep)

"""Compressed-domain sorted-set ops over UidPack blocks (block-skip).

The host-side hot cost of every traversal is "parse -> UidPack decode"
(posting/memlayer.py): the query engine eagerly decodes whole
block-compressed posting lists to flat u64 arrays before ops/setops.py
ever runs, even when an intersection touches a tiny fraction of blocks.
This module mirrors the reference's compressed-domain variants
(algo/packed.go IntersectCompressedWith / IntersectCompressedWithBin),
now through the adaptive per-block set-representation engine:

  1. the native kernels (codec.cpp pack_pair_setop / pack_stream_setop)
     walk the operands' per-block (base, max) range arrays with a
     two-pointer skip — whole blocks outside the other side's ranges
     are never touched,
  2. each overlapping block PAIR runs the cheapest kernel for its
     container mix: word-wise bitmap AND/ANDNOT for dense blocks
     (codec/uidpack.block_bitmaps bitsets), bitset probes for
     bitmap x packed pairs, and a galloping offsets merge for
     packed x packed — neither operand ever materializes,
  3. without the native engine, candidate blocks found by vectorized
     searchsorted partially decode (codec/uidpack.decode_blocks) and
     the ordinary set kernels run on the spans — via the device
     dispatcher's vmapped kernels (query/dispatch.py) or host loops.

The technique combines the block-skip intersection of Lemire & Boytsov
(SIMD Compression and the Intersection of Sorted Integers, arxiv
1401.6399) with the bitmap/slice container hybrid of arxiv 1907.01032:
intersections are fastest against block-compressed layouts with
skippable block metadata and density-matched container forms.

32-bit segment rule: UidPack blocks never span a hi-32 boundary
(codec.go:117 split rule, enforced by uidpack.encode), so every candidate
span decodes into ranges that the dispatcher's segment split maps onto
uint32 device kernels exactly as the decoded path does — packed results
are element-exact against ops/setops.py, including across segment
boundaries.

All functions are exact: a block skipped by range disjointness cannot
contribute to the result. Decode accounting (for the decode_bytes_per_query
benchmark metric and the packed-vs-decode tuning) is kept in module
counters — reset()/snapshot() for measurement windows.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional, Tuple

import numpy as np

from dgraph_tpu.codec import uidpack
from dgraph_tpu.codec.uidpack import UidPack, block_maxes, decode_blocks

DecodeFn = Callable[[UidPack, np.ndarray], np.ndarray]

_EMPTY64 = np.zeros((0,), np.uint64)
_EMPTY_IDX = np.zeros((0,), np.int64)


class _Counters(threading.local):
    """Per-thread decode accounting (threads serve independent queries)."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.decoded_uids = 0  # UIDs actually materialized
        self.skipped_uids = 0  # UIDs left compressed by block skipping
        self.streamed_uids = 0  # UIDs compared compressed-domain (no
        #                         materialization: bitmap/probe/gallop)
        self.packed_ops = 0
        # per-representation kernel counts (block pairs, adaptive engine)
        self.bitmap_pairs = 0
        self.probe_pairs = 0
        self.gallop_pairs = 0

    def snapshot(self) -> dict:
        full = self.decoded_uids + self.skipped_uids + self.streamed_uids
        return {
            "decoded_uids": self.decoded_uids,
            "skipped_uids": self.skipped_uids,
            "streamed_uids": self.streamed_uids,
            "full_decode_uids": full,
            "decoded_bytes": self.decoded_uids * 8,
            "full_decode_bytes": full * 8,
            "packed_ops": self.packed_ops,
            "bitmap_pairs": self.bitmap_pairs,
            "probe_pairs": self.probe_pairs,
            "gallop_pairs": self.gallop_pairs,
        }


COUNTERS = _Counters()


def reset_counters():
    COUNTERS.reset()


def counters() -> dict:
    return COUNTERS.snapshot()


def _account(pack: UidPack, idxs: np.ndarray):
    dec = int(pack.counts[idxs].sum()) if idxs.size else 0
    COUNTERS.decoded_uids += dec
    COUNTERS.skipped_uids += pack.num_uids - dec


# ---------------------------------------------------------------------------
# Adaptive per-block engine (bitmap/packed hybrid containers).
#
# Native kernels (codec.cpp pack_pair_setop / pack_stream_setop) pick per
# BLOCK PAIR among {bitmap AND/ANDNOT, bitmap probe, packed galloping
# merge} using the per-block cardinality metadata (uidpack.block_bitmaps
# eligibility); whole blocks outside the other operand's ranges are
# skipped without a touch. Neither operand ever materializes, so the
# engine wins at EVERY selectivity — it replaced the old whole-operand
# PACKED_MIN_RATIO cliff that fell back to full decode at dense ratios.
# ---------------------------------------------------------------------------


def engine_available() -> bool:
    """True when the native adaptive block engine is compiled in. Without
    it the packed ops fall back to candidate-block decode (exact, but
    only profitable at selective ratios — dispatchers re-apply the old
    ratio cliff in that case, see dispatch.packed_min_ratio)."""
    from dgraph_tpu import native

    return native.NATIVE_AVAILABLE


def _note_kernels(kc) -> None:
    """Fold a kernel_counts vector into the per-thread counters and the
    cluster metrics (per-representation kernel accounting)."""
    COUNTERS.bitmap_pairs += int(kc[0])
    COUNTERS.probe_pairs += int(kc[1])
    COUNTERS.gallop_pairs += int(kc[2])
    COUNTERS.streamed_uids += int(kc[3])
    try:
        from dgraph_tpu.utils.observe import METRICS

        if kc[0]:
            METRICS.inc("setop_block_bitmap_total", int(kc[0]))
        if kc[1]:
            METRICS.inc("setop_block_probe_total", int(kc[1]))
        if kc[2]:
            METRICS.inc("setop_block_gallop_total", int(kc[2]))
    except Exception:
        pass


def _pair_engine(op_code: int, pa: UidPack, pb: UidPack):
    """pack x pack through the native per-block engine; None -> caller
    falls back to the candidate-block decode path."""
    from dgraph_tpu import native

    if not native.NATIVE_AVAILABLE:
        return None
    got = native.pack_pair_setop(
        op_code,
        pa,
        pb,
        uidpack.block_bitmaps(pa),
        uidpack.block_bitmaps(pb),
        uidpack.BITMAP_BITS,
    )
    if got is None:
        return None
    out, kc = got
    _note_kernels(kc)
    COUNTERS.skipped_uids += max(
        0, pa.num_uids + pb.num_uids - int(kc[3])
    )
    return out


def _stream_engine(op_code: int, a: np.ndarray, pack: UidPack):
    """sorted array x pack through the native streaming engine; None ->
    caller falls back to the candidate-block decode path."""
    from dgraph_tpu import native

    if not native.NATIVE_AVAILABLE:
        return None
    got = native.pack_stream_setop(
        op_code, a, pack, uidpack.block_bitmaps(pack), uidpack.BITMAP_BITS
    )
    if got is None:
        return None
    out, kc = got
    _note_kernels(kc)
    COUNTERS.skipped_uids += max(0, pack.num_uids - int(kc[3]))
    return out


# ---------------------------------------------------------------------------
# Candidate-block search: vectorized gallop over block range arrays.
# ---------------------------------------------------------------------------


def candidate_blocks_for_array(a: np.ndarray, pack: UidPack) -> np.ndarray:
    """Indices of `pack` blocks whose [base, max] range contains at least
    one element of sorted u64 array `a` — the asymmetric (frontier vs big
    packed list) form, the dominant query shape.

    Search direction flips on the smaller side, the vectorized analog of
    the reference's linear/jump/binary strategy pick: a tiny frontier
    gallops into the block-base array (|a| log nblocks); a wide frontier
    is galloped INTO by the block ranges (nblocks log |a|)."""
    if a.size == 0 or pack.nblocks == 0:
        return _EMPTY_IDX
    bases = pack.bases
    maxes = block_maxes(pack)
    if a.size < pack.nblocks:
        # each element's only possible containing block (ranges are
        # disjoint ascending): the last block with base <= x
        pos = np.searchsorted(bases, a, side="right") - 1
        pos = np.maximum(pos, 0)
        hit = (a >= bases[pos]) & (a <= maxes[pos])
        return np.unique(pos[hit]).astype(np.int64)
    lo = np.searchsorted(a, bases, side="left")
    hi = np.searchsorted(a, maxes, side="right")
    return np.flatnonzero(hi > lo).astype(np.int64)


def candidate_block_pairs(
    pa: UidPack, pb: UidPack
) -> Tuple[np.ndarray, np.ndarray]:
    """Block indices of each pack whose range overlaps ANY block range of
    the other (ref algo/packed.go: the per-block Base comparisons that let
    IntersectCompressed skip whole blocks). Exact superset of the blocks
    that can contribute to an intersection."""
    if pa.nblocks == 0 or pb.nblocks == 0:
        return _EMPTY_IDX, _EMPTY_IDX
    abase, amax = pa.bases, block_maxes(pa)
    bbase, bmax = pb.bases, block_maxes(pb)
    # A block i overlaps some B block j iff any j has bbase<=amax_i and
    # bmax>=abase_i; block ranges are disjoint+ascending so both bounds
    # come from one searchsorted each.
    lo = np.searchsorted(bmax, abase, side="left")
    hi = np.searchsorted(bbase, amax, side="right")
    a_idx = np.flatnonzero(hi > lo).astype(np.int64)
    lo = np.searchsorted(amax, bbase, side="left")
    hi = np.searchsorted(abase, bmax, side="right")
    b_idx = np.flatnonzero(hi > lo).astype(np.int64)
    return a_idx, b_idx


# ---------------------------------------------------------------------------
# Compressed-domain set ops.
# ---------------------------------------------------------------------------


# Frontiers at/below this size test membership directly against the packed
# offset rows (one (k, 256) vectorized compare) — no block decode at all.
_SMALL_DIRECT = 512


def _member_mask_direct(a: np.ndarray, pack: UidPack) -> np.ndarray:
    """Membership of each a[i] in the pack WITHOUT decoding: locate the one
    block whose range can hold a[i], then compare its in-block offsets
    against the element's local offset (padding is masked by count, so
    offset 0xFFFFFFFF remains a legal value)."""
    bases = pack.bases
    maxes = block_maxes(pack)
    pos = np.searchsorted(bases, a, side="right") - 1
    pos = np.maximum(pos, 0)
    in_range = (a >= bases[pos]) & (a <= maxes[pos])
    out = np.zeros((a.size,), bool)
    if not in_range.any():
        _account(pack, _EMPTY_IDX)
        return out
    blocks = pos[in_range]
    _account(pack, np.unique(blocks))
    rows = pack.offsets[blocks]
    local = (a[in_range] - bases[blocks]).astype(np.uint32)
    valid = (
        np.arange(rows.shape[1], dtype=np.int32)[None, :]
        < pack.counts[blocks][:, None]
    )
    out[in_range] = np.any((rows == local[:, None]) & valid, axis=1)
    return out


def _native_small_intersect(
    a: np.ndarray, pack: UidPack
) -> Optional[np.ndarray]:
    """One-call native block-probe intersect; ctypes pointers for the
    pack's block arrays are built once and cached on the pack."""
    from dgraph_tpu import native

    if not native.NATIVE_AVAILABLE:
        return None
    maxes = block_maxes(pack)
    ptrs = getattr(pack, "_nptrs", None)
    if ptrs is None:
        ptrs = native.pack_ptrs(pack.bases, pack.counts, pack.offsets, maxes)
        pack._nptrs = ptrs
    hits, touched = native.pack_intersect_small(
        pack.bases, pack.counts, pack.offsets, maxes, a, ptrs=ptrs
    )
    COUNTERS.decoded_uids += touched
    COUNTERS.skipped_uids += pack.num_uids - touched
    return hits


def _host_op(op: str, a: np.ndarray, b: np.ndarray) -> np.ndarray:
    from dgraph_tpu import native

    if op == "intersect":
        return native.intersect(a, b)
    if op == "difference":
        return native.difference(a, b)
    raise ValueError(op)


def _run_span_op(op, a, b, runner):
    """Run `op` on two decoded candidate spans. `runner` (the dispatcher's
    run_pairs) routes big spans through the existing vmapped device
    kernels; None keeps everything on the native host loops."""
    if runner is not None:
        return runner(op, [(a, b)])[0]
    return _host_op(op, a, b)


def intersect_packed(
    a,
    pack_b: UidPack,
    decode_b: Optional[DecodeFn] = None,
    runner=None,
    decode_a: Optional[DecodeFn] = None,
) -> np.ndarray:
    """Sorted-set intersection where at least the big side stays packed.

    `a` is a sorted u64 array OR a UidPack (decoded via `decode_a` then —
    pass the owning list's block-cached decoder to reuse decoded blocks
    across traversals). Only blocks whose ranges overlap the other operand
    decode (ref algo/packed.go IntersectCompressedWith); the op itself
    runs on the decoded candidate spans via `runner` (device) or the
    native host loops."""
    decode_b = decode_b or decode_blocks
    COUNTERS.packed_ops += 1
    if isinstance(a, UidPack):
        if a.num_uids <= _SMALL_DIRECT:
            # tiny packed frontier: materialize it (a few blocks) and take
            # the zero-decode probe against b below — decoding candidate
            # b-blocks here would forfeit the whole tiny-frontier win
            all_a = np.arange(a.nblocks, dtype=np.int64)
            _account(a, all_a)
            a = (decode_a or decode_blocks)(a, all_a)
        else:
            # pack x pack: the adaptive per-block engine keeps BOTH sides
            # compressed (bitmap AND / probe / galloping merge per pair)
            got = _pair_engine(0, a, pack_b)
            if got is not None:
                return got
            a_idx, b_idx = candidate_block_pairs(a, pack_b)
            _account(a, a_idx)
            _account(pack_b, b_idx)
            if a_idx.size == 0 or b_idx.size == 0:
                return _EMPTY64
            da = (decode_a or decode_blocks)(a, a_idx)
            db = decode_b(pack_b, b_idx)
            return _run_span_op("intersect", da, db, runner)
    a = np.asarray(a, np.uint64)
    if a.size == 0 or pack_b.nblocks == 0:
        return _EMPTY64
    if a.size <= _SMALL_DIRECT:
        # tiny frontier: membership straight off the packed rows, zero
        # decode (the IntersectCompressedWithBin shape)
        got = _native_small_intersect(a, pack_b)
        if got is not None:
            return got
        return a[_member_mask_direct(a, pack_b)]
    # wide frontier: stream it against the pack's blocks (bitmap probe /
    # in-block merge), still zero decode
    got = _stream_engine(0, a, pack_b)
    if got is not None:
        return got
    b_idx = candidate_blocks_for_array(a, pack_b)
    _account(pack_b, b_idx)
    if b_idx.size == 0:
        return _EMPTY64
    db = decode_b(pack_b, b_idx)
    return _run_span_op("intersect", a, db, runner)


def difference_packed(
    a,
    pack_b: UidPack,
    decode_b: Optional[DecodeFn] = None,
    runner=None,
) -> np.ndarray:
    """a \\ b with b kept packed: only b blocks overlapping a's range can
    remove elements, so the rest never touch. A packed `a` runs the
    per-block pair engine (bitmap ANDNOT / probe / galloping merge) with
    BOTH sides compressed; an array `a` streams against b's blocks."""
    decode_b = decode_b or decode_blocks
    COUNTERS.packed_ops += 1
    if isinstance(a, UidPack):
        if a.num_uids and pack_b.nblocks and a.num_uids > _SMALL_DIRECT:
            got = _pair_engine(1, a, pack_b)
            if got is not None:
                return got
        a = uidpack.decode(a)
    a = np.asarray(a, np.uint64)
    if a.size == 0:
        return _EMPTY64
    if pack_b.nblocks == 0:
        return a
    if a.size <= _SMALL_DIRECT:
        return a[~_member_mask_direct(a, pack_b)]
    got = _stream_engine(1, a, pack_b)
    if got is not None:
        return got
    b_idx = candidate_blocks_for_array(a, pack_b)
    _account(pack_b, b_idx)
    if b_idx.size == 0:
        return a
    db = decode_b(pack_b, b_idx)
    return _run_span_op("difference", a, db, runner)


def membership_packed(
    a: np.ndarray,
    pack_b: UidPack,
    decode_b: Optional[DecodeFn] = None,
) -> np.ndarray:
    """Boolean mask: a[i] in pack_b — elements outside every candidate
    block are non-members without any decode (the compressed analog of
    ops/setops.membership)."""
    decode_b = decode_b or decode_blocks
    COUNTERS.packed_ops += 1
    a = np.asarray(a, np.uint64)
    if a.size == 0 or pack_b.nblocks == 0:
        return np.zeros((a.size,), bool)
    if a.size <= _SMALL_DIRECT:
        return _member_mask_direct(a, pack_b)
    b_idx = candidate_blocks_for_array(a, pack_b)
    _account(pack_b, b_idx)
    if b_idx.size == 0:
        return np.zeros((a.size,), bool)
    db = decode_b(pack_b, b_idx)
    pos = np.searchsorted(db, a)
    pos_c = np.minimum(pos, db.size - 1)
    return db[pos_c] == a

from dgraph_tpu.ops.setops import (
    membership,
    intersect,
    union,
    difference,
    merge_sorted,
    compact,
    pad_sorted,
    UINT32_MAX,
)

"""Device sorted-set algebra over padded uint32 UID arrays.

TPU-native replacement for the reference's adaptive scalar intersect loops
(/root/reference/algo/uidlist.go:142 IntersectWith, :297 IntersectSorted,
:332 Difference, :448 MergeSorted) and the compressed-domain variants
(algo/packed.go). Instead of per-pair adaptive linear/jump/binary strategies,
every op is a fixed-shape, fully-vectorized XLA program that is `vmap`-ped
over a *batch* of list pairs, so one device dispatch covers an entire
`handleUidPostings`-style fan-out (/root/reference/worker/task.go:783).

Representation
--------------
A list is a sorted uint32 array padded to a static size with UINT32_MAX,
plus an explicit int32 length. Validity is *always* judged by index < length,
never by sentinel value, so UINT32_MAX is still a legal UID. Padding must be
UINT32_MAX so the padded array stays sorted (searchsorted correctness).

64-bit UIDs are handled one level up (codec/uidpack.py): lists are segmented
by the high 32 bits — mirroring the reference's block-split rule when the 32
MSBs differ (codec/codec.go:117) — and ops run per matching segment in the
32-bit local space.

All functions are jit-friendly (static shapes, no data-dependent control
flow) and have `jax.vmap` applied by the batch dispatcher (query/dispatch.py).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

UINT32_MAX = np.uint32(0xFFFFFFFF)


def pad_sorted(arr: np.ndarray, size: int) -> np.ndarray:
    """Host helper: pad a sorted uint32 array to `size` with UINT32_MAX."""
    arr = np.asarray(arr, dtype=np.uint32)
    if arr.shape[0] > size:
        raise ValueError(f"array of length {arr.shape[0]} > pad size {size}")
    out = np.full((size,), UINT32_MAX, dtype=np.uint32)
    out[: arr.shape[0]] = arr
    return out


def _iota_mask(n: int, length) -> jnp.ndarray:
    return jnp.arange(n, dtype=jnp.int32) < length


def _searchsorted(b, a):
    """Shape-adaptive search: unrolled binary search when the query side is
    much smaller than the target (log2(n) vectorized steps), sort-based
    search when both sides are large (one fused sort amortizes better on
    the TPU) — the static-shape analog of the reference's linear/jump/binary
    strategy pick (algo/uidlist.go:142-168)."""
    if a.shape[0] * 32 <= b.shape[0]:
        return jnp.searchsorted(b, a, method="scan_unrolled")
    return jnp.searchsorted(b, a, method="sort")


def membership(a, la, b, lb):
    """mask[i] = (i < la) and (a[i] in b[:lb]).

    Vectorized binary search replaces the scalar jump/binary loops of
    algo/uidlist.go:195,226.
    """
    idx = _searchsorted(b, a)
    idx_c = jnp.minimum(idx, b.shape[0] - 1)
    hit = (idx < lb) & (jnp.take(b, idx_c) == a)
    return hit & _iota_mask(a.shape[0], la)


def compact(a, keep):
    """Stable-compact elements of `a` where `keep`; returns (padded, count).

    Uses a stable argsort on the keep mask (members first) — a sort-based
    stream compaction that XLA maps onto the TPU well; padding is restored
    to UINT32_MAX to preserve the sortedness invariant.
    """
    order = jnp.argsort(~keep, stable=True)
    out = jnp.take(a, order)
    n = jnp.sum(keep, dtype=jnp.int32)
    out = jnp.where(_iota_mask(a.shape[0], n), out, UINT32_MAX)
    return out, n


def intersect(a, la, b, lb):
    """Sorted-set intersection -> (padded result sized like a, count).

    Replaces algo/uidlist.go:142 IntersectWith (and the compressed
    IntersectCompressedWith path used by posting/list.go:1799).
    """
    return compact(a, membership(a, la, b, lb))


def difference(a, la, b, lb):
    """a \\ b -> (padded result sized like a, count). Ref algo/uidlist.go:332."""
    keep = _iota_mask(a.shape[0], la) & ~membership(a, la, b, lb)
    return compact(a, keep)


def union(a, la, b, lb):
    """Sorted-set union -> (padded result sized len(a)+len(b), count).

    Ref algo/uidlist.go:448 MergeSorted (2-way case): concatenate, single
    sort with invalid-last composite key, adjacent-dedupe, compact.
    """
    x = jnp.concatenate([a, b])
    valid = jnp.concatenate(
        [_iota_mask(a.shape[0], la), _iota_mask(b.shape[0], lb)]
    )
    order = jnp.lexsort((x, ~valid))
    xs = jnp.take(x, order)
    vs = jnp.take(valid, order)
    prev_diff = jnp.concatenate(
        [jnp.ones((1,), dtype=bool), xs[1:] != xs[:-1]]
    )
    return compact(xs, vs & prev_diff)


def merge_sorted(lists, lengths):
    """K-way sorted union. lists: (k, n) uint32, lengths: (k,) int32.

    Replaces the threaded 10-way heap merge of algo/uidlist.go:465-542 with
    one flattened sort + dedupe on device.
    """
    k, n = lists.shape
    x = lists.reshape(-1)
    valid = (
        jnp.arange(n, dtype=jnp.int32)[None, :] < lengths[:, None]
    ).reshape(-1)
    order = jnp.lexsort((x, ~valid))
    xs = jnp.take(x, order)
    vs = jnp.take(valid, order)
    prev_diff = jnp.concatenate(
        [jnp.ones((1,), dtype=bool), xs[1:] != xs[:-1]]
    )
    return compact(xs, vs & prev_diff)


def intersect_many(lists, lengths):
    """Intersection of k sorted lists. lists: (k, n), lengths: (k,).

    Replaces algo/uidlist.go:297 IntersectSorted (smallest-first fold) with a
    membership-count formulation: an element of list 0 survives iff it is
    found in all k lists. One searchsorted per list, fully batched.
    """
    k, n = lists.shape
    a = lists[0]
    la = lengths[0]

    def body(i, cnt):
        m = membership(a, la, lists[i], lengths[i])
        return cnt + m.astype(jnp.int32)

    cnt = jax.lax.fori_loop(1, k, body, jnp.zeros((n,), jnp.int32))
    keep = _iota_mask(n, la) & (cnt == k - 1)
    return compact(a, keep)


def index_of(a, la, u):
    """Position of u in a[:la], or -1. Ref algo/uidlist.go:546."""
    idx = jnp.searchsorted(a, u, method="scan_unrolled")
    idx_c = jnp.minimum(idx, a.shape[0] - 1)
    hit = (idx < la) & (jnp.take(a, idx_c) == u)
    return jnp.where(hit, idx, -1)


# ---------------------------------------------------------------------------
# Batched (vmapped) forms — one device dispatch per fan-out level.
# ---------------------------------------------------------------------------

batch_membership = jax.vmap(membership)
batch_intersect = jax.vmap(intersect)
batch_difference = jax.vmap(difference)
batch_union = jax.vmap(union)
batch_merge_sorted = jax.vmap(merge_sorted)
batch_intersect_many = jax.vmap(intersect_many)

"""Language-aware string collation for ordered queries.

The reference sorts lang-tagged values with a per-language collator
(x/text/collate via query sort on name@de etc. — see the
LanguageOrderIndexed golden suite: German sorts o-umlaut next to o,
Swedish sorts it after z). We implement the small rule set those suites
exercise: diacritic-folding as the general Latin rule, with the
Scandinavian letters re-based after 'z' for sv/da/nb/nn/fi.
"""

from __future__ import annotations

import unicodedata

# Scandinavian alphabets append these AFTER z, in this order
_SCAN_ORDER = {
    "å": "{a", "ä": "{b", "æ": "{b", "ö": "{c", "ø": "{c",
    "Å": "{a", "Ä": "{b", "Æ": "{b", "Ö": "{c", "Ø": "{c",
}
_SCAN_LANGS = {"sv", "da", "nb", "nn", "no", "fi", "is"}


def _fold(ch: str) -> str:
    d = unicodedata.normalize("NFD", ch)
    return "".join(c for c in d if not unicodedata.combining(c))


def collate_key(s: str, lang: str = "") -> tuple:
    """Sort key matching the reference's per-language collation closely
    enough for the golden suites: primary = folded letters (or the
    rebased Scandinavian ones), secondary = the raw string for
    deterministic ties."""
    base = lang.split("-")[0].lower() if lang else ""
    out = []
    for ch in s:
        if base in _SCAN_LANGS and ch in _SCAN_ORDER:
            out.append(_SCAN_ORDER[ch])
        else:
            out.append(_fold(ch).lower())
    return ("".join(out), s)

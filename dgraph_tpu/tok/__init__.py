from dgraph_tpu.tok.tok import get_tokenizer, get_tokenizers, Tokenizer, build_tokens

"""Light stemmers + stopword lists for multi-language fulltext.

The reference's fulltext tokenizer analyzes per-language via bleve
(tok/tok.go FullTextTokenizer{lang}, LangBase resolution): stemming and
stopwords switch on the value's @lang tag. This module provides compact
"light" suffix-strippers (the Lucene light-stemmer family shape — strip
plural/gender/case endings, no full snowball tables) for the languages
the test corpus exercises, with English delegating to the Porter stemmer
in tok.py. Unknown languages fall back to no-op stemming with an empty
stopword set — same degradation bleve applies for unsupported langs.
"""

from __future__ import annotations

from typing import Callable, Dict, FrozenSet, Tuple

_ES_STOP = frozenset(
    "de la que el en y a los del se las por un para con no una su al es "
    "lo como más pero sus le ya o este sí porque esta entre cuando muy "
    "sin sobre también me hasta hay donde quien desde todo nos durante "
    "todos uno les ni contra otros ese eso ante ellos e esto mí antes "
    "algunos qué unos yo otro otras otra él tanto esa estos mucho".split()
)

_FR_STOP = frozenset(
    "au aux avec ce ces dans de des du elle en et eux il je la le les leur "
    "lui ma mais me même mes moi mon ne nos notre nous on ou par pas "
    "pour qu que qui sa se ses son sur ta te tes toi ton tu un une vos "
    "votre vous c d j l à m n s t y été étée être".split()
)

_DE_STOP = frozenset(
    "aber alle als also am an auch auf aus bei bin bis bist da damit "
    "dann der den des dem die das daß du er sie es ein eine einem einen "
    "einer eines für hatte hatten hier ich ihr ihre im in ist ja kann "
    "können mein mit muss nach nicht noch nun nur oder sehr sind so "
    "über um und uns unter vom von vor war waren wenn werden wie wieder "
    "wir wird zu zum zur".split()
)

_PT_STOP = frozenset(
    "de a o que e do da em um para é com não uma os no se na por mais "
    "as dos como mas foi ao ele das tem à seu sua ou ser quando muito "
    "há nos já está eu também só pelo pela até isso ela entre era "
    "depois sem mesmo aos ter seus quem nas me esse eles estão você".split()
)

_IT_STOP = frozenset(
    "ad al allo ai agli alla alle con col da dal dallo dai dagli dalla "
    "dalle di del dello dei degli della delle in nel nello nei negli "
    "nella nelle su sul sullo sui sugli sulla sulle per tra contro io "
    "tu lui lei noi voi loro mio mia miei mie che chi cui non più e è "
    "il lo la i gli le un uno una ma ed se perché anche come".split()
)

_RU_STOP = frozenset(
    "и в во не что он на я с со как а то все она так его но да ты к у "
    "же вы за бы по только ее мне было вот от меня еще нет о из ему "
    "теперь когда даже ну ли если уже или ни быть был него до вас "
    "нибудь опять уж вам ведь там потом себя ничего ей может они тут "
    "где есть надо ней для мы тебя их чем была сам чтоб без будто".split()
)


def _strip(word: str, suffixes, min_len: int = 4) -> str:
    for suf in suffixes:
        if word.endswith(suf) and len(word) - len(suf) >= min_len - 1:
            return word[: len(word) - len(suf)]
    return word


def _es(word: str) -> str:
    return _strip(
        word,
        (
            "amientos", "imientos", "amiento", "imiento", "aciones",
            "adoras", "adores", "ancias", "ación", "adora", "ador",
            "ancia", "mente", "ibles", "istas", "able", "ible", "ista",
            "osos", "osas", "oso", "osa", "ces", "es", "os", "as", "a",
            "o", "e",
        ),
    )


def _fr(word: str) -> str:
    return _strip(
        word,
        (
            "issements", "issement", "atrices", "ateurs", "ations",
            "atrice", "ateur", "ation", "euses", "ments", "ement",
            "euse", "ances", "ance", "ence", "ités", "ité", "eurs",
            "eur", "ives", "ive", "ifs", "if", "es", "s", "e",
        ),
    )


def _de(word: str) -> str:
    return _strip(
        word,
        ("erinnen", "erin", "heiten", "heit", "keiten", "keit", "ungen",
         "ung", "isch", "chen", "lein", "ern", "em", "er", "es", "en",
         "e", "s", "n"),
    )


def _pt(word: str) -> str:
    return _strip(
        word,
        ("amentos", "imentos", "amento", "imento", "adoras", "adores",
         "aço~es", "ações", "ação", "mente", "idades", "idade", "ismos",
         "ismo", "istas", "ista", "osos", "osas", "oso", "osa", "es",
         "os", "as", "a", "o", "e"),
    )


def _it(word: str) -> str:
    return _strip(
        word,
        ("azioni", "azione", "amenti", "imenti", "amento", "imento",
         "mente", "atrici", "atori", "atore", "anze", "anza", "ichi",
         "iche", "abili", "abile", "ibili", "ibile", "oso", "osa",
         "osi", "ose", "i", "e", "a", "o"),
    )


def _ru(word: str) -> str:
    return _strip(
        word,
        ("иями", "ями", "ами", "ией", "иям", "ием", "иях", "ого",
         "его", "ому", "ему", "ыми", "ими", "ая", "яя", "ое", "ее",
         "ие", "ые", "ой", "ей", "ий", "ый", "ам", "ям", "ах", "ях",
         "ов", "ев", "ы", "и", "а", "я", "о", "е", "у", "ю", "ь"),
        min_len=3,
    )


# lang -> (stemmer, stopwords); "en" resolves inside tok.py (Porter)
REGISTRY: Dict[str, Tuple[Callable[[str], str], FrozenSet[str]]] = {
    "es": (_es, _ES_STOP),
    "fr": (_fr, _FR_STOP),
    "de": (_de, _DE_STOP),
    "pt": (_pt, _PT_STOP),
    "it": (_it, _IT_STOP),
    "ru": (_ru, _RU_STOP),
}


def lang_base(lang: str) -> str:
    """'fr-CA' -> 'fr' (ref tok LangBase)."""
    return (lang or "").split("-")[0].split("_")[0].lower()

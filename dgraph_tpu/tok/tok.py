"""Tokenizer registry for index maintenance.

Mirrors /root/reference/tok/tok.go: the Tokenizer interface (:58 — Name,
Type, Tokens, Identifier byte, IsSortable, IsLossy) and the builtin set
(registry :84-108): term, exact, full-text (stemmed), int, float, bool,
datetime granularities (year/month/day/hour), hash, trigram, sha256, geo.

Each token is prefixed with the tokenizer's identifier byte (tok.go:33-56)
so different tokenizers' terms never collide inside one predicate's index
range and sortable indexes iterate in order.
"""

from __future__ import annotations

import hashlib
import re
import struct
import unicodedata
from typing import Dict, List

from dgraph_tpu.types.types import TypeID, Val, convert

# identifier bytes (ref tok/tok.go:33-56)
IDENT_TERM = 0x1
IDENT_EXACT = 0x2
IDENT_YEAR = 0x4
IDENT_MONTH = 0x41
IDENT_DAY = 0x42
IDENT_HOUR = 0x43
IDENT_GEO = 0x5
IDENT_INT = 0x6
IDENT_FLOAT = 0x7
IDENT_FULLTEXT = 0x8
IDENT_BOOL = 0x9
IDENT_TRIGRAM = 0xA
IDENT_HASH = 0xB
IDENT_SHA = 0xC
IDENT_BIGFLOAT = 0xD
IDENT_VFLOAT = 0xE


class Tokenizer:
    name: str = ""
    type_id: TypeID = TypeID.STRING
    identifier: int = 0
    is_sortable: bool = False
    is_lossy: bool = True

    def tokens(self, v: Val) -> List[bytes]:
        raise NotImplementedError

    def prefix(self) -> bytes:
        return bytes([self.identifier])

    def _wrap(self, toks: List[bytes]) -> List[bytes]:
        p = self.prefix()
        return [p + t for t in toks]


_STOPWORDS = frozenset(
    """a an and are as at be by for from has he in is it its of on that the
    to was were will with this those these you your i we they them he she our
    not no or but if then so what which who whom""".split()
)

_word_re = re.compile(r"[\w']+", re.UNICODE)


def _normalize(s: str) -> str:
    # strip accents, lowercase (ref tok uses bleve's unicode normalizer)
    nfkd = unicodedata.normalize("NFKD", s)
    return "".join(c for c in nfkd if not unicodedata.combining(c)).lower()


def _porter_stem(w: str) -> str:
    """Tiny porter-style suffix stripper (stand-in for bleve stemmers,
    ref tok/stemmers.go; full porter in later rounds)."""
    for suf in ("ingly", "edly", "ing", "ed", "ly", "ies", "es", "s"):
        if w.endswith(suf) and len(w) - len(suf) >= 3:
            w = w[: -len(suf)]
            if suf == "ies":
                w += "y"
            break
    return w


class TermTokenizer(Tokenizer):
    name = "term"
    type_id = TypeID.STRING
    identifier = IDENT_TERM

    def tokens(self, v: Val) -> List[bytes]:
        words = _word_re.findall(_normalize(str(v.value)))
        return self._wrap(sorted({w.encode("utf-8") for w in words}))


class ExactTokenizer(Tokenizer):
    name = "exact"
    type_id = TypeID.STRING
    identifier = IDENT_EXACT
    is_sortable = True
    is_lossy = False

    def tokens(self, v: Val) -> List[bytes]:
        return self._wrap([str(v.value).encode("utf-8")])


_CJK_LANGS = frozenset(("zh", "ja", "ko"))


def _is_cjk(ch: str) -> bool:
    o = ord(ch)
    return (
        0x4E00 <= o <= 0x9FFF      # CJK unified ideographs
        or 0x3400 <= o <= 0x4DBF   # extension A
        or 0x3040 <= o <= 0x30FF   # hiragana + katakana
        or 0xAC00 <= o <= 0xD7AF   # hangul syllables
        or 0xF900 <= o <= 0xFAFF   # compatibility ideographs
    )


def _has_cjk(s: str) -> bool:
    return any(_is_cjk(c) for c in s)


def _cjk_terms(text: str):
    """bleve cjk_bigram semantics: each run of CJK characters emits
    overlapping bigrams (a lone character emits itself); intervening
    non-CJK segments tokenize as plain lowercase words."""
    out = []
    run: List[str] = []
    other: List[str] = []

    def flush_run():
        if len(run) == 1:
            out.append(run[0])
        else:
            for i in range(len(run) - 1):
                out.append(run[i] + run[i + 1])
        run.clear()

    def flush_other():
        if other:
            out.extend(_word_re.findall(_normalize("".join(other))))
            other.clear()

    for ch in text:
        if _is_cjk(ch):
            flush_other()
            run.append(ch)
        else:
            flush_run() if run else None
            other.append(ch)
    flush_run() if run else None
    flush_other()
    return out


class FulltextTokenizer(Tokenizer):
    """Language-aware full-text analysis (ref tok.go FullTextTokenizer:
    per-@lang bleve analyzers; LangBase resolution). English stems with
    Porter; other supported languages use the light stemmers in
    stemmers.py; unknown languages tokenize without stemming."""

    name = "fulltext"
    type_id = TypeID.STRING
    identifier = IDENT_FULLTEXT

    def tokens(self, v: Val, lang: str = "") -> List[bytes]:
        from dgraph_tpu.tok.stemmers import REGISTRY, lang_base

        text = str(v.value)
        base = lang_base(lang)
        if base in _CJK_LANGS:
            # CJK analysis (tag-driven ONLY — sniffing content would
            # desync index vs query tokenization for mixed text): no
            # stemming/stopwords; ideograph runs index as overlapping
            # bigrams (bleve's cjk_bigram filter, the analyzer tok.go
            # selects for zh/ja/ko); other script runs go through the
            # plain word pipeline
            toks = {t.encode("utf-8") for t in _cjk_terms(text)}
            return self._wrap(sorted(toks))
        words = _word_re.findall(_normalize(text))
        if base and base != "en" and base in REGISTRY:
            stem, stop = REGISTRY[base]
            toks = {
                stem(w).encode("utf-8") for w in words if w not in stop
            }
        else:
            toks = {
                _porter_stem(w).encode("utf-8")
                for w in words
                if w not in _STOPWORDS
            }
        return self._wrap(sorted(toks))


IDENT_NGRAM = 0xF


class NGramTokenizer(Tokenizer):
    """Word-shingle n-grams over the fulltext pipeline (ref tok.go:522
    NGramTokenizer). Index time emits 1..4-gram shingles per position;
    query time emits a sliding min(3, n)-gram window. Shingles >= 30
    chars are replaced by their blake2b-256 digest (tok.go:475)."""

    name = "ngram"
    type_id = TypeID.STRING
    identifier = IDENT_NGRAM

    @staticmethod
    def _analyze(v: Val, lang: str = "") -> List[str]:
        from dgraph_tpu.tok.stemmers import REGISTRY, lang_base

        words = _word_re.findall(_normalize(str(v.value)))
        base = lang_base(lang)
        if base and base != "en" and base in REGISTRY:
            stem, stop = REGISTRY[base]
            return [stem(w) for w in words if w not in stop]
        return [_porter_stem(w) for w in words if w not in _STOPWORDS]

    @staticmethod
    def _shingle(tok: str) -> bytes:
        # 30-byte cutoff is in UTF-8 bytes, not chars (ref tok.go:475)
        raw = tok.encode("utf-8")
        if len(raw) < 30:
            return raw
        import hashlib

        return hashlib.blake2b(raw, digest_size=32).digest()

    def tokens(self, v: Val, lang: str = "") -> List[bytes]:
        ws = self._analyze(v, lang)
        out = set()
        for i in range(len(ws)):
            for g in (1, 2, 3, 4):
                if i + g <= len(ws):
                    out.add(self._shingle(" ".join(ws[i : i + g])))
        return self._wrap(sorted(out))

    def query_tokens(self, v: Val, lang: str = "") -> List[bytes]:
        ws = self._analyze(v, lang)
        if not ws:
            return []
        g = min(3, len(ws))
        out = {
            self._shingle(" ".join(ws[i : i + g]))
            for i in range(len(ws) - g + 1)
        }
        return self._wrap(sorted(out))


def _enc_int_sortable(x: int) -> bytes:
    # flip sign bit so lexicographic byte order == numeric order
    return struct.pack(">Q", (x + (1 << 63)) & ((1 << 64) - 1))


class IntTokenizer(Tokenizer):
    name = "int"
    type_id = TypeID.INT
    identifier = IDENT_INT
    is_sortable = True
    is_lossy = False

    def tokens(self, v: Val) -> List[bytes]:
        return self._wrap([_enc_int_sortable(int(convert(v, TypeID.INT).value))])


class FloatTokenizer(Tokenizer):
    name = "float"
    type_id = TypeID.FLOAT
    identifier = IDENT_FLOAT
    is_sortable = True
    is_lossy = True

    def tokens(self, v: Val) -> List[bytes]:
        # reference floats index at int granularity (tok.go FloatTokenizer)
        return self._wrap(
            [_enc_int_sortable(int(convert(v, TypeID.FLOAT).value))]
        )


class BoolTokenizer(Tokenizer):
    name = "bool"
    type_id = TypeID.BOOL
    identifier = IDENT_BOOL
    is_lossy = False

    def tokens(self, v: Val) -> List[bytes]:
        return self._wrap([b"\x01" if convert(v, TypeID.BOOL).value else b"\x00"])


class _DateTokenizer(Tokenizer):
    type_id = TypeID.DATETIME
    is_sortable = True

    def _parts(self, v: Val):
        return convert(v, TypeID.DATETIME).value

    def _enc(self, *fields: int) -> List[bytes]:
        return self._wrap([b"".join(struct.pack(">H", f) for f in fields)])


class YearTokenizer(_DateTokenizer):
    name = "year"
    identifier = IDENT_YEAR

    def tokens(self, v):
        dt = self._parts(v)
        return self._enc(dt.year)


class MonthTokenizer(_DateTokenizer):
    name = "month"
    identifier = IDENT_MONTH

    def tokens(self, v):
        dt = self._parts(v)
        return self._enc(dt.year, dt.month)


class DayTokenizer(_DateTokenizer):
    name = "day"
    identifier = IDENT_DAY

    def tokens(self, v):
        dt = self._parts(v)
        return self._enc(dt.year, dt.month, dt.day)


class HourTokenizer(_DateTokenizer):
    name = "hour"
    identifier = IDENT_HOUR

    def tokens(self, v):
        dt = self._parts(v)
        return self._enc(dt.year, dt.month, dt.day, dt.hour)


class HashTokenizer(Tokenizer):
    name = "hash"
    type_id = TypeID.STRING
    identifier = IDENT_HASH
    is_lossy = False  # treated as non-lossy for eq (ref tok.go:372)

    def tokens(self, v: Val) -> List[bytes]:
        h = hashlib.blake2b(
            str(v.value).encode("utf-8"), digest_size=8
        ).digest()
        return self._wrap([h])


class Sha256Tokenizer(Tokenizer):
    name = "sha256"
    type_id = TypeID.STRING
    identifier = IDENT_SHA
    is_lossy = False

    def tokens(self, v: Val) -> List[bytes]:
        return self._wrap([hashlib.sha256(str(v.value).encode()).digest()])


class TrigramTokenizer(Tokenizer):
    name = "trigram"
    type_id = TypeID.STRING
    identifier = IDENT_TRIGRAM

    def tokens(self, v: Val) -> List[bytes]:
        s = str(v.value)
        if len(s) < 3:
            return []
        toks = {s[i : i + 3].encode("utf-8") for i in range(len(s) - 2)}
        return self._wrap(sorted(toks))


class GeoTokenizer(Tokenizer):
    """Geo cell tokenizer. Reference uses S2 cell coverings
    (types/s2index.go IndexCells); we use a quadtree cell scheme over
    lon/lat with levels 5..12 — same contract (a point indexes the chain of
    containing cells; near/within queries expand to cover cells)."""

    name = "geo"
    type_id = TypeID.GEO
    identifier = IDENT_GEO

    MIN_LEVEL = 5
    MAX_LEVEL = 12

    @staticmethod
    def cell_at(lon: float, lat: float, level: int) -> bytes:
        x = int((lon + 180.0) / 360.0 * (1 << level))
        y = int((lat + 90.0) / 180.0 * (1 << level))
        x = min(max(x, 0), (1 << level) - 1)
        y = min(max(y, 0), (1 << level) - 1)
        return struct.pack(">BII", level, x, y)

    def tokens(self, v: Val) -> List[bytes]:
        geo = v.value
        if isinstance(geo, (str, bytes)):
            import json

            geo = json.loads(geo)
        coords = _geo_points(geo)
        toks = set()
        for lon, lat in coords:
            for lvl in range(self.MIN_LEVEL, self.MAX_LEVEL + 1):
                toks.add(self.cell_at(lon, lat, lvl))
        # areal geometries additionally index their bbox COVER cells per
        # level (bounded per level), so contains(point)/intersects(poly)
        # lookups hit interior cells — the S2 covering contract
        # (ref types/s2index.go IndexCells for regions)
        if geo.get("type", "").lower() in ("polygon", "multipolygon"):
            lons = [p[0] for p in coords]
            lats = [p[1] for p in coords]
            lon0, lon1 = min(lons), max(lons)
            lat0, lat1 = min(lats), max(lats)
            for lvl in range(self.MIN_LEVEL, self.MAX_LEVEL + 1):
                cw = 360.0 / (1 << lvl)
                ch = 180.0 / (1 << lvl)
                nx = int((lon1 - lon0) / cw) + 2
                ny = int((lat1 - lat0) / ch) + 2
                if nx * ny > 256:
                    break  # finer levels explode; coarse cover suffices
                x = lon0
                while x <= lon1 + cw:
                    y = lat0
                    while y <= lat1 + ch:
                        toks.add(self.cell_at(min(x, lon1), min(y, lat1), lvl))
                        y += ch
                    x += cw
        return self._wrap(sorted(toks))


def _geo_points(geo) -> List[tuple]:
    t = geo.get("type", "").lower()
    c = geo.get("coordinates")
    if t == "point":
        return [tuple(c)]
    if t == "polygon":
        return [tuple(p) for ring in c for p in ring]
    if t == "multipolygon":
        return [tuple(p) for poly in c for ring in poly for p in ring]
    if t == "linestring":
        return [tuple(p) for p in c]
    raise ValueError(f"unsupported geo type {t!r}")


_REGISTRY: Dict[str, Tokenizer] = {}


def register(t: Tokenizer):
    if t.name in _REGISTRY:
        raise ValueError(f"duplicate tokenizer {t.name}")
    _REGISTRY[t.name] = t


for _t in (
    TermTokenizer(),
    ExactTokenizer(),
    FulltextTokenizer(),
    IntTokenizer(),
    FloatTokenizer(),
    BoolTokenizer(),
    YearTokenizer(),
    MonthTokenizer(),
    DayTokenizer(),
    HourTokenizer(),
    HashTokenizer(),
    Sha256Tokenizer(),
    TrigramTokenizer(),
    GeoTokenizer(),
    NGramTokenizer(),
):
    register(_t)


def get_tokenizer(name: str) -> Tokenizer:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise ValueError(f"unknown tokenizer {name!r}") from None


def get_tokenizers(names) -> List[Tokenizer]:
    return [get_tokenizer(n) for n in names]


def default_tokenizer_for(tid: TypeID) -> Tokenizer:
    """Default index tokenizer per type (ref schema defaults)."""
    return {
        TypeID.INT: get_tokenizer("int"),
        TypeID.FLOAT: get_tokenizer("float"),
        TypeID.BOOL: get_tokenizer("bool"),
        TypeID.DATETIME: get_tokenizer("year"),
        TypeID.GEO: get_tokenizer("geo"),
        TypeID.STRING: get_tokenizer("term"),
        TypeID.DEFAULT: get_tokenizer("term"),
    }.get(tid, get_tokenizer("term"))


def build_tokens(v: Val, tokenizers, lang: str = "") -> List[bytes]:
    """All index tokens for value v under the given tokenizers
    (ref posting/index.go:52 indexTokens). `lang` reaches the
    language-aware tokenizers (fulltext) from the posting's @lang tag."""
    out: List[bytes] = []
    for t in tokenizers:
        conv = convert(v, t.type_id) if v.tid != t.type_id else v
        if isinstance(t, FulltextTokenizer):
            out.extend(t.tokens(conv, lang=lang))
        else:
            out.extend(t.tokens(conv))
    return out

"""dgraph_tpu: a TPU-native distributed graph database framework.

A from-scratch rebuild of the capabilities of dgraph-io/dgraph (reference at
/root/reference): predicate-sharded posting lists, MVCC transactions with a
Zero-style oracle, DQL query execution, full-text/geo/vector indexing, loaders,
backup/export — with the hot query kernels (sorted-UID set algebra, batched
per-predicate task fan-out, vector top-k) redesigned as batched JAX/XLA
kernels running on TPU.

Layer map (mirrors SURVEY.md §1):
  ops/      — device kernels: sorted-set algebra, top-k    (ref: algo/, codec/)
  codec/    — UID pack block codec, host<->device format   (ref: codec/codec.go)
  x/        — key layout, config, errors                   (ref: x/)
  types/    — scalar types & conversion                    (ref: types/)
  tok/      — tokenizer registry                           (ref: tok/)
  schema/   — schema parser & state                        (ref: schema/)
  storage/  — host KV store (badger equivalent)            (ref: badger dep)
  posting/  — MVCC posting lists, local cache              (ref: posting/)
  zero/     — ts/UID leasing, txn oracle                   (ref: dgraph/cmd/zero)
  dql/      — DQL lexer + parser                           (ref: lex/, dql/)
  query/    — SubGraph executor w/ batched device dispatch (ref: query/, worker/task.go)
  models/   — vector index families (brute/IVF)            (ref: tok/hnsw)
  parallel/ — mesh, shardings, distributed kernels         (ref: conn/, worker sharding)
  loaders/  — RDF/JSON chunker, bulk/live loaders          (ref: chunker/, cmd/bulk, cmd/live)
  api/      — transaction/API front-end                    (ref: edgraph/)
"""

__version__ = "0.1.0"

# Persistent XLA compile cache, engine-wide (tests/conftest.py sets the
# same for tests). Query shapes are pow2-bucketed, so a warm cache turns
# every recurring bucket's compile (seconds on this 1-core host; 60-115s
# through the remote-TPU compile service) into a disk hit. Env vars are
# read at first backend init, which is always after package import.
import os as _os
import tempfile as _tempfile

_os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    _os.path.join(
        _tempfile.gettempdir(), f"dgraph_tpu_jax_cache-{_os.getuid()}"
    ),
)
_os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
_os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
del _os, _tempfile

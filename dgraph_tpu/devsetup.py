"""Backend setup helpers for scripts and benchmarks.

The environment's sitecustomize registers a remote-TPU ("axon") PJRT
backend in every python process. When that tunnel is down, ANY jax
backend initialization can hang — even with JAX_PLATFORMS=cpu, because
enumeration still initializes registered plugins. The reliable
neutralization (same as tests/conftest.py) is to unregister the factory
before the first backend init.

Call `force_cpu()` at the top of a script that must run on the host, or
set DGRAPH_TPU_FORCE_CPU=1 (honored by the benchmarks and by bench.py's
fallback path).
"""

from __future__ import annotations

import os


def force_cpu(device_count: int = 1) -> None:
    """Unregister the axon backend and pin jax to the CPU platform.
    Must run before any jax backend is initialized."""
    import re

    flags = os.environ.get("XLA_FLAGS", "")
    if device_count > 1:
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "", flags)
        flags = (
            flags + f" --xla_force_host_platform_device_count={device_count}"
        ).strip()
        os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    try:
        from jax._src import xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass
    jax.config.update("jax_platforms", "cpu")


def maybe_force_cpu() -> None:
    """Honor DGRAPH_TPU_FORCE_CPU=1 or JAX_PLATFORMS=cpu."""
    from dgraph_tpu.x import config

    if (
        config.get("FORCE_CPU")
        or os.environ.get("JAX_PLATFORMS", "") == "cpu"
    ):
        force_cpu()

"""Protocol buffers for the public client API.

api_pb2 is generated from api.proto by protoc at first import (and cached
beside the .proto): checking generated code in would pin a protobuf
runtime version, and the baked toolchain already has protoc.
"""

import importlib
import os
import subprocess
import sys

_HERE = os.path.dirname(__file__)


def _ensure_generated():
    gen = os.path.join(_HERE, "api_pb2.py")
    proto = os.path.join(_HERE, "api.proto")
    if not os.path.exists(gen) or os.path.getmtime(gen) < os.path.getmtime(
        proto
    ):
        subprocess.run(
            ["protoc", f"-I{_HERE}", f"--python_out={_HERE}", proto],
            check=True,
        )


def load_api_pb2():
    _ensure_generated()
    if _HERE not in sys.path:
        sys.path.insert(0, _HERE)
    return importlib.import_module("api_pb2")

"""Minimal RFC6455 websocket frames + the graphql-transport-ws protocol.

The reference serves GraphQL subscriptions over websockets
(/root/reference/graphql/subscription/poller.go with the graphql-ws
message protocol); this module gives the HTTP front-end the same
transport with no external dependencies: handshake, text-frame codec
(client->server frames are masked per the RFC), ping/pong, and the
message flow connection_init -> connection_ack, subscribe -> next*/
complete, with both the modern `graphql-transport-ws` and legacy
`graphql-ws` (start/data/stop) vocabularies accepted.
"""

from __future__ import annotations

import base64
import hashlib
import json
import struct
from typing import Optional

_WS_MAGIC = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"


def is_upgrade(headers) -> bool:
    return (
        headers.get("Upgrade", "").lower() == "websocket"
        and "upgrade" in headers.get("Connection", "").lower()
    )


def handshake(handler) -> bool:
    """Complete the server side of the websocket handshake on a
    BaseHTTPRequestHandler. Returns True when the socket is upgraded."""
    key = handler.headers.get("Sec-WebSocket-Key")
    if not key:
        handler.send_response(400)
        handler.end_headers()
        return False
    accept = base64.b64encode(
        hashlib.sha1((key + _WS_MAGIC).encode()).digest()
    ).decode()
    proto = handler.headers.get("Sec-WebSocket-Protocol", "")
    chosen = ""
    for p in (x.strip() for x in proto.split(",")):
        if p in ("graphql-transport-ws", "graphql-ws"):
            chosen = p
            break
    lines = [
        "HTTP/1.1 101 Switching Protocols",
        "Upgrade: websocket",
        "Connection: Upgrade",
        f"Sec-WebSocket-Accept: {accept}",
    ]
    if chosen:
        lines.append(f"Sec-WebSocket-Protocol: {chosen}")
    handler.connection.sendall(("\r\n".join(lines) + "\r\n\r\n").encode())
    return True


def _read_exact(sock, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        got = sock.recv(n - len(buf))
        if not got:
            return None
        buf += got
    return buf


def recv_frame(sock):
    """Returns (opcode, payload bytes) or None on close/EOF."""
    hdr = _read_exact(sock, 2)
    if hdr is None:
        return None
    b1, b2 = hdr
    opcode = b1 & 0x0F
    masked = bool(b2 & 0x80)
    ln = b2 & 0x7F
    if ln == 126:
        ext = _read_exact(sock, 2)
        if ext is None:
            return None
        (ln,) = struct.unpack(">H", ext)
    elif ln == 127:
        ext = _read_exact(sock, 8)
        if ext is None:
            return None
        (ln,) = struct.unpack(">Q", ext)
    mask = b""
    if masked:
        mask = _read_exact(sock, 4)
        if mask is None:
            return None
    payload = _read_exact(sock, ln) if ln else b""
    if payload is None:
        return None
    if masked and payload:
        payload = bytes(c ^ mask[i % 4] for i, c in enumerate(payload))
    return opcode, payload


def send_frame(sock, payload: bytes, opcode: int = 0x1) -> None:
    n = len(payload)
    hdr = bytes([0x80 | opcode])
    if n < 126:
        hdr += bytes([n])
    elif n < 1 << 16:
        hdr += bytes([126]) + struct.pack(">H", n)
    else:
        hdr += bytes([127]) + struct.pack(">Q", n)
    sock.sendall(hdr + payload)


def send_json(sock, obj) -> None:
    send_frame(sock, json.dumps(obj).encode())


def serve_graphql_ws(handler, engine) -> None:
    """Run the graphql-transport-ws session loop on an upgraded socket.

    `subscribe` payloads execute through the engine's GraphQL layer when
    the operation targets it (default), and re-run on every commit that
    touches their predicates — the reference's poller semantics
    (subscription/poller.go) driven by commit events instead of a timer.
    """
    sock = handler.connection
    sock.settimeout(None)
    sub_ids: dict = {}  # ws op id -> Subscriptions sid
    subs = getattr(engine, "_subscriptions", None)
    if subs is None:
        from dgraph_tpu.api.subscriptions import Subscriptions

        subs = Subscriptions(engine)
    import threading

    send_lock = threading.Lock()

    def push(obj):
        with send_lock:
            send_json(sock, obj)

    try:
        while True:
            got = recv_frame(sock)
            if got is None:
                break
            opcode, payload = got
            if opcode == 0x8:  # close
                break
            if opcode == 0x9:  # ping -> pong
                with send_lock:
                    send_frame(sock, payload, opcode=0xA)
                continue
            if opcode not in (0x1, 0x2):
                continue
            try:
                msg = json.loads(payload.decode() or "{}")
            except Exception:
                continue
            mtype = msg.get("type")
            if mtype == "connection_init":
                push({"type": "connection_ack"})
            elif mtype in ("subscribe", "start"):
                op_id = msg.get("id")
                q = (msg.get("payload") or {}).get("query", "")
                variables = (msg.get("payload") or {}).get("variables")
                data_type = "next" if mtype == "subscribe" else "data"

                def cb(result, _id=op_id, _dt=data_type):
                    push({"id": _id, "type": _dt, "payload": result})

                try:
                    sid = subs.subscribe_graphql(
                        q, cb, variables=variables
                    )
                    sub_ids[op_id] = sid
                except Exception as e:
                    push(
                        {
                            "id": op_id,
                            "type": "error",
                            "payload": [{"message": str(e)}],
                        }
                    )
            elif mtype in ("complete", "stop"):
                sid = sub_ids.pop(msg.get("id"), None)
                if sid is not None:
                    subs.unsubscribe(sid)
            elif mtype == "ping":
                push({"type": "pong"})
    finally:
        for sid in sub_ids.values():
            subs.unsubscribe(sid)
        try:
            sock.close()
        except Exception:
            pass

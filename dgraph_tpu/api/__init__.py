from dgraph_tpu.api.server import Server, TxnHandle

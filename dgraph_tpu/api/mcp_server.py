"""MCP server: expose the engine to LLM agents over JSON-RPC.

Mirrors /root/reference/dgraph/cmd/mcp (mcp_server.go:58 NewMCPServer):
tools RunQuery / RunMutation / AlterSchema / GetSchema / GetCommonQueries
over the Model Context Protocol (JSON-RPC 2.0, stdio framing or direct
handle() calls for embedding/tests).
"""

from __future__ import annotations

import json
import sys
from typing import Any, Dict, Optional

_TOOLS = [
    {
        "name": "run_query",
        "description": "Run a DQL query and return JSON results",
        "inputSchema": {
            "type": "object",
            "properties": {"query": {"type": "string"}},
            "required": ["query"],
        },
    },
    {
        "name": "run_mutation",
        "description": "Apply an RDF mutation (set and/or delete N-Quads)",
        "inputSchema": {
            "type": "object",
            "properties": {
                "set_rdf": {"type": "string"},
                "del_rdf": {"type": "string"},
            },
        },
    },
    {
        "name": "alter_schema",
        "description": "Apply a schema definition",
        "inputSchema": {
            "type": "object",
            "properties": {"schema": {"type": "string"}},
            "required": ["schema"],
        },
    },
    {
        "name": "get_schema",
        "description": "Fetch the current schema",
        "inputSchema": {"type": "object", "properties": {}},
    },
    {
        "name": "get_common_queries",
        "description": "Example DQL queries for this database",
        "inputSchema": {"type": "object", "properties": {}},
    },
]


class McpServer:
    def __init__(self, engine):
        self.engine = engine

    # -- JSON-RPC ------------------------------------------------------------

    def handle(self, request: dict) -> Optional[dict]:
        rid = request.get("id")
        method = request.get("method", "")
        try:
            if method == "initialize":
                result = {
                    "protocolVersion": "2024-11-05",
                    "capabilities": {"tools": {}},
                    "serverInfo": {"name": "dgraph-tpu-mcp", "version": "0.1.0"},
                }
            elif method == "tools/list":
                result = {"tools": _TOOLS}
            elif method == "tools/call":
                params = request.get("params", {})
                out = self._call_tool(
                    params.get("name", ""), params.get("arguments", {}) or {}
                )
                result = {
                    "content": [
                        {"type": "text", "text": json.dumps(out, default=str)}
                    ]
                }
            elif method == "notifications/initialized":
                return None
            else:
                return _err(rid, -32601, f"method not found: {method}")
            return {"jsonrpc": "2.0", "id": rid, "result": result}
        except Exception as e:  # noqa: BLE001 — protocol error envelope
            return _err(rid, -32000, str(e))

    def _call_tool(self, name: str, args: Dict[str, Any]):
        if name == "run_query":
            return self.engine.query(args["query"])
        if name == "run_mutation":
            txn = self.engine.new_txn()
            uids = txn.mutate_rdf(
                set_rdf=args.get("set_rdf", ""),
                del_rdf=args.get("del_rdf", ""),
                commit_now=True,
            )
            return {"uids": uids}
        if name == "alter_schema":
            self.engine.alter(args["schema"])
            return {"code": "Success"}
        if name == "get_schema":
            from dgraph_tpu.admin.export import _schema_line

            return {
                "schema": "\n".join(
                    _schema_line(self.engine.schema.get(p))
                    for p in self.engine.schema.predicates()
                )
            }
        if name == "get_common_queries":
            return {
                "examples": [
                    '{ q(func: has(<pred>)) { uid expand(_all_) } }',
                    '{ q(func: eq(<pred>, "value")) { uid } }',
                    '{ q(func: similar_to(<vec-pred>, 5, "[...]")) { uid } }',
                ]
            }
        raise ValueError(f"unknown tool {name!r}")

    # -- stdio loop (ref mcp stdio transport) ---------------------------------

    def serve_stdio(self, stdin=None, stdout=None):
        stdin = stdin or sys.stdin
        stdout = stdout or sys.stdout
        for line in stdin:
            line = line.strip()
            if not line:
                continue
            try:
                req = json.loads(line)
            except json.JSONDecodeError:
                continue
            resp = self.handle(req)
            if resp is not None:
                stdout.write(json.dumps(resp) + "\n")
                stdout.flush()


def _err(rid, code, msg):
    return {
        "jsonrpc": "2.0",
        "id": rid,
        "error": {"code": code, "message": msg},
    }

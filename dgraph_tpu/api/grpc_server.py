"""gRPC front-end: the api.Dgraph service stock clients speak.

Mirrors /root/reference/edgraph/server.go (Query/doQuery:1396,
CommitOrAbort:2108, Alter:355) behind the public wire protocol
(protos/api.proto here; ref protos/pb.proto:559-604 service Dgraph), so a
dgo/pydgraph-style client can login, run txn queries, mutate, and commit
without knowing this isn't the reference implementation.

Txn protocol (the dgo contract): the first Query/Mutate in a txn carries
start_ts=0; the server opens a txn and returns its start_ts in
Response.txn. Later requests carry that start_ts; CommitOrAbort ends it.
"""

from __future__ import annotations

import json
import threading
import time
from concurrent import futures
from typing import Dict, Optional

import grpc

from dgraph_tpu.api.server import Server, TxnHandle
from dgraph_tpu.protos import load_api_pb2

pb = load_api_pb2()


class DgraphServicer:
    def __init__(self, engine: Server):
        self.engine = engine
        self._txns: Dict[int, TxnHandle] = {}
        self._lock = threading.Lock()

    # -- txn bookkeeping ------------------------------------------------------

    def _txn_for(self, start_ts: int) -> TxnHandle:
        with self._lock:
            if start_ts == 0:
                h = self.engine.new_txn()
                self._txns[h.start_ts] = h
                return h
            h = self._txns.get(start_ts)
            if h is None:
                # a read at an established ts from another replica/client:
                # synthesize a read-only view at that snapshot
                h = TxnHandle.__new__(TxnHandle)
                h.server = self.engine
                h.start_ts = start_ts
                from dgraph_tpu.posting.lists import Txn

                h.txn = Txn(self.engine.kv, start_ts, mem=self.engine.mem)
                h.read_only = True
                h.finished = False
                self._txns[start_ts] = h
            return h

    def _drop_txn(self, start_ts: int):
        with self._lock:
            self._txns.pop(start_ts, None)

    # -- rpc methods ----------------------------------------------------------

    def Login(self, request, context):
        jwt = {"accessJwt": "", "refreshJwt": ""}
        if self.engine.acl is not None:
            try:
                out = self.engine.login(
                    request.userid, request.password, request.namespace
                )
                jwt = {
                    "accessJwt": out["accessJwt"],
                    "refreshJwt": out.get("refreshJwt", ""),
                }
            except Exception as e:
                context.abort(grpc.StatusCode.UNAUTHENTICATED, str(e))
        resp = pb.Response()
        resp.json = json.dumps(jwt).encode()
        return resp

    def Query(self, request, context):
        t0 = time.monotonic_ns()
        resp = pb.Response()
        try:
            if request.mutations:
                return self._do_mutations(request, resp, t0)
            variables = dict(request.vars) if request.vars else None
            # EXPLAIN/ANALYZE over gRPC: a reserved "debug" entry in
            # Request.vars (stripped before parse — it is a transport
            # flag, not a query variable) turns on plan capture
            debug = False
            if variables is not None:
                debug = variables.pop("debug", "") in ("true", "1")
                variables = variables or None
            if request.resp_format == pb.Request.RDF:
                resp.rdf = self.engine.query_rdf(
                    request.query, variables=variables
                ).encode()
                resp.txn.start_ts = 0
                resp.latency.total_ns = time.monotonic_ns() - t0
                return resp
            if request.read_only:
                out = self.engine.query(
                    request.query, variables=variables, want="raw",
                    debug=debug,
                )
                resp.txn.start_ts = 0
            else:
                h = self._txn_for(request.start_ts)
                h.txn.materialize_cols()  # read-your-writes over columns
                out = self.engine._query_parsed(
                    __import__("dgraph_tpu.dql", fromlist=["parse"]).parse(
                        request.query, variables
                    ),
                    h.txn.cache,
                    0,
                    None,
                    want="raw",
                )
                resp.txn.start_ts = h.start_ts
            d = out["data"]
            # pre-encoded arena bytes splice straight into the proto
            # Json field (query/streamjson.py); plain dicts (schema
            # blocks, the txn path) dump as before
            rawb = getattr(d, "raw", None)
            resp.json = rawb if rawb is not None else json.dumps(d).encode()
            plan = (out.get("extensions") or {}).get("plan")
            if debug and plan is not None:
                # the EXPLAIN plan rides the hdrs side channel so the
                # Json payload stays byte-identical to a non-debug run
                resp.hdrs.append("plan=" + json.dumps(plan))
        except Exception as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        resp.latency.total_ns = time.monotonic_ns() - t0
        return resp

    def _do_mutations(self, request, resp, t0):
        """Request carrying mutations: plain mutate or upsert block
        (ref edgraph/server.go doMutate/buildUpsert)."""
        h = self._txn_for(request.start_ts)
        resp.txn.start_ts = h.start_ts
        uids: Dict[str, str] = {}
        commit_now = request.commit_now or any(
            m.commit_now for m in request.mutations
        )
        for m in request.mutations:
            if request.query:
                got = h.upsert(
                    request.query,
                    set_rdf=m.set_nquads.decode() if m.set_nquads else "",
                    del_rdf=m.del_nquads.decode() if m.del_nquads else "",
                    cond=m.cond or None,
                    commit_now=False,
                )
            elif m.set_json or m.delete_json:
                got = h.mutate_json(
                    set_obj=json.loads(m.set_json) if m.set_json else None,
                    del_obj=(
                        json.loads(m.delete_json) if m.delete_json else None
                    ),
                    commit_now=False,
                )
            else:
                got = h.mutate_rdf(
                    set_rdf=m.set_nquads.decode() if m.set_nquads else "",
                    del_rdf=m.del_nquads.decode() if m.del_nquads else "",
                    commit_now=False,
                )
            uids.update(got or {})
        if commit_now:
            commit_ts = h.commit()
            resp.txn.commit_ts = commit_ts
            self._drop_txn(h.start_ts)
        for k, v in uids.items():
            resp.uids[k] = v
        resp.latency.total_ns = time.monotonic_ns() - t0
        return resp

    def Alter(self, request, context):
        try:
            if request.drop_all or request.drop_op == pb.Operation.ALL:
                self.engine.alter(drop_all=True)
            elif request.drop_attr or request.drop_op == pb.Operation.ATTR:
                self.engine.alter(
                    drop_attr=request.drop_attr or request.drop_value
                )
            else:
                self.engine.alter(schema_text=request.schema)
        except Exception as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        out = pb.Payload()
        out.Data = b"Done"
        return out

    def CommitOrAbort(self, request, context):
        h = self._txns.get(request.start_ts)
        ctx = pb.TxnContext()
        ctx.start_ts = request.start_ts
        if request.aborted:
            if h is not None and not h.finished:
                h.discard()
            self._drop_txn(request.start_ts)
            ctx.aborted = True
            return ctx
        if h is None:
            context.abort(
                grpc.StatusCode.NOT_FOUND,
                f"no transaction at start_ts {request.start_ts}",
            )
        try:
            ctx.commit_ts = h.commit()
        except Exception as e:
            ctx.aborted = True
            self._drop_txn(request.start_ts)
            context.abort(grpc.StatusCode.ABORTED, str(e))
        self._drop_txn(request.start_ts)
        return ctx

    def CheckVersion(self, request, context):
        v = pb.Version()
        v.tag = "dgraph-tpu"
        return v


def serve(engine: Server, host: str = "127.0.0.1", port: int = 0):
    """Start the gRPC server; returns (grpc_server, bound_port)."""
    servicer = DgraphServicer(engine)
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
    handlers = {
        "Login": grpc.unary_unary_rpc_method_handler(
            servicer.Login,
            request_deserializer=pb.LoginRequest.FromString,
            response_serializer=pb.Response.SerializeToString,
        ),
        "Query": grpc.unary_unary_rpc_method_handler(
            servicer.Query,
            request_deserializer=pb.Request.FromString,
            response_serializer=pb.Response.SerializeToString,
        ),
        "Alter": grpc.unary_unary_rpc_method_handler(
            servicer.Alter,
            request_deserializer=pb.Operation.FromString,
            response_serializer=pb.Payload.SerializeToString,
        ),
        "CommitOrAbort": grpc.unary_unary_rpc_method_handler(
            servicer.CommitOrAbort,
            request_deserializer=pb.TxnContext.FromString,
            response_serializer=pb.TxnContext.SerializeToString,
        ),
        "CheckVersion": grpc.unary_unary_rpc_method_handler(
            servicer.CheckVersion,
            request_deserializer=pb.Check.FromString,
            response_serializer=pb.Version.SerializeToString,
        ),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler("api.Dgraph", handlers),)
    )
    bound = server.add_insecure_port(f"{host}:{port}")
    server.start()
    return server, bound

"""In-process API front-end: the edgraph.Server equivalent.

Mirrors /root/reference/edgraph/server.go: Query (doQuery:1396),
Mutate (doMutate:575), Alter (:355 schema & drop ops),
CommitOrAbort (:2108) — single-process round 1 with the ZeroLite seam
standing in for the Zero cluster (ref hooks/config.go ZeroHooks).

Mutations accept RDF text (set/delete) or structured edges; blank nodes
(`_:x`) get fresh uids (ref query/mutation.go:187 AssignUids). Queries run
through dql.parse -> query.Executor -> JsonEncoder.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from dgraph_tpu import dql
from dgraph_tpu.loaders.rdf import NQuad, parse_rdf
from dgraph_tpu.posting import colwrite
from dgraph_tpu.posting.lists import LocalCache, Txn
from dgraph_tpu.posting.mutation import (
    DirectedEdge,
    apply_edge,
    apply_edges,
    delete_entity_attr,
    ingest_vectors,
)
from dgraph_tpu.posting.pl import OP_DEL, OP_SET, encode_deltas
from dgraph_tpu.worker.groupcommit import (
    assign_verdicts,
    columnar_writes,
    commit_phase_ns,
)
from dgraph_tpu.query.streamjson import encode_response_data
from dgraph_tpu.query.subgraph import Executor
from dgraph_tpu.serving.digest import DIGESTS
from dgraph_tpu.schema.schema import State, parse_schema
from dgraph_tpu.storage.kv import KV, open_kv
from dgraph_tpu.types.types import TypeID, Val
from dgraph_tpu.utils import observe
from dgraph_tpu.x import keys
from dgraph_tpu.zero.zero import TxnConflictError, ZeroLite


class TxnHandle:
    """Client-side transaction handle (dgo Txn equivalent)."""

    def __init__(self, server: "Server", read_only: bool = False):
        self.server = server
        self.start_ts = server.zero.begin_txn()
        self.txn = Txn(server.kv, self.start_ts, mem=server.mem)
        self.read_only = read_only
        self.finished = False
        if not read_only:
            colwrite.maybe_enable(self.txn, server)

    def query(self, q: str, access_jwt: Optional[str] = None) -> dict:
        """Query within this txn's snapshot (sees own uncommitted writes)."""
        self.txn.materialize_cols()  # read-your-writes over columns
        blocks = dql.parse(q)
        ns = keys.GALAXY_NS
        allowed = None
        if self.server.acl is not None:
            from dgraph_tpu.acl.acl import READ, AclError

            if access_jwt is None:
                raise AclError("no access token (ACL enabled)")
            claims = self.server.acl.claims(access_jwt)
            ns = int(claims.get("namespace", 0))
            self.server.acl.authorize_preds(
                access_jwt, _query_preds(blocks), READ, claims=claims
            )
            allowed = self.server.acl.readable_preds(claims)
        return self.server._query_parsed(blocks, self.txn.cache, ns, allowed)

    def mutate_rdf(
        self,
        set_rdf: str = "",
        del_rdf: str = "",
        commit_now: bool = False,
        access_jwt: Optional[str] = None,
    ) -> Dict[str, str]:
        from dgraph_tpu.loaders.rdf import parse_rdf as _prdf

        set_nqs, del_nqs = _prdf(set_rdf), _prdf(del_rdf)
        body = f"set:{set_rdf!r} del:{del_rdf!r}"
        ns, user = self.server._authorize_mutation(
            access_jwt,
            sorted({nq.predicate for nq in set_nqs + del_nqs}),
            body,
        )
        self.txn.tenant_ns = ns  # per-tenant commit SLO slice
        uids = self.server._apply_nquads(self.txn, set_nqs, del_nqs, ns)
        if commit_now:
            self.commit()
        return uids

    def mutate_json(
        self,
        set_obj=None,
        del_obj=None,
        commit_now: bool = False,
        access_jwt: Optional[str] = None,
    ):
        if self.server.acl is None and self.server.audit is None:
            # the common unsecured path: computing the predicate set
            # and dumping the audit body would be pure waste per write
            ns = keys.GALAXY_NS
        else:
            body = json.dumps(
                {"set": set_obj, "delete": del_obj}, default=str
            )
            ns, _ = self.server._authorize_mutation(
                access_jwt,
                sorted(_json_preds(set_obj) | _json_preds(del_obj)),
                body,
            )
        self.txn.tenant_ns = ns  # per-tenant commit SLO slice
        uids = self.server._apply_json(self.txn, set_obj, del_obj, ns)
        if commit_now:
            self.commit()
        return uids

    def _upsert_prologue(
        self, query: str, mutation_preds_fn, access_jwt: Optional[str]
    ):
        """Shared upsert front half: ACL (READ on query preds, WRITE on
        mutation preds, JWT namespace) + query execution binding
        uid/val vars. `mutation_preds_fn` is called only when ACL is on
        (computing preds means parsing the mutation — skip it for the
        common unsecured path). Returns (ns, uid_vars, val_vars)."""
        blocks = dql.parse(query) if query.strip() else []
        ns = keys.GALAXY_NS
        if self.server.acl is not None:
            from dgraph_tpu.acl.acl import READ, WRITE, AclError

            if access_jwt is None:
                raise AclError("no access token (ACL enabled)")
            claims = self.server.acl.claims(access_jwt)
            ns = int(claims.get("namespace", 0))
            self.server.acl.authorize_preds(
                access_jwt, _query_preds(blocks), READ, claims=claims
            )
            self.server.acl.authorize_preds(
                access_jwt, sorted(mutation_preds_fn()), WRITE,
                claims=claims,
            )
        uid_vars: Dict[str, List[int]] = {}
        val_vars: Dict[str, dict] = {}
        if blocks:
            self.txn.materialize_cols()  # upsert query reads own writes
            ex = Executor(
                self.txn.cache,
                self.server.schema,
                ns=ns,
                vector_indexes=self.server.vector_indexes,
            )
            ex.process(blocks)
            uid_vars = {
                k: [int(u) for u in v] for k, v in ex.uid_vars.items()
            }
            val_vars = ex.val_vars
        return ns, uid_vars, val_vars

    def upsert(
        self,
        query: str,
        set_rdf: str = "",
        del_rdf: str = "",
        cond: Optional[str] = None,
        commit_now: bool = True,
        access_jwt: Optional[str] = None,
    ) -> Dict[str, str]:
        """Upsert block: run query, substitute uid(v)/val(v) refs in the
        mutation, apply (ref edgraph/server.go:874 buildUpsertQuery +
        dql upsert blocks). `cond` is '@if(eq(len(v), 0))'-style gate."""
        def mpreds():
            from dgraph_tpu.loaders.rdf import parse_rdf as _prdf

            return {
                nq.predicate for nq in _prdf(set_rdf) + _prdf(del_rdf)
            }

        ns, uid_vars, val_vars = self._upsert_prologue(
            query, mpreds, access_jwt
        )
        if cond is not None and not _eval_cond(cond, uid_vars):
            if commit_now:
                self.commit()
            return {}

        out = self.server._apply_rdf_with_vars(
            self.txn, set_rdf, del_rdf, uid_vars, val_vars, ns=ns
        )
        if commit_now:
            self.commit()
        return out

    def upsert_json(
        self,
        query: str,
        mutations: List[dict],
        commit_now: bool = True,
        access_jwt: Optional[str] = None,
    ) -> Dict[str, str]:
        """Multi-mutation JSON upsert: one query block binding uid vars,
        then a list of {"set": obj, "delete": obj, "cond": "@if(...)"}
        mutations applied against those bindings (ref edgraph/server.go
        doQuery with req.Mutations[] — the shape the GraphQL rewriters
        emit, graphql/resolve/mutation_rewriter.go UpsertMutation)."""
        def mpreds():
            return {
                p
                for m in mutations
                for p in (
                    _json_preds(m.get("set"))
                    | _json_preds(m.get("delete"))
                )
            }

        ns, uid_vars, val_vars = self._upsert_prologue(
            query, mpreds, access_jwt
        )
        blanks: Dict[str, int] = {}  # blank-node map SHARED across the
        # request's mutations (ref: one AssignUids per request)
        for m in mutations:
            cond = m.get("cond")
            if cond and not _eval_cond(cond, uid_vars):
                continue
            self.server._apply_json_with_vars(
                self.txn, m.get("set"), m.get("delete"), uid_vars,
                ns=ns, blank=blanks, val_vars=val_vars,
            )
        if commit_now:
            self.commit()
        return {k[2:]: hex(v) for k, v in blanks.items()}

    def commit(self) -> int:
        if self.finished:
            raise RuntimeError("transaction already finished")
        self.finished = True
        return self.server._commit(self.txn)

    def discard(self):
        self.finished = True
        self.server.zero.abort(self.start_ts)


class Server:
    """Single-node engine (Alpha + embedded Zero-lite)."""

    def __init__(
        self,
        data_dir: Optional[str] = None,
        encryption_key: Optional[bytes] = None,
    ):
        self.kv: KV = open_kv(data_dir, encryption_key=encryption_key)
        self.zero = ZeroLite()
        self.schema = State()
        self.vector_indexes: Dict[str, object] = {}
        self._lock = threading.Lock()
        self._group_commit = None  # lazy (worker/groupcommit.py)
        from dgraph_tpu.posting.memlayer import MemoryLayer

        self.acl = None  # enabled via enable_acl() (ref --acl superflag)
        self.audit = None  # enabled via enable_audit()
        from dgraph_tpu.x import config as _config

        # slow-query threshold (instance override of the registry knob)
        self.slow_query_ms = float(_config.get("SLOW_QUERY_MS"))
        self.mem = MemoryLayer()  # shared decoded-list read cache
        from dgraph_tpu.utils.cmsketch import StatsHolder

        self.stats = StatsHolder()  # selectivity stats (auto-fed on commit)
        from dgraph_tpu.serving import ServingFront

        # high-QPS serving front: plan cache + cross-query micro-batcher
        # + admission control (serving/). _snapshot_ts is the batcher's
        # snapshot watermark: the last commit made VISIBLE (published
        # before zero.applied, the barrier read_ts waits on), so two
        # fresh read timestamps covering the same watermark coalesce.
        self._snapshot_ts = 0
        self.serving = ServingFront(
            stats=self.stats,
            schema_fn=lambda: self.schema,
            last_commit_fn=lambda: self._snapshot_ts,
        )
        self._bootstrap_schema()
        if data_dir is not None:
            self._load_persisted_state()
        # warm the native C++ layer off the request path (first import
        # compiles codec.cpp; without this the first query/rollup pays it)
        threading.Thread(
            target=lambda: __import__("dgraph_tpu.native"), daemon=True
        ).start()

    # -- security (ref edgraph/access.go; audit/) -----------------------------

    def enable_acl(self, secret: Optional[bytes] = None, groot_password="password"):
        from dgraph_tpu.acl.acl import AclManager

        self.acl = AclManager(self, secret)
        self.acl.bootstrap(groot_password=groot_password)
        return self.acl

    def enable_audit(self, out_dir: str, key: Optional[bytes] = None):
        from dgraph_tpu.audit.audit import AuditLog

        self.audit = AuditLog(out_dir, key=key)
        return self.audit

    def login(self, user: str, password: str, ns: int = keys.GALAXY_NS):
        if self.acl is None:
            raise RuntimeError("ACL not enabled")
        try:
            out = self.acl.login(user, password, ns)
            self._audit("login", user=user, ns=ns)
            return out
        except Exception:
            self._audit("login", user=user, ns=ns, status="DENIED")
            raise

    def _audit(self, endpoint, user="", ns=0, body="", status="OK"):
        if self.audit is not None:
            self.audit.record(endpoint, user=user, ns=ns, body=body, status=status)

    def _authorize(self, access_jwt, preds, need) -> int:
        """Returns the caller's namespace (0 when ACL off)."""
        if self.acl is None:
            return keys.GALAXY_NS
        from dgraph_tpu.acl.acl import AclError

        if access_jwt is None:
            raise AclError("no access token (ACL enabled)")
        claims = self.acl.claims(access_jwt)
        self.acl.authorize_preds(access_jwt, preds, need)
        return int(claims.get("namespace", 0))

    def _authorize_mutation(self, access_jwt, preds, audit_body):
        """WRITE authorization + audit for any mutation entry point.
        Returns (namespace, user)."""
        ns, user = keys.GALAXY_NS, ""
        if self.acl is not None:
            from dgraph_tpu.acl.acl import WRITE, AclError

            try:
                if access_jwt is None:
                    raise AclError("no access token (ACL enabled)")
                claims = self.acl.claims(access_jwt)
                user = claims.get("userid", "")
                ns = int(claims.get("namespace", 0))
                self.acl.authorize_preds(
                    access_jwt, preds, WRITE, claims=claims
                )
            except Exception:
                self._audit(
                    "mutate", user=user, body=audit_body, status="DENIED"
                )
                raise
        self._audit("mutate", user=user, ns=ns, body=audit_body)
        return ns, user

    def _apply_nquads(self, txn, set_nqs, del_nqs, ns) -> Dict[str, str]:
        blank: Dict[str, int] = {}
        fresh_uids: set = set()  # uids leased by THIS request

        def resolve(ref: str) -> int:
            if ref.startswith("_:"):
                if ref not in blank:
                    blank[ref] = self.zero.assign_uids(1)
                    fresh_uids.add(blank[ref])
                return blank[ref]
            if ref.startswith("0x"):
                return int(ref, 16)
            return int(ref)

        # batched application: plain edges accumulate and flush through
        # apply_edges (bulk reads + bulk tokens); a star delete flushes
        # first so it observes every edge that preceded it in order
        pending: List[DirectedEdge] = []

        def flush():
            if pending:
                apply_edges(txn, self.schema, pending)
                pending.clear()

        for nqs, op in ((set_nqs, OP_SET), (del_nqs, OP_DEL)):
            for nq in nqs:
                if nq.star:
                    if op != OP_DEL:
                        raise ValueError("S P * only valid in delete")
                    flush()
                    delete_entity_attr(
                        txn, self.schema, resolve(nq.subject),
                        nq.predicate, ns,
                    )
                    continue
                e = self._nquad_edge(nq, resolve, op, ns=ns)
                e.fresh = e.entity in fresh_uids
                pending.append(e)
        flush()
        return {k[2:]: hex(v) for k, v in blank.items()}

    def _bootstrap_schema(self):
        # system predicates (ref schema/schema.go initialSchema)
        for su in parse_schema(
            "dgraph.type: [string] @index(exact) .\n"
            "dgraph.xid: string @index(exact) .\n"
        )[0]:
            self.schema.set(su)

    def _load_persisted_state(self):
        """Recover schema + max ts/uid from the KV after restart (ref
        schema load in schema/schema.go LoadFromDb; Zero state from raft)."""
        max_ts = 0
        max_uid = 0
        for key, vers in self.kv.iterate_versions(b"", (1 << 62)):
            if vers:
                max_ts = max(max_ts, vers[0][0])
            try:
                pk = keys.parse_key(key)
            except Exception:
                continue  # non-graph meta keys (e.g. namespace counter)
            if pk.uid is not None:
                max_uid = max(max_uid, pk.uid)
            if pk.is_schema:
                preds, _ = parse_schema(vers[0][1].decode("utf-8"))
                for su in preds:
                    self.schema.set(su)
                    if su.vector_specs:
                        self._ensure_vector_index(su)
            elif pk.is_type:
                _, types = parse_schema(vers[0][1].decode("utf-8"))
                for tu in types:
                    self.schema.set_type(tu)
        while self.zero.max_assigned < max_ts:
            self.zero.next_ts(max_ts - self.zero.max_assigned)
        # re-lease uids past everything on disk, or fresh blank nodes would
        # reuse (and overwrite) existing entities' uids
        if max_uid and max_uid < (1 << 62) and self.zero._max_uid <= max_uid:
            self.zero.assign_uids(max_uid - self.zero._max_uid)
        # seed the snapshot watermark past everything recovered, so
        # watermark reads see the restored store from the first query
        # (max()-guarded: online restore can run beside live commits)
        self._snapshot_ts = max(self._snapshot_ts, self.zero.read_ts())
        self.rebuild_vector_indexes()

    def rebuild_vector_indexes(self):
        """Re-ingest stored vectors into the in-memory vector indexes
        (ref posting/index.go:1354 vector-index rebuild prefixes)."""
        ts = self.zero.read_ts()
        read = LocalCache(self.kv, ts)
        for pred in self.schema.predicates():
            su = self.schema.get(pred)
            if not su or not su.vector_specs:
                continue
            self._ensure_vector_index(su)
            vidx = self.vector_indexes[pred]
            for k, _, _ in self.kv.iterate(keys.DataPrefix(pred), ts):
                pk = keys.parse_key(k)
                for p in read.values(k):
                    vidx.insert(pk.uid, p.val().value)

    # -- alter (ref edgraph/server.go:355) -----------------------------------

    def alter(self, schema_text: str = "", drop_attr: str = "", drop_all: bool = False):
        self.serving.on_commit()  # schema changes invalidate cached plans
        try:
            return self._alter_inner(schema_text, drop_attr, drop_all)
        finally:
            # alters write outside the txn/applied barrier: advance the
            # batcher watermark past every read_ts allocated during the
            # alter, so queries that raced the (non-transactional)
            # schema writes never coalesce with post-alter traffic
            # (max()-guarded like every other watermark writer)
            self._snapshot_ts = max(
                self._snapshot_ts, self.zero.next_ts()
            )

    def _alter_inner(self, schema_text, drop_attr, drop_all):
        with self._lock:
            if drop_all:
                # wipe every key (data + persisted schema/types) so a
                # restart cannot resurrect dropped state
                self.kv.drop_prefix(b"")
                self.schema = State()
                self._bootstrap_schema()
                self.vector_indexes.clear()
                return
            if drop_attr:
                self.kv.drop_prefix(keys.PredicatePrefix(drop_attr))
                self.kv.drop_prefix(keys.SplitPredicatePrefix(drop_attr))
                self.kv.drop_prefix(keys.SchemaKey(drop_attr))
                self.schema.delete(drop_attr)
                self.vector_indexes.pop(drop_attr, None)
                return
            preds, types = parse_schema(schema_text)
            ts = self.zero.next_ts()
            from dgraph_tpu.admin.export import _schema_line

            for su in preds:
                old = self.schema.get(su.predicate)
                self.schema.set(su)
                self.kv.put(
                    keys.SchemaKey(su.predicate),
                    ts,
                    _schema_line(su).encode("utf-8"),
                )
                if su.vector_specs:
                    self._ensure_vector_index(su)
                if old is not None and (
                    old.tokenizers != su.tokenizers
                ):
                    self._reindex(su)
            for tu in types:
                self.schema.set_type(tu)
                fields = "\n  ".join(tu.fields)
                self.kv.put(
                    keys.TypeKey(tu.name),
                    ts,
                    f"type {tu.name} {{\n  {fields}\n}}\n".encode("utf-8"),
                )

    def bump_snapshot(self) -> int:
        """Advance the snapshot watermark past every timestamp leased
        so far. Direct-KV writers that bypass the commit path (bulk
        loaders, the namespace counter) MUST call this after their
        writes land, or watermark reads would never see them; commits
        and alters advance it themselves. Returns the new watermark.
        max()-guarded like every other watermark writer: a commit
        leased after our read_ts may publish a larger watermark before
        this assignment runs."""
        self._snapshot_ts = max(self._snapshot_ts, self.zero.read_ts())
        return self._snapshot_ts

    def _ensure_vector_index(self, su):
        from dgraph_tpu.models.vector import VectorIndex

        if su.predicate not in self.vector_indexes:
            self.vector_indexes[su.predicate] = VectorIndex(
                pred=su.predicate,
                metric=su.vector_specs[0].metric,
            )

    def _reindex(self, su):
        """Full index rebuild for a predicate (ref posting/index.go:1115
        IndexRebuild): drop index range, re-tokenize all values."""
        pred = su.predicate
        self.kv.drop_prefix(keys.IndexPrefix(pred))
        ts = self.zero.next_ts()
        read = LocalCache(self.kv, ts)
        from dgraph_tpu.posting.pl import Posting
        from dgraph_tpu.tok.tok import build_tokens

        tokenizers = su.tokenizer_objs()
        if not tokenizers:
            return
        from dgraph_tpu.posting.pl import encode_delta

        # aggregate uids per index key: entities sharing a token must land
        # in ONE record, since MemKV overwrites same-(key, ts) versions
        # (ref posting/index.go IndexRebuild emits complete per-key lists)
        by_key: Dict[bytes, set] = {}
        for k, _, _ in self.kv.iterate(keys.DataPrefix(pred), ts):
            pk = keys.parse_key(k)
            for p in read.values(k):
                for tokb in build_tokens(p.val(), tokenizers):
                    by_key.setdefault(keys.IndexKey(pred, tokb), set()).add(pk.uid)
        self.kv.put_batch(
            (
                ikey,
                ts,
                encode_delta([Posting(uid=u, op=OP_SET) for u in sorted(uids)]),
            )
            for ikey, uids in by_key.items()
        )

    # -- transactions ---------------------------------------------------------

    def new_txn(self, read_only: bool = False) -> TxnHandle:
        return TxnHandle(self, read_only)

    def _commit(self, txn: Txn) -> int:
        from dgraph_tpu.x import config as _config

        from dgraph_tpu.utils.observe import METRICS as _METRICS

        # a commit-time consumer of Posting objects that appeared after
        # txn creation (CDC sink, subscription, vector index) forces
        # collected columns back to the serial representation
        colwrite.commit_guard(txn, self)
        # admission costs writes too: a commit charges the same
        # in-flight token budget queries draw from (retryable 429 over
        # budget; no-op with DGRAPH_TPU_ADMISSION off)
        n_edges = txn.pending_postings()
        ticket = self.serving.admit_write(n_edges)
        t_commit0 = time.monotonic()
        try:
            if not bool(_config.get("GROUP_COMMIT")):
                # escape hatch (DGRAPH_TPU_GROUP_COMMIT=0): today's
                # serial per-txn path, byte-for-byte
                commit_ts = self._commit_serial(txn)
            else:
                gc = self._group_commit
                if gc is None:
                    with self._lock:
                        gc = self._group_commit
                        if gc is None:
                            from dgraph_tpu.worker.groupcommit import (
                                GroupCommit,
                            )

                            gc = self._group_commit = GroupCommit(
                                self._gc_propose,
                                serial_fn=self._gc_serial,
                            )
                with _METRICS.timer("commit_latency_seconds"):
                    commit_ts = gc.commit(txn)
                if not getattr(txn, "gc_bypassed", False):
                    # the bypass ran the serial path, whose inline
                    # post-commit work already happened
                    self._post_commit(txn, commit_ts)
            # counted for BOTH arms (only on success — the metric is
            # postings WRITTEN): the A/B escape hatch must not turn
            # the edge-throughput denominator dark. Recounted after
            # the commit: the columnar kernel reports its exact
            # posting count (n_edges above was the admission estimate)
            _METRICS.inc(
                "mutation_edges_total",
                sum(len(p) for p in txn.cache.deltas.values())
                + getattr(txn, "col_nposts", 0),
            )
            # per-tenant SLO slice: mutate paths stamp the resolved
            # namespace onto the txn; untagged txns (direct _commit
            # callers) count against the galaxy default
            observe.note_tenant(
                "commit",
                getattr(txn, "tenant_ns", keys.GALAXY_NS),
                time.monotonic() - t_commit0,
            )
            return commit_ts
        finally:
            self.serving.release_write(ticket)

    def _gc_propose(self, members):
        """Group-commit propose phase (batch leader's thread): ONE
        oracle exchange decides every member, then all committed
        members' deltas land under ONE lock hold. Returns the apply
        barrier (watermark + zero.applied in commit-ts order)."""
        from dgraph_tpu.utils.observe import METRICS, TRACER

        with TRACER.span("commit", batch=len(members)):
            t0 = time.perf_counter_ns()
            committed = assign_verdicts(
                members,
                self.zero.commit_batch(
                    [
                        (m.txn.start_ts, m.txn.conflict_keys)
                        for m in members
                    ],
                    track=True,
                ),
            )
            t1 = time.perf_counter_ns()
            try:
                # encode OUTSIDE the lock — columnar members through
                # ONE batch_apply kernel call (worker/groupcommit
                # columnar_writes, which must precede encode_deltas: a
                # materialized fallback lands in cache.deltas), the
                # rest through posting/pl.encode_deltas (one native
                # batched call per txn) — then all batch members'
                # writes land in ONE put_batch under one lock hold
                col_writes = columnar_writes(committed)
                writes = []
                for m in committed:
                    cts = m.commit_ts
                    for key, recb, _attr in col_writes.get(m, ()):
                        writes.append((key, cts, recb))
                    for key, recb in encode_deltas(m.txn.cache.deltas):
                        writes.append((key, cts, recb))
                with self._lock:
                    self.kv.put_batch(writes)
            except Exception as e:
                # NEVER raise past the oracle: the verdicts are
                # tracked pending, and only the barrier below clears
                # them — an exception escaping here would leak
                # _pending entries and stall every later
                # begin_txn/read_ts for the full wait bound
                for m in committed:
                    if m.error is None:
                        m.error = e
            commit_phase_ns(
                oracle=t1 - t0, propose=time.perf_counter_ns() - t1
            )

        def barrier():
            tb = time.perf_counter_ns()
            try:
                with self._lock:
                    for m in committed:
                        # watermark BEFORE the apply barrier, advanced
                        # in commit-ts order (members cts-ascending,
                        # barriers FIFO) — the micro-batcher's
                        # snapshot-grouping proof needs monotonicity;
                        # max() so a concurrent bump_snapshot (bulk
                        # load, namespace counter) never regresses
                        self._snapshot_ts = max(
                            self._snapshot_ts, m.commit_ts
                        )
                        self.zero.applied(m.commit_ts)
                # CDC rides the FIFO barrier, not _post_commit: members
                # here are commit-ts ascending and barriers run in
                # ticket order, so the sink stream stays strictly
                # commit-ts ordered even across batches
                cdc = getattr(self, "_cdc", None)
                if cdc is not None:
                    for m in committed:
                        if m.error is None:
                            cdc.emit_commit(
                                m.commit_ts, m.txn.cache.deltas
                            )
            finally:
                ok = 0
                for m in committed:
                    self.mem.invalidate(m.txn.cache.deltas.keys())
                    ck = getattr(m.txn, "col_keys", None)
                    if ck:
                        self.mem.invalidate(ck)
                    if m.error is None:
                        ok += 1
                if ok:
                    METRICS.inc("num_commits", ok)
                    self.serving.on_commit()  # ONE epoch bump per batch
                commit_phase_ns(apply=time.perf_counter_ns() - tb)

        return barrier

    def _post_commit(self, txn: Txn, commit_ts: int) -> None:
        """Per-txn post-commit work on the committer's own thread
        (stats feed, CDC, subscriptions, vector ingest) — everything
        after the apply barrier that doesn't need batch ordering."""
        self._feed_stats(txn.cache.deltas)
        colwrite.feed_col_stats(self.stats, txn)
        # CDC emission moved into the batch barrier (strict commit-ts
        # order across group-commit batches)
        subs = getattr(self, "_subscriptions", None)
        if subs is not None:
            subs.on_commit(txn.cache.deltas)
        # vector index ingestion at commit (shared factory seam)
        ingest_vectors(self.vector_indexes, txn.cache.deltas)

    def _gc_serial(self, txn: Txn) -> int:
        """Adaptive group-commit bypass target (worker/groupcommit.py):
        the serial path minus its own latency timer (gc.commit's
        caller already runs one), with the txn marked so _commit skips
        the batch-path _post_commit — the serial path does that work
        inline."""
        txn.gc_bypassed = True
        return self._commit_serial(txn, timed=False)

    def _commit_serial(self, txn: Txn, timed: bool = True) -> int:
        # serialized: MemKV is single-writer, and readers must not see a
        # commit_ts whose deltas aren't written yet (ADVICE r1 #2)
        import contextlib

        from dgraph_tpu.utils.observe import METRICS, TRACER

        from dgraph_tpu.worker.groupcommit import commit_phase_ns

        with TRACER.span("commit"), (
            METRICS.timer("commit_latency_seconds")
            if timed
            else contextlib.nullcontext()
        ), self._lock:
            t0 = time.perf_counter_ns()
            commit_ts = self.zero.commit(txn.start_ts, txn.conflict_keys, track=True)
            t1 = time.perf_counter_ns()
            try:
                txn.write_deltas(self.kv, commit_ts)
            finally:
                t2 = time.perf_counter_ns()
                # watermark BEFORE the apply barrier: any read_ts
                # allocated after this commit becomes visible observes
                # the advanced watermark (micro-batcher snapshot key);
                # max() guards a concurrent bump_snapshot
                self._snapshot_ts = max(self._snapshot_ts, commit_ts)
                self.zero.applied(commit_ts)
                commit_phase_ns(
                    oracle=t1 - t0,
                    propose=t2 - t1,
                    apply=time.perf_counter_ns() - t2,
                )
        METRICS.inc("num_commits")
        self.mem.invalidate(txn.cache.deltas.keys())
        ck = getattr(txn, "col_keys", None)
        if ck:
            self.mem.invalidate(ck)
        self.serving.on_commit()  # commit-epoch plan invalidation
        self._feed_stats(txn.cache.deltas)
        colwrite.feed_col_stats(self.stats, txn)
        cdc = getattr(self, "_cdc", None)
        if cdc is not None:
            cdc.emit_commit(commit_ts, txn.cache.deltas)
        subs = getattr(self, "_subscriptions", None)
        if subs is not None:
            subs.on_commit(txn.cache.deltas)
        # vector index ingestion at commit (factory seam)
        for key, posts in txn.cache.deltas.items():
            pk = keys.parse_key(key)
            vidx = self.vector_indexes.get(pk.attr)
            if vidx is not None and pk.is_data:
                for p in posts:
                    if p.is_value and p.op == OP_SET:
                        vidx.insert(pk.uid, p.val().value)
                    elif p.op == OP_DEL:
                        vidx.remove(pk.uid)
        return commit_ts

    def _feed_stats(self, deltas):
        """Count index-key postings into the cm-sketch (ref posting/stats
        collection feeding planForEqFilter)."""
        from dgraph_tpu.utils.cmsketch import feed_stats

        feed_stats(self.stats, deltas)

    # -- mutations -------------------------------------------------------------

    def _apply_rdf(
        self, txn: Txn, set_rdf: str, del_rdf: str, ns: int = keys.GALAXY_NS
    ) -> Dict[str, str]:
        return self._apply_nquads(
            txn, parse_rdf(set_rdf), parse_rdf(del_rdf), ns
        )

    def _apply_rdf_with_vars(
        self, txn: Txn, set_rdf: str, del_rdf: str, uid_vars, val_vars,
        ns: int = keys.GALAXY_NS,
    ) -> Dict[str, str]:
        """RDF application where subjects/objects may be uid(v) refs and
        values val(v) refs; the mutation fans out over the var's uids
        (ref dql upsert semantics)."""
        blank: Dict[str, int] = {}

        def resolve_many(ref: str) -> List[int]:
            if ref.startswith("uid("):
                var = ref[4:-1]
                return uid_vars.get(var, [])
            if ref.startswith("_:"):
                if ref not in blank:
                    blank[ref] = self.zero.assign_uids(1)
                return [blank[ref]]
            return [int(ref, 16) if ref.startswith("0x") else int(ref)]

        def apply_all(rdf: str, op: int):
            for nq in parse_rdf(rdf):
                for subj in resolve_many(nq.subject):
                    if nq.object_id and nq.object_id.startswith("val("):
                        # val(v): per-subject value substitution
                        var = nq.object_id[4:-1]
                        v = val_vars.get(var, {}).get(subj)
                        if v is None:
                            continue
                        apply_edge(
                            txn,
                            self.schema,
                            DirectedEdge(
                                subj, nq.predicate, value=v,
                                facets=nq.facets, op=op, ns=ns,
                            ),
                        )
                        continue
                    objs = (
                        resolve_many(nq.object_id) if nq.object_id else [None]
                    )
                    for obj in objs:
                        self._apply_nquad(
                            txn, nq, None, op, subj_uid=subj, obj_uid=obj,
                            ns=ns,
                        )

        apply_all(set_rdf, OP_SET)
        apply_all(del_rdf, OP_DEL)
        return {k[2:]: hex(v) for k, v in blank.items()}

    def _nquad_edge(
        self,
        nq: NQuad,
        resolve,
        op: int,
        subj_uid: Optional[int] = None,
        obj_uid: Optional[int] = None,
        ns: int = keys.GALAXY_NS,
    ) -> DirectedEdge:
        """Build the DirectedEdge for one (non-star) N-Quad."""
        subj = subj_uid if subj_uid is not None else resolve(nq.subject)
        if nq.object_id:
            return DirectedEdge(
                subj,
                nq.predicate,
                value_id=obj_uid if obj_uid is not None else resolve(nq.object_id),
                facets=nq.facets,
                op=op,
                ns=ns,
            )
        return DirectedEdge(
            subj,
            nq.predicate,
            value=nq.object_value,
            lang=nq.lang,
            facets=nq.facets,
            op=op,
            ns=ns,
        )

    def _apply_nquad(
        self,
        txn: Txn,
        nq: NQuad,
        resolve,
        op: int,
        subj_uid: Optional[int] = None,
        obj_uid: Optional[int] = None,
        ns: int = keys.GALAXY_NS,
    ):
        """Apply one N-Quad. Callers either pass a `resolve` function or
        pre-resolved subject/object uids (the upsert fan-out path — pinned
        by role, so `uid(v) <p> uid(v)` self-pairs resolve correctly)."""
        if nq.star:
            if op != OP_DEL:
                raise ValueError("S P * only valid in delete")
            subj = subj_uid if subj_uid is not None else resolve(nq.subject)
            delete_entity_attr(txn, self.schema, subj, nq.predicate, ns)
            return
        edge = self._nquad_edge(
            nq, resolve, op, subj_uid=subj_uid, obj_uid=obj_uid, ns=ns
        )
        apply_edge(txn, self.schema, edge)

    def _apply_json(
        self, txn: Txn, set_obj, del_obj, ns: int = keys.GALAXY_NS
    ) -> Dict[str, str]:
        """JSON mutation format (ref chunker/json_parser.go): nested
        objects with "uid" refs; blank nodes via "_:name". Delegates to
        the var-aware walker (no vars bound) so set/delete semantics —
        schema-typed conversion, bare-uid node deletes, null-predicate
        deletes — stay in one place."""
        return self._apply_json_with_vars(txn, set_obj, del_obj, {}, ns=ns)

    def _node_type_preds(self, txn: Txn, uid: int, ns=keys.GALAXY_NS):
        """Predicates expanded from the node's dgraph.type definitions
        (ref worker/mutation.go expandEdges for S * * deletes)."""
        tkey = keys.DataKey("dgraph.type", uid, ns)
        preds = []
        for p in txn.cache.values(tkey):
            tu = self.schema.get_type(str(p.val().value))
            if tu is not None:
                preds.extend(tu.fields)
        return preds

    def _apply_json_with_vars(
        self, txn: Txn, set_obj, del_obj, uid_vars,
        ns: int = keys.GALAXY_NS, blank: Optional[Dict[str, int]] = None,
        val_vars: Optional[Dict[str, dict]] = None,
    ) -> Dict[str, str]:
        """JSON mutations whose uid refs may be upsert vars — the format
        the reference's GraphQL mutation rewriters emit (setjson /
        deletejson with "uid(x)" refs and @if conds, ref
        graphql/resolve/mutation_rewriter.go + edgraph doMutate var
        expansion). Values convert by schema type (geo dicts, datetimes),
        a bare {"uid": U} in delete drops the whole node (S * *), and a
        null field value in delete drops the predicate (S P *)."""
        blank = blank if blank is not None else {}

        fresh_uids: set = set()  # uids leased by THIS request

        def resolve_many(ref) -> List[int]:
            if isinstance(ref, int):
                return [ref]
            if ref.startswith("uid("):
                return list(uid_vars.get(ref[4:-1], []))
            if ref.startswith("_:"):
                if ref not in blank:
                    blank[ref] = self.zero.assign_uids(1)
                    fresh_uids.add(blank[ref])
                return [blank[ref]]
            return [int(ref, 16) if ref.startswith("0x") else int(ref)]

        def to_val(su, v) -> Val:
            # (geo dicts never reach here — walk() routes them through
            # is_geo_literal directly; `su` is the caller's schema
            # entry — one lookup per field, not one per item)
            tid = su.value_type if su is not None else None
            if tid == TypeID.DATETIME:
                from dgraph_tpu.types.types import parse_datetime

                return Val(TypeID.DATETIME, parse_datetime(str(v)))
            if tid == TypeID.PASSWORD:
                from dgraph_tpu.types.types import convert

                return convert(Val(TypeID.STRING, str(v)), TypeID.PASSWORD)
            if tid == TypeID.VFLOAT and isinstance(v, list):
                return Val(TypeID.VFLOAT, np.asarray(v, dtype=np.float32))
            return _json_to_val(v)

        def is_geo_literal(v) -> bool:
            return (
                isinstance(v, dict)
                and "coordinates" in v
                and v.get("type")
                in ("Point", "Polygon", "MultiPolygon", "MultiPoint")
            )

        # batched application: edges accumulate and flush through
        # apply_edges (bulk reads + bulk tokens, posting/mutation.py);
        # every delete flushes first so it observes the edges that
        # preceded it in walk order
        pending: List[DirectedEdge] = []

        def flush():
            if pending:
                apply_edges(txn, self.schema, pending)
                pending.clear()

        def edge(subj, pred, op, value=None, value_id=None, lang=""):
            pending.append(
                DirectedEdge(
                    subj, pred, value=value, value_id=value_id,
                    lang=lang, op=op, ns=ns,
                    fresh=subj in fresh_uids,
                )
            )

        def walk(obj, op, top=False) -> List[int]:
            uid_ref = obj.get("uid")
            subjects = resolve_many(
                uid_ref if uid_ref is not None else f"_:auto{id(obj)}"
            )
            rest = [(k, v) for k, v in obj.items() if k != "uid"]
            if op == OP_DEL and not rest and top:
                # bare top-level {"uid": U}: delete the node outright
                # (nested bare refs are edge targets, not node deletes)
                flush()
                for subj in subjects:
                    for pred in self._node_type_preds(txn, subj, ns):
                        delete_entity_attr(txn, self.schema, subj, pred, ns)
                    delete_entity_attr(
                        txn, self.schema, subj, "dgraph.type", ns
                    )
                return subjects
            schema_get = self.schema.get
            pending_append = pending.append
            for subj in subjects:
                fresh = subj in fresh_uids
                for k, v in rest:
                    if k == "dgraph.type":
                        for t in _as_list(v):
                            edge(
                                subj, "dgraph.type", op,
                                value=Val(TypeID.STRING, t),
                            )
                        continue
                    pred, lang = (
                        k.split("@", 1) if "@" in k else (k, "")
                    )
                    if v is None:
                        if op == OP_DEL:
                            flush()
                            delete_entity_attr(
                                txn, self.schema, subj, pred, ns
                            )
                        continue
                    su = schema_get(pred)
                    # flat-scalar fast path: the dominant live-loader
                    # shape is {"pred": <str|int|float|bool>} — one
                    # constructor each, skipping the list/geo/dict
                    # dispatch below (per-edge GIL work on the write
                    # hot path). DATETIME/PASSWORD convert in to_val.
                    tv = type(v)
                    if tv is str:
                        if not v.startswith("val(") and (
                            su is None
                            or su.value_type not in _SLOW_JSON_TIDS
                        ):
                            pending_append(DirectedEdge(
                                subj, pred, Val(TypeID.STRING, v),
                                None, lang, None, op, ns, fresh,
                            ))
                            continue
                    elif tv is bool or tv is int or tv is float:
                        if (
                            su is None
                            or su.value_type not in _SLOW_JSON_TIDS
                        ):
                            pending_append(DirectedEdge(
                                subj, pred,
                                Val(
                                    TypeID.BOOL if tv is bool
                                    else TypeID.INT if tv is int
                                    else TypeID.FLOAT, v,
                                ),
                                None, lang, None, op, ns, fresh,
                            ))
                            continue
                    if (
                        su is not None
                        and su.value_type == TypeID.VFLOAT
                        and isinstance(v, list)
                        and v
                        and isinstance(v[0], (int, float))
                    ):
                        edge(subj, pred, op, value=to_val(su, v))
                        continue
                    for item in _as_list(v):
                        if is_geo_literal(item):
                            edge(subj, pred, op, value=Val(TypeID.GEO, item))
                        elif isinstance(item, dict):
                            if len(item) == 1 and "uid" in item:
                                # bare nested ref: resolve without the
                                # recursive walk frame
                                for child in resolve_many(item["uid"]):
                                    pending_append(DirectedEdge(
                                        subj, pred, None, child, "",
                                        None, op, ns, fresh,
                                    ))
                                continue
                            for child in walk(item, op):
                                edge(subj, pred, op, value_id=child)
                        elif (
                            isinstance(item, str)
                            and item.startswith("val(")
                            and item.endswith(")")
                        ):
                            # val(v): per-subject value substitution,
                            # like the RDF upsert path
                            vv = (val_vars or {}).get(item[4:-1], {})
                            got = vv.get(subj)
                            if got is not None:
                                edge(subj, pred, op, value=got, lang=lang)
                        else:
                            edge(
                                subj, pred, op,
                                value=to_val(su, item), lang=lang,
                            )
            return subjects

        for obj in _as_list(set_obj):
            walk(obj, OP_SET, top=True)
        for obj in _as_list(del_obj):
            walk(obj, OP_DEL, top=True)
        flush()
        return {k[2:]: hex(v) for k, v in blank.items()}

    # -- observability ----------------------------------------------------------

    def health(self) -> dict:
        """Single-node health/SLO rollup (/debug/healthz body): the
        process healthz (admission rates, pipeline depth, SLO burn
        windows) plus this engine's snapshot-watermark lag. No raft
        groups here — the cluster engines report those."""
        from dgraph_tpu.utils import observe

        out = observe.healthz("alpha")
        out["snapshot_watermark"] = int(self._snapshot_ts)
        ma = getattr(self.zero, "max_assigned", None)
        if isinstance(ma, (int, float)):
            out["watermark_lag"] = max(0, int(ma) - self._snapshot_ts)
        return out

    # -- queries ----------------------------------------------------------------

    def _plan_cache_tiers(self) -> Dict[str, float]:
        from dgraph_tpu.posting.lists import cache_tier_snapshot

        return cache_tier_snapshot(self.mem)

    def query(
        self,
        q: str,
        read_ts: Optional[int] = None,
        access_jwt: Optional[str] = None,
        variables: Optional[Dict[str, str]] = None,
        timeout_ms: Optional[float] = None,
        want: str = "dict",
        debug: bool = False,
    ) -> dict:
        """Run a read-only query at a fresh (or given) read ts.
        timeout_ms bounds execution (ref x/limits --query timeout).
        The response carries reference-shaped extensions.server_latency
        plus the per-query profile; slow queries are force-sampled and
        appended to the slow-query JSONL log (DGRAPH_TPU_SLOW_QUERY_MS,
        DGRAPH_TPU_SLOW_QUERY_LOG).

        `want="raw"` skips the dict-API parse-back: `data` comes back
        as a streamjson.RawJson byte shell for response assembly to
        splice (the HTTP/gRPC serving surface).

        `debug=True` (EXPLAIN/ANALYZE — HTTP ?debug=true, gRPC
        Request.vars["debug"]) turns on the decision-capture hooks and
        attaches the structured plan tree as `extensions.plan`. Capture
        is observation-only: response `data` bytes are identical with
        the flag on or off (golden-enforced, tests/test_explain.py)."""
        import time as _time

        t_begin = _time.monotonic()
        # info is now always collected: the digest store records the
        # plan-cache outcome per shape, not just EXPLAIN requests (the
        # fill is three dict writes — observation-only either way)
        parse_info: dict = {}
        digested = False  # one digest record per query, on every path
        try:
            # plan cache: repeated query shapes skip parse entirely
            blocks, shape, literals = self.serving.parse(
                q, variables, info=parse_info
            )
        except Exception:
            # unparseable queries accrue to the per-ns `other` bucket —
            # a flood of malformed text is an operator-visible shape
            if DIGESTS.enabled():
                DIGESTS.record(
                    keys.GALAXY_NS, None,
                    _time.monotonic() - t_begin, error=True,
                )
            raise
        t_parsed = _time.monotonic()
        # admission gate BEFORE the read-ts allocation: a shed must be
        # FAST and side-effect-free — under overload the oracle's
        # applied-barrier wait is exactly where queries queue, and a
        # request that will be refused must neither join that queue
        # nor lease a timestamp
        ticket = self.serving.admit(shape, blocks)
        slow = False
        completed = False  # clean, untruncated execution
        try:
            ns = keys.GALAXY_NS
            allowed = None
            user = ""
            if self.acl is not None:
                from dgraph_tpu.acl.acl import READ, AclError

                try:
                    if access_jwt is None:
                        raise AclError("no access token (ACL enabled)")
                    claims = self.acl.claims(access_jwt)
                    user = claims.get("userid", "")
                    ns = int(claims.get("namespace", 0))
                    self.acl.authorize_preds(
                        access_jwt, _query_preds(blocks), READ,
                        claims=claims,
                    )
                    allowed = self.acl.readable_preds(claims)
                except Exception:
                    self._audit("query", user=user, body=q, status="DENIED")
                    raise
            self._audit("query", user=user, ns=ns, body=q)
            from dgraph_tpu.query.functions import QueryBudgetError
            from dgraph_tpu.utils import observe
            from dgraph_tpu.utils.observe import (
                METRICS,
                TRACER,
                profile_scope,
            )

            deadline = (
                _time.monotonic() + timeout_ms / 1e3
                if timeout_ms is not None
                else None
            )
            degrade_deadline = None
            if ticket.degrade:
                # saturated: run under a bounded budget and return a
                # partial/degraded response on exhaustion instead of
                # queueing at full budget (PR 3's partial-result shape)
                degrade_deadline = (
                    _time.monotonic() + self.serving.degrade_budget_s()
                )
                deadline = (
                    degrade_deadline
                    if deadline is None
                    else min(deadline, degrade_deadline)
                )
            truncated = False
            # snapshot-watermark read (ref worker/oracle MaxAssigned):
            # `_snapshot_ts` is published only after a commit's deltas
            # are written, and advances in commit-ts order — so a read
            # AT the watermark sees a complete store without leasing a
            # fresh ts and waiting out the apply barrier. Under mixed
            # traffic that wait serialized every read behind the write
            # pipeline's in-flight window; an in-flight (unacked)
            # commit is legitimately excluded from the snapshot. 0 =
            # nothing committed yet: fall back to a fresh barrier-
            # waited lease.
            # the watermark is sampled ONCE and reused for BOTH the
            # read ts and the result-cache key: re-reading
            # _snapshot_ts at key time would let a commit landing in
            # between cache watermark-N bytes under the watermark-N+1
            # key (a one-line TOCTOU that breaks the never-stale
            # proof)
            wm = self._snapshot_ts
            ts = (
                read_ts
                if read_ts is not None
                else (wm or self.zero.read_ts())
            )
            t_assigned = _time.monotonic()
            # snapshot-keyed result reuse (serving/resultcache.py):
            # watermark reads with no ACL are a pure function of
            # (shape, literals, vars, ns, watermark) — the PR 7/11
            # proof — so the whole response's wire bytes can be
            # served from the LRU. Caller-pinned read_ts never
            # caches; EXPLAIN queries always execute but record the
            # would-hit tier in the plan.
            rc_key = None
            rc_probe = False
            raw_hit = None
            if read_ts is None and self.acl is None:
                rc_key, raw_hit, rc_probe = self.serving.result_probe(
                    shape, literals, variables, ns, wm, debug,
                )
            if raw_hit is not None:
                from dgraph_tpu.serving.resultcache import hit_response

                METRICS.inc("num_queries")
                t_done = _time.monotonic()
                # hits are SERVED traffic: they must land in the
                # latency histogram the SLO/health surface reads. The
                # sample is the PROCESSING span (post-assign), the
                # same span the miss path's METRICS.timer covers — a
                # hit recording full wall time would make hit samples
                # incomparable with miss samples in one histogram
                METRICS.observe(
                    "query_latency_seconds", t_done - t_assigned
                )
                # shape stays out of the cost EWMA (finally passes
                # shape only when `completed`): a hit's latency
                # describes the cache, not the shape's execution cost
                # the admission gate estimates
                if DIGESTS.enabled():
                    DIGESTS.record(
                        ns, shape, t_done - t_begin,
                        nbytes=len(raw_hit),
                        plan_hit=bool(parse_info.get("hit")),
                        result_hit=True,
                    )
                    digested = True
                observe.note_tenant("query", ns, t_done - t_assigned)
                return hit_response(
                    raw_hit, want,
                    parsing_ns=int((t_parsed - t_begin) * 1e9),
                    assign_ns=int((t_assigned - t_parsed) * 1e9),
                    processing_ns=int((t_done - t_assigned) * 1e9),
                    watermark=wm,
                )
            cache_base = self._plan_cache_tiers() if debug else None
            with TRACER.span("query", ns=ns) as root, \
                    profile_scope(debug=debug) as prof, \
                    METRICS.timer("query_latency_seconds"):
                try:
                    cache = LocalCache(self.kv, ts, mem=self.mem)
                    # caller-pinned read_ts never coalesces: the
                    # snapshot-watermark argument only covers fresh
                    # engine-allocated timestamps (which waited on the
                    # applied barrier)
                    out = self._query_parsed(
                        blocks,
                        cache,
                        ns,
                        allowed,
                        deadline=deadline,
                        batcher=(
                            self.serving.batcher_for(cache)
                            if read_ts is None
                            else None
                        ),
                        want=want,
                    )
                except QueryBudgetError:
                    # only the degraded-admission budget converts a
                    # deadline trip into a partial result; semantic
                    # errors (different type) and a tighter CLIENT
                    # timeout (trips before the degrade budget) raise
                    if (
                        degrade_deadline is None
                        or _time.monotonic() < degrade_deadline
                    ):
                        raise
                    out = {"data": {}}
                    truncated = True
            METRICS.inc("num_queries")
            t_done = _time.monotonic()
            took_ms = (t_done - t_begin) * 1e3
            ext = out.setdefault("extensions", {})
            # encoding happens inside _query_parsed; it reports the
            # wire-bytes production time through the profile and the
            # processing component gives it up so the parts still sum
            # to total_ns with no unattributed gap (the dict-API
            # parse-back, when present, stays inside processing and is
            # itemized as profile.encode.parse_ns)
            enc_ns = int(prof.encode.get("encode_ns", 0))
            total_ns = int((t_done - t_begin) * 1e9)
            ext["server_latency"] = {
                # new order: parse -> admission/ACL/ts -> execute; the
                # admission + ACL + audit time rides in the assign
                # component
                "parsing_ns": int((t_parsed - t_begin) * 1e9),
                "assign_timestamp_ns": int((t_assigned - t_parsed) * 1e9),
                "processing_ns": max(
                    int((t_done - t_assigned) * 1e9) - enc_ns, 0
                ),
                "encoding_ns": enc_ns,
                "total_ns": total_ns,
            }
            if total_ns > 0 and prof.encode:
                prof.encode["share"] = round(enc_ns / total_ns, 4)
            ext["profile"] = prof.to_dict()
            if prof.plan is not None:
                prof.plan.plan_cache = parse_info or {}
                prof.plan.admission = {
                    "enabled": self.serving.admission.enabled(),
                    "cost": round(ticket.cost, 3),
                    "degrade": ticket.degrade,
                }
                if cache_base is not None:
                    now_tiers = self._plan_cache_tiers()
                    prof.plan.cache = {
                        k: now_tiers[k] - cache_base.get(k, 0)
                        for k in now_tiers
                    }
                prof.plan.result_cache = {
                    "enabled": self.serving.results.capacity() > 0,
                    "eligible": rc_key is not None,
                    "would_hit": bool(rc_probe),
                    "watermark": int(self._snapshot_ts),
                }
                prof.plan.meta = {
                    "read_ts": int(ts),
                    "snapshot_watermark": int(self._snapshot_ts),
                    "wall_ns": total_ns,
                }
                ext["plan"] = prof.plan.to_dict()
            if root.trace_id:
                ext["trace_id"] = f"{root.trace_id:032x}"
            if ticket.degrade:
                ext["degraded_admission"] = True
            if truncated:
                METRICS.inc("degraded_queries_total")
                ext["degraded"] = True
                ext["partial"] = True
            if DIGESTS.enabled():
                data = out.get("data")
                rows = (
                    sum(
                        len(v)
                        for v in data.values()
                        if isinstance(v, list)
                    )
                    if isinstance(data, dict)
                    else 0
                )
                DIGESTS.record(
                    ns, shape, t_done - t_begin,
                    rows=rows,
                    nbytes=int(prof.encode.get("bytes", 0)),
                    error=truncated,
                    plan_hit=bool(parse_info.get("hit")),
                    setop_pairs=int(
                        prof.events.get("setop_pairs_total", 0)
                    ),
                    setop_packed=int(
                        prof.events.get("setop_packed_total", 0)
                    ),
                )
                digested = True
            observe.note_tenant("query", ns, t_done - t_assigned)
            # structured slow-query log (ref x/log.go LogSlowOperation,
            # edgraph/server.go:1448): force-sample + bounded JSONL —
            # the digest shape key rides along so a slow entry joins
            # its aggregate row in /debug/digests
            slow = observe.maybe_log_slow(
                "query", q, took_ms, root,
                extra={"ns": ns, "shape": shape},
                threshold_ms=self.slow_query_ms,
            )
            completed = not truncated
            if rc_key is not None and completed:
                raw = getattr(out.get("data"), "raw", None)
                if raw is not None:
                    self.serving.results.put(rc_key, raw)
            return out
        finally:
            # a query that entered execution but never reached a digest
            # record (ACL denial, semantic error, client deadline)
            # still counts against its shape — errors are a first-class
            # digest column
            if not digested and DIGESTS.enabled():
                DIGESTS.record(
                    ns, shape, _time.monotonic() - t_begin, error=True,
                )
            # only clean completions feed the shape cost EWMA: a
            # truncated/denied/failed run's latency describes the
            # failure, not the shape
            self.serving.finish(
                ticket,
                shape if completed else None,
                (_time.monotonic() - t_begin) * 1e3,
                slow=slow,
            )

    def query_rdf(
        self,
        q: str,
        read_ts: Optional[int] = None,
        variables: Optional[Dict[str, str]] = None,
    ) -> str:
        """Query with RDF (N-Quads) response encoding (ref
        query/outputrdf.go ToRDF; resp_format=RDF on the wire)."""
        from dgraph_tpu.query.outputrdf import encode_rdf

        ts = read_ts if read_ts is not None else self.zero.read_ts()
        blocks = dql.parse(q, variables)
        ex = Executor(
            LocalCache(self.kv, ts, mem=self.mem),
            self.schema,
            vector_indexes=self.vector_indexes,
        )
        nodes = ex.process(blocks)
        return encode_rdf(nodes)

    def _query(self, q: str, cache: LocalCache) -> dict:
        return self._query_parsed(dql.parse(q), cache, keys.GALAXY_NS)

    def _schema_query(self, gq) -> dict:
        """schema {} / schema(pred: ...) / schema(type: ...) blocks
        (ref dql parseSchema + worker schema retrieval; golden shapes in
        query0_test.go TestSchemaBlock*)."""
        from dgraph_tpu.types.types import type_name as _tn

        if gq.expand:  # schema(type: A) / schema(type: [A, B])
            types = []
            for tname in sorted(gq.expand.split(",")):
                tu = self.schema.get_type(tname)
                if tu is not None:
                    types.append(
                        {
                            "name": tu.name,
                            "fields": [{"name": f} for f in tu.fields],
                        }
                    )
            return {"data": {"types": types} if types else {}}
        want = set(gq.facet_names)  # requested fields ({} = all)
        preds = gq.groupby_attrs or sorted(self.schema.predicates())
        out = []
        for pred in preds:
            su = self.schema.get(pred)
            if su is None:
                continue  # unknown preds silently dropped (ref behavior)
            row: dict = {"predicate": pred}

            def put(field, value, truthy=True):
                if want and field not in want:
                    return
                if truthy and not value:
                    return
                row[field] = value

            put("type", _tn(su.value_type), truthy=False)
            put("index", bool(su.directive_index))
            if su.directive_index:
                put("tokenizer", list(su.tokenizers))
            put("reverse", su.directive_reverse)
            put("count", su.count)
            put("lang", su.lang)
            put("list", su.is_list)
            put("upsert", su.upsert)
            put("unique", su.unique)
            put("no_conflict", su.no_conflict)
            out.append(row)
        return {"data": {"schema": out}}

    def _query_parsed(
        self,
        blocks,
        cache: LocalCache,
        ns: int,
        allowed_preds=None,
        deadline=None,
        batcher=None,
        want: str = "dict",
    ) -> dict:
        if len(blocks) == 1 and blocks[0].attr == "__schema__":
            return self._schema_query(blocks[0])
        ex = Executor(
            cache,
            self.schema,
            ns=ns,
            vector_indexes=self.vector_indexes,
            allowed_preds=allowed_preds,
            stats=self.stats,
            deadline=deadline,
            batcher=batcher,
        )
        nodes = ex.process(blocks)
        data, enc_stats = encode_response_data(
            nodes, val_vars=ex.val_vars, schema=self.schema, want=want
        )
        prof = observe.current_profile()
        if prof is not None:
            prof.encode.update(enc_stats)
            if prof.plan is not None:
                prof.plan.planner = (
                    ex.planner.explain()
                    if ex.planner is not None
                    else {"enabled": False}
                )
        return {"data": data}


def _query_preds(blocks) -> list:
    """All predicates a query touches (for ACL checks,
    ref edgraph/server.go authorizeRequest)."""
    preds = set()

    def from_func(fn):
        if fn is None or not fn.attr:
            return
        if fn.name == "type":
            preds.add("dgraph.type")  # attr holds the type NAME, not a pred
        else:
            preds.add(fn.attr.lstrip("~"))

    def from_filter(ft):
        if ft is None:
            return
        from_func(ft.func)
        for c in ft.children:
            from_filter(c)

    def walk(g):
        from_func(g.func)
        from_filter(g.filter)
        # classify by node kind (flags), not by attr-name heuristics — a
        # data predicate literally named "q"/"var" must still be checked
        is_virtual = (
            g.is_uid
            or g.val_var
            or g.aggregator
            or g.math_expr is not None
            or g.expand  # expanded preds are ACL-filtered at execution
            or (g.is_count and g.attr == "uid")
        )
        if g.attr and not is_virtual:
            preds.add(g.attr.lstrip("~"))
        for ga in g.groupby_attrs:
            preds.add(ga.lstrip("~"))
        for o in g.order:
            if o.attr:
                preds.add(o.attr)
        for c in g.children:
            walk(c)

    for b in blocks:
        for c in b.children:
            walk(c)
        from_func(b.func)
        from_filter(b.filter)
        for ga in b.groupby_attrs:
            preds.add(ga.lstrip("~"))
        for o in b.order:
            if o.attr:
                preds.add(o.attr)
    return sorted(preds)


def _json_preds(obj) -> set:
    """Predicates referenced by a JSON mutation object tree."""
    preds = set()

    def walk(o):
        if isinstance(o, list):
            for it in o:
                walk(it)
            return
        if not isinstance(o, dict):
            return
        for k, v in o.items():
            if k == "uid":
                continue
            preds.add(k.split("@", 1)[0])
            if isinstance(v, (dict, list)):
                walk(v)

    walk(obj)
    return preds


def _eval_cond(cond: str, uid_vars) -> bool:
    """Evaluate '@if(...)' upsert conditions: len(var) comparisons
    combined with AND/OR/NOT and parentheses (ref dql conditional
    mutations, edgraph/server.go parseMutationObject cond handling)."""
    import re as _re

    m = _re.match(r"\s*@if\s*\((.*)\)\s*$", cond, _re.S)
    if not m:
        raise ValueError(f"unsupported upsert condition {cond!r}")
    expr = m.group(1)

    tokens = _re.findall(
        r"\(|\)|AND\b|OR\b|NOT\b|and\b|or\b|not\b|"
        r"(?:eq|lt|le|gt|ge)\s*\(\s*len\s*\(\s*\w+\s*\)\s*,\s*\d+\s*\)",
        expr,
    )
    if not tokens or "".join(tokens).replace(" ", "") != expr.replace(" ", ""):
        raise ValueError(f"unsupported upsert condition {cond!r}")
    pos = 0

    def atom(tok: str) -> bool:
        am = _re.match(
            r"(eq|lt|le|gt|ge)\s*\(\s*len\s*\(\s*(\w+)\s*\)\s*,\s*(\d+)\s*\)",
            tok,
        )
        op, var, n = am.group(1), am.group(2), int(am.group(3))
        ln = len(uid_vars.get(var, []))
        return {
            "eq": ln == n,
            "lt": ln < n,
            "le": ln <= n,
            "gt": ln > n,
            "ge": ln >= n,
        }[op]

    def parse_or() -> bool:
        nonlocal pos
        left = parse_and()
        while pos < len(tokens) and tokens[pos].lower() == "or":
            pos += 1
            right = parse_and()
            left = left or right
        return left

    def parse_and() -> bool:
        nonlocal pos
        left = parse_not()
        while pos < len(tokens) and tokens[pos].lower() == "and":
            pos += 1
            right = parse_not()
            left = left and right
        return left

    def parse_not() -> bool:
        nonlocal pos
        if pos < len(tokens) and tokens[pos].lower() == "not":
            pos += 1
            return not parse_not()
        return parse_primary()

    def parse_primary() -> bool:
        nonlocal pos
        tok = tokens[pos]
        if tok == "(":
            pos += 1
            v = parse_or()
            if pos >= len(tokens) or tokens[pos] != ")":
                raise ValueError(f"unbalanced parens in {cond!r}")
            pos += 1
            return v
        pos += 1
        return atom(tok)

    out = parse_or()
    if pos != len(tokens):
        raise ValueError(f"trailing tokens in upsert condition {cond!r}")
    return out


# schema value types whose JSON scalars need to_val's conversion work
# (everything else takes the flat-scalar fast path in the JSON walker)
_SLOW_JSON_TIDS = (TypeID.DATETIME, TypeID.PASSWORD)


def _as_list(x):
    if x is None:
        return []
    return x if isinstance(x, list) else [x]


def _json_to_val(item) -> Val:
    if isinstance(item, bool):
        return Val(TypeID.BOOL, item)
    if isinstance(item, int):
        return Val(TypeID.INT, item)
    if isinstance(item, float):
        return Val(TypeID.FLOAT, item)
    if isinstance(item, list):
        return Val(TypeID.VFLOAT, np.asarray(item, dtype=np.float32))
    return Val(TypeID.STRING, str(item))

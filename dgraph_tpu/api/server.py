"""In-process API front-end: the edgraph.Server equivalent.

Mirrors /root/reference/edgraph/server.go: Query (doQuery:1396),
Mutate (doMutate:575), Alter (:355 schema & drop ops),
CommitOrAbort (:2108) — single-process round 1 with the ZeroLite seam
standing in for the Zero cluster (ref hooks/config.go ZeroHooks).

Mutations accept RDF text (set/delete) or structured edges; blank nodes
(`_:x`) get fresh uids (ref query/mutation.go:187 AssignUids). Queries run
through dql.parse -> query.Executor -> JsonEncoder.
"""

from __future__ import annotations

import json
import threading
from typing import Dict, List, Optional

import numpy as np

from dgraph_tpu import dql
from dgraph_tpu.loaders.rdf import NQuad, parse_rdf
from dgraph_tpu.posting.lists import LocalCache, Txn
from dgraph_tpu.posting.mutation import DirectedEdge, apply_edge, delete_entity_attr
from dgraph_tpu.posting.pl import OP_DEL, OP_SET
from dgraph_tpu.query.outputjson import JsonEncoder
from dgraph_tpu.query.subgraph import Executor
from dgraph_tpu.schema.schema import State, parse_schema
from dgraph_tpu.storage.kv import KV, open_kv
from dgraph_tpu.types.types import TypeID, Val
from dgraph_tpu.x import keys
from dgraph_tpu.zero.zero import TxnConflictError, ZeroLite


class TxnHandle:
    """Client-side transaction handle (dgo Txn equivalent)."""

    def __init__(self, server: "Server", read_only: bool = False):
        self.server = server
        self.start_ts = server.zero.next_ts()
        self.txn = Txn(server.kv, self.start_ts)
        self.read_only = read_only
        self.finished = False

    def query(self, q: str) -> dict:
        return self.server._query(q, self.txn.cache)

    def mutate_rdf(
        self, set_rdf: str = "", del_rdf: str = "", commit_now: bool = False
    ) -> Dict[str, str]:
        uids = self.server._apply_rdf(self.txn, set_rdf, del_rdf)
        if commit_now:
            self.commit()
        return uids

    def mutate_json(self, set_obj=None, del_obj=None, commit_now: bool = False):
        uids = self.server._apply_json(self.txn, set_obj, del_obj)
        if commit_now:
            self.commit()
        return uids

    def commit(self) -> int:
        if self.finished:
            raise RuntimeError("transaction already finished")
        self.finished = True
        return self.server._commit(self.txn)

    def discard(self):
        self.finished = True
        self.server.zero.abort(self.start_ts)


class Server:
    """Single-node engine (Alpha + embedded Zero-lite)."""

    def __init__(self, data_dir: Optional[str] = None):
        self.kv: KV = open_kv(data_dir)
        self.zero = ZeroLite()
        self.schema = State()
        self.vector_indexes: Dict[str, object] = {}
        self._lock = threading.Lock()
        self._bootstrap_schema()

    def _bootstrap_schema(self):
        # system predicates (ref schema/schema.go initialSchema)
        for su in parse_schema(
            "dgraph.type: [string] @index(exact) .\n"
            "dgraph.xid: string @index(exact) .\n"
        )[0]:
            self.schema.set(su)

    # -- alter (ref edgraph/server.go:355) -----------------------------------

    def alter(self, schema_text: str = "", drop_attr: str = "", drop_all: bool = False):
        with self._lock:
            if drop_all:
                ts = self.zero.next_ts()
                for pred in self.schema.predicates():
                    self.kv.drop_prefix(keys.PredicatePrefix(pred))
                self.schema = State()
                self._bootstrap_schema()
                self.vector_indexes.clear()
                return
            if drop_attr:
                self.kv.drop_prefix(keys.PredicatePrefix(drop_attr))
                self.schema.delete(drop_attr)
                self.vector_indexes.pop(drop_attr, None)
                return
            preds, types = parse_schema(schema_text)
            for su in preds:
                old = self.schema.get(su.predicate)
                self.schema.set(su)
                if su.vector_specs:
                    self._ensure_vector_index(su)
                if old is not None and (
                    old.tokenizers != su.tokenizers
                ):
                    self._reindex(su)
            for tu in types:
                self.schema.set_type(tu)

    def _ensure_vector_index(self, su):
        from dgraph_tpu.models.vector import VectorIndex

        if su.predicate not in self.vector_indexes:
            self.vector_indexes[su.predicate] = VectorIndex(
                pred=su.predicate,
                metric=su.vector_specs[0].metric,
            )

    def _reindex(self, su):
        """Full index rebuild for a predicate (ref posting/index.go:1115
        IndexRebuild): drop index range, re-tokenize all values."""
        pred = su.predicate
        self.kv.drop_prefix(keys.IndexPrefix(pred))
        ts = self.zero.next_ts()
        read = LocalCache(self.kv, ts)
        from dgraph_tpu.posting.pl import Posting
        from dgraph_tpu.tok.tok import build_tokens

        tokenizers = su.tokenizer_objs()
        if not tokenizers:
            return
        writes = []
        for k, _, _ in self.kv.iterate(keys.DataPrefix(pred), ts):
            pk = keys.parse_key(k)
            for p in read.values(k):
                for tokb in build_tokens(p.val(), tokenizers):
                    ikey = keys.IndexKey(pred, tokb)
                    from dgraph_tpu.posting.pl import encode_delta

                    writes.append((ikey, ts, encode_delta([Posting(uid=pk.uid, op=OP_SET)])))
        self.kv.put_batch(writes)

    # -- transactions ---------------------------------------------------------

    def new_txn(self, read_only: bool = False) -> TxnHandle:
        return TxnHandle(self, read_only)

    def _commit(self, txn: Txn) -> int:
        commit_ts = self.zero.commit(txn.start_ts, txn.conflict_keys)
        txn.write_deltas(self.kv, commit_ts)
        # vector index ingestion at commit (factory seam)
        for key, posts in txn.cache.deltas.items():
            pk = keys.parse_key(key)
            vidx = self.vector_indexes.get(pk.attr)
            if vidx is not None and pk.is_data:
                for p in posts:
                    if p.is_value and p.op == OP_SET:
                        vidx.insert(pk.uid, p.val().value)
                    elif p.op == OP_DEL:
                        vidx.remove(pk.uid)
        return commit_ts

    # -- mutations -------------------------------------------------------------

    def _apply_rdf(self, txn: Txn, set_rdf: str, del_rdf: str) -> Dict[str, str]:
        blank: Dict[str, int] = {}

        def resolve(ref: str) -> int:
            if ref.startswith("_:"):
                if ref not in blank:
                    blank[ref] = self.zero.assign_uids(1)
                return blank[ref]
            if ref.startswith("0x"):
                return int(ref, 16)
            return int(ref)

        for nq in parse_rdf(set_rdf):
            self._apply_nquad(txn, nq, resolve, OP_SET)
        for nq in parse_rdf(del_rdf):
            self._apply_nquad(txn, nq, resolve, OP_DEL)
        return {k[2:]: hex(v) for k, v in blank.items()}

    def _apply_nquad(self, txn: Txn, nq: NQuad, resolve, op: int):
        subj = resolve(nq.subject)
        if nq.star:
            if op != OP_DEL:
                raise ValueError("S P * only valid in delete")
            delete_entity_attr(txn, self.schema, subj, nq.predicate)
            return
        if nq.object_id:
            edge = DirectedEdge(
                subj,
                nq.predicate,
                value_id=resolve(nq.object_id),
                facets=nq.facets,
                op=op,
            )
        else:
            edge = DirectedEdge(
                subj,
                nq.predicate,
                value=nq.object_value,
                lang=nq.lang,
                facets=nq.facets,
                op=op,
            )
        apply_edge(txn, self.schema, edge)

    def _apply_json(self, txn: Txn, set_obj, del_obj) -> Dict[str, str]:
        """JSON mutation format (ref chunker/json_parser.go): nested objects
        with "uid" refs; blank nodes via "_:name"."""
        blank: Dict[str, int] = {}

        def resolve(ref) -> int:
            if isinstance(ref, int):
                return ref
            if ref.startswith("_:"):
                if ref not in blank:
                    blank[ref] = self.zero.assign_uids(1)
                return blank[ref]
            return int(ref, 16) if ref.startswith("0x") else int(ref)

        def walk(obj, op) -> int:
            uid = resolve(obj.get("uid", f"_:auto{id(obj)}"))
            for k, v in obj.items():
                if k == "uid":
                    continue
                if k == "dgraph.type":
                    vs = v if isinstance(v, list) else [v]
                    for t in vs:
                        apply_edge(
                            txn,
                            self.schema,
                            DirectedEdge(
                                uid, "dgraph.type",
                                value=Val(TypeID.STRING, t), op=op,
                            ),
                        )
                    continue
                lang = ""
                pred = k
                if "@" in k:
                    pred, lang = k.split("@", 1)
                vs = v if isinstance(v, list) else [v]
                for item in vs:
                    if isinstance(item, dict):
                        child = walk(item, op)
                        apply_edge(
                            txn,
                            self.schema,
                            DirectedEdge(uid, pred, value_id=child, op=op),
                        )
                    else:
                        val = _json_to_val(item)
                        apply_edge(
                            txn,
                            self.schema,
                            DirectedEdge(uid, pred, value=val, lang=lang, op=op),
                        )
            return uid

        for obj in _as_list(set_obj):
            walk(obj, OP_SET)
        for obj in _as_list(del_obj):
            walk(obj, OP_DEL)
        return {k[2:]: hex(v) for k, v in blank.items()}

    # -- queries ----------------------------------------------------------------

    def query(self, q: str, read_ts: Optional[int] = None) -> dict:
        """Run a read-only query at a fresh (or given) read ts."""
        ts = read_ts if read_ts is not None else self.zero.read_ts()
        return self._query(q, LocalCache(self.kv, ts))

    def _query(self, q: str, cache: LocalCache) -> dict:
        blocks = dql.parse(q)
        ex = Executor(
            cache, self.schema, vector_indexes=self.vector_indexes
        )
        nodes = ex.process(blocks)
        enc = JsonEncoder(val_vars=ex.val_vars, schema=self.schema)
        return {"data": enc.encode_blocks(nodes)}


def _as_list(x):
    if x is None:
        return []
    return x if isinstance(x, list) else [x]


def _json_to_val(item) -> Val:
    if isinstance(item, bool):
        return Val(TypeID.BOOL, item)
    if isinstance(item, int):
        return Val(TypeID.INT, item)
    if isinstance(item, float):
        return Val(TypeID.FLOAT, item)
    if isinstance(item, list):
        return Val(TypeID.VFLOAT, np.asarray(item, dtype=np.float32))
    return Val(TypeID.STRING, str(item))

"""Query subscriptions: re-run a query when its predicates change.

Mirrors /root/reference/graphql/subscription/ + worker/worker.go:75
Subscribe (badger-prefix subscription -> poller re-running the query):
a subscription registers the predicates its query touches; every commit
that writes one of them re-evaluates the query, and the callback fires
when the result actually changed.
"""

from __future__ import annotations

import json
import threading
from typing import Callable, Dict, List, Optional

from dgraph_tpu.x import keys


class Subscriptions:
    def __init__(self, server):
        self.server = server
        self._subs: Dict[int, dict] = {}
        self._next_id = 0
        self._lock = threading.Lock()
        server._subscriptions = self

    def subscribe(
        self,
        query: str,
        callback: Callable[[dict], None],
        access_jwt: Optional[str] = None,
    ) -> int:
        """Register; fires callback immediately with the current result and
        then on every change. With ACL enabled the subscriber's token is
        captured and used for every re-evaluation. Returns a sub id."""
        from dgraph_tpu import dql
        from dgraph_tpu.api.server import _query_preds

        blocks = dql.parse(query)
        preds = set(_query_preds(blocks))
        result = self.server.query(query, access_jwt=access_jwt)
        with self._lock:
            self._next_id += 1
            sid = self._next_id
            self._subs[sid] = {
                "query": query,
                "preds": preds,
                "callback": callback,
                "jwt": access_jwt,
                "last": json.dumps(result, sort_keys=True, default=str),
            }
        callback(result)
        return sid

    def unsubscribe(self, sid: int):
        with self._lock:
            self._subs.pop(sid, None)

    def on_commit(self, deltas):
        """Called by the engine post-commit with the touched keys."""
        touched = set()
        for key in deltas:
            try:
                touched.add(keys.parse_key(key).attr)
            except Exception:
                continue
        with self._lock:
            subs = list(self._subs.items())
        for sid, sub in subs:
            if not (sub["preds"] & touched):
                continue
            # never let a subscriber error fail the commit that triggered it
            try:
                result = self.server.query(sub["query"], access_jwt=sub["jwt"])
                blob = json.dumps(result, sort_keys=True, default=str)
                if blob != sub["last"]:
                    sub["last"] = blob
                    sub["callback"](result)
            except Exception:
                import logging

                logging.getLogger("dgraph_tpu.subs").exception(
                    "subscription %d re-evaluation failed", sid
                )

"""Query subscriptions: re-run a query when its predicates change.

Mirrors /root/reference/graphql/subscription/ + worker/worker.go:75
Subscribe (badger-prefix subscription -> poller re-running the query):
a subscription registers the predicates its query touches; every commit
that writes one of them re-evaluates the query, and the callback fires
when the result actually changed.
"""

from __future__ import annotations

import json
import threading
from typing import Callable, Dict, List, Optional

from dgraph_tpu.x import keys


class Subscriptions:
    def __init__(self, server):
        self.server = server
        self._subs: Dict[int, dict] = {}
        self._next_id = 0
        self._lock = threading.Lock()
        server._subscriptions = self

    def subscribe(
        self,
        query: str,
        callback: Callable[[dict], None],
        access_jwt: Optional[str] = None,
    ) -> int:
        """Register; fires callback immediately with the current result and
        then on every change. With ACL enabled the subscriber's token is
        captured and used for every re-evaluation. Returns a sub id."""
        from dgraph_tpu import dql
        from dgraph_tpu.api.server import _query_preds

        blocks = dql.parse(query)
        preds = set(_query_preds(blocks))
        result = self.server.query(query, access_jwt=access_jwt)
        with self._lock:
            self._next_id += 1
            sid = self._next_id
            self._subs[sid] = {
                "query": query,
                "preds": preds,
                "callback": callback,
                "jwt": access_jwt,
                "last": json.dumps(result, sort_keys=True, default=str),
            }
        callback(result)
        return sid

    def subscribe_graphql(
        self,
        query: str,
        callback: Callable[[dict], None],
        variables: Optional[dict] = None,
    ) -> int:
        """GraphQL subscription: `subscription { queryT ... }` runs through
        the engine's GraphQL layer and re-fires on commits touching the
        selected types' predicates (ref graphql/subscription/poller.go,
        commit-driven instead of timed polling)."""
        import re as _re

        gql = getattr(self.server, "graphql", None)
        if gql is None:
            raise ValueError("no GraphQL schema configured")
        # a subscription op is evaluated like a query op
        body = _re.sub(r"^\s*subscription\b", "query", query, count=1)

        # predicates: every field predicate of every type the selection
        # tree touches (nested object selections included — a commit on a
        # child type must re-fire too)
        from dgraph_tpu.graphql.parser import parse_operation

        preds = set()

        def walk(t, sels):
            # owner-qualified: inherited fields live under the
            # interface's predicate (Character.name, not Human.name)
            preds.update(t.pred(f) for f in t.fields)
            preds.add("dgraph.type")
            for s in sels:
                if s.name == "...":
                    ft = (
                        t if not s.frag_on else gql.types.get(s.frag_on)
                    )
                    if ft is not None:
                        walk(ft, s.selections)
                    continue
                f = t.fields.get(s.name)
                if f is not None and not f.is_scalar:
                    ct = gql.types.get(f.type_name)
                    if ct is not None:
                        walk(ct, s.selections)

        op = parse_operation(body, variables)
        for sel in op.selections:
            m = _re.match(r"(?:get|query|aggregate)(\w+)", sel.name)
            t = gql.types.get(m.group(1)) if m else None
            if t is not None:
                walk(t, sel.selections)

        def evaluate():
            return gql.execute(body, variables)

        result = evaluate()
        with self._lock:
            self._next_id += 1
            sid = self._next_id
            self._subs[sid] = {
                "preds": preds,
                "callback": callback,
                "jwt": None,
                "evaluate": evaluate,
                "last": json.dumps(result, sort_keys=True, default=str),
            }
        callback(result)
        return sid

    def unsubscribe(self, sid: int):
        with self._lock:
            self._subs.pop(sid, None)

    def on_commit(self, deltas):
        """Called by the engine post-commit with the touched keys."""
        touched = set()
        for key in deltas:
            try:
                touched.add(keys.parse_key(key).attr)
            except Exception:
                continue
        with self._lock:
            subs = list(self._subs.items())
        for sid, sub in subs:
            if not (sub["preds"] & touched):
                continue
            # never let a subscriber error fail the commit that triggered it
            try:
                ev = sub.get("evaluate")
                result = (
                    ev()
                    if ev is not None
                    else self.server.query(sub["query"], access_jwt=sub["jwt"])
                )
                blob = json.dumps(result, sort_keys=True, default=str)
                if blob != sub["last"]:
                    sub["last"] = blob
                    sub["callback"](result)
            except Exception:
                import logging

                logging.getLogger("dgraph_tpu.subs").exception(
                    "subscription %d re-evaluation failed", sid
                )

"""HTTP front-end: the Alpha endpoint surface.

Mirrors /root/reference/dgraph/cmd/alpha (setupServer run.go:458, http.go,
admin.go): /query, /mutate, /commit, /alter, /health, /state,
/admin/schema, /admin/export, /admin/backup, /debug/prometheus_metrics.
JSON bodies and response envelope follow the reference's
{"data": ..., "extensions": {"server_latency": ...}} shape.
"""

from __future__ import annotations

import json
import threading
import time
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional
from urllib.parse import parse_qs, urlparse

from dgraph_tpu.acl.acl import AclError
from dgraph_tpu.acl.jwt import JwtError
from dgraph_tpu.dql.parser import ParseError
from dgraph_tpu.query import streamjson
from dgraph_tpu.query.functions import QueryError
from dgraph_tpu.api.server import Server, TxnHandle
from dgraph_tpu.serving import TooManyRequestsError
from dgraph_tpu.worker.remote import RetryBudgetExhausted
from dgraph_tpu.worker.tabletmove import TabletFencedError
from dgraph_tpu.zero.zero import TxnConflictError


class _Handler(BaseHTTPRequestHandler):
    server_version = "dgraph-tpu/0.1"
    engine: Server = None  # type: ignore[assignment]
    txns: Dict[int, TxnHandle] = {}
    txn_owner: Dict[int, str] = {}
    metrics: Dict[str, float] = {}

    def log_message(self, *a):  # quiet
        pass

    # -- helpers -------------------------------------------------------------

    def _body(self) -> bytes:
        n = int(self.headers.get("Content-Length", 0))
        return self.rfile.read(n) if n else b""

    def _reply(self, obj, code=200):
        # responses whose `data` carries pre-encoded wire bytes (the
        # streaming arena encoder, query/streamjson.py) are SPLICED —
        # the result tree never runs through json.dumps a second time
        raw = (
            streamjson.response_bytes(obj)
            if isinstance(obj, dict)
            else None
        )
        data = raw if raw is not None else json.dumps(obj).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _error(self, msg, code=400):
        self._reply(
            {"errors": [{"message": str(msg), "extensions": {"code": "Error"}}]},
            code,
        )

    def _count(self, name):
        self.metrics[name] = self.metrics.get(name, 0) + 1

    # -- routes ---------------------------------------------------------------

    def do_GET(self):
        parsed_url = urlparse(self.path)
        path = parsed_url.path
        get_qs = parse_qs(parsed_url.query)
        if path == "/graphql":
            from dgraph_tpu.api import ws

            if ws.is_upgrade(self.headers):
                # GraphQL subscriptions over websocket (ref
                # graphql/subscription/poller.go transport)
                if ws.handshake(self):
                    ws.serve_graphql_ws(self, self.engine)
                self.close_connection = True
                return
        if path == "/health":
            self._reply(
                [
                    {
                        "instance": "alpha",
                        "status": "healthy",
                        "version": "0.1.0",
                        "uptime": int(time.time() - _START),
                    }
                ]
            )
        elif path == "/state":
            self._reply(
                {
                    "counter": self.engine.zero.max_assigned,
                    "maxUID": self.engine.zero._max_uid,
                    "groups": {"1": {"tablets": {
                        p: {"predicate": p}
                        for p in self.engine.schema.predicates()
                    }}},
                }
            )
        elif path == "/admin/schema":
            from dgraph_tpu.admin.export import _schema_line

            lines = [
                _schema_line(self.engine.schema.get(p))
                for p in self.engine.schema.predicates()
            ]
            self._reply({"data": {"schema": "\n".join(lines)}})
        elif path == "/debug/traces":
            from dgraph_tpu.utils.observe import TRACER

            # a cluster engine (ProcCluster) merges every process's
            # spans; single-process engines serve the local ring
            merged_traces = getattr(self.engine, "merged_traces", None)
            if merged_traces is not None:
                self._reply({"spans": merged_traces(200)})
            else:
                self._reply({"spans": TRACER.recent(200)})
        elif path == "/debug/tablets":
            from dgraph_tpu.utils.observe import TABLETS

            # cluster engines merge every alpha's traffic rows (plus
            # unreachable_instances); single-process engines serve the
            # local accumulator
            merged_tablets = getattr(self.engine, "merged_tablets", None)
            if merged_tablets is not None:
                self._reply(merged_tablets())
            else:
                TABLETS.publish()
                self._reply({"tablets": TABLETS.snapshot()})
        elif path == "/debug/healthz":
            from dgraph_tpu.utils import observe

            health = getattr(self.engine, "health", None)
            self._reply(health() if health is not None else observe.healthz())
        elif path == "/debug/digests":
            # cluster engines merge every process's digest store
            # (rows summed by (ns, shape)); single-process engines
            # serve the local store
            merged_digests = getattr(self.engine, "merged_digests", None)
            if merged_digests is not None:
                self._reply(merged_digests())
            else:
                from dgraph_tpu.serving.digest import DIGESTS

                self._reply({"digests": DIGESTS.snapshot()})
        elif path == "/debug/history":
            from dgraph_tpu.utils.observe import HISTORY

            try:
                window = float(get_qs.get("window", ["600"])[0])
            except ValueError:
                window = 600.0
            merged_history = getattr(self.engine, "merged_history", None)
            if merged_history is not None:
                self._reply(merged_history(window))
            else:
                self._reply(HISTORY.report(window))
        elif path == "/debug/profile":
            from dgraph_tpu.utils.profiler import AUTO, PROFILER

            if get_qs.get("last"):
                folded = AUTO.last() or ""
                data = folded.encode()
                self.send_response(200 if folded else 404)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            else:
                try:
                    seconds = float(get_qs.get("seconds", ["5"])[0])
                except ValueError:
                    seconds = 5.0
                data = PROFILER.profile(
                    min(max(seconds, 0.05), 60.0)
                ).encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
        elif path == "/debug/slowlog":
            from dgraph_tpu.utils.observe import slow_query_log

            body = b""
            log = slow_query_log()
            if log is not None:
                try:
                    with open(log.path, "rb") as f:
                        body = f.read()
                except OSError:
                    body = b""
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif path == "/debug/config":
            from dgraph_tpu.x import config as _cfg

            self._reply(_cfg.resolved())
        elif path == "/debug/bundle":
            bundle = getattr(self.engine, "debug_bundle", None)
            if bundle is None:
                return self._error("no cluster engine behind this facade", 404)
            try:
                window = float(get_qs.get("window", ["600"])[0])
            except ValueError:
                window = 600.0
            self._reply(bundle(window))
        elif path == "/debug/openmetrics":
            from dgraph_tpu.utils.observe import METRICS

            data = METRICS.render_openmetrics().encode()
            self.send_response(200)
            self.send_header(
                "Content-Type",
                "application/openmetrics-text; version=1.0.0; "
                "charset=utf-8",
            )
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
        elif path == "/debug/prometheus_metrics":
            from dgraph_tpu.utils.observe import METRICS

            out = []
            for k, v in sorted(self.metrics.items()):
                out.append(f"# TYPE dgraph_tpu_http_{k} counter")
                out.append(f"dgraph_tpu_http_{k} {v}")
            # cluster engines scrape every alpha/zero process and merge
            # (counters summed, histogram buckets merged, per-instance
            # labels); single-process engines render the local registry
            merged = getattr(self.engine, "merged_metrics", None)
            out.append(merged() if merged is not None else METRICS.render())
            data = ("\n".join(out) + "\n").encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
        else:
            self._error(f"no route {path}", 404)

    def do_POST(self):
        t0 = time.time()
        parsed = urlparse(self.path)
        path = parsed.path
        qs = parse_qs(parsed.query)
        token = self.headers.get("X-Dgraph-AccessToken")
        # admin/DDL routes are guardian-only once ACL is enabled
        # (ref edgraph alter/admin guardian checks)
        _GUARDED = (
            "/alter", "/admin", "/admin/export", "/admin/backup",
            "/admin/restore", "/admin/cdc",
            "/admin/schema/graphql", "/admin/draining", "/admin/shutdown",
            "/admin/task",
            # GraphQL resolvers run inside the engine without per-predicate
            # enforcement this round; guardian-only when ACL is on (the
            # reference gates GraphQL with its own @auth system instead)
            "/graphql",
        )
        try:
            if self.engine.acl is not None and path in _GUARDED:
                if not self.engine.acl.is_guardian(token):
                    return self._error(
                        "only guardians can access this endpoint", 403
                    )
            if self.engine.acl is not None and path == "/commit":
                # commits/aborts are bound to the txn owner's identity
                ts_q = int(qs.get("startTs", ["0"])[0])
                owner = self.txn_owner.get(ts_q)
                try:
                    caller = self.engine.acl.claims(token)["userid"] if token else None
                except Exception:
                    caller = None
                if caller is None or (owner is not None and owner != caller):
                    return self._error(
                        "access token required to commit this transaction", 401
                    )
            if path == "/login":
                if self.engine.acl is None:
                    return self._error("ACL not enabled", 400)
                body = json.loads(self._body().decode("utf-8"))
                if body.get("refreshToken"):
                    toks = self.engine.acl.refresh(body["refreshToken"])
                    self.engine._audit("login-refresh")
                else:
                    toks = self.engine.login(
                        body.get("userid", ""),
                        body.get("password", ""),
                        int(body.get("namespace", 0)),
                    )
                self._reply({"data": toks})
            elif path == "/query":
                self._count("num_queries")
                if qs.get("respFormat", [""])[0] == "rdf":
                    raw = self._body().decode("utf-8")
                    rdf = self.engine.query_rdf(raw)
                    data = rdf.encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/n-quads")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                    return
                raw = self._body().decode("utf-8")
                variables = None
                # EXPLAIN/ANALYZE: ?debug=true (the reference's debug
                # query param) or a "debug": true JSON body field turns
                # on plan capture; data bytes are unchanged by it
                debug = qs.get("debug", ["false"])[0] == "true"
                if "json" in self.headers.get("Content-Type", ""):
                    body = json.loads(raw)
                    if not isinstance(body, dict):
                        raise ValueError("JSON query body must be an object")
                    raw = body.get("query", "")
                    variables = body.get("variables")
                    if variables is not None and not isinstance(variables, dict):
                        raise ValueError('"variables" must be an object')
                    # accept only explicit truthy spellings: a client
                    # sending the STRING "false" must not enable debug
                    debug = body.get("debug", debug) in (True, "true", "1")
                timeout_ms = None
                if qs.get("timeout"):
                    t = qs["timeout"][0]  # "5s" / "500ms" (ref ?timeout=)
                    timeout_ms = (
                        float(t[:-2]) if t.endswith("ms")
                        else float(t.rstrip("s")) * 1e3
                    )
                res = self.engine.query(
                    raw,
                    access_jwt=token,
                    variables=variables,
                    timeout_ms=timeout_ms,
                    # serving surface: data stays wire bytes end-to-end
                    # (no dict parse-back; _reply splices the arena)
                    want="raw",
                    debug=debug,
                )
                # keep the engine's server_latency/profile/trace_id and
                # stamp the HTTP-layer total on top (reference envelope)
                ext = res.setdefault("extensions", {})
                lat = ext.setdefault("server_latency", {})
                lat["total_ns"] = int((time.time() - t0) * 1e9)
                self._reply(res)
            elif path == "/mutate":
                if getattr(self.engine, "draining", False):
                    return self._error(
                        "the server is in draining mode", 503
                    )
                self._count("num_mutations")
                self._handle_mutate(qs, token)
            elif path == "/commit":
                ts = int(qs.get("startTs", ["0"])[0])
                txn = self.txns.pop(ts, None)
                self.txn_owner.pop(ts, None)
                if txn is None:
                    return self._error(f"no pending txn with startTs {ts}")
                if qs.get("abort", ["false"])[0] == "true":
                    txn.discard()
                    return self._reply({"data": {"code": "Success", "message": "Done"}})
                commit_ts = txn.commit()
                self._reply({"data": {"code": "Success", "commitTs": commit_ts}})
            elif path == "/alter":
                if getattr(self.engine, "draining", False):
                    return self._error("the server is in draining mode", 503)
                body = self._body().decode("utf-8")
                try:
                    op = json.loads(body)
                except json.JSONDecodeError:
                    op = {"schema": body}
                if op.get("drop_all"):
                    self.engine.alter(drop_all=True)
                elif op.get("drop_attr"):
                    self.engine.alter(drop_attr=op["drop_attr"])
                else:
                    self.engine.alter(op.get("schema", ""))
                self._reply({"data": {"code": "Success", "message": "Done"}})
            elif path == "/graphql":
                body = json.loads(self._body().decode("utf-8"))
                gql = getattr(self.engine, "graphql", None)
                if gql is None:
                    return self._error("no GraphQL schema configured", 400)
                # @auth JWT: read the header named by Dgraph.Authorization
                token = None
                if gql.auth_config is not None:
                    token = self.headers.get(gql.auth_config.header)
                self._reply(
                    gql.execute(
                        body.get("query", ""),
                        body.get("variables"),
                        jwt_token=token,
                    )
                )
            elif path == "/admin":
                # the admin GraphQL schema (ref graphql/admin/admin.go)
                from dgraph_tpu.graphql.admin import AdminGraphQL

                body = json.loads(self._body().decode("utf-8"))
                self._reply(
                    AdminGraphQL(self.engine).execute(
                        body.get("query", ""), body.get("variables")
                    )
                )
            elif path == "/admin/schema/graphql":
                # upload an SDL schema (ref graphql/admin updateGQLSchema)
                from dgraph_tpu.graphql import GraphQLServer

                sdl = self._body().decode("utf-8")
                self.engine.graphql = GraphQLServer(self.engine, sdl)
                self._reply({"data": {"code": "Success", "message": "Done"}})
            elif path == "/admin/export":
                import tempfile

                from dgraph_tpu.admin import tasks

                out_dir = qs.get(
                    "destination", [tempfile.mkdtemp(prefix="dgraph_export_")]
                )[0]
                tid = tasks.enqueue_export(self.engine, out_dir)
                st = tasks._queue_of(self.engine).wait(tid)
                ok = st.get("status") == "Success"
                self._reply(
                    {"data": {"code": st.get("status", "Unknown"), **st}},
                    200 if ok else 500,
                )
            elif path == "/admin/backup":
                from dgraph_tpu.admin import tasks

                dest = qs.get("destination", ["/tmp/dgraph_tpu_backup"])[0]
                full = qs.get("full", ["false"])[0] == "true"
                tid = tasks.enqueue_backup(
                    self.engine, dest, incremental=not full
                )
                if qs.get("wait", ["true"])[0] == "true":
                    # distributed online backups can legitimately run
                    # long (move drains alone cost up to the fence
                    # deadline per tablet) — the queue default of 30s
                    # would 500 a backup that later succeeds
                    st = tasks._queue_of(self.engine).wait(
                        tid, timeout=300
                    )
                    ok = st.get("status") == "Success"
                    self._reply(
                        {"data": {"code": st.get("status", "Unknown"), **st}},
                        200 if ok else 500,
                    )
                else:
                    self._reply(
                        {"data": {"code": "Success", "taskId": f"{tid:#x}"}}
                    )
            elif path == "/admin/restore":
                from dgraph_tpu.admin import tasks

                src = qs.get("source", [""])[0]
                if not src:
                    return self._error("restore needs ?source=<dir>")
                tid = tasks.enqueue_restore(self.engine, src)
                if qs.get("wait", ["true"])[0] == "true":
                    st = tasks._queue_of(self.engine).wait(tid, timeout=300)
                    ok = st.get("status") == "Success"
                    self._reply(
                        {"data": {"code": st.get("status", "Unknown"), **st}},
                        200 if ok else 500,
                    )
                else:
                    self._reply(
                        {"data": {"code": "Success", "taskId": f"{tid:#x}"}}
                    )
            elif path == "/admin/cdc":
                from dgraph_tpu.admin.cdc import cdc_for_uri

                sink = qs.get("sink", [""])[0]
                cdc = getattr(self.engine, "_cdc", None)
                if qs.get("disable", [""])[0] == "true":
                    if cdc is not None:
                        cdc.close()
                    self._reply({"data": {"code": "Success",
                                          "enabled": False}})
                elif sink:
                    if cdc is not None:
                        cdc.close()
                    cdc = cdc_for_uri(self.engine, sink)
                    self._reply(
                        {
                            "data": {
                                "code": "Success",
                                "enabled": True,
                                "sink": sink,
                                "checkpoint": cdc.checkpoint,
                            }
                        }
                    )
                else:
                    # status probe; `dead` means the emitter thread is
                    # gone and events defer to replay — re-enable with
                    # ?sink= to recover the stream
                    self._reply(
                        {
                            "data": {
                                "enabled": cdc is not None,
                                "sink": getattr(cdc, "sink_uri", None),
                                "checkpoint": (
                                    cdc.checkpoint if cdc else 0
                                ),
                                "dead": bool(
                                    cdc is not None and cdc.dead
                                ),
                            }
                        }
                    )
            elif path == "/admin/task":
                tid = int(qs.get("id", ["0"])[0], 16)
                from dgraph_tpu.admin import tasks

                st = tasks._queue_of(self.engine).status(tid)
                if st is None:
                    return self._error(f"no task {tid:#x}", 404)
                self._reply({"data": st})
            elif path == "/admin/draining":
                enable = qs.get("enable", ["true"])[0] == "true"
                self.engine.draining = enable
                self._reply(
                    {"data": {"code": "Success",
                              "message": f"draining mode set to {enable}"}}
                )
            elif path == "/admin/shutdown":
                self._reply({"data": {"code": "Success", "message": "Done"}})
                threading.Thread(
                    target=self.server.shutdown, daemon=True
                ).start()
            else:
                self._error(f"no route {path}", 404)
        except TooManyRequestsError as e:
            # admission shed: retryable — clients back off and resend
            self._reply(
                {
                    "errors": [
                        {
                            "message": str(e),
                            "extensions": {
                                "code": TooManyRequestsError.code,
                                "retryable": True,
                            },
                        }
                    ]
                },
                429,
            )
        except TabletFencedError as e:
            # tablet move fence: the window is bounded (or awaiting
            # recovery) — retryable, never wrong data
            self._reply(
                {
                    "errors": [
                        {
                            "message": str(e),
                            "extensions": {
                                "code": TabletFencedError.code,
                                "retryable": True,
                            },
                        }
                    ]
                },
                503,
            )
        except RetryBudgetExhausted as e:
            # the query's retry/hedge budget ran dry (brownout): shed
            # retryable instead of letting clients amplify the storm
            self._reply(
                {
                    "errors": [
                        {
                            "message": str(e),
                            "extensions": {
                                "code": RetryBudgetExhausted.code,
                                "retryable": True,
                            },
                        }
                    ]
                },
                503,
            )
        except TxnConflictError as e:
            self._error(f"Transaction has been aborted. Please retry. {e}", 409)
        except (AclError, JwtError) as e:
            self._error(e, 401)
        except (json.JSONDecodeError, ValueError, ParseError, QueryError) as e:
            self._error(e, 400)  # malformed client input/query
        except Exception as e:
            traceback.print_exc()
            self._error(e, 500)

    def _handle_mutate(self, qs, token=None):
        body = self._body().decode("utf-8")
        commit_now = qs.get("commitNow", ["false"])[0] == "true"
        start_ts = int(qs.get("startTs", ["0"])[0])
        ctype = self.headers.get("Content-Type", "application/rdf")

        if start_ts and start_ts in self.txns:
            txn = self.txns[start_ts]
        else:
            txn = self.engine.new_txn()

        if "json" in ctype:
            obj = json.loads(body) if body.strip() else {}
            uids = txn.mutate_json(
                set_obj=obj.get("set"),
                del_obj=obj.get("delete"),
                access_jwt=token,
            )
        else:
            # RDF body: {set { ... } delete { ... }} or bare nquads
            set_rdf, del_rdf = _split_rdf_blocks(body)
            uids = txn.mutate_rdf(
                set_rdf=set_rdf, del_rdf=del_rdf, access_jwt=token
            )

        if commit_now:
            self.txns.pop(txn.start_ts, None)  # finished txns don't linger
            commit_ts = txn.commit()
            self._reply(
                {
                    "data": {
                        "code": "Success",
                        "uids": uids,
                        "commitTs": commit_ts,
                    }
                }
            )
        else:
            self.txns[txn.start_ts] = txn
            if self.engine.acl is not None and token:
                try:
                    self.txn_owner[txn.start_ts] = self.engine.acl.claims(
                        token
                    )["userid"]
                except Exception:
                    pass
            self._reply(
                {"data": {"code": "Success", "uids": uids, "startTs": txn.start_ts}}
            )


_START = time.time()


def _scan_block(body: str, keyword: str) -> str:
    """Extract the `keyword { ... }` block with quote-aware brace scanning
    ('}' inside RDF string literals, e.g. GeoJSON values, must not
    terminate the block; ref chunker mutation lexing)."""
    import re

    m = re.search(rf"\b{keyword}\s*\{{", body)
    if not m:
        return ""
    i = m.end()
    in_quote = False
    n = len(body)
    start = i
    while i < n:
        c = body[i]
        if in_quote:
            if c == "\\":
                i += 2
                continue
            if c == '"':
                in_quote = False
        elif c == '"':
            in_quote = True
        elif c == "}":
            return body[start:i]
        i += 1
    return body[start:]


def _split_rdf_blocks(body: str):
    """Parse `{ set { ... } delete { ... } }` mutation envelopes
    (ref chunker mutation parsing); bare N-Quads treated as set."""
    set_block = _scan_block(body, "set")
    del_block = _scan_block(body, "delete")
    if set_block or del_block:
        return set_block, del_block
    return body, ""


class HTTPServer:
    """Embeddable HTTP server (the Alpha's 8080 surface)."""

    def __init__(self, engine: Server, host: str = "127.0.0.1", port: int = 8080):
        handler = type(
            "BoundHandler",
            (_Handler,),
            {"engine": engine, "txns": {}, "txn_owner": {}, "metrics": {}},
        )
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()

"""Shared plumbing for the project-invariant analyzer suite.

The suite is NOT a general-purpose linter: every checker encodes an
invariant this codebase depends on for correctness (lock discipline,
deadline propagation, ctypes ABI fidelity, config-registry routing,
JAX host/device hygiene). A violation is therefore either a real defect
to fix or a deliberate exception — which must be allowlisted with a
written reason (`allowlist.py`). There is no third state.

Vocabulary:

  Violation — (checker, code, path, line, message). `code` names the
    defect class (e.g. "raw-env-read", "lock-order-cycle") so tests and
    allowlist entries can match classes, not message spelling.
  Allow — a deliberate exception: checker + repo-relative path +
    a match string (substring of the message, or exactly the code) +
    a mandatory human reason. One entry may cover several violations
    of the same class in the same file (e.g. three fault-injection
    sleeps in conn/rpc.py).
  Report — partitioned outcome: `violations` (unallowlisted — the
    gate fails on any), `suppressed` ((violation, allow) pairs), and
    `unused_allows` (stale entries; the gate fails on those too, so
    the allowlist can never rot).
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple


@dataclass(frozen=True)
class Violation:
    checker: str
    code: str
    path: str  # repo-relative posix path
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.checker}/{self.code}] {self.message}"


@dataclass(frozen=True)
class Allow:
    checker: str
    path: str
    match: str  # substring of message, or exactly the violation code
    reason: str

    def covers(self, v: Violation) -> bool:
        return (
            self.checker == v.checker
            and self.path == v.path
            and (self.match == v.code or self.match in v.message)
        )


@dataclass
class Source:
    """One parsed Python file of the scanned tree."""

    path: str  # absolute
    rel: str  # repo-relative posix path (e.g. "conn/rpc.py")
    text: str
    tree: Optional[ast.Module]  # None when the file failed to parse

    _parents: Optional[Dict[ast.AST, ast.AST]] = None

    def parent_map(self) -> Dict[ast.AST, ast.AST]:
        if self._parents is None:
            parents: Dict[ast.AST, ast.AST] = {}
            if self.tree is not None:
                for node in ast.walk(self.tree):
                    for child in ast.iter_child_nodes(node):
                        parents[child] = node
            self._parents = parents
        return self._parents


@dataclass
class Report:
    violations: List[Violation] = field(default_factory=list)
    suppressed: List[Tuple[Violation, Allow]] = field(default_factory=list)
    unused_allows: List[Allow] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations and not self.unused_allows

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "violations": [v.__dict__ for v in self.violations],
            "suppressed": [
                {**v.__dict__, "reason": a.reason}
                for v, a in self.suppressed
            ],
            "unused_allows": [a.__dict__ for a in self.unused_allows],
        }


Checker = Callable[[List[Source], str], List[Violation]]


def load_sources(root: str, skip_dirs: Sequence[str] = ()) -> List[Source]:
    """Parse every .py file under `root`. A syntax error becomes a
    "parse" violation downstream rather than crashing the suite."""
    out: List[Source] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames
            if d not in ("__pycache__",) and d not in skip_dirs
        )
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            with open(path, encoding="utf-8") as f:
                text = f.read()
            try:
                tree = ast.parse(text, filename=rel)
            except SyntaxError:
                tree = None
            out.append(Source(path=path, rel=rel, text=text, tree=tree))
    return out


def apply_allowlist(
    found: List[Violation], allows: Sequence[Allow]
) -> Report:
    report = Report()
    used = [False] * len(allows)
    for v in sorted(found, key=lambda v: (v.path, v.line, v.checker)):
        hit = None
        for i, a in enumerate(allows):
            if a.covers(v):
                hit = a
                used[i] = True
                break
        if hit is None:
            report.violations.append(v)
        else:
            report.suppressed.append((v, hit))
    report.unused_allows = [a for i, a in enumerate(allows) if not used[i]]
    return report


# -- small AST helpers shared by checkers -----------------------------------


def module_aliases(tree: ast.Module, module: str) -> set:
    """Names under which `module` (e.g. "os", "time") is importable in
    this file: `import os` -> {"os"}, `import os as _os` -> {"_os"}."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == module:
                    names.add(a.asname or a.name)
    return names


def imported_names(tree: ast.Module, module: str) -> Dict[str, str]:
    """{local_name: original_name} for `from <module> import ...`."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == module:
            for a in node.names:
                out[a.asname or a.name] = a.name
    return out


def sleep_call_matcher(tree: ast.Module):
    """Predicate for `time.sleep(...)` calls under ANY import alias
    (`import time as _t`, `from time import sleep as snooze`) — shared
    by the lock-discipline and deadline-hygiene checkers so alias
    handling cannot drift between them."""
    aliases = module_aliases(tree, "time") | {"time"}
    froms = {
        local
        for local, orig in imported_names(tree, "time").items()
        if orig == "sleep"
    }

    def is_sleep(node: ast.Call) -> bool:
        parts = dotted(node.func).split(".")
        return (
            len(parts) == 2 and parts[0] in aliases and parts[1] == "sleep"
        ) or (len(parts) == 1 and parts[0] in froms)

    return is_sleep


def call_name(node: ast.Call) -> str:
    """Dotted name of a call target, best effort ("os.environ.get")."""
    return dotted(node.func)


def dotted(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""

"""Project-invariant static-analysis suite (`dgraph-tpu lint`).

Eight AST/source-level checkers, each enforcing an invariant that was
first introduced by convention and is here machine-checked:

  config-registry   every DGRAPH_TPU_* env knob goes through x/config
  lock-discipline   no blocking work / native decodes under known
                    locks; pairwise intra-file acquisition order
  lock-order        the CROSS-module lock-acquisition graph (lexical
                    nesting + resolved call chains) has no cycles —
                    a cycle is a potential deadlock
  shared-state      instance/module state written from thread-entry
                    functions (Thread targets, pool submits) is either
                    lock-guarded or carries a `# race-ok: <reason>`
                    ownership annotation
  deadline-hygiene  retry loops use conn/retry.RetryPolicy; no
                    call-site settimeout constants (conn/worker/zero/raft)
  ctypes-abi        native DECLS match the extern "C" C++ signatures
                    (arity, widths, signedness, restype)
  jax-hygiene       no host numpy / implicit syncs inside jitted fns
                    (ops/, query/dispatch.py)
  metrics-registry  every METRICS.inc/observe/set_gauge/timer name is
                    declared in utils/observe.METRIC_DEFS (METRICS.md)

`run()` scans the installed package by default, applies the allowlist
(`allowlist.py`; every entry carries a reason, stale entries fail the
gate) and returns a Report. Wired into tier-1 via
tests/test_static_analysis.py and into CI via `dgraph-tpu lint
[--json]` (exit 0 clean / 1 violations / 2 internal error).
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

from dgraph_tpu.analysis import (
    check_config,
    check_ctypes_abi,
    check_deadline,
    check_jax,
    check_lockorder,
    check_locks,
    check_metrics,
    check_shared_state,
)
from dgraph_tpu.analysis.allowlist import ALLOWLIST
from dgraph_tpu.analysis.core import (
    Allow,
    Report,
    Source,
    Violation,
    apply_allowlist,
    load_sources,
)

CHECKERS = {
    check_config.NAME: check_config.check,
    check_locks.NAME: check_locks.check,
    check_lockorder.NAME: check_lockorder.check,
    check_shared_state.NAME: check_shared_state.check,
    check_deadline.NAME: check_deadline.check,
    check_ctypes_abi.NAME: check_ctypes_abi.check,
    check_jax.NAME: check_jax.check,
    check_metrics.NAME: check_metrics.check,
}


def package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run(
    root: Optional[str] = None,
    checkers: Optional[Sequence[str]] = None,
    allows: Optional[Sequence[Allow]] = None,
) -> Report:
    """Run the suite over `root` (default: the dgraph_tpu package)."""
    if root is None:
        root = package_root()
        if allows is None:
            allows = ALLOWLIST
    allows = allows if allows is not None else []
    names = list(checkers) if checkers is not None else list(CHECKERS)
    # a partial run must not report other checkers' entries as stale
    allows = [a for a in allows if a.checker in names or a.checker == "parse"]
    sources = load_sources(root)
    found: List[Violation] = []
    for src in sources:
        if src.tree is None:
            found.append(Violation(
                "parse", "syntax-error", src.rel, 1,
                "file does not parse; all checkers skipped it",
            ))
    for name in names:
        found.extend(CHECKERS[name](sources, root))
    return apply_allowlist(found, allows)


__all__ = [
    "Allow",
    "ALLOWLIST",
    "CHECKERS",
    "Report",
    "Source",
    "Violation",
    "package_root",
    "run",
]

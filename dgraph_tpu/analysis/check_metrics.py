"""metrics-registry checker: every metric name is declared in observe.py.

The metric-name registry (utils/observe.METRIC_DEFS) is the single
source of truth for what this package exports at
/debug/prometheus_metrics — one line of doc per name, rendered to
METRICS.md. A counter incremented under a typo'd or undeclared name
silently forks a new series nobody scrapes, dashboards keep graphing
the dead one, and the cluster merge sums the wrong thing. This checker
makes that class of drift machine-caught (mirror of the config-registry
checker for DGRAPH_TPU_* knobs).

Defect classes:

  unregistered-metric — a `METRICS.inc/observe/set_gauge/timer` call
    whose literal name is not declared in METRIC_DEFS (exact match or a
    `*` family like span_*_seconds).

  dynamic-metric-name — the name is an f-string whose constant shape
    does not correspond to a registered `*` family, or a non-literal
    expression the checker cannot resolve. Dynamic families are fine —
    declare the glob (e.g. fault_*_total) and format within it.

Only calls on the module-global `METRICS` registry are checked; local
`Metrics()` instances (tests, ad-hoc registries) are out of scope.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from dgraph_tpu.analysis.core import Source, Violation, dotted
from dgraph_tpu.utils.observe import METRIC_DEFS, registered_metric

NAME = "metrics-registry"

_METHODS = {"inc", "observe", "set_gauge", "timer"}


def _fstring_glob(node: ast.JoinedStr) -> str:
    """Collapse an f-string's formatted fields to `*`, keeping constant
    parts: f"span_{name}_seconds" -> "span_*_seconds"."""
    parts: List[str] = []
    for v in node.values:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            parts.append(v.value)
        else:
            parts.append("*")
    return "".join(parts)


def _name_arg(call: ast.Call) -> Optional[ast.AST]:
    if call.args:
        return call.args[0]
    for kw in call.keywords:
        if kw.arg == "name":
            return kw.value
    return None


def check(sources: List[Source], root: str) -> List[Violation]:
    out: List[Violation] = []
    for src in sources:
        if src.tree is None:
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            target = dotted(node.func)
            if target not in {f"METRICS.{m}" for m in _METHODS}:
                continue
            arg = _name_arg(node)
            line = getattr(node, "lineno", 1)
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                if not registered_metric(arg.value):
                    out.append(Violation(
                        checker=NAME,
                        code="unregistered-metric",
                        path=src.rel,
                        line=line,
                        message=(
                            f"{target}({arg.value!r}) uses an "
                            f"undeclared metric name — declare it in "
                            f"utils/observe.py METRIC_DEFS (and regen "
                            f"METRICS.md) or fix the typo"
                        ),
                    ))
            elif isinstance(arg, ast.JoinedStr):
                glob = _fstring_glob(arg)
                if glob not in METRIC_DEFS:
                    out.append(Violation(
                        checker=NAME,
                        code="dynamic-metric-name",
                        path=src.rel,
                        line=line,
                        message=(
                            f"{target}(f\"...\") formats the family "
                            f"{glob!r}, which is not a declared `*` "
                            f"family in utils/observe.py METRIC_DEFS"
                        ),
                    ))
            else:
                out.append(Violation(
                    checker=NAME,
                    code="dynamic-metric-name",
                    path=src.rel,
                    line=line,
                    message=(
                        f"{target}(<non-literal>) — metric names must "
                        f"be string literals or f-strings matching a "
                        f"declared `*` family so the registry stays "
                        f"checkable"
                    ),
                ))
    return out

"""ctypes ABI cross-checker: C++ `extern "C"` exports vs Python DECLS.

The native kernels are bound by hand-maintained ctypes declarations
(`dgraph_tpu/native/__init__.py` DECLS). Nothing at runtime validates
them: ctypes will happily call an `int64_t`-returning function with the
default `c_int` restype and hand back the low 32 bits — a decode count
or file offset past 2**31 silently corrupts memory downstream. This
checker re-derives the ABI from the C++ source on every lint run:

  undeclared-export — an exported (non-static) extern "C" function
    with no DECLS entry: it would be called with guessed types.
  stale-decl — a DECLS entry with no C++ export (renamed/removed).
  arity-mismatch — parameter count differs.
  arg-type-mismatch — width/signedness/pointer shape differs for a
    parameter (8-bit pointers are interchangeable: char*, uint8_t*).
  restype-mismatch — declared restype (None == void) does not match
    the C++ return type. This is the truncation class.

Both sides reduce to the same canonical descriptor:
(kind, bit width, signed, pointer depth). `void*` and `T**` compare by
pointer shape; signedness is ignored at 8 bits (byte buffers).
"""

from __future__ import annotations

import ctypes
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

from dgraph_tpu.analysis.core import Source, Violation

NAME = "ctypes-abi"

# (kind, width, signed); kind "void" only for the void return type
_C_BASE = {
    "void": ("void", 0, False),
    "char": ("int", 8, True),
    "signed char": ("int", 8, True),
    "unsigned char": ("int", 8, False),
    "int8_t": ("int", 8, True),
    "uint8_t": ("int", 8, False),
    "short": ("int", 16, True),
    "unsigned short": ("int", 16, False),
    "int16_t": ("int", 16, True),
    "uint16_t": ("int", 16, False),
    "int": ("int", 32, True),
    "unsigned": ("int", 32, False),
    "unsigned int": ("int", 32, False),
    "int32_t": ("int", 32, True),
    "uint32_t": ("int", 32, False),
    "long long": ("int", 64, True),
    "unsigned long long": ("int", 64, False),
    "int64_t": ("int", 64, True),
    "uint64_t": ("int", 64, False),
    "size_t": ("int", 64, False),
    "float": ("float", 32, True),
    "double": ("float", 64, True),
}

Desc = Tuple[str, int, bool, int]  # (kind, width, signed, ptr_depth)


def _typedefs(text: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for m in re.finditer(r"\busing\s+(\w+)\s*=\s*([^;]+);", text):
        out[m.group(1)] = m.group(2).strip()
    for m in re.finditer(r"\btypedef\s+([^;]+?)\s+(\w+)\s*;", text):
        out[m.group(2)] = m.group(1).strip()
    return out


def _canon_c_type(raw: str, typedefs: Dict[str, str]) -> Optional[Desc]:
    t = raw.strip()
    for _ in range(8):  # resolve typedef chains
        base = t.replace("*", " ").replace("const", " ").strip()
        base = " ".join(base.split())
        if base in typedefs:
            t = t.replace(base, typedefs[base])
        else:
            break
    ptr = t.count("*")
    base = t.replace("*", " ").replace("const", " ").strip()
    base = " ".join(base.split())
    if base not in _C_BASE:
        return None
    kind, width, signed = _C_BASE[base]
    return (kind, width, signed, ptr)


def _extern_c_regions(text: str) -> str:
    """Concatenated bodies of `extern "C" { ... }` blocks (brace-matched)."""
    out = []
    for m in re.finditer(r'extern\s+"C"\s*\{', text):
        depth = 1
        i = m.end()
        start = i
        while i < len(text) and depth:
            if text[i] == "{":
                depth += 1
            elif text[i] == "}":
                depth -= 1
            i += 1
        out.append(text[start:i - 1])
    return "\n".join(out)


_FN_RE = re.compile(
    r"^(?P<quals>(?:static\s+|inline\s+)*)"
    r"(?P<ret>[A-Za-z_][\w ]*?[\w\*]\**)\s+"
    r"(?P<name>\w+)\s*\(",
    re.M,
)


def parse_cpp_exports(
    text: str,
) -> Dict[str, Tuple[str, List[str], int]]:
    """{name: (return_type, [param_types], line)} for non-static
    functions defined inside extern "C" blocks."""
    region = _extern_c_regions(text)
    # line numbers: map region offsets back via a search in `text`
    exports: Dict[str, Tuple[str, List[str], int]] = {}
    for m in _FN_RE.finditer(region):
        if "static" in m.group("quals"):
            continue
        name = m.group("name")
        ret = m.group("ret").strip()
        if ret in ("return", "else", "if", "while"):
            continue
        # capture the parameter list up to the matching ')'
        depth = 1
        i = m.end()
        while i < len(region) and depth:
            if region[i] == "(":
                depth += 1
            elif region[i] == ")":
                depth -= 1
            i += 1
        params_raw = region[m.end():i - 1]
        # a definition follows with '{'; prototypes (';') also accepted
        params: List[str] = []
        if params_raw.strip() not in ("", "void"):
            for part in _split_params(params_raw):
                # drop the trailing parameter name (if any)
                part = part.strip()
                pm = re.match(r"^(.*?)(\b\w+)?$", part, re.S)
                typ = (pm.group(1) or part).strip() if pm else part
                if not typ:  # unnamed parameter, e.g. "void*"
                    typ = part
                params.append(" ".join(typ.split()))
        # line number of the definition in the original text
        dm = re.search(
            rf"^\s*(?:[\w\* ]+?)\b{re.escape(name)}\s*\(", text, re.M
        )
        line = text.count("\n", 0, dm.start()) + 1 if dm else 1
        exports[name] = (ret, params, line)
    return exports


def _split_params(s: str) -> List[str]:
    parts, depth, cur = [], 0, []
    for ch in s:
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
            continue
        if ch in "(<[":
            depth += 1
        elif ch in ")>]":
            depth -= 1
        cur.append(ch)
    if "".join(cur).strip():
        parts.append("".join(cur))
    return parts


# -- Python (ctypes) side ----------------------------------------------------

_CT_BASE = {
    ctypes.c_int8: ("int", 8, True),
    ctypes.c_uint8: ("int", 8, False),
    ctypes.c_char: ("int", 8, True),
    ctypes.c_int16: ("int", 16, True),
    ctypes.c_uint16: ("int", 16, False),
    ctypes.c_int32: ("int", 32, True),
    ctypes.c_uint32: ("int", 32, False),
    ctypes.c_int64: ("int", 64, True),
    ctypes.c_uint64: ("int", 64, False),
    ctypes.c_float: ("float", 32, True),
    ctypes.c_double: ("float", 64, True),
}


def canon_ctype(t) -> Optional[Desc]:
    """Canonical descriptor for a ctypes type (None == void)."""
    if t is None:
        return ("void", 0, False, 0)
    if t is ctypes.c_void_p:
        return ("void", 0, False, 1)
    if t is ctypes.c_char_p:
        return ("int", 8, True, 1)
    depth = 0
    while hasattr(t, "_type_") and not isinstance(t._type_, str):
        depth += 1
        t = t._type_
    if t in _CT_BASE:
        kind, width, signed = _CT_BASE[t]
        return (kind, width, signed, depth)
    # c_int/c_long resolve to one of the sized aliases above on every
    # supported platform; anything else is unknown
    return None


def _match(c: Desc, py: Desc) -> bool:
    ck, cw, cs, cp = c
    pk, pw, ps, pp = py
    if cp != pp:
        return False
    if cp > 0:
        # pointer: void* matches only void*; 8-bit pointees are
        # interchangeable (char* / uint8_t* byte buffers)
        if ck == "void" or pk == "void":
            return ck == pk
        if cw == 8 and pw == 8:
            return True
        return (ck, cw, cs) == (pk, pw, ps)
    if ck == "void" or pk == "void":
        return ck == pk
    return (ck, cw, cs) == (pk, pw, ps)


def _fmt(d: Optional[Desc]) -> str:
    if d is None:
        return "<unknown>"
    kind, width, signed, ptr = d
    if kind == "void":
        base = "void"
    else:
        base = f"{'' if signed else 'u'}{kind}{width}"
    return base + "*" * ptr


def check_abi(
    cpp_texts: Dict[str, str],
    decls: Dict[str, tuple],
    decl_path: str,
    decl_lines: Optional[Dict[str, int]] = None,
) -> List[Violation]:
    """Core comparison, parameterized so self-tests can feed synthetic
    sources. cpp_texts: {rel_path: source}; decls: name -> (restype,
    [argtypes]) with real ctypes objects."""
    out: List[Violation] = []
    decl_lines = decl_lines or {}
    exports: Dict[str, Tuple[str, List[str], int, str, Dict[str, str]]] = {}
    for rel, text in cpp_texts.items():
        tds = _typedefs(text)
        for name, (ret, params, line) in parse_cpp_exports(text).items():
            exports[name] = (ret, params, line, rel, tds)

    for name, (ret, params, line, rel, tds) in sorted(exports.items()):
        if name not in decls:
            out.append(Violation(
                NAME, "undeclared-export", rel, line,
                f"extern \"C\" {name} has no entry in native DECLS — "
                f"ctypes would guess int-sized types for it",
            ))
            continue
        restype, argtypes = decls[name]
        dline = decl_lines.get(name, 1)
        if len(params) != len(argtypes):
            out.append(Violation(
                NAME, "arity-mismatch", decl_path, dline,
                f"{name}: C++ takes {len(params)} args "
                f"({rel}:{line}), DECLS declares {len(argtypes)}",
            ))
            continue
        c_ret = _canon_c_type(ret, tds)
        py_ret = canon_ctype(restype)
        if c_ret is None or py_ret is None or not _match(c_ret, py_ret):
            out.append(Violation(
                NAME, "restype-mismatch", decl_path, dline,
                f"{name}: C++ returns {ret!r} ({_fmt(c_ret)}) but "
                f"restype is {_fmt(py_ret)} — an unset/narrow restype "
                f"truncates through ctypes' c_int default",
            ))
        for i, (cparam, pyt) in enumerate(zip(params, argtypes)):
            c_d = _canon_c_type(cparam, tds)
            py_d = canon_ctype(pyt)
            if c_d is None or py_d is None or not _match(c_d, py_d):
                out.append(Violation(
                    NAME, "arg-type-mismatch", decl_path, dline,
                    f"{name} arg {i}: C++ {cparam!r} ({_fmt(c_d)}) vs "
                    f"declared {_fmt(py_d)}",
                ))
    for name in sorted(decls):
        if name not in exports:
            out.append(Violation(
                NAME, "stale-decl", decl_path, decl_lines.get(name, 1),
                f"DECLS entry {name} has no extern \"C\" definition in "
                f"the native sources",
            ))
    return out


def check(sources: List[Source], root: str) -> List[Violation]:
    native_dir = os.path.join(root, "native")
    if not os.path.isdir(native_dir):
        return []
    cpp_texts: Dict[str, str] = {}
    for fn in sorted(os.listdir(native_dir)):
        if fn.endswith(".cpp"):
            with open(os.path.join(native_dir, fn), encoding="utf-8") as f:
                cpp_texts[f"native/{fn}"] = f.read()
    from dgraph_tpu import native as native_mod

    decl_rel = "native/__init__.py"
    decl_lines: Dict[str, int] = {}
    init_path = os.path.join(native_dir, "__init__.py")
    if os.path.exists(init_path):
        with open(init_path, encoding="utf-8") as f:
            for i, ln in enumerate(f, 1):
                m = re.match(r'\s*"(\w+)":', ln)
                if m:
                    decl_lines.setdefault(m.group(1), i)
    return check_abi(
        cpp_texts, native_mod.DECLS, decl_rel, decl_lines
    )

"""lock-order checker: cross-module lock-acquisition graph + cycles.

The lock-discipline checker (check_locks.py) is lexical and
intra-function: it sees `with A: with B:` in one body, so it can only
catch an inversion both of whose halves live in the same file. The
concurrent planes PRs 11-18 added don't deadlock that way — they
deadlock ACROSS modules: the commit lock (worker/groups.py /
worker/harness.py) is held while `GroupCommit.drain()` waits on the
coalescer's queue lock, the tablet mover's registry lock wraps calls
back into engines that take the commit lock, the replica picker's
breaker lock is touched from hedge pools that already hold serving
locks, and so on.

This checker builds ONE global graph:

  node — a lock, identified class-attribute-level
    ("worker/groupcommit.py:GroupCommit._lock") or module-level
    ("worker/applyshard.py:_LOCK"). Conditions canonicalize to their
    underlying lock (check_locks._collect_locks).

  edge A -> B — somewhere in the package, B is acquired while A is
    held. Two edge sources:
      (1) lexical nesting: `with A: ... with B:` in one body;
      (2) call chains: `with A: ... f()` where f (resolved best
          effort, see below) transitively acquires B.

  lock-order-cycle — a strongly connected component of >= 2 locks:
    two threads taking the component's locks along different edges can
    deadlock. Reported once per component with a witness cycle and the
    code location of every edge on it.

Call resolution is static and type-less, so it is deliberately
conservative-but-useful:

  * `self.m()` binds to method m of the lexically enclosing class;
  * bare `f()` binds to a module-level def in the same file;
  * `mod.f()` binds through `from dgraph_tpu.pkg import mod` /
    `import dgraph_tpu.pkg.mod` to that module's top-level f;
  * `obj.m()` on an arbitrary receiver binds ONLY when exactly one
    class in the scanned tree defines m AND that method (transitively)
    acquires a lock AND m is not a generic vocabulary name
    (_AMBIENT_METHODS) — unique-name resolution. Anything ambiguous
    is skipped, never guessed.

A self-edge (A -> A through a call chain) is NOT reported here:
re-acquisition is the lock-discipline checker's domain (RLocks make it
legal) and instance-level aliasing (two instances of one class) cannot
be told apart statically.

`lock_graph(sources)` exposes the raw graph for tests and for the
ARCHITECTURE.md sketch; `check()` is the analyzer entry point.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from dgraph_tpu.analysis.core import Source, Violation, dotted
from dgraph_tpu.analysis.check_locks import (
    _collect_locks,
    _ModuleLocks,
    _resolve_lock,
)

NAME = "lock-order"

# method names too generic for unique-name resolution: a call through
# one of these on an unknown receiver is always skipped, even when only
# one class in the tree defines it (dict/list/queue/file objects answer
# them too, and a false edge here manufactures a false deadlock)
_AMBIENT_METHODS = {
    "get", "set", "put", "add", "pop", "clear", "update", "items",
    "keys", "values", "copy", "join", "submit", "result", "acquire",
    "release", "wait", "notify", "notify_all", "flush", "close",
    "open", "read", "write", "send", "recv", "run", "start", "stop",
    "append", "extend", "remove", "discard", "next", "query", "commit",
    "state", "exec", "call", "apply", "render", "encode", "decode",
    "snapshot", "observe", "inc", "info", "health",
}

_MAX_DEPTH = 8  # call-chain propagation bound


@dataclass
class _Fn:
    key: str                      # "rel:Class.name" / "rel:name"
    rel: str
    cls: Optional[str]
    node: ast.AST
    # direct lexical acquisitions: (lock, line)
    acquires: List[Tuple[str, int]] = field(default_factory=list)
    # lexical nesting edges: (outer, inner, line)
    edges: List[Tuple[str, str, int]] = field(default_factory=list)
    # calls made while holding locks: (held tuple, callee expr, line)
    calls: List[Tuple[Tuple[str, ...], ast.Call, int]] = field(
        default_factory=list
    )
    # ALL calls (held or not) — needed so closures propagate through
    # intermediate frames that hold nothing themselves
    all_calls: List[Tuple[Tuple[str, ...], ast.Call, int]] = field(
        default_factory=list
    )


@dataclass
class _FileIndex:
    locks: _ModuleLocks
    # import alias -> repo-relative module path ("worker/groupcommit.py")
    mod_aliases: Dict[str, str]
    # module-level function names -> fn key
    top_fns: Dict[str, str]
    # class name -> {method name -> fn key}
    methods: Dict[str, Dict[str, str]]


def _module_rel(modpath: str) -> Optional[str]:
    """dgraph_tpu.worker.groupcommit -> worker/groupcommit.py"""
    parts = modpath.split(".")
    if parts[0] != "dgraph_tpu" or len(parts) < 2:
        return None
    return "/".join(parts[1:]) + ".py"


def _import_aliases(tree: ast.Module) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                rel = _module_rel(a.name)
                if rel is not None:
                    # `import dgraph_tpu.worker.remote as rem`
                    out[a.asname or a.name.split(".")[-1]] = rel
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                rel = _module_rel(f"{node.module}.{a.name}")
                if rel is not None:
                    out[a.asname or a.name] = rel
    return out


class _Extractor:
    """Walks every file once: lock defs, function frames, edges."""

    def __init__(self, sources: Sequence[Source]):
        self.fns: Dict[str, _Fn] = {}
        self.files: Dict[str, _FileIndex] = {}
        # method name -> [fn keys] across the whole tree (for
        # unique-name resolution)
        self.by_method: Dict[str, List[str]] = {}
        self.sources = {s.rel: s for s in sources}
        for src in sources:
            if src.tree is not None:
                self._index_file(src)
        for src in sources:
            if src.tree is not None:
                self._walk_file(src)

    # -- pass 1: indexes ----------------------------------------------------

    def _index_file(self, src: Source):
        locks = _collect_locks(src)
        top_fns: Dict[str, str] = {}
        methods: Dict[str, Dict[str, str]] = {}
        for node in src.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                top_fns[node.name] = f"{src.rel}:{node.name}"
            elif isinstance(node, ast.ClassDef):
                tbl: Dict[str, str] = {}
                for sub in node.body:
                    if isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        key = f"{src.rel}:{node.name}.{sub.name}"
                        tbl[sub.name] = key
                        self.by_method.setdefault(sub.name, []).append(key)
                methods[node.name] = tbl
        self.files[src.rel] = _FileIndex(
            locks=locks,
            mod_aliases=_import_aliases(src.tree),
            top_fns=top_fns,
            methods=methods,
        )

    # -- pass 2: frames -----------------------------------------------------

    def _walk_file(self, src: Source):
        idx = self.files[src.rel]
        for node in src.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk_fn(src, idx, node, None, f"{src.rel}:{node.name}")
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        self._walk_fn(
                            src, idx, sub, node.name,
                            f"{src.rel}:{node.name}.{sub.name}",
                        )

    def _walk_fn(
        self,
        src: Source,
        idx: _FileIndex,
        fn_node: ast.AST,
        cls: Optional[str],
        key: str,
    ):
        fn = _Fn(key=key, rel=src.rel, cls=cls, node=fn_node)
        self.fns[key] = fn
        held: List[str] = []

        def visit(node: ast.AST):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn_node:
                # nested def: its body runs later (often on a thread) —
                # fresh frame, same class context, deterministic key
                nkey = f"{key}.<{node.name}>"
                self._walk_fn(src, idx, node, cls, nkey)
                return
            if isinstance(node, ast.With):
                acquired: List[str] = []
                for item in node.items:
                    lid = _resolve_lock(idx.locks, src, cls, item.context_expr)
                    if lid is not None:
                        fn.acquires.append((lid, node.lineno))
                        for outer in held:
                            if outer != lid:
                                fn.edges.append((outer, lid, node.lineno))
                        held.append(lid)
                        acquired.append(lid)
                for sub in node.body:
                    visit(sub)
                for _ in acquired:
                    held.pop()
                return
            if isinstance(node, ast.Call):
                rec = (tuple(held), node, node.lineno)
                fn.all_calls.append(rec)
                if held:
                    fn.calls.append(rec)
            for sub in ast.iter_child_nodes(node):
                visit(sub)

        for stmt in getattr(fn_node, "body", []):
            visit(stmt)

    # -- call resolution ----------------------------------------------------

    def resolve(self, caller: _Fn, call: ast.Call) -> List[str]:
        idx = self.files[caller.rel]
        f = call.func
        # bare f()
        if isinstance(f, ast.Name):
            key = idx.top_fns.get(f.id)
            if key is None and caller.cls is None and "." not in f.id:
                # nested helper defined in this same frame
                nkey = f"{caller.key}.<{f.id}>"
                if nkey in self.fns:
                    return [nkey]
            return [key] if key else []
        if not isinstance(f, ast.Attribute):
            return []
        attr = f.attr
        base = f.value
        # self.m()
        if isinstance(base, ast.Name) and base.id == "self" \
                and caller.cls is not None:
            key = idx.methods.get(caller.cls, {}).get(attr)
            if key:
                return [key]
            # fall through: an inherited/other-class method — try unique
        # mod.f()
        if isinstance(base, ast.Name) and base.id in idx.mod_aliases:
            target_rel = idx.mod_aliases[base.id]
            tidx = self.files.get(target_rel)
            if tidx:
                key = tidx.top_fns.get(attr)
                if key:
                    return [key]
        # unique-name method resolution (cross-module edges): only when
        # unambiguous, lock-acquiring, and not vocabulary
        if attr in _AMBIENT_METHODS or attr.startswith("__"):
            return []
        cands = self.by_method.get(attr, [])
        if len(cands) == 1:
            return cands
        return []


def _closures(ex: _Extractor) -> Dict[str, Set[str]]:
    """fn key -> set of locks (transitively) acquired by calling it."""
    memo: Dict[str, Set[str]] = {}

    def go(key: str, depth: int, stack: Set[str]) -> Set[str]:
        if key in memo:
            return memo[key]
        if key in stack or depth > _MAX_DEPTH:
            return set()
        fn = ex.fns.get(key)
        if fn is None:
            return set()
        stack.add(key)
        acc: Set[str] = {lid for lid, _ in fn.acquires}
        for _, call, _ in fn.all_calls:
            for callee in ex.resolve(fn, call):
                acc |= go(callee, depth + 1, stack)
        stack.discard(key)
        if depth == 0:
            memo[key] = acc
        return acc

    for key in ex.fns:
        go(key, 0, set())
    return memo


Edge = Tuple[str, str]


def lock_graph(
    sources: Sequence[Source],
) -> Dict[Edge, Tuple[str, int, str]]:
    """{(outer, inner): (path, line, kind)} over the whole tree, where
    kind is "nest" (lexical) or "call:<fn key>" (through a resolved
    call chain)."""
    ex = _Extractor(sources)
    closures = _closures(ex)
    edges: Dict[Edge, Tuple[str, int, str]] = {}
    for fn in ex.fns.values():
        for outer, inner, line in fn.edges:
            edges.setdefault((outer, inner), (fn.rel, line, "nest"))
    for fn in ex.fns.values():
        for held, call, line in fn.calls:
            for callee in ex.resolve(fn, call):
                for inner in closures.get(callee, ()):
                    for outer in held:
                        if outer != inner:
                            edges.setdefault(
                                (outer, inner),
                                (fn.rel, line, f"call:{callee}"),
                            )
    return edges


def _sccs(nodes: Set[str], adj: Dict[str, Set[str]]) -> List[List[str]]:
    """Tarjan strongly connected components, iterative, deterministic."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    for root in sorted(nodes):
        if root in index:
            continue
        work: List[Tuple[str, int]] = [(root, 0)]
        while work:
            v, pi = work[-1]
            if pi == 0:
                index[v] = low[v] = counter[0]
                counter[0] += 1
                stack.append(v)
                on_stack.add(v)
            advanced = False
            succs = sorted(adj.get(v, ()))
            for i in range(pi, len(succs)):
                w = succs[i]
                if w not in index:
                    work[-1] = (v, i + 1)
                    work.append((w, 0))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1:
                    out.append(sorted(comp))
    return out


def _witness_cycle(
    comp: List[str], adj: Dict[str, Set[str]]
) -> List[str]:
    """One concrete cycle through the component, for the message."""
    comp_set = set(comp)
    start = comp[0]
    path = [start]
    seen = {start}
    cur = start
    while True:
        nxts = sorted(n for n in adj.get(cur, ()) if n in comp_set)
        nxt = next((n for n in nxts if n == start), None)
        if nxt is not None and len(path) > 1:
            return path
        nxt = next((n for n in nxts if n not in seen), None)
        if nxt is None:
            # fall back: close on any in-component successor
            return path
        path.append(nxt)
        seen.add(nxt)
        cur = nxt


def check(sources: List[Source], root: str) -> List[Violation]:
    edges = lock_graph(sources)
    adj: Dict[str, Set[str]] = {}
    nodes: Set[str] = set()
    for (a, b) in edges:
        adj.setdefault(a, set()).add(b)
        nodes.add(a)
        nodes.add(b)
    out: List[Violation] = []
    for comp in _sccs(nodes, adj):
        cyc = _witness_cycle(comp, adj)
        hops = []
        first_loc: Optional[Tuple[str, int]] = None
        ring = cyc + [cyc[0]]
        for a, b in zip(ring, ring[1:]):
            loc = edges.get((a, b))
            if loc is None:
                continue
            path, line, kind = loc
            if first_loc is None:
                first_loc = (path, line)
            via = "" if kind == "nest" else f" (via {kind[5:]})"
            hops.append(f"{a} -> {b} at {path}:{line}{via}")
        path, line = first_loc or (comp and comp[0].split(":")[0], 1)
        out.append(Violation(
            NAME, "lock-order-cycle", path or "", line or 1,
            "lock acquisition cycle — two threads taking these locks "
            "along different edges can deadlock: " + "; ".join(hops),
        ))
    return sorted(out, key=lambda v: (v.path, v.line))

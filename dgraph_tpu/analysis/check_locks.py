"""lock-discipline checker: blocking work under locks + ordering cycles.

Locks are discovered, not configured: any `threading.Lock() / RLock() /
Condition()` bound to a module-level name or to `self.<attr>` in a class
body is tracked. A `Condition(self._lock)` is canonicalized to its
underlying lock, so `with self._cv:` counts as acquiring `self._lock`.

Defect classes:

  blocking-under-lock — a call from the blocking vocabulary
    (time.sleep, socket connect/accept/recv/sendall/makefile,
    subprocess run/check_*/Popen, future .result(), thread .join())
    made lexically inside a `with <lock>:` body. A blocked holder
    stalls every reader of that lock — on the MemoryLayer or METRICS
    locks that is a whole-process stall.

  native-call-under-lock — a function imported from dgraph_tpu.native
    called while a lock is held. Native decodes run milliseconds on
    big packs; the level-batched read path deliberately decodes
    OUTSIDE the MemoryLayer lock and only publishes under it.

  cv-wait-under-other-lock — Condition.wait(_for) releases ITS OWN
    lock while sleeping, but any OTHER lock held at that point stays
    held for the full wait: deadlock risk.

  lock-order-cycle — lock A is taken inside B somewhere and B inside
    A somewhere else. Reported once per unordered pair, with both
    locations.

Analysis is lexical and intra-function: locks passed across call
boundaries are out of scope here — check_lockorder.py builds the
cross-module acquisition graph (call-chain resolution included) and
catches the inversions whose halves live in different files; this
checker's lock-order-cycle stays as the fast intra-file form.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from dgraph_tpu.analysis.core import (
    Source,
    Violation,
    dotted,
    module_aliases,
    sleep_call_matcher,
)

NAME = "lock-discipline"

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}

_BLOCKING_METHODS = {
    "connect", "connect_ex", "accept", "recv", "recv_into", "recvfrom",
    "makefile", "create_connection", "getaddrinfo",
    "result", "join",
}
_SUBPROCESS_FNS = {"run", "check_call", "check_output", "call", "Popen"}


def _is_lock_ctor(node: ast.AST, th_aliases: set) -> Optional[ast.Call]:
    """The Call node when `node` is threading.Lock()/RLock()/Condition()
    under any alias of the threading module (or a bare from-import)."""
    if not isinstance(node, ast.Call):
        return None
    name = dotted(node.func)
    last = name.rsplit(".", 1)[-1]
    if last in _LOCK_CTORS and (
        "." not in name or name.split(".", 1)[0] in th_aliases
    ):
        return node
    return None


@dataclass
class _ModuleLocks:
    # lock identity -> canonical identity (Conditions alias their lock)
    canonical: Dict[str, str]
    module_names: Set[str]  # module-level lock variable names
    class_attrs: Dict[str, Set[str]]  # class name -> {self attrs}


def _collect_locks(src: Source) -> _ModuleLocks:
    canonical: Dict[str, str] = {}
    module_names: Set[str] = set()
    class_attrs: Dict[str, Set[str]] = {}
    th_aliases = (
        module_aliases(src.tree, "threading") | {"threading"}
        if src.tree is not None
        else {"threading"}
    )

    def lock_id(cls: Optional[str], attr: str) -> str:
        return f"{src.rel}:{cls + '.' if cls else ''}{attr}"

    def record(cls: Optional[str], attr: str, ctor: ast.Call):
        lid = lock_id(cls, attr)
        target = lid
        # Condition(self._lock) aliases the underlying lock
        fname = dotted(ctor.func).rsplit(".", 1)[-1]
        if fname == "Condition" and ctor.args:
            arg = ctor.args[0]
            if (
                isinstance(arg, ast.Attribute)
                and isinstance(arg.value, ast.Name)
                and arg.value.id == "self"
            ):
                target = lock_id(cls, arg.attr)
            elif isinstance(arg, ast.Name):
                target = lock_id(None, arg.id)
        canonical[lid] = target
        if cls is None:
            module_names.add(attr)
        else:
            class_attrs.setdefault(cls, set()).add(attr)

    if src.tree is None:
        return _ModuleLocks(canonical, module_names, class_attrs)

    for node in src.tree.body:  # module-level assigns
        if isinstance(node, ast.Assign) and \
                _is_lock_ctor(node.value, th_aliases):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    record(None, t.id, node.value)

    for node in ast.walk(src.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) and \
                    _is_lock_ctor(sub.value, th_aliases):
                for t in sub.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        record(node.name, t.attr, sub.value)
    return _ModuleLocks(canonical, module_names, class_attrs)


def _resolve_lock(
    locks: _ModuleLocks, src: Source, cls: Optional[str], expr: ast.AST
) -> Optional[str]:
    """Canonical lock id for a with-item context expr, or None."""
    lid = None
    if isinstance(expr, ast.Name) and expr.id in locks.module_names:
        lid = f"{src.rel}:{expr.id}"
    elif (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
        and cls is not None
        and expr.attr in locks.class_attrs.get(cls, ())
    ):
        lid = f"{src.rel}:{cls}.{expr.attr}"
    if lid is None:
        return None
    return locks.canonical.get(lid, lid)


def _native_imports(src: Source) -> Set[str]:
    """Local names bound to dgraph_tpu.native functions or the module."""
    names: Set[str] = set()
    if src.tree is None:
        return names
    for node in ast.walk(src.tree):
        if isinstance(node, ast.ImportFrom):
            if node.module == "dgraph_tpu.native":
                for a in node.names:
                    names.add(a.asname or a.name)
            elif node.module == "dgraph_tpu" and any(
                a.name == "native" for a in node.names
            ):
                for a in node.names:
                    if a.name == "native":
                        names.add(a.asname or "native")
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "dgraph_tpu.native":
                    names.add((a.asname or "dgraph_tpu.native").split(".")[0])
    return names


def _receiver(node: ast.Call) -> Optional[ast.AST]:
    if isinstance(node.func, ast.Attribute):
        return node.func.value
    return None


def _is_str_join(node: ast.Call) -> bool:
    recv = _receiver(node)
    if isinstance(recv, ast.Constant) and isinstance(recv.value, str):
        return True
    name = dotted(recv) if recv is not None else ""
    return "path" in name.split(".")  # os.path.join and friends


def check(sources: List[Source], root: str) -> List[Violation]:
    out: List[Violation] = []
    # (outer, inner) -> first location
    edges: Dict[Tuple[str, str], Tuple[str, int]] = {}

    for src in sources:
        if src.tree is None:
            continue
        locks = _collect_locks(src)
        native_names = _native_imports(src)
        is_sleep_call = sleep_call_matcher(src.tree)

        def walk_fn(fn: ast.AST, cls: Optional[str]):
            held: List[str] = []

            def visit(node: ast.AST):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and node is not fn:
                    walk_fn(node, cls)  # nested defs start a fresh frame
                    return
                if isinstance(node, ast.With):
                    acquired: List[str] = []
                    for item in node.items:
                        lid = _resolve_lock(
                            locks, src, cls, item.context_expr
                        )
                        if lid is not None:
                            for outer in held:
                                if outer != lid:
                                    edges.setdefault(
                                        (outer, lid),
                                        (src.rel, node.lineno),
                                    )
                            held.append(lid)
                            acquired.append(lid)
                    for sub in node.body:
                        visit(sub)
                    for _ in acquired:
                        held.pop()
                    return
                if isinstance(node, ast.Call) and held:
                    _flag_call(node)
                for sub in ast.iter_child_nodes(node):
                    visit(sub)

            def _flag_call(node: ast.Call):
                name = dotted(node.func)
                parts = name.split(".")
                innermost = held[-1]
                # time.sleep under any lock
                if is_sleep_call(node):
                    out.append(Violation(
                        NAME, "blocking-under-lock", src.rel, node.lineno,
                        f"time.sleep while holding {', '.join(held)}",
                    ))
                    return
                if len(parts) == 2 and parts[0] in (
                    "subprocess", "_subprocess"
                ) and parts[1] in _SUBPROCESS_FNS:
                    out.append(Violation(
                        NAME, "blocking-under-lock", src.rel, node.lineno,
                        f"subprocess.{parts[1]} while holding "
                        f"{', '.join(held)}",
                    ))
                    return
                # condition wait: fine on the innermost held lock (it
                # releases it), deadlock risk when other locks are held
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr in ("wait", "wait_for"):
                    recv_lock = _resolve_lock(
                        locks, src, cls, node.func.value
                    )
                    if recv_lock is not None:
                        others = [h for h in held if h != recv_lock]
                        if others:
                            out.append(Violation(
                                NAME, "cv-wait-under-other-lock",
                                src.rel, node.lineno,
                                f"{node.func.attr}() on {recv_lock} while "
                                f"ALSO holding {', '.join(others)} — those "
                                f"stay held for the full wait",
                            ))
                        return
                    # wait on an unknown receiver: treat as blocking
                    out.append(Violation(
                        NAME, "blocking-under-lock", src.rel, node.lineno,
                        f".{node.func.attr}() while holding "
                        f"{', '.join(held)}",
                    ))
                    return
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr in _BLOCKING_METHODS:
                    if node.func.attr == "join" and _is_str_join(node):
                        return
                    out.append(Violation(
                        NAME, "blocking-under-lock", src.rel, node.lineno,
                        f".{node.func.attr}() while holding "
                        f"{', '.join(held)}",
                    ))
                    return
                if parts and parts[0] in native_names:
                    out.append(Violation(
                        NAME, "native-call-under-lock", src.rel,
                        node.lineno,
                        f"native call {name}() while holding {innermost} "
                        f"— decode outside the lock, publish under it",
                    ))

            body = getattr(fn, "body", [])
            for stmt in body:
                visit(stmt)

        # only top-level functions and direct class methods seed frames;
        # nested defs are reached through visit() so they aren't walked
        # twice with the wrong class context
        for node in src.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk_fn(node, None)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        walk_fn(sub, node.name)

    # ordering cycles: A->B and B->A both observed
    seen_pairs = set()
    for (a, b), (path, line) in sorted(edges.items()):
        if (b, a) in edges and frozenset((a, b)) not in seen_pairs:
            seen_pairs.add(frozenset((a, b)))
            p2, l2 = edges[(b, a)]
            out.append(Violation(
                NAME, "lock-order-cycle", path, line,
                f"inconsistent lock order: {a} -> {b} here but "
                f"{b} -> {a} at {p2}:{l2}",
            ))
    return out

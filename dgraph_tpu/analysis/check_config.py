"""config-registry checker: every environment read goes through x/config.

Defect classes:

  raw-dgraph-env — a `DGRAPH_TPU_*` variable read or written via raw
    `os.environ` / `os.getenv` outside x/config.py. These previously
    duplicated defaults per call site (and let them drift); the typed
    registry is the single source of truth, so any raw access is a
    hard violation — migrate to `config.get` / `config.set_env`.

  raw-env-read — any other `os.environ` / `os.getenv` access outside
    x/config.py. Foreign-runtime knobs (JAX_PLATFORMS, XLA_FLAGS,
    subprocess environment inheritance) are legitimately raw, but each
    site must carry an allowlist entry stating why, so new env
    couplings can't slip in silently.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from dgraph_tpu.analysis.core import (
    Source,
    Violation,
    dotted,
    imported_names,
    module_aliases,
)

NAME = "config-registry"
EXEMPT = ("x/config.py",)

_ENV_METHODS = {"get", "setdefault", "pop", "__getitem__", "update"}


def _literal_key(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _env_access_key(src: Source, environ_node: ast.AST) -> Optional[str]:
    """The env-var name touched through this `os.environ` node, when it
    is a literal: environ["X"], environ.get("X", ...), os.getenv("X")."""
    parents = src.parent_map()
    p = parents.get(environ_node)
    if isinstance(p, ast.Subscript):
        return _literal_key(p.slice)
    if isinstance(p, ast.Attribute) and p.attr in _ENV_METHODS:
        call = parents.get(p)
        if isinstance(call, ast.Call) and call.args:
            return _literal_key(call.args[0])
    return None


def check(sources: List[Source], root: str) -> List[Violation]:
    out: List[Violation] = []
    for src in sources:
        if src.tree is None or src.rel in EXEMPT:
            continue
        os_names = module_aliases(src.tree, "os")
        from_os = imported_names(src.tree, "os")  # from os import environ
        for node in ast.walk(src.tree):
            key = None
            line = getattr(node, "lineno", 1)
            what = None
            if isinstance(node, ast.Attribute) and node.attr in (
                "environ", "getenv", "putenv", "unsetenv"
            ):
                base = node.value
                if isinstance(base, ast.Name) and base.id in os_names:
                    what = f"os.{node.attr}"
                    if node.attr == "environ":
                        key = _env_access_key(src, node)
                    elif node.attr == "getenv":
                        call = src.parent_map().get(node)
                        if isinstance(call, ast.Call) and call.args:
                            key = _literal_key(call.args[0])
            elif isinstance(node, ast.Name) and node.id in from_os and \
                    from_os[node.id] in ("environ", "getenv"):
                what = f"os.{from_os[node.id]}"
                if from_os[node.id] == "environ":
                    key = _env_access_key(src, node)
                else:  # bare getenv("X"): the Name is the call func
                    call = src.parent_map().get(node)
                    if isinstance(call, ast.Call) and call.func is node \
                            and call.args:
                        key = _literal_key(call.args[0])
            if what is None:
                continue
            # one finding per environ/getenv mention; classify by key
            if key is not None and key.startswith("DGRAPH_TPU_"):
                out.append(Violation(
                    checker=NAME,
                    code="raw-dgraph-env",
                    path=src.rel,
                    line=line,
                    message=(
                        f"raw {what} access of {key} — DGRAPH_TPU_* knobs "
                        f"must go through dgraph_tpu.x.config "
                        f"(get/set_env)"
                    ),
                ))
            else:
                shown = key or "<dynamic>"
                out.append(Violation(
                    checker=NAME,
                    code="raw-env-read",
                    path=src.rel,
                    line=line,
                    message=(
                        f"raw {what} access ({shown}) outside x/config.py "
                        f"— register a knob or allowlist with a reason"
                    ),
                ))
    return out

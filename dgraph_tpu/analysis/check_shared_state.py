"""shared-state checker: unguarded writes from thread-context functions.

Every Python-level race this repo has shipped (the `begin_txn` lost
update, the GroupCommit `drain()` race, the `_quant_view` snapshot
race) had the same shape: a function that RUNS ON ANOTHER THREAD —
a `threading.Thread(target=...)`, a `pool.submit(...)` callable, a
timer/poll loop — wrote instance or module state that the owning
object also touches, with no lock and no stated ownership story.

This checker makes that shape illegal by default:

  unguarded-shared-write — inside a thread-entry function (or a def
    lexically nested in one, which inherits its thread context), an
    assignment / aug-assignment / subscript-store whose target is
    `self.<attr>` or a module-level name, NOT lexically inside a
    `with <known lock>:` block and NOT annotated.

Thread-entry discovery (same file, lexical):

  * `threading.Thread(target=X, ...)` / `Timer(..., X)`;
  * `<anything>.submit(X, ...)` — executor pool submission;
  * `<anything>.map(X, ...)` where X resolves to a local def;
  * X may be `self.m` (method of the enclosing class), a bare name
    (module-level or nested def), or a lambda (its body is scanned
    in place).

Escape hatch — the ownership annotation, NOT the allowlist: a line
(or the entry function's `def` line) carrying

    # race-ok: <why this write is safe>

suppresses the finding. The annotation must state an ownership
argument (single-writer, monotonic flag, GIL-atomic publish of an
immutable value, ...): bare `# race-ok` without a reason still fails
(code `race-ok-missing-reason`). This keeps the exception next to the
code it excuses, where the next editor will see it.

Known limitations (documented, deliberate): purely lexical — writes
in functions the thread entry CALLS are not attributed to it (the
lock-order checker's call resolution exists for lock edges, where a
false positive is cheap; here it would drown the signal); mutating
METHOD calls (list.append on shared state) are out of scope for the
same reason. The analyzer is a tripwire for the common shape, not a
proof of freedom from races — TSan and the GIL-fuzz harness cover the
dynamic side.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

from dgraph_tpu.analysis.core import Source, Violation, dotted
from dgraph_tpu.analysis.check_locks import _collect_locks, _resolve_lock

NAME = "shared-state"

_POOL_METHODS = {"submit", "map"}
_THREAD_CTORS = {"Thread", "Timer"}


def _line_has_race_ok(lines: List[str], lineno: int) -> Optional[bool]:
    """None = no annotation; True = annotated with a reason;
    False = bare annotation without a reason.

    Looks at the flagged line itself, then (if it carries no marker)
    at immediately preceding pure-comment lines — the idiomatic spot
    when the statement is too long for a trailing comment.
    """
    if not (1 <= lineno <= len(lines)):
        return None
    got = _race_ok_in(lines[lineno - 1])
    ln = lineno - 1
    while got is None and ln >= 1 and lines[ln - 1].lstrip().startswith("#"):
        got = _race_ok_in(lines[ln - 1])
        ln -= 1
    return got


def _race_ok_in(text: str) -> Optional[bool]:
    i = text.find("# race-ok")
    if i < 0:
        return None
    rest = text[i + len("# race-ok"):].strip()
    if rest.startswith(":"):
        rest = rest[1:].strip()
    return len(rest.split()) >= 2


@dataclass
class _Entry:
    """A function body that runs on another thread."""

    node: ast.AST            # FunctionDef / Lambda
    cls: Optional[str]       # enclosing class, for self.<attr> locks
    reason_line: int         # where it was made a thread entry (for msgs)
    how: str                 # "Thread(target=...)", ".submit(...)", ...


def _local_defs(
    tree: ast.Module,
) -> Tuple[Dict[str, ast.AST], Dict[Tuple[str, str], ast.AST]]:
    """({name: def} for every def at any nesting level,
    {(cls, name): def} for direct class methods)."""
    by_name: Dict[str, ast.AST] = {}
    by_method: Dict[Tuple[str, str], ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, node)
        if isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(
                    sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    by_method[(node.name, sub.name)] = sub
    return by_name, by_method


def _enclosing_class(src: Source, node: ast.AST) -> Optional[str]:
    parents = src.parent_map()
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return cur.name
        cur = parents.get(cur)
    return None


def _find_entries(src: Source) -> List[_Entry]:
    by_name, by_method = _local_defs(src.tree)
    entries: List[_Entry] = []
    seen: Set[int] = set()

    def add(target: ast.AST, line: int, how: str, ctx_cls: Optional[str]):
        node: Optional[ast.AST] = None
        cls = ctx_cls
        if isinstance(target, ast.Lambda):
            node = target
        elif isinstance(target, ast.Name):
            node = by_name.get(target.id)
            if node is not None:
                cls = _enclosing_class(src, node)
        elif (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"
            and ctx_cls is not None
        ):
            node = by_method.get((ctx_cls, target.attr))
            cls = ctx_cls
        if node is not None and id(node) not in seen:
            seen.add(id(node))
            entries.append(_Entry(node, cls, line, how))

    for call in ast.walk(src.tree):
        if not isinstance(call, ast.Call):
            continue
        name = dotted(call.func)
        last = name.rsplit(".", 1)[-1]
        ctx_cls = _enclosing_class(src, call)
        if last in _THREAD_CTORS:
            for kw in call.keywords:
                if kw.arg == "target":
                    add(kw.value, call.lineno, f"{last}(target=...)", ctx_cls)
            # Timer(interval, fn)
            if last == "Timer" and len(call.args) >= 2:
                add(call.args[1], call.lineno, "Timer(...)", ctx_cls)
        elif (
            isinstance(call.func, ast.Attribute)
            and call.func.attr in _POOL_METHODS
            and call.args
        ):
            add(
                call.args[0], call.lineno,
                f".{call.func.attr}(...)", ctx_cls,
            )
            # submit(copy_context().run, real_fn, ...) — the context
            # wrapper forwards; the second arg is the actual entry
            first = call.args[0]
            if (
                isinstance(first, ast.Attribute)
                and first.attr == "run"
                and len(call.args) >= 2
            ):
                add(
                    call.args[1], call.lineno,
                    f".{call.func.attr}(ctx.run, ...)", ctx_cls,
                )
    return entries


def _module_level_names(tree: ast.Module) -> Set[str]:
    out: Set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            if isinstance(node.target, ast.Name):
                out.add(node.target.id)
    return out


def _target_desc(
    t: ast.AST, module_names: Set[str], local_names: Set[str]
) -> Optional[str]:
    """Shared-state description for a store target, or None if local."""
    # self.attr  /  self.attr[...]
    node = t
    if isinstance(node, ast.Subscript):
        node = node.value
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        suffix = "[...]" if isinstance(t, ast.Subscript) else ""
        return f"self.{node.attr}{suffix}"
    # bare module-level name (global or container slot)
    if isinstance(t, ast.Name):
        # plain `x = ...` rebinding without `global` is a local; the
        # `global` case is handled by the caller adding to local_names
        return None
    if isinstance(t, ast.Subscript) and isinstance(t.value, ast.Name):
        nm = t.value.id
        if nm not in local_names and nm in module_names:
            return f"{nm}[...]"
    return None


def check(sources: List[Source], root: str) -> List[Violation]:
    out: List[Violation] = []
    for src in sources:
        if src.tree is None:
            continue
        locks = _collect_locks(src)
        module_names = _module_level_names(src.tree)
        lines = src.text.splitlines()
        for entry in _find_entries(src):
            _scan_entry(src, locks, module_names, lines, entry, out)
    # a def that is both a thread entry itself and nested inside one is
    # scanned twice — report each (path, line, code) once
    uniq: Dict[Tuple[str, int, str], Violation] = {}
    for v in out:
        uniq.setdefault((v.path, v.line, v.code), v)
    return sorted(
        uniq.values(), key=lambda v: (v.path, v.line, v.message)
    )


def _scan_entry(
    src: Source,
    locks,
    module_names: Set[str],
    lines: List[str],
    entry: _Entry,
    out: List[Violation],
):
    fn = entry.node
    def_line = getattr(fn, "lineno", entry.reason_line)
    fn_ok = _line_has_race_ok(lines, def_line)
    if fn_ok is True:
        return
    fn_name = getattr(fn, "name", "<lambda>")

    # locals: params + names assigned at any depth without `global`
    local_names: Set[str] = set()
    args = getattr(fn, "args", None)
    if args is not None:
        for a in (
            list(args.posonlyargs) + list(args.args)
            + list(args.kwonlyargs)
            + ([args.vararg] if args.vararg else [])
            + ([args.kwarg] if args.kwarg else [])
        ):
            local_names.add(a.arg)
    globals_declared: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Global):
            globals_declared.update(node.names)
        elif isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            a = node.args
            for p in (
                list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
                + ([a.vararg] if a.vararg else [])
                + ([a.kwarg] if a.kwarg else [])
            ):
                local_names.add(p.arg)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    local_names.add(t.id)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            if isinstance(node.target, ast.Name):
                local_names.add(node.target.id)
        elif isinstance(node, (ast.For, ast.comprehension)):
            tgt = node.target
            for n in ast.walk(tgt):
                if isinstance(n, ast.Name):
                    local_names.add(n.id)
    local_names -= globals_declared

    held: List[str] = []

    def flag(t: ast.AST, lineno: int):
        if isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                flag(el, lineno)
            return
        if isinstance(t, ast.Starred):
            flag(t.value, lineno)
            return
        desc = _target_desc(t, module_names, local_names)
        if desc is None and isinstance(t, ast.Name) \
                and t.id in globals_declared:
            desc = t.id
        if desc is None:
            return
        ann = _line_has_race_ok(lines, lineno)
        if ann is True:
            return
        if ann is False or fn_ok is False:
            out.append(Violation(
                NAME, "race-ok-missing-reason", src.rel, lineno,
                f"`# race-ok` on the {desc} write needs a stated "
                f"ownership reason (single-writer, monotonic, ...)",
            ))
            return
        out.append(Violation(
            NAME, "unguarded-shared-write", src.rel, lineno,
            f"{desc} written in {fn_name}() — which runs on another "
            f"thread ({entry.how} at line {entry.reason_line}) — "
            f"without a lock held; guard it or annotate the line "
            f"with `# race-ok: <ownership reason>`",
        ))

    def visit(node: ast.AST):
        if isinstance(node, ast.With):
            acquired: List[str] = []
            for item in node.items:
                lid = _resolve_lock(locks, src, entry.cls, item.context_expr)
                if lid is not None:
                    held.append(lid)
                    acquired.append(lid)
            for sub in node.body:
                visit(sub)
            for _ in acquired:
                held.pop()
            return
        if not held:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    flag(t, node.lineno)
            elif isinstance(node, ast.AugAssign):
                flag(node.target, node.lineno)
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                flag(node.target, node.lineno)
        for sub in ast.iter_child_nodes(node):
            visit(sub)

    body = getattr(fn, "body", None)
    if isinstance(body, list):
        for stmt in body:
            visit(stmt)
    elif body is not None:  # lambda
        visit(body)

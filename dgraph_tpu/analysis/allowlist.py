"""Deliberate exceptions to the analyzer suite — every entry carries a
reason. An entry that stops matching anything makes the gate FAIL
(`unused_allows`), so this list can only shrink or stay honest.

Match semantics (core.Allow): checker + exact repo-relative path +
(`match` == violation code, or `match` is a substring of the message).
One entry may cover several violations of the same class in one file.

shared-state findings do NOT belong here: their sanctioned exception
is the in-source `# race-ok: <ownership reason>` annotation, which
keeps the justification next to the write it excuses. lock-order
cycles have no exception mechanism at all — a real cycle is a
deadlock waiting for a schedule, so fix the ordering.
"""

from __future__ import annotations

from typing import List

from dgraph_tpu.analysis.core import Allow

ALLOWLIST: List[Allow] = [
    # -- config-registry -----------------------------------------------------
    Allow(
        "config-registry", "__init__.py", "raw-env-read",
        "package __init__ seeds the JAX persistent-compile-cache env "
        "BEFORE jax import; these are jax's knobs, not DGRAPH_TPU_* — "
        "routing them through the registry would import-order-invert",
    ),
    Allow(
        "config-registry", "devsetup.py", "raw-env-read",
        "XLA_FLAGS / JAX_PLATFORMS are foreign runtime knobs owned by "
        "jax; force_cpu() must read-modify-write them before the first "
        "backend init",
    ),
    Allow(
        "config-registry", "query/dispatch.py", "raw-env-read",
        "JAX_PLATFORMS is jax's own platform pin; reading it is how the "
        "dispatcher avoids initializing a backend just to learn it is "
        "CPU",
    ),
    Allow(
        "config-registry", "worker/harness.py", "raw-env-read",
        "dict(os.environ) snapshots the WHOLE environment to inherit it "
        "into spawned alpha/zero replicas (incl. fault plans); "
        "env[...]= writes there mutate the child's copy, not this "
        "process",
    ),
    # -- lock-discipline -----------------------------------------------------
    Allow(
        "lock-discipline", "conn/rpc.py", "blocking-under-lock",
        "RpcClient._lock serializes the ONE shared socket per client; "
        "the request/response exchange — including an injected "
        "fault-plan delay simulating a slow link — is exactly the "
        "lock's protected region",
    ),
    # -- deadline-hygiene ----------------------------------------------------
    Allow(
        "deadline-hygiene", "conn/rpc.py", "naked-sleep-in-loop",
        "fault-injection delays (FaultPlan act.delay_s): the sleep IS "
        "the injected network latency under test, not a retry backoff",
    ),
    Allow(
        "deadline-hygiene", "raft/tcp.py", "naked-sleep-in-loop",
        "fault-injection delays (FaultPlan act.delay_s) on the raft "
        "plane — injected latency, not retry backoff",
    ),
    Allow(
        "deadline-hygiene", "zero/zero_process.py", "naked-sleep-in-loop",
        "raft tick pacing: a fixed-cadence periodic pump (20ms logical "
        "ticks), not a retry loop — jitter would skew election timers",
    ),
    Allow(
        "deadline-hygiene", "worker/alpha_process.py", "naked-sleep-in-loop",
        "raft tick pacing, same fixed-cadence pump as zero_process",
    ),
    Allow(
        "deadline-hygiene", "worker/groups.py",
        "self._pump_ms",
        "the cluster pump thread is a fixed-cadence periodic driver "
        "(configured period), not a retry loop",
    ),
]

"""deadline/retry hygiene checker for the cluster stack.

PR 3 unified failure handling behind `conn/retry.RetryPolicy` (jittered
exponential backoff bounded by the ambient `Deadline`). This checker
keeps that from regressing inside the cluster directories (conn/,
worker/, zero/, raft/):

  naked-sleep-in-loop — `time.sleep` inside a while/for loop. A fixed
    sleep in a retry loop is exactly the pattern RetryPolicy replaced:
    no jitter (thundering herds), no deadline coupling (sleeps past
    the caller's budget). Poll loops use
    `RetryPolicy(...).sleep(attempt, deadline)`; genuinely periodic
    pumps (raft tick cadence) carry an allowlist entry saying so.

  raw-settimeout-constant — `settimeout(<numeric literal>)` outside
    conn/retry.py. Per-attempt socket budgets must derive from the
    ambient Deadline (`dl.clamp(...)`) or a configured policy value,
    never a constant invented at the call site — that is how the
    pre-PR-3 stack accumulated independent 5s/8s/15s layers.
"""

from __future__ import annotations

import ast
from typing import List

from dgraph_tpu.analysis.core import Source, Violation, sleep_call_matcher

NAME = "deadline-hygiene"

SCOPES = ("conn/", "worker/", "zero/", "raft/")
EXEMPT = ("conn/retry.py",)


def _in_scope(rel: str) -> bool:
    return rel.startswith(SCOPES) and rel not in EXEMPT


def check(sources: List[Source], root: str) -> List[Violation]:
    out: List[Violation] = []
    for src in sources:
        if src.tree is None or not _in_scope(src.rel):
            continue
        lines = src.text.splitlines()
        is_sleep_call = sleep_call_matcher(src.tree)

        def visit(node: ast.AST, loop_depth: int):
            if isinstance(node, (ast.While, ast.For)):
                loop_depth += 1
            if isinstance(node, ast.Call):
                if is_sleep_call(node) and loop_depth > 0:
                    snippet = ""
                    if 0 < node.lineno <= len(lines):
                        snippet = lines[node.lineno - 1].strip()
                    out.append(Violation(
                        NAME, "naked-sleep-in-loop", src.rel, node.lineno,
                        "time.sleep in a loop — retry/poll loops must "
                        "use conn.retry.RetryPolicy (jitter + deadline) "
                        f"[{snippet}]",
                    ))
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "settimeout"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, (int, float))
                ):
                    out.append(Violation(
                        NAME, "raw-settimeout-constant", src.rel,
                        node.lineno,
                        f"settimeout({node.args[0].value!r}) literal — "
                        f"derive per-attempt budgets from the ambient "
                        f"Deadline (conn/retry.py), not a call-site "
                        f"constant",
                    ))
            for sub in ast.iter_child_nodes(node):
                visit(sub, loop_depth)

        visit(src.tree, 0)
    return out

"""JAX hygiene checker for the device data plane (ops/, query/dispatch.py).

Inside a jit-traced function, host numpy is at best a silent constant-
fold (the np result is baked into the trace, wrong when inputs change)
and at worst a TracerConversionError or an implicit device->host sync.
The device kernels are the paper's hot path; a stray `np.` there
defeats the whole dispatch design.

Defect classes (scoped to functions that are actually jitted — plain
helpers may use numpy freely):

  np-in-jit — a call through the numpy module alias inside a function
    decorated with @jax.jit / @functools.partial(jax.jit, ...) or
    wrapped as `f = jax.jit(g)`.
  host-sync-in-jit — `.item()` / `.tolist()` / `np.asarray(...)` /
    `float(tracer)`-style `.block_until_ready()` calls inside a jitted
    function: each forces a device sync (or fails to trace).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from dgraph_tpu.analysis.core import (
    Source,
    Violation,
    dotted,
    module_aliases,
)

NAME = "jax-hygiene"

SCOPE_PREFIXES = ("ops/",)
SCOPE_FILES = ("query/dispatch.py",)

_SYNC_METHODS = {"item", "tolist", "block_until_ready"}


def _in_scope(rel: str) -> bool:
    return rel.startswith(SCOPE_PREFIXES) or rel in SCOPE_FILES


def _numpy_aliases(tree: ast.Module) -> Set[str]:
    names = module_aliases(tree, "numpy")
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "numpy":
                    names.add(a.asname or "numpy")
    return names


def _jit_decorated(fn: ast.AST, jax_aliases: Set[str]) -> bool:
    def is_jit(expr: ast.AST) -> bool:
        name = dotted(expr)
        if name in ("jit",):
            return True
        parts = name.split(".")
        return len(parts) == 2 and parts[0] in jax_aliases and \
            parts[1] == "jit"

    for dec in getattr(fn, "decorator_list", []):
        if is_jit(dec):
            return True
        if isinstance(dec, ast.Call):
            if is_jit(dec.func):
                return True
            # functools.partial(jax.jit, ...)
            if dotted(dec.func).rsplit(".", 1)[-1] == "partial" and \
                    dec.args and is_jit(dec.args[0]):
                return True
    return False


def _wrapped_names(tree: ast.Module, jax_aliases: Set[str]) -> Set[str]:
    """Function names wrapped as `f = jax.jit(g)` / `g = jit(g)`."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            name = dotted(node.value.func)
            parts = name.split(".")
            if name == "jit" or (
                len(parts) == 2 and parts[0] in jax_aliases
                and parts[1] == "jit"
            ):
                for a in node.value.args:
                    if isinstance(a, ast.Name):
                        out.add(a.id)
    return out


def check(sources: List[Source], root: str) -> List[Violation]:
    out: List[Violation] = []
    for src in sources:
        if src.tree is None or not _in_scope(src.rel):
            continue
        jax_aliases = module_aliases(src.tree, "jax") | {"jax"}
        np_aliases = _numpy_aliases(src.tree)
        wrapped = _wrapped_names(src.tree, jax_aliases)

        def scan_jitted(fn: ast.FunctionDef):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted(node.func)
                parts = name.split(".")
                if parts and parts[0] in np_aliases:
                    code = (
                        "host-sync-in-jit"
                        if parts[-1] in ("asarray", "array")
                        else "np-in-jit"
                    )
                    out.append(Violation(
                        NAME, code, src.rel, node.lineno,
                        f"{name}() inside jitted {fn.name}() — host "
                        f"numpy constant-folds into the trace (use jnp)",
                    ))
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _SYNC_METHODS
                ):
                    out.append(Violation(
                        NAME, "host-sync-in-jit", src.rel, node.lineno,
                        f".{node.func.attr}() inside jitted {fn.name}() "
                        f"— forces a device->host sync at trace/run "
                        f"time",
                    ))

        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if _jit_decorated(node, jax_aliases) or \
                        node.name in wrapped:
                    scan_jitted(node)
    return out
